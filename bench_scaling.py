"""Scaling bench: dp weak-scaling ledger + flagship-XL mp rungs.

BENCH_SCALING.json historically carried the RL weak-scaling ladder
(per-chip clips/s at 1/2/4/8 virtual CPU devices). This bench becomes its
producer: it PRESERVES that committed dp block (``points`` + ``summary``
— re-measuring it is bench_rl_async.py territory) and adds the model-
parallel rungs the flagship-XL refactor introduces:

- ``mp=1``  — the replicated stride composite (the exact program
  ops/decode_pallas._reference_stride pins), jitted on one device;
- ``mp=2``  — ops/decode_mp.mp_decode_stride on a 2-shard 'mp' mesh of
  virtual CPU devices: each shard runs the decode over its vocab slice,
  selection/logsumexp merge cross-shard.

The in-run parity gate asserts the mp=2 stride tokens are BIT-exact vs
mp=1 (logprobs within a few f32 ulps — the documented reassociation
allowance) and the mp=2 beam candidates are candidate-for-candidate
identical; the rungs ledger both the ANALYTIC merge bytes per stride
step (emb psum + (m,s) logsumexp merge + selected-logit psum + argmax
all-gathers) and the embedding-gradient dp-allreduce bytes under mp
sharding (parallel/comms.ledger mp_devices accounting).

Weak-scaling caveat (same as the dp summary's): both "shards" of the
mp=2 rung share this host's cores, so raw steps/s conflates core
contention with merge cost — the analytic bytes are the honest scaling
signal; NOT absolute TPU throughput.

Usage: python bench_scaling.py [--smoke] [--steps N] [--json PATH]
  --smoke   tiny dims, parity gate only, no BENCH_SCALING.json unless
            --json given — the CPU functional gate scripts/lint.sh runs
            (JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# the mp mesh needs devices: force 8 fake CPU devices BEFORE jax's backend
# initializes (no-op for the TPU backend)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax                                        # noqa: E402
import jax.numpy as jnp                           # noqa: E402
import numpy as np                                # noqa: E402

from cst_captioning_tpu.config.config import ModelConfig       # noqa: E402
from cst_captioning_tpu.models import CaptionModel             # noqa: E402
from cst_captioning_tpu.ops.decode_mp import (                 # noqa: E402
    mp_beam_step,
    mp_decode_stride,
)
from cst_captioning_tpu.ops.decode_pallas import (             # noqa: E402
    _reference_beam_topk,
    _reference_stride,
)
from cst_captioning_tpu.parallel.comms import ledger           # noqa: E402
from cst_captioning_tpu.train.mesh import make_mesh            # noqa: E402


def _setup(V: int, B: int, d: int, F: int, K: int, seed: int = 0):
    cfg = ModelConfig(
        vocab_size=V, modalities=(("resnet", 16),), d_embed=d, d_hidden=d,
        d_att=max(4, d // 2), encoder="temporal_attention", dropout=0.0,
        max_len=8, max_frames=F, dtype="float32", num_layers=1,
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(seed)
    feats = {"resnet": jnp.asarray(rng.normal(size=(B, F, 16)), jnp.float32)}
    masks = {"resnet": jnp.asarray(
        np.arange(F)[None] < rng.integers(2, F + 1, size=(B, 1)), jnp.float32
    )}
    labels = jnp.asarray(rng.integers(4, V, size=(B, 8)), jnp.int32)
    params = model.init(jax.random.key(0), feats, masks, labels)
    enc = model.apply(params, feats, masks, method=CaptionModel.encode)
    G = 1 + K
    carry = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (G,) + x.shape), enc.carry
    )
    token = jnp.full((G, B), 1, jnp.int32)
    return model, params, enc, carry, token, rng


def merge_bytes_per_step(G: int, B: int, E: int, mp: int,
                         emb_bytes: int = 4) -> dict:
    """Analytic cross-shard bytes of ONE sharded stride step, per device:
    the embedding psum, the (m, s) logsumexp merge + selected-logit psum,
    and the two (value, index) argmax all-gathers."""
    emb_psum = G * B * E * emb_bytes
    lse_merge = 3 * G * B * 4            # pmax(m) + psum(s) + psum(selected)
    argmax_gathers = 2 * mp * G * B * 4  # all_gather of values + indices
    return {
        "emb_psum": emb_psum,
        "lse_and_select": lse_merge,
        "argmax_all_gather": argmax_gathers,
        "total": emb_psum + lse_merge + argmax_gathers,
    }


def _time(fn, reps: int) -> float:
    fn()  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def run_mp_block(V: int, B: int, d: int, F: int, K: int, S: int,
                 reps: int) -> dict:
    model, params, enc, carry, token, rng = _setup(V, B, d, F, K)
    cell = params["params"]["cell"]
    G = 1 + K
    finished = jnp.zeros((G, B), bool)
    noise = jnp.asarray(rng.gumbel(size=(S, K, B, V)), jnp.float32)
    t0 = jnp.asarray(0, jnp.int32)
    temperature, min_len = 0.7, 2

    ref = jax.jit(lambda c, tk, n: _reference_stride(
        cell, c, tk, finished, enc.memory, enc.memory_proj, enc.memory_mask,
        n, t0, steps=S, temperature=temperature, min_len=min_len,
    ))
    c_r, tok_r, lp_r = ref(carry, token, noise)

    mesh = make_mesh(num_devices=2, mp_devices=2)
    mp = mesh.shape["mp"]
    c_m, tok_m, lp_m = mp_decode_stride(
        cell, carry, token, finished, enc.memory, enc.memory_proj,
        enc.memory_mask, noise, t0, mesh=mesh, steps=S,
        temperature=temperature, min_len=min_len,
    )
    stride_tokens_exact = bool(
        (np.asarray(tok_m) == np.asarray(tok_r)).all()
    )
    lp_diff = float(np.abs(np.asarray(lp_m) - np.asarray(lp_r)).max())

    # beam: one sharded step vs the replicated composite
    W = min(3, V // mp)
    carry_b = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (W,) + x.shape), enc.carry
    )
    token_b = jnp.full((W, B), 1, jnp.int32)
    fin_b = jnp.zeros((W, B), bool).at[W - 1].set(True)
    scores = jnp.asarray(rng.normal(size=(W, B)), jnp.float32)
    t = jnp.asarray(1, jnp.int32)
    _cb, ts_r, fl_r = _reference_beam_topk(
        cell, carry_b, token_b, fin_b, scores, enc.memory, enc.memory_proj,
        enc.memory_mask, t=t, min_len=min_len,
    )
    _cm, ts_m, fl_m = mp_beam_step(
        cell, carry_b, token_b, fin_b, scores, enc.memory, enc.memory_proj,
        enc.memory_mask, mesh=mesh, t=1, min_len=min_len,
    )
    beam_flat_exact = bool((np.asarray(fl_m) == np.asarray(fl_r)).all())
    beam_score_diff = float(
        np.abs(np.asarray(ts_m) - np.asarray(ts_r)).max()
    )

    sec_ref = _time(lambda: ref(carry, token, noise), reps)
    sec_mp = _time(lambda: mp_decode_stride(
        cell, carry, token, finished, enc.memory, enc.memory_proj,
        enc.memory_mask, noise, t0, mesh=mesh, steps=S,
        temperature=temperature, min_len=min_len,
    ), reps)

    # embedding-grad dp-allreduce bytes under mp sharding (comms ledger)
    led_1 = ledger(params, None)
    led_mp = ledger(params, None, mp_devices=mp)

    return {
        "metric": "mp_stride_seconds_per_stride_cpu_mesh",
        "dims": {"V": V, "B": B, "d": d, "frames": F, "lanes": G,
                 "steps": S},
        "rungs": [
            {"mp": 1, "seconds_per_stride": round(sec_ref, 5),
             "strides_per_sec": round(1.0 / sec_ref, 2)},
            {"mp": mp, "seconds_per_stride": round(sec_mp, 5),
             "strides_per_sec": round(1.0 / sec_mp, 2),
             "merge_bytes_per_step_per_device":
                 merge_bytes_per_step(G, B, d, mp)},
        ],
        "parity": {
            "stride_tokens_bit_exact": stride_tokens_exact,
            "beam_candidates_bit_exact": beam_flat_exact,
            "stride_logprob_max_abs_diff": lp_diff,
            "beam_score_max_abs_diff": beam_score_diff,
        },
        "embedding_grad_ledger": {
            "mp1_bytes_on_wire_per_update":
                led_1["bytes_on_wire_per_update"],
            "mp2_bytes_on_wire_per_update":
                led_mp["bytes_on_wire_per_update"],
        },
        "device_kind": jax.devices()[0].device_kind,
        "note": (
            "mp weak scaling on forced-CPU virtual devices sharing this "
            "host's core(s): raw seconds conflate core contention with "
            "merge cost — the analytic merge bytes and the embedding-grad "
            "ledger are the honest scaling signal. NOT absolute TPU "
            "throughput."
        ),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny dims, parity gate only, no JSON write "
                         "unless --json given")
    ap.add_argument("--steps", type=int, default=0,
                    help="stride length (default 6; smoke 4)")
    ap.add_argument("--json", default="",
                    help="output path (default BENCH_SCALING.json; smoke "
                         "writes none)")
    args = ap.parse_args()

    if args.smoke:
        dims = dict(V=32, B=4, d=12, F=5, K=2, S=args.steps or 4, reps=1)
    else:
        dims = dict(V=256, B=16, d=64, F=10, K=4, S=args.steps or 6,
                    reps=3)
    block = run_mp_block(**dims)

    gate_ok = (block["parity"]["stride_tokens_bit_exact"]
               and block["parity"]["beam_candidates_bit_exact"]
               and block["parity"]["stride_logprob_max_abs_diff"] < 1e-5)
    print(json.dumps({"mp": {k: block[k] for k in
                             ("metric", "rungs", "parity")}}, indent=2))
    if not gate_ok:
        print("bench_scaling: PARITY GATE FAILED", file=sys.stderr)
        sys.exit(1)

    path = args.json or ("" if args.smoke else "BENCH_SCALING.json")
    if path:
        out = {}
        if os.path.exists(path):
            # preserve the committed dp weak-scaling block — this bench
            # only owns the mp rungs
            with open(path, encoding="utf-8") as f:
                out = json.load(f)
        out["mp"] = block
        with open(path, "w", encoding="utf-8") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
        print(f"bench_scaling: wrote {path}")


if __name__ == "__main__":
    main()
