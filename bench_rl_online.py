"""Online RL from served traffic: the serving-as-actor closed-loop bench.

The decoupled ladder (BENCH_RL_ASYNC.json) measured actor/learner overlap
on disjoint submeshes; this bench closes the remaining loop from README
"Online RL from served traffic": a live :class:`CaptionService` serves a
seeded, replayable traffic trace while :class:`OnlineSCSTTrainer` consumes
the served (1+K)-lane rollouts at zero extra dispatch, applies REINFORCE
updates, and hot-swaps the new params back into the service drain-free
(version-pinned in-flight lanes). Two rungs over the SAME trace:

- ``frozen`` — the service serves the whole trace under the initial
  params; the serving throughput baseline and the reward floor;
- ``online`` — the feedback loop live: captures -> ring -> staleness-gated
  updates -> version-stamped publishes, all on the serving thread.

The acceptance evidence is functional, not throughput:

- **swap parity** (THE pin): every completed request — including every
  request in flight across a swap — replayed through a FRESH service under
  its admission-pinned param version must match token- AND
  logprob-bit-exactly, and re-decoded offline through ``fused_decode``
  under that version must match token-bit-exactly with logprobs within a
  few f32 ulps, with >= 2 versions genuinely straddled. (The paged stride
  program and the dense fused program are different XLA programs; on
  optimizer-produced param trees their logprobs can differ by one ulp in
  the last reduction even though both are individually deterministic —
  ``tests/test_serving.py`` pins full bit-exactness of the engine against
  itself, and the replay leg here pins it for every published version.);
- **determinism**: a second online run over the same trace and swap
  schedule ends with bit-identical learner params;
- **reward trend**: per-update reward_mean over the seeded trace, next to
  the frozen rung's reward floor, plus the staleness drop ledger.

Writes ``BENCH_RL_ONLINE.json``. Usage:
    python bench_rl_online.py [--smoke] [--requests N] [--json PATH]
  --smoke   tiny dims, swap-parity fatal, no JSON unless --json given —
            the CPU functional gate scripts/lint.sh runs (JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# mirror the other RL benches: fake CPU devices are harmless here and keep
# the XLA_FLAGS preamble uniform for anyone composing bench scripts
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np


class _TokenReward:
    """Rigged consensus scorer: +1 per occurrence of a target token."""

    def __init__(self, target: int):
        self.target = target

    def __call__(self, video_ids, rows):
        rows = np.asarray(rows)
        return (rows == self.target).sum(axis=1).astype(np.float32)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny dims; the CPU swap-parity gate")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--json", default="", metavar="PATH",
                    help="output path (default BENCH_RL_ONLINE.json; smoke "
                         "writes no file unless given)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from cst_captioning_tpu.config.config import (
        EOS_ID,
        ModelConfig,
        RLConfig,
        TrainConfig,
    )
    from cst_captioning_tpu.decoding.fused import fused_decode
    from cst_captioning_tpu.models import CaptionModel
    from cst_captioning_tpu.rl import OnlineSCSTTrainer
    from cst_captioning_tpu.serving import CaptionService, ClipRequest
    from cst_captioning_tpu.serving.traffic import (
        TrafficSpec,
        make_trace,
        synth_request_features,
    )
    from cst_captioning_tpu.train import create_train_state, make_optimizer

    on_tpu = jax.default_backend() == "tpu"
    if args.smoke:
        capacity, n_req = 4, args.requests or 12
        vocab_n, frames, max_len = 97, 6, 12
        modal = (("resnet", 16),)
        d_embed = d_hidden = 16
        d_att = 8
        K = 2
        batch_size, depth, bound, swap_every = 2, 1, 4, 1
    else:
        capacity = 32 if on_tpu else 8
        n_req = args.requests or (256 if on_tpu else 32)
        vocab_n = 9000 if on_tpu else 1000
        frames = 20 if on_tpu else 8
        max_len = 30 if on_tpu else 16
        modal = (("resnet", 2048), ("c3d", 500)) if on_tpu else \
            (("resnet", 128),)
        d_embed = d_hidden = 512 if on_tpu else 64
        d_att = 256 if on_tpu else 32
        K = 5 if on_tpu else 2
        batch_size, depth, bound, swap_every = 4, 2, 4, 1

    kind = jax.devices()[0].device_kind
    backend = jax.default_backend()
    print(f"bench_rl_online: backend={backend} capacity={capacity} K={K} "
          f"T={max_len} requests={n_req}", file=sys.stderr)

    mcfg = ModelConfig(
        vocab_size=vocab_n, modalities=modal, d_embed=d_embed,
        d_hidden=d_hidden, d_att=d_att, encoder="temporal_attention",
        dropout=0.0, max_len=max_len, max_frames=frames, dtype="float32",
    )
    model = CaptionModel(mcfg)
    rng = np.random.default_rng(0)
    feats0 = {
        name: jnp.asarray(rng.normal(size=(2, frames, dim)), jnp.float32)
        for name, dim in modal
    }
    masks0 = {k: jnp.ones((2, frames), jnp.float32) for k in feats0}
    labels0 = jnp.asarray(
        rng.integers(4, vocab_n, size=(2, max_len)), jnp.int32
    )
    tx = make_optimizer(TrainConfig(lr=5e-2, grad_clip=5.0), 10)
    state0 = create_train_state(model, tx, (feats0, masks0, labels0), seed=1)
    # EOS-bias the initial params so caption lengths vary: lanes free at
    # different strides, which is what makes swaps straddle live traffic
    p = jax.tree.map(lambda x: x, state0.params)
    bias = p["params"]["cell"]["out_proj"]["bias"]
    p["params"]["cell"]["out_proj"]["bias"] = bias.at[EOS_ID].add(2.0)
    state0 = state0.replace(params=p)

    rcfg = RLConfig(
        enabled=True, num_rollouts=K, baseline="greedy", lr=5e-2,
        rollout_depth=depth, staleness_bound=bound,
        online_batch_size=batch_size, swap_every=swap_every,
    )

    # the seeded, replayable trace every rung serves (arrival order only —
    # realtime pacing would couple the swap schedule to the wall clock and
    # break the two-run bit-identity pin)
    spec = TrafficSpec(
        kind="poisson", rate_rps=50.0, num_requests=n_req, seed=7,
        frame_choices=(max(frames // 4, 1), frames),
    )
    trace = make_trace(spec)

    def requests_for() -> list[ClipRequest]:
        out = []
        for item in trace.items:
            f, m = synth_request_features(item, modal)
            out.append(ClipRequest(
                req_id=item.req_id, feats=f, masks=m, seed=item.seed,
                arrival_s=item.arrival_s,
            ))
        return out

    def service() -> CaptionService:
        return CaptionService(
            model, state0.params, capacity=capacity, num_rollouts=K,
            stride=4, frame_bucket=max(frames // 4, 1),
        )

    # warm the encode buckets + stride program off the clock
    warm = service()
    warm.serve(requests_for()[:3])

    results: dict[str, dict] = {}

    # -- frozen rung: serving baseline, no learner ---------------------------
    # rigged scorer counts EOS: present at every vocab size (a vocab-relative
    # target token can simply never be sampled at flagship dims, flattening
    # the trend to 0), and genuinely learnable — the EOS-biased init gives
    # the learner a real gradient toward shorter captions
    reward_fn = _TokenReward(EOS_ID)
    svc = service()
    t0 = time.perf_counter()
    frozen_rep = svc.serve(requests_for())
    sec = time.perf_counter() - t0
    frozen_rewards = [
        float(reward_fn([rid], res.tokens[:1])[0])
        for rid, res in frozen_rep.results.items()
    ]
    results["frozen"] = {
        "requests_per_s": round(n_req / sec, 2),
        "completed": frozen_rep.completed,
        "param_version": svc.param_version,
        "reward_mean": round(float(np.mean(frozen_rewards)), 4),
    }

    # -- online rung: the closed loop ----------------------------------------
    def run_online():
        trainer = OnlineSCSTTrainer(
            model, _TokenReward(EOS_ID), rcfg, state0,
        )
        # retain every published version's tree for the offline oracle
        version_params = {0: state0.params}
        base_event = trainer.on_event

        def on_event(event, **fields):
            if event == "rl_online_step":
                version_params[fields["param_version"]] = trainer.state.params
            base_event(event, **fields)

        trainer.on_event = on_event
        svc = service()
        trainer.attach(svc)
        t0 = time.perf_counter()
        rep = svc.serve(requests_for())
        trainer.flush()
        sec = time.perf_counter() - t0
        return trainer, svc, rep, version_params, sec

    trainer, svc_o, online_rep, version_params, sec = run_online()
    results["online"] = {
        "requests_per_s": round(n_req / sec, 2),
        "completed": online_rep.completed,
        "learner_updates": trainer.version,
        "param_swaps": len(svc_o._swap_history),
        "final_param_version": svc_o.param_version,
        "dropped_stale": trainer.last_dropped,
        "staleness_histogram": {
            str(k): v for k, v in sorted(trainer.last_staleness.items())
        },
        "reward_trend": [
            round(m["reward_mean"], 4) for m in trainer.history
        ],
        "overhead_vs_frozen": round(
            sec / (n_req / results["frozen"]["requests_per_s"]), 3
        ),
    }
    for name, r in results.items():
        print(f"bench_rl_online: {name} {r['requests_per_s']} req/s  "
              f"reward {r.get('reward_mean', r.get('reward_trend'))}",
              file=sys.stderr)

    # -- swap parity: every request vs fused_decode under its pinned version
    def offline(params, req):
        pad = frames - req.num_frames
        f1 = {
            k: jnp.asarray(np.pad(
                np.asarray(v, np.float32), ((0, pad), (0, 0))
            )[None]) for k, v in req.feats.items()
        }
        m1 = {
            k: jnp.asarray(np.pad(
                np.asarray(v, np.float32), ((0, pad),)
            )[None]) for k, v in req.masks.items()
        }
        g, gl, s, sl = jax.tree.map(np.asarray, fused_decode(
            model, params, f1, m1, jax.random.key(req.seed), num_rollouts=K,
        ))
        return (np.concatenate([g, s[:, 0]], axis=0),
                np.concatenate([gl, sl[:, 0]], axis=0))

    tokens_exact = replay_exact = True
    lp_max_diff = lp_max_ulp = 0.0
    versions_seen = set()
    check = requests_for() if args.smoke else requests_for()[:16]
    by_version: dict[int, list] = {}
    for req in check:
        res = online_rep.results[req.req_id]
        versions_seen.add(res.param_version)
        by_version.setdefault(res.param_version, []).append(req)
        tok, lp = offline(version_params[res.param_version], req)
        tokens_exact &= bool(np.array_equal(res.tokens, tok))
        diff = np.abs(res.logprobs - lp)
        lp_max_diff = max(lp_max_diff, float(np.max(diff)))
        spacing = np.spacing(np.maximum(
            np.abs(res.logprobs), np.abs(lp)
        ).astype(np.float32))
        lp_max_ulp = max(lp_max_ulp, float(np.max(diff / spacing)))
    # the bit-exact leg: replay each straddled version's requests through a
    # FRESH service under the pinned tree — same program as the live run, so
    # tokens AND logprobs must reproduce exactly (per-request parity makes
    # the replay independent of the original co-scheduled traffic)
    for version, reqs in sorted(by_version.items()):
        svc_r = CaptionService(
            model, version_params[version], capacity=capacity,
            num_rollouts=K, stride=4, frame_bucket=max(frames // 4, 1),
        )
        rep_r = svc_r.serve(reqs)
        for req in reqs:
            res, res_r = online_rep.results[req.req_id], rep_r.results[req.req_id]
            replay_exact &= bool(np.array_equal(res.tokens, res_r.tokens))
            replay_exact &= bool(np.array_equal(res.logprobs, res_r.logprobs))

    # -- determinism: a second run over the same trace + swap schedule -------
    trainer2, _, _, _, _ = run_online()
    runs_identical = trainer.version == trainer2.version and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(
            jax.tree.leaves(trainer.state.params),
            jax.tree.leaves(trainer2.state.params),
        )
    )

    parity = {
        "swap_parity_tokens_bit_exact": bool(tokens_exact),
        "swap_parity_replay_bit_exact": bool(replay_exact),
        # paged-stride vs dense-fused are different XLA programs: on
        # optimizer-produced trees logprobs may differ in the last ulps
        "swap_parity_logprobs_ulp_bounded_vs_fused": lp_max_ulp <= 4.0,
        "swap_parity_logprobs_max_ulp_vs_fused": lp_max_ulp,
        "swap_parity_logprobs_max_abs_diff_vs_fused": lp_max_diff,
        "swap_straddled_live_traffic": len(versions_seen) >= 2,
        "two_runs_bit_identical_params": bool(runs_identical),
        "versions_straddled": len(versions_seen),
        "requests_checked": len(check),
    }
    ok = all(v for v in parity.values() if isinstance(v, bool))
    if args.smoke and not ok:
        sys.exit(f"bench_rl_online: SMOKE FAILURE — the hot-swap loop broke "
                 f"a pin: {parity}")

    out = {
        "metric": "online_rl_requests_per_s",
        "capacity": capacity,
        "rollouts": K,
        "max_len": max_len,
        "requests": n_req,
        "device_kind": kind,
        "backend": backend,
        "smoke": bool(args.smoke),
        "online_batch_size": batch_size,
        "rollout_depth": depth,
        "staleness_bound": bound,
        "swap_every": swap_every,
        "trace_seed": spec.seed,
        "rungs": results,
        "parity": parity,
        "parity_ok": bool(ok),
        "note": (
            None if backend == "tpu" else
            "non-TPU run at mid dims: the swap-parity block, two-run "
            "bit-identity, staleness ledger, and reward trend are "
            "platform-independent (the acceptance content); requests/s "
            "measures CPU decode compute. Regenerate on TPU at flagship "
            "dims for throughput acceptance."
        ),
    }
    print(json.dumps(out))
    path = args.json or ("" if args.smoke else "BENCH_RL_ONLINE.json")
    if path:
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        print(f"bench_rl_online: wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
