"""Checkpointing: durable msgpack saves, best/latest/step_* policy, handoff.

Capability parity with the reference's ``torch.save`` of model/optimizer/
``infos`` + ``--start_from`` resume (SURVEY.md §3.5, §5), hardened by the
resilience layer: fsync'd atomic writes with a checksum manifest verified on
load, a demoted ``<name>.prev`` generation as crash fallback, mid-epoch
``step_*`` checkpoints with keep-last-K rotation, and ``resume="auto"``
picking the newest checkpoint that passes verification (corrupt candidates
are logged as ``ckpt_corrupt`` events, never silently skipped). The RL phase
loads params-only from the best XE checkpoint with a fresh optimizer.
"""

from cst_captioning_tpu.ckpt.checkpoint import (
    CheckpointManager,
    load_params,
    load_state,
    save_state,
)
from cst_captioning_tpu.resilience.durable import CorruptCheckpointError

__all__ = [
    "CheckpointManager",
    "CorruptCheckpointError",
    "save_state",
    "load_state",
    "load_params",
]
