"""Checkpointing: atomic msgpack saves, best/latest policy, XE->RL handoff.

Capability parity with the reference's ``torch.save`` of model/optimizer/
``infos`` + ``--start_from`` resume (SURVEY.md §3.5, §5): atomic writes (tmp +
rename) so a crash never corrupts the latest checkpoint, ``resume="auto"``
picks the newest valid one, and the RL phase loads params-only from the best
XE checkpoint with a fresh optimizer.
"""

from cst_captioning_tpu.ckpt.checkpoint import (
    CheckpointManager,
    load_params,
    load_state,
    save_state,
)

__all__ = ["CheckpointManager", "save_state", "load_state", "load_params"]
