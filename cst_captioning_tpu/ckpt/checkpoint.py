"""Durable TrainState checkpoints via flax.serialization msgpack.

Layout per checkpoint name (``best`` / ``latest`` / ``step_00001200``):

    <dir>/<name>/state.msgpack   — params + opt state + step + rng
    <dir>/<name>/infos.json      — epoch, phase, batch_index, config snapshot
    <dir>/<name>/manifest.json   — sha256 + size per file, verified on load

msgpack via ``flax.serialization`` (not pickle) keeps checkpoints
language-neutral and safe to load. Durability (resilience/durable.py):
every file is fsync'd, the tmp dir is fsync'd, the swap is ``os.replace``,
and the parent dir is fsync'd after — a host crash at ANY instant leaves
either the old or the new checkpoint fully intact. An existing checkpoint is
demoted to ``<name>.prev`` (not deleted) before the swap, so even the
replace window and a post-"success" torn write have a fallback generation.
"""

from __future__ import annotations

import errno
import json
import os
import re
import shutil
from typing import Any, Callable, Mapping

import jax
from flax import serialization

from cst_captioning_tpu import obs
from cst_captioning_tpu.resilience import chaos
from cst_captioning_tpu.resilience.durable import (
    CorruptCheckpointError,
    MANIFEST_FILE,
    fsync_dir,
    verify_manifest,
    write_bytes_durable,
    write_manifest,
)
from cst_captioning_tpu.resilience.retry import RetryPolicy, retry_call
from cst_captioning_tpu.train.state import TrainState

STATE_FILE = "state.msgpack"
INFOS_FILE = "infos.json"

_STEP_NAME_RE = re.compile(r"^step_(\d+)$")


def _is_prng_key(x) -> bool:
    return hasattr(x, "dtype") and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)


def _keys_to_data(tree):
    """Typed PRNG keys -> raw uint32 key data (msgpack can't hold key dtypes)."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_prng_key(x) else x, tree
    )


def _data_to_keys(loaded, template):
    """Re-wrap raw key data as typed keys wherever the template has them."""
    return jax.tree.map(
        lambda t, x: jax.random.wrap_key_data(x) if _is_prng_key(t) else x,
        template,
        loaded,
    )


def save_state(ckpt_dir: str, name: str, state: TrainState,
               infos: dict[str, Any] | None = None,
               extra_files: Mapping[str, bytes] | None = None) -> str:
    """Durably write state+infos under ``ckpt_dir/name``; returns the path.

    ``extra_files`` (name -> bytes) ride along in the same atomic swap and
    are covered by the manifest — the drain-aware RL seam (``seam.npz``)
    uses this so the seam tokens can never outlive or predate the state
    they belong to.

    CONTRACT: one writer per ``ckpt_dir`` at a time — crash-atomic (a kill
    mid-save leaves the previous generation intact: only the stale ``.tmp``
    is lost, reclaimed by the next save; a kill inside the swap leaves the
    demoted ``<name>.prev``), not concurrency-atomic. Multi-host runs
    satisfy this via the Trainer's process-0 checkpoint gate."""
    final = os.path.join(ckpt_dir, name)
    tmp = final + ".tmp"
    chaos.visit("ckpt.save")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp, exist_ok=True)
    # fully materialize on host before serializing
    host_state = _keys_to_data(jax.device_get(state))
    state_bytes = serialization.to_bytes(host_state)
    infos_bytes = json.dumps(infos or {}, indent=2, default=float).encode()
    write_bytes_durable(os.path.join(tmp, STATE_FILE), state_bytes)
    chaos.visit("ckpt.state_written")
    write_bytes_durable(os.path.join(tmp, INFOS_FILE), infos_bytes)
    blobs = {STATE_FILE: state_bytes, INFOS_FILE: infos_bytes}
    for extra_name, blob in (extra_files or {}).items():
        if extra_name in blobs or os.sep in extra_name:
            raise ValueError(f"bad extra checkpoint file name {extra_name!r}")
        write_bytes_durable(os.path.join(tmp, extra_name), blob)
        blobs[extra_name] = blob
    write_manifest(tmp, blobs)
    fsync_dir(tmp)
    chaos.visit("ckpt.pre_replace")
    if os.path.exists(final):
        # demote, don't delete: the previous generation survives both a
        # crash inside this swap and a latent torn write in the new files
        prev = final + ".prev"
        if os.path.exists(prev):
            shutil.rmtree(prev)
        os.replace(final, prev)
    os.replace(tmp, final)
    fsync_dir(ckpt_dir)
    return final


def load_state(ckpt_dir: str, name: str, template: TrainState) -> tuple[TrainState, dict]:
    """Restore a full TrainState (shape/dtype from ``template``) + infos.

    Verifies the manifest checksums first (when present — legacy checkpoints
    without one load unverified); raises
    :class:`~cst_captioning_tpu.resilience.durable.CorruptCheckpointError`
    on any mismatch instead of deserializing a torn file."""
    path = os.path.join(ckpt_dir, name)
    verify_manifest(path)
    data_template = _keys_to_data(jax.device_get(template))
    with open(os.path.join(path, STATE_FILE), "rb") as f:
        loaded = serialization.from_bytes(data_template, f.read())
    state = _data_to_keys(loaded, template)
    infos = {}
    infos_path = os.path.join(path, INFOS_FILE)
    if os.path.exists(infos_path):
        with open(infos_path) as f:
            infos = json.load(f)
    return state, infos


def load_params(ckpt_dir: str, name: str, params_template) -> Any:
    """Params-only restore — the XE -> RL handoff (fresh optimizer)."""
    path = os.path.join(ckpt_dir, name)
    verify_manifest(path)
    with open(os.path.join(path, STATE_FILE), "rb") as f:
        blob = f.read()
    state_dict = serialization.msgpack_restore(blob)
    return serialization.from_state_dict(params_template, state_dict["params"])


def _read_infos(path: str) -> dict:
    """Best-effort infos.json read for candidate ordering (not for load)."""
    try:
        with open(os.path.join(path, INFOS_FILE), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


class CheckpointManager:
    """best-by-metric + latest policy, mid-epoch ``step_*`` checkpoints with
    keep-last-K rotation, and checksum-verified auto-resume (SURVEY.md §5)."""

    def __init__(self, ckpt_dir: str, metric: str = "CIDEr-D", mode: str = "max",
                 keep: int = 3, log: Callable[..., None] | None = None,
                 retry: RetryPolicy | None = None):
        self.ckpt_dir = ckpt_dir
        self.metric = metric
        self.mode = mode
        self.keep = keep
        self.log = log or (lambda event, **fields: None)
        self.retry = retry or RetryPolicy()
        self.best_value: float | None = None
        os.makedirs(ckpt_dir, exist_ok=True)
        # recover best_value from an existing best checkpoint (resume case)
        best_infos = os.path.join(ckpt_dir, "best", INFOS_FILE)
        if os.path.exists(best_infos):
            with open(best_infos) as f:
                self.best_value = json.load(f).get("best_value")

    def _improved(self, value: float) -> bool:
        if self.best_value is None:
            return True
        return value > self.best_value if self.mode == "max" else value < self.best_value

    def _save(self, name: str, state: TrainState, infos: dict,
              extra_files: Mapping[str, bytes] | None = None) -> str:
        """One durable save with jittered-backoff retries on transient I/O.

        ENOSPC gets a reclaim step before each retry: the oldest ``step_*``
        generation (then any demoted ``*.prev``) is deleted — a full disk
        costs the oldest history, never the run — with a structured
        ``ckpt_enospc`` event + ``resilience.ckpt_enospc`` counter."""

        def attempt():
            try:
                return save_state(
                    self.ckpt_dir, name, state, infos,
                    extra_files=extra_files,
                )
            except OSError as e:
                if getattr(e, "errno", None) == errno.ENOSPC:
                    freed = self._reclaim_space(exclude=name)
                    obs.counter("resilience.ckpt_enospc").inc()
                    self.log(
                        "ckpt_enospc", name=name, freed=freed, detail=str(e),
                    )
                raise

        # the span covers retries + backoff sleeps: its dur IS the stall a
        # save inflicts on the step loop (the "ckpt" phase of the report)
        with obs.span("ckpt.save", ckpt=name):
            return retry_call(
                attempt,
                policy=self.retry,
                on_retry=lambda info: self.log("ckpt_retry", name=name, **info),
            )

    def _reclaim_space(self, exclude: str = "") -> list[str]:
        """Free checkpoint-dir space for an ENOSPC retry: oldest ``step_*``
        generation first, demoted ``*.prev`` generations next. Never touches
        ``best``/``latest`` or the checkpoint being written."""
        victims: list[str] = []
        for _, step_name in self.step_checkpoints():
            if step_name != exclude:
                victims.append(step_name)
                break
        if not victims:
            victims = sorted(
                e for e in os.listdir(self.ckpt_dir)
                if e.endswith(".prev") and e != f"{exclude}.prev"
                and os.path.isdir(os.path.join(self.ckpt_dir, e))
            )[:1]
        for victim in victims:
            shutil.rmtree(
                os.path.join(self.ckpt_dir, victim), ignore_errors=True
            )
        return victims

    def save(self, state: TrainState, value: float | None = None,
             infos: dict | None = None) -> bool:
        """Save 'latest' always; promote to 'best' when the metric improves.

        Returns True when a new best was recorded.
        """
        infos = dict(infos or {})
        improved = value is not None and self._improved(value)
        if improved:
            self.best_value = float(value)
        # both checkpoints carry the post-update best so 'latest' metadata
        # never lags 'best' (ADVICE r1)
        infos["best_value"] = self.best_value
        self._save("latest", state, infos)
        if improved:
            self._save("best", state, infos)
        return improved

    def save_step(self, state: TrainState, step: int,
                  infos: dict | None = None,
                  extra_files: Mapping[str, bytes] | None = None) -> str:
        """Mid-epoch ``step_<n>`` checkpoint + keep-last-``keep`` rotation."""
        infos = dict(infos or {})
        infos.setdefault("global_step", int(step))
        infos["best_value"] = self.best_value
        path = self._save(
            f"step_{int(step):08d}", state, infos, extra_files=extra_files
        )
        if self.keep > 0:
            for _, name in self.step_checkpoints()[:-self.keep]:
                shutil.rmtree(
                    os.path.join(self.ckpt_dir, name), ignore_errors=True
                )
        return path

    def step_checkpoints(self) -> list[tuple[int, str]]:
        """Existing ``step_*`` checkpoint (step, dirname) pairs, ascending."""
        out = []
        for entry in os.listdir(self.ckpt_dir):
            m = _STEP_NAME_RE.match(entry)
            if m and os.path.isdir(os.path.join(self.ckpt_dir, entry)):
                out.append((int(m.group(1)), entry))
        return sorted(out)

    def _candidates(self) -> list[str]:
        """Restore candidates, newest first.

        Ordered by the recorded ``global_step`` (epoch-end and mid-epoch
        saves share one clock), tie-broken by role: an in-flight ``latest``
        beats a ``step_*`` beats ``best`` beats any demoted ``*.prev``
        generation. Legacy checkpoints without ``global_step`` sort last in
        role order — exactly the old latest-then-best behavior."""
        rank = {"latest": 3, "best": 1}
        cands = []
        for entry in sorted(os.listdir(self.ckpt_dir)):
            path = os.path.join(self.ckpt_dir, entry)
            if entry.endswith(".tmp") or not os.path.isdir(path):
                continue
            if not os.path.exists(os.path.join(path, STATE_FILE)):
                continue
            base = entry[:-5] if entry.endswith(".prev") else entry
            role = 0 if entry.endswith(".prev") else (
                rank.get(base, 2 if _STEP_NAME_RE.match(base) else 0)
            )
            step = _read_infos(path).get("global_step")
            cands.append((-1 if step is None else int(step), role, entry))
        return [e for _, _, e in sorted(cands, reverse=True)]

    def restore_latest(self, template: TrainState,
                       prefer: str | None = None) -> tuple[TrainState, dict] | None:
        """Auto-resume: newest checkpoint that passes verification.

        A corrupt/partial candidate is never silently skipped: each failure
        is logged as a structured ``ckpt_corrupt`` event (candidate name,
        error class, detail) AND counts on ``resilience.ckpt_corrupt``
        before falling back to the next generation.

        ``prefer`` names a candidate to try FIRST regardless of rank: the
        elastic drain paths pass the seam checkpoint they just wrote, whose
        phase-local step ordinal may sort below an older epoch-end save —
        the ranked order remains the fallback if it fails verification."""
        with obs.span("ckpt.restore"):
            cands = self._candidates()
            if prefer is not None and prefer in cands:
                cands = [prefer] + [c for c in cands if c != prefer]
            for name in cands:
                try:
                    state, infos = load_state(self.ckpt_dir, name, template)
                    # which candidate won matters to the caller (sidecar
                    # files like the RL seam live next to the state)
                    infos.setdefault("ckpt_name", name)
                    return state, infos
                except Exception as e:
                    obs.counter("resilience.ckpt_corrupt").inc()
                    self.log(
                        "ckpt_corrupt",
                        name=name,
                        error=type(e).__name__,
                        detail=str(e),
                    )
                    continue  # verified-corrupt (and logged): try the next
            return None
