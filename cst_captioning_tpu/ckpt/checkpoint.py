"""Atomic TrainState checkpoints via flax.serialization msgpack.

Layout per checkpoint name (e.g. ``best`` / ``latest`` / ``step_1200``):

    <dir>/<name>/state.msgpack   — params + opt state + step + rng
    <dir>/<name>/infos.json      — epoch, metric history, config snapshot

msgpack via ``flax.serialization`` (not pickle) keeps checkpoints
language-neutral and safe to load; writes go to a tmp dir + atomic rename.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any

import jax
from flax import serialization

from cst_captioning_tpu.train.state import TrainState

STATE_FILE = "state.msgpack"
INFOS_FILE = "infos.json"


def _is_prng_key(x) -> bool:
    return hasattr(x, "dtype") and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)


def _keys_to_data(tree):
    """Typed PRNG keys -> raw uint32 key data (msgpack can't hold key dtypes)."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_prng_key(x) else x, tree
    )


def _data_to_keys(loaded, template):
    """Re-wrap raw key data as typed keys wherever the template has them."""
    return jax.tree.map(
        lambda t, x: jax.random.wrap_key_data(x) if _is_prng_key(t) else x,
        template,
        loaded,
    )


def save_state(ckpt_dir: str, name: str, state: TrainState,
               infos: dict[str, Any] | None = None) -> str:
    """Atomically write state+infos under ``ckpt_dir/name``; returns the path.

    CONTRACT: one writer per ``ckpt_dir`` at a time — crash-atomic (a kill
    mid-save leaves only the stale ``.tmp``, reclaimed by the next save),
    not concurrency-atomic (directory swap is rmtree+rename). Multi-host
    runs satisfy this via the Trainer's process-0 checkpoint gate."""
    final = os.path.join(ckpt_dir, name)
    tmp = final + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp, exist_ok=True)
    # fully materialize on host before serializing
    host_state = _keys_to_data(jax.device_get(state))
    with open(os.path.join(tmp, STATE_FILE), "wb") as f:
        f.write(serialization.to_bytes(host_state))
    with open(os.path.join(tmp, INFOS_FILE), "w") as f:
        json.dump(infos or {}, f, indent=2, default=float)
    if os.path.exists(final):
        shutil.rmtree(final)
    os.replace(tmp, final)
    return final


def load_state(ckpt_dir: str, name: str, template: TrainState) -> tuple[TrainState, dict]:
    """Restore a full TrainState (shape/dtype from ``template``) + infos."""
    path = os.path.join(ckpt_dir, name)
    data_template = _keys_to_data(jax.device_get(template))
    with open(os.path.join(path, STATE_FILE), "rb") as f:
        loaded = serialization.from_bytes(data_template, f.read())
    state = _data_to_keys(loaded, template)
    infos = {}
    infos_path = os.path.join(path, INFOS_FILE)
    if os.path.exists(infos_path):
        with open(infos_path) as f:
            infos = json.load(f)
    return state, infos


def load_params(ckpt_dir: str, name: str, params_template) -> Any:
    """Params-only restore — the XE -> RL handoff (fresh optimizer)."""
    path = os.path.join(ckpt_dir, name, STATE_FILE)
    with open(path, "rb") as f:
        blob = f.read()
    state_dict = serialization.msgpack_restore(blob)
    return serialization.from_state_dict(params_template, state_dict["params"])


class CheckpointManager:
    """best-by-metric + latest policy with auto-resume (SURVEY.md §5)."""

    def __init__(self, ckpt_dir: str, metric: str = "CIDEr-D", mode: str = "max"):
        self.ckpt_dir = ckpt_dir
        self.metric = metric
        self.mode = mode
        self.best_value: float | None = None
        os.makedirs(ckpt_dir, exist_ok=True)
        # recover best_value from an existing best checkpoint (resume case)
        best_infos = os.path.join(ckpt_dir, "best", INFOS_FILE)
        if os.path.exists(best_infos):
            with open(best_infos) as f:
                self.best_value = json.load(f).get("best_value")

    def _improved(self, value: float) -> bool:
        if self.best_value is None:
            return True
        return value > self.best_value if self.mode == "max" else value < self.best_value

    def save(self, state: TrainState, value: float | None = None,
             infos: dict | None = None) -> bool:
        """Save 'latest' always; promote to 'best' when the metric improves.

        Returns True when a new best was recorded.
        """
        infos = dict(infos or {})
        improved = value is not None and self._improved(value)
        if improved:
            self.best_value = float(value)
        # both checkpoints carry the post-update best so 'latest' metadata
        # never lags 'best' (ADVICE r1)
        infos["best_value"] = self.best_value
        save_state(self.ckpt_dir, "latest", state, infos)
        if improved:
            save_state(self.ckpt_dir, "best", state, infos)
        return improved

    def restore_latest(self, template: TrainState) -> tuple[TrainState, dict] | None:
        """Auto-resume: newest valid checkpoint (latest, falling back to best)."""
        for name in ("latest", "best"):
            path = os.path.join(self.ckpt_dir, name, STATE_FILE)
            if os.path.exists(path):
                try:
                    return load_state(self.ckpt_dir, name, template)
                except Exception:
                    continue  # corrupt/partial: try the next candidate
        return None
