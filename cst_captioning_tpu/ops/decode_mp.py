"""Sharded-vocab decode: the stride/beam kernels on an 'mp' model axis.

Flagship-XL vocabularies push the output projection ``[H, V]`` and the
embedding table ``[V, E]`` past one chip's weight budget. This module runs
the EXISTING decode kernels (ops/decode_pallas.py) unchanged on each model-
parallel shard over its vocab slice — Megatron-style column parallelism —
and recovers the replicated kernels' exact token stream with three small
cross-shard merges:

- **logsumexp** (and with it every logprob): online ``(m, s)`` merge —
  ``m = pmax(m_local)``, ``s = psum(s_local * exp(m_local - m))`` — tokens
  come out bit-exact, logprobs within a few f32 ulps of the one-shot
  reduction (reassociated sum);
- **argmax selection** (greedy + Gumbel lanes): each shard reports its
  local first-max (value, GLOBAL index); the all-gathered maxima resolve
  ties to the lowest shard, which — because the slices are disjoint and
  order-consistent — IS the global first-index argmax the replicated
  ``jnp.argmax`` computes. Bit-exact, not approximately;
- **top-W candidates** (beam): each shard top-Ws its ``[B, W * V_s]``
  slice, rebases local flat ids ``w * V_s + v`` into the replicated
  kernel's ``w * V + off + v`` namespace, and an explicit W-pass merge
  over the all-gathered ``mp * W`` candidates keeps ``lax.top_k``'s
  tie-to-lower-flat-id order exactly.

The next-token embedding under a row-sharded table is a masked LOCAL
gather (rows outside the shard contribute zeros) followed by one psum —
exact, since exactly one shard owns each token id. The recurrent cell
weights stay replicated on this path: the decode kernels consume them
whole, and their mp sharding (MP_PARAM_PARTITION_RULES) is a training-
side layout.

Everything here is built to run inside ``shard_map`` over the 'mp' axis of
a ``train.mesh.make_mesh(mp_devices=...)`` mesh; the ``mp_*`` wrappers
construct that program through parallel/compile.py. Parity with the
replicated kernels is pinned in tests/test_mp.py on the 8-device CPU mesh
(interpret mode — the per-shard kernel falls back to its composite there,
exactly like the unsharded path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.ops.decode_pallas import NEG, fused_decode_step
from cst_captioning_tpu.train.mesh import MP_PARAM_PARTITION_RULES, match_rule

# the decode path only shards the vocab dimension; these are the rule
# families (train/mesh.py) that carry it
VOCAB_FAMILIES = ("word_embed", "output_head_kernel", "output_head_bias")


def mp_cell_specs(cell_params, axis: str = "mp"):
    """PartitionSpecs for the DecoderCell subtree on the decode path:
    vocab-dimension families shard over ``axis``, everything else (the
    recurrent weights the kernels consume whole) replicates."""

    def spec(path, _leaf):
        name = "params/cell/" + "/".join(
            str(getattr(k, "key", k)) for k in path
        )
        family, s = match_rule(MP_PARAM_PARTITION_RULES, name)
        if family not in VOCAB_FAMILIES:
            return P()
        if axis == "mp":
            return s
        return P(*(axis if a == "mp" else a for a in s))

    return jax.tree_util.tree_map_with_path(spec, cell_params)


def _set_owned(x, global_id: int, off, value):
    """``x.at[..., global_id].set(value)`` when this shard's slice
    ``[off, off + V_s)`` owns the id; identity elsewhere."""
    vs = x.shape[-1]
    li = global_id - off
    owned = (li >= 0) & (li < vs)
    lic = jnp.clip(li, 0, vs - 1)
    return jnp.where(owned, x.at[..., lic].set(value), x)


def _psum_embed(table, token, off, axis: str):
    """Masked local gather + one psum: exact row-sharded embedding lookup
    (exactly one shard owns each id, the rest add zeros)."""
    vs = table.shape[0]
    li = token - off
    owned = (li >= 0) & (li < vs)
    lic = jnp.clip(li, 0, vs - 1)
    rows = table[lic]
    return jax.lax.psum(
        jnp.where(owned[..., None], rows, jnp.zeros_like(rows)), axis
    )


def _merge_argmax(vals, off, axis: str):
    """Global first-index argmax over vocab-sharded ``vals [..., V_s]``.

    Ties across shards resolve to the lowest shard (jnp.argmax over the
    gathered shard axis), which is the lowest global index because the
    slices are ordered — matching the replicated ``jnp.argmax``."""
    lv = jnp.max(vals, axis=-1)
    li = jnp.argmax(vals, axis=-1).astype(jnp.int32) + off
    avs = jax.lax.all_gather(lv, axis)          # [mp, ...]
    ais = jax.lax.all_gather(li, axis)
    sel = jnp.argmax(avs, axis=0)
    return jnp.take_along_axis(ais, sel[None], axis=0)[0]


def _merge_lse(logits, axis: str):
    """Online (m, s) logsumexp across vocab shards: tokens downstream stay
    bit-exact; the value itself sits within a few f32 ulps of the one-shot
    ``jax.nn.logsumexp`` (the cross-shard sum reassociates)."""
    m_l = jnp.max(logits, axis=-1)
    m = jax.lax.pmax(m_l, axis)
    s = jax.lax.psum(
        jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), axis
    )
    return m + jnp.log(s)


def _psum_select(logits, idx, off, axis: str):
    """The selected GLOBAL id's logit, summed from its owning shard."""
    vs = logits.shape[-1]
    li = idx - off
    owned = (li >= 0) & (li < vs)
    lic = jnp.clip(li, 0, vs - 1)
    val = jnp.take_along_axis(logits, lic[..., None], axis=-1)[..., 0]
    return jax.lax.psum(jnp.where(owned, val, 0.0), axis)


def _validate(cell_params, mesh, axis: str):
    if axis not in mesh.axis_names:
        raise ValueError(
            f"mesh axes {mesh.axis_names!r} have no {axis!r} axis — build "
            f"one with train.mesh.make_mesh(mp_devices=...)"
        )
    V = cell_params["out_proj"]["kernel"].shape[-1]
    mp = mesh.shape[axis]
    if V % mp:
        raise ValueError(
            f"vocab {V} does not divide over mp={mp} shards"
        )
    return V, mp


# ---- stride ------------------------------------------------------------------


def _stride_body(cell, carry, token, finished, memory, memory_proj,
                 memory_mask, noise, t0, *, steps: int, temperature: float,
                 min_len: int, axis: str):
    """Per-shard stride: S chained kernel steps with the driving loop's
    exact selection semantics (_reference_stride), selection merged across
    the vocab shards."""
    vs = cell["out_proj"]["kernel"].shape[-1]
    off = jax.lax.axis_index(axis) * vs
    table = jnp.asarray(cell["word_embed"]["embedding"])
    toks, lps = [], []
    for s in range(steps):
        emb = _psum_embed(table, token, off, axis)
        carry, logits = fused_decode_step(
            cell, carry, token, memory, memory_proj, memory_mask, emb=emb
        )
        logits = _set_owned(logits, PAD_ID, off, NEG)
        logits = _set_owned(logits, BOS_ID, off, NEG)
        if min_len > 0:
            blocked = _set_owned(logits, EOS_ID, off, NEG)
            logits = jnp.where(t0 + s < min_len, blocked, logits)
        g_nxt = _merge_argmax(logits[0], off, axis)
        s_nxt = _merge_argmax(
            logits[1:] / temperature + noise[s], off, axis
        )
        nxt = jnp.concatenate([g_nxt[None], s_nxt], axis=0).astype(jnp.int32)
        lse = _merge_lse(logits, axis)
        lp = _psum_select(logits, nxt, off, axis) - lse
        nxt = jnp.where(finished, jnp.full_like(nxt, PAD_ID), nxt)
        lp = jnp.where(finished, jnp.zeros_like(lp), lp)
        finished = finished | (nxt == EOS_ID)
        toks.append(nxt)
        lps.append(lp)
        token = nxt
    return carry, jnp.stack(toks), jnp.stack(lps)


def mp_decode_stride(cell_params, carry, token, finished, memory,
                     memory_proj, memory_mask, noise, t0, *, mesh,
                     steps: int, temperature: float = 1.0, min_len: int = 0,
                     axis: str = "mp"):
    """Vocab-sharded :func:`~cst_captioning_tpu.ops.decode_pallas.
    fused_decode_stride`: same signature semantics and the same
    ``(new_carry, tokens [S, G, B], logprobs [S, G, B])`` outputs, with the
    output head and embedding sharded over ``mesh``'s ``axis``.

    Tokens are bit-exact vs the replicated kernel; logprobs sit within a
    few f32 ulps (module docstring). ``noise`` [S, K, B, V] shards on its
    vocab dimension with the logits.
    """
    V, _ = _validate(cell_params, mesh, axis)
    if noise.shape[-1] != V:
        raise ValueError(
            f"noise vocab dim {noise.shape[-1]} != vocab {V}"
        )

    fn = _stride_program(
        mesh, jax.tree_util.tree_structure(cell_params), steps, temperature,
        min_len, axis,
    )
    return fn(cell_params, carry, token, finished, memory, memory_proj,
              memory_mask, noise, jnp.asarray(t0, jnp.int32))


@functools.lru_cache(maxsize=None)
def _stride_program(mesh, cell_treedef, steps: int, temperature: float,
                    min_len: int, axis: str):
    """One shard_map program per (mesh, cell structure, static knobs) —
    cached so repeated strides (the serving loop's shape) reuse the jit
    cache instead of rebuilding a fresh wrapper every call."""
    from cst_captioning_tpu.parallel.compile import CompilePlan, compile_fn

    def body(cell, carry, token, finished, memory, memory_proj, memory_mask,
             noise, t0):
        return _stride_body(
            cell, carry, token, finished, memory, memory_proj, memory_mask,
            noise, t0, steps=steps, temperature=temperature,
            min_len=min_len, axis=axis,
        )

    # mp_cell_specs only reads the tree's paths, so a structure-shaped
    # dummy yields the real specs
    dummy = jax.tree_util.tree_unflatten(
        cell_treedef, [0] * cell_treedef.num_leaves
    )
    return compile_fn(body, CompilePlan(
        mesh=mesh,
        in_specs=(mp_cell_specs(dummy, axis), P(), P(), P(), P(),
                  P(), P(), P(None, None, None, axis), P()),
        out_specs=(P(), P(), P()),
    ))


# ---- beam --------------------------------------------------------------------


def _merge_topw(pool_s, pool_f, W: int):
    """Top-W over per-shard candidate pools with ``lax.top_k``'s exact tie
    order: strictly-greater score wins, equal scores go to the lower GLOBAL
    flat id. Flat ids are globally unique per row, so eliminating the
    selected id by value is exact."""
    fmax = jnp.iinfo(jnp.int32).max
    alive = jnp.ones(pool_s.shape, bool)
    outs, outf = [], []
    for _ in range(W):
        s_eff = jnp.where(alive, pool_s, -jnp.inf)
        m = jnp.max(s_eff, axis=-1)
        is_m = alive & (s_eff == m[:, None])
        fsel = jnp.min(jnp.where(is_m, pool_f, fmax), axis=-1)
        outs.append(m)
        outf.append(fsel)
        alive = alive & (pool_f != fsel[:, None])
    return jnp.stack(outs, axis=1), jnp.stack(outf, axis=1).astype(jnp.int32)


def _beam_body(cell, carry, token, finished, scores, memory, memory_proj,
               memory_mask, t, *, min_len: int, axis: str, V: int, mp: int,
               W: int):
    """Per-shard beam step: the kernel over the local slice, a local top-W
    in the rebased global flat namespace, then the cross-shard merge."""
    vs = cell["out_proj"]["kernel"].shape[-1]
    off = jax.lax.axis_index(axis) * vs
    table = jnp.asarray(cell["word_embed"]["embedding"])
    B = token.shape[1]

    emb = _psum_embed(table, token, off, axis)
    carry, logits = fused_decode_step(
        cell, carry, token, memory, memory_proj, memory_mask, emb=emb
    )
    logits = _set_owned(logits, PAD_ID, off, NEG)
    logits = _set_owned(logits, BOS_ID, off, NEG)
    if min_len > 0:
        blocked = _set_owned(logits, EOS_ID, off, NEG)
        logits = jnp.where(t < min_len, blocked, logits)
    logp = logits - _merge_lse(logits, axis)[..., None]
    logp = logp.transpose(1, 0, 2)                       # [B, W, V_s]
    # the PAD continuation row, restricted to the columns this shard owns
    pad_row = _set_owned(jnp.full((vs,), NEG), PAD_ID, off, 0.0)
    cont = jnp.where(finished.T[:, :, None], pad_row[None, None, :], logp)
    total = scores.T[:, :, None] + cont
    ts, fl = jax.lax.top_k(total.reshape(B, W * vs), W)
    # local flat w * V_s + v -> the replicated kernel's w * V + off + v
    gf = (fl // vs) * V + off + (fl % vs)
    pool_s = jax.lax.all_gather(ts, axis, axis=1).reshape(B, mp * W)
    pool_f = jax.lax.all_gather(gf, axis, axis=1).reshape(B, mp * W)
    top_scores, top_flat = _merge_topw(pool_s, pool_f, W)
    return carry, top_scores, top_flat


def mp_beam_step(cell_params, carry, token, finished, scores, memory,
                 memory_proj, memory_mask, *, mesh, t, min_len: int = 0,
                 axis: str = "mp"):
    """Vocab-sharded :func:`~cst_captioning_tpu.ops.decode_pallas.
    fused_beam_step`: same ``(new_carry, top_scores [B, W], top_flat
    [B, W])`` outputs with ``flat = lane * V + token`` in the replicated
    kernel's namespace — candidate-for-candidate identical including
    ``top_k`` tie order (module docstring)."""
    V, mp = _validate(cell_params, mesh, axis)
    W, _B = token.shape
    if W > V // mp:
        raise ValueError(
            f"beam width {W} > per-shard vocab {V // mp}: every shard must "
            f"fill a full local top-{W} candidate list"
        )

    fn = _beam_program(
        mesh, jax.tree_util.tree_structure(cell_params), min_len, axis,
        V, mp, W,
    )
    return fn(cell_params, carry, token, finished, scores, memory,
              memory_proj, memory_mask, jnp.asarray(t, jnp.int32))


@functools.lru_cache(maxsize=None)
def _beam_program(mesh, cell_treedef, min_len: int, axis: str, V: int,
                  mp: int, W: int):
    """Cached shard_map beam program (see :func:`_stride_program`)."""
    from cst_captioning_tpu.parallel.compile import CompilePlan, compile_fn

    def body(cell, carry, token, finished, scores, memory, memory_proj,
             memory_mask, t):
        return _beam_body(
            cell, carry, token, finished, scores, memory, memory_proj,
            memory_mask, t, min_len=min_len, axis=axis, V=V, mp=mp, W=W,
        )

    dummy = jax.tree_util.tree_unflatten(
        cell_treedef, [0] * cell_treedef.num_leaves
    )
    return compile_fn(body, CompilePlan(
        mesh=mesh,
        in_specs=(mp_cell_specs(dummy, axis), P(), P(), P(), P(),
                  P(), P(), P(), P()),
        out_specs=(P(), P(), P()),
    ))
