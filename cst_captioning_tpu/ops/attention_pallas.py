"""Pallas TPU kernel: fused masked additive-attention context.

The per-step temporal attention computes

    s    = v . tanh(memory_proj + q[:, None, :])        # [B, M]
    s    = where(mask > 0, s, -1e9)
    ctx  = softmax_f32(s) @ memory                      # [B, E]

(models/attention.py — the CST paper's Bahdanau scoring). This kernel
streams the frame axis through VMEM in blocks with a flash-attention-style
online softmax: running (row max, denominator, weighted-sum accumulator)
scratch, one pass over M, and only [B, E] ever written back.

PERF STATUS (measured round 4, TPU v5e, `bench_attention.py` /
BENCH_ATTENTION.json): the XLA composite ties or beats this kernel (within
±10%) at every resolvable M in {40..8192} x {f32, bf16} — both run at ~730 GB/s of
HBM, i.e. the op is bandwidth-bound on its inputs and current XLA already
fuses the [B, M, d_att] tanh intermediate instead of materializing it (the
original motivation for this kernel). Kept as opt-in
(model.attention_impl="pallas") long-context insurance against XLA fusion
regressions; there is no configuration where it is recommended today.

Numerics match the reference composite exactly in structure: masked slots
participate with score -1e9 (so a fully-masked row degrades to the same
uniform softmax over the M real slots), padding added for block alignment is
EXCLUDED from the softmax entirely, and all softmax statistics accumulate in
f32 regardless of the memory dtype.

The op is differentiable: a ``jax.custom_vjp`` whose backward re-runs the
plain XLA composite under ``jax.vjp`` (recompute-style — decode, the hot
path, never takes gradients; training pays one extra fused forward).

Off-TPU (CPU tests) the kernel runs in Pallas interpret mode automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cst_captioning_tpu.compat import vma_of

NEG = -1.0e9


def _reference(q, v, memory, memory_proj, mask):
    """The XLA composite (attention.py math) — backward + parity oracle."""
    t = jnp.tanh(memory_proj + q[:, None, :])
    s = jnp.einsum("bmd,d->bm", t, v.astype(t.dtype))
    s = jnp.where(mask > 0, s, NEG).astype(jnp.float32)
    w = jax.nn.softmax(s, axis=-1).astype(memory.dtype)
    return jnp.einsum("bm,bme->be", w, memory)


def _kernel(q_ref, v_ref, mem_ref, proj_ref, mask_ref, o_ref,
            m_scr, d_scr, a_scr, *, m_true: int, block_m: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        d_scr[:] = jnp.zeros_like(d_scr)
        a_scr[:] = jnp.zeros_like(a_scr)

    q = q_ref[:]                                        # [Bb, d_att]
    t = jnp.tanh(proj_ref[:] + q[:, None, :]).astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)                    # [d_att]
    s = jnp.sum(t * v[None, None, :], axis=-1)          # [Bb, Mb] (VPU)
    s = jnp.where(mask_ref[:] > 0, s, NEG)
    # block-alignment padding is excluded from the softmax entirely;
    # merely-masked REAL slots stay in at -1e9 (reference semantics: a
    # fully-masked row yields the uniform softmax over its M real slots)
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_m
    live = col < m_true
    s = jnp.where(live, s, -jnp.inf)

    m_prev = m_scr[:, 0]                                # [Bb]
    m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    # all-padding block (or first block): guard exp(-inf - -inf)
    alpha = jnp.where(
        m_prev == -jnp.inf, 0.0, jnp.exp(m_prev - m_cur)
    )
    p = jnp.where(live, jnp.exp(s - m_cur[:, None]), 0.0)  # [Bb, Mb]
    d_new = d_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
    # batched [Bb,Mb] x [Bb,Mb,E] weighted sum on the VPU (Mosaic here has
    # no batched-dot lowering; the op is HBM-bandwidth-bound regardless)
    ctx = jnp.sum(
        p[:, :, None] * mem_ref[:].astype(jnp.float32), axis=1
    )                                                   # [Bb, E]
    a_new = a_scr[:] * alpha[:, None] + ctx

    m_scr[:, 0] = m_cur
    d_scr[:, 0] = d_new
    a_scr[:] = a_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        den = jnp.maximum(d_scr[:, 0], 1e-30)
        o_ref[:] = (a_scr[:] / den[:, None]).astype(o_ref.dtype)


def _pad_to(x, axis, mult, value=0.0):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _fused_forward(q, v, memory, memory_proj, mask,
                   block_b: int, block_m: int, interpret: bool):
    B, M, E = memory.shape
    d_att = q.shape[-1]
    qp = _pad_to(q, 0, block_b)
    memp = _pad_to(_pad_to(memory, 0, block_b), 1, block_m)
    projp = _pad_to(_pad_to(memory_proj, 0, block_b), 1, block_m)
    maskp = _pad_to(_pad_to(mask, 0, block_b), 1, block_m)
    Bp, Mp = maskp.shape

    # inside a shard_map with the varying-axis check on (the DP train step),
    # the output's vma must be declared: it varies over every axis any
    # input varies over
    vma = frozenset()
    for x in (q, memory, memory_proj, mask):
        vma = vma | vma_of(x)
    if vma:
        out_shape = jax.ShapeDtypeStruct((Bp, E), memory.dtype, vma=vma)
    else:
        # also the 0.4.x path, whose ShapeDtypeStruct has no vma parameter
        out_shape = jax.ShapeDtypeStruct((Bp, E), memory.dtype)

    grid = (Bp // block_b, Mp // block_m)
    out = pl.pallas_call(
        functools.partial(_kernel, m_true=M, block_m=block_m),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, d_att), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d_att), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, block_m, E), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, block_m, d_att), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, block_m), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (block_b, E), lambda i, j: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_b, 128), jnp.float32),   # running row max
            pltpu.VMEM((block_b, 128), jnp.float32),   # running denominator
            pltpu.VMEM((block_b, E), jnp.float32),     # weighted-sum acc
        ],
        interpret=interpret,
    )(qp, v.reshape(1, d_att), memp, projp, maskp)
    return out[:B]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def fused_additive_attention(q, v, memory, memory_proj, mask,
                             block_b: int = 8, block_m: int = 128):
    """Fused masked additive-attention context -> [B, E].

    Args: ``q`` [B, d_att] (query_proj already applied), ``v`` [d_att] (the
    score vector), ``memory`` [B, M, E], ``memory_proj`` [B, M, d_att],
    ``mask`` [B, M]. Matches models/attention.py's composite bit-for-
    structure (see module docstring); gradients recompute via the composite.
    """
    interpret = jax.default_backend() != "tpu"
    if interpret and any(
        vma_of(x) for x in (q, memory, memory_proj, mask)
    ):
        # Pallas INTERPRET mode can't execute under a varying-axis-checked
        # shard_map (the interpreter's loop constants are axis-invariant and
        # trip the vma check) — fall back to the composite there. Only the
        # CPU-test DP train step hits this; compiled Mosaic on TPU runs the
        # kernel in every context.
        return _reference(q, v, memory, memory_proj, mask)
    return _fused_forward(q, v, memory, memory_proj, mask,
                          block_b, block_m, interpret)


def _fwd(q, v, memory, memory_proj, mask, block_b, block_m):
    out = fused_additive_attention(q, v, memory, memory_proj, mask,
                                   block_b, block_m)
    return out, (q, v, memory, memory_proj, mask)


def _bwd(block_b, block_m, residuals, g):
    q, v, memory, memory_proj, mask = residuals
    _, vjp = jax.vjp(_reference, q, v, memory, memory_proj, mask)
    dq, dv, dmem, dproj, dmask = vjp(g)
    return dq, dv, dmem, dproj, dmask


fused_additive_attention.defvjp(_fwd, _bwd)
