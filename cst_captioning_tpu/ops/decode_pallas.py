"""Pallas TPU kernel: fused weight-stationary caption decode step.

One RL/eval decode step is, per lane ``g`` and batch row ``b`` (the exact
``DecoderCell.__call__`` math, models/decoder.py, dropout off — decode is
deterministic):

    q    = h_top @ Wq + bq                               # [B, A]
    s    = v . tanh(memory_proj + q[:, None, :])         # [B, M]
    ctx  = softmax_f32(where(mask, s, -1e9)) @ memory    # [B, E]
    x    = [word_emb(token), ctx]                        # [B, 2E]
    (c, h)_l = lstm_l(x) for each layer                  # [B, H]
    logits = h @ Wo + bo                                 # [B, V] f32

The XLA path lowers this to ~a dozen kernels per step, each re-reading its
operands from HBM; at round-5 dims the whole decode program ran at MFU
0.010 / bw_util 0.015 — latency-bound on dispatch, not on a resource. This
kernel runs the entire step as ONE ``pallas_call`` over a
``(batch-block, lane, vocab-block)`` grid in which every decoder weight has
a grid-invariant index map — Pallas fetches each weight block into VMEM
once and keeps it resident across the whole row grid (the weight-stationary
layout of TPU decode kernels, Ragged Paged Attention arXiv:2604.15464) —
and the memory bank block is fetched once per batch block and reused by all
1+K lanes. The output projection is blocked over the vocab axis
(``block_v``) so the full ``[H, V]`` matrix never has to fit VMEM; the
post-LSTM hidden is computed at the first vocab block and stashed in
scratch for the rest.

Boundaries, stated so the kernel can't be over-read:

- the embed gather ``word_emb[token]`` happens OUTSIDE the kernel (one XLA
  gather per step): keeping the ``[V, E]`` table out of VMEM is what lets
  the LSTM + attention weights stay resident at the flagship dims, and a
  [rows, E] gather is already a single optimal HBM op;
- residency spans one pallas_call, i.e. one time step across all rows and
  lanes. Cross-step residency (weights pinned across the
  ``scan_until_finished`` stride) would need token selection inside the
  kernel; that headroom is recorded in ROADMAP.md;
- token selection (argmax / ``jax.random.categorical``) stays outside, so
  the XLA and Pallas impls share one RNG stream and selection semantics.

Decode never takes gradients (the REINFORCE update teacher-forces through
its own path), so there is no VJP: differentiating the op raises.

Numerics: all compute in f32 regardless of the model dtype (scores, softmax,
gates); masked-but-real slots score -1e9 (a fully-masked row degrades to the
uniform softmax over its M real slots, reference semantics) while
block-alignment padding is EXCLUDED from the softmax entirely. Parity vs
the XLA step is pinned by the {f32, bf16} x {small, flagship-ish} sweep in
tests/test_ops_decode_pallas.py.

Off-TPU (CPU tests) the kernel runs in Pallas interpret mode automatically;
inside a varying-axis-checked shard_map in interpret mode it falls back to
the jnp composite (same caveat as ops/attention_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cst_captioning_tpu.compat import vma_of
from cst_captioning_tpu.models.decoder import LSTM_GATE_ORDER

NEG = -1.0e9


def _num_layers(cell_params) -> int:
    n = sum(1 for k in cell_params if k.startswith("lstm"))
    if n == 0:
        raise ValueError("cell params carry no lstm<i> layers")
    return n


def _gate_weights(layer_params):
    """flax OptimizedLSTMCell per-gate Dense params -> (Wi [in, 4H],
    Wh [H, 4H], b [1, 4H]), concatenated in LSTM_GATE_ORDER — the same
    order the cell's own concatenated matmul splits on."""
    wi = jnp.concatenate(
        [layer_params[f"i{g}"]["kernel"] for g in LSTM_GATE_ORDER], axis=-1
    )
    wh = jnp.concatenate(
        [layer_params[f"h{g}"]["kernel"] for g in LSTM_GATE_ORDER], axis=-1
    )
    b = jnp.concatenate(
        [layer_params[f"h{g}"]["bias"] for g in LSTM_GATE_ORDER], axis=-1
    )
    return wi, wh, b[None, :]


def _lstm_math(x, c, h, wi, wh, b):
    """One OptimizedLSTMCell step in f32: gates split i|f|g|o."""
    gates = (
        jnp.dot(x, wi, preferred_element_type=jnp.float32)
        + jnp.dot(h, wh, preferred_element_type=jnp.float32)
        + b
    )
    i_, f_, g_, o_ = jnp.split(gates, 4, axis=-1)
    c_new = jax.nn.sigmoid(f_) * c + jax.nn.sigmoid(i_) * jnp.tanh(g_)
    h_new = jax.nn.sigmoid(o_) * jnp.tanh(c_new)
    return c_new, h_new


def _reference(cell_params, carry, token, memory, memory_proj, memory_mask):
    """The decode step as a plain-jnp composite over the cell's param tree
    (f32 compute, like the kernel) — the interpret-mode shard_map fallback
    and the parity oracle's cross-check."""
    L = _num_layers(cell_params)
    emb = jnp.asarray(
        cell_params["word_embed"]["embedding"]
    )[token].astype(jnp.float32)
    wq = cell_params["attention"]["query_proj"]["kernel"].astype(jnp.float32)
    bq = cell_params["attention"]["query_proj"]["bias"].astype(jnp.float32)
    v = cell_params["attention"]["score"]["kernel"][:, 0].astype(jnp.float32)
    h_top = carry[-1][1].astype(jnp.float32)
    q = h_top @ wq + bq
    t = jnp.tanh(memory_proj.astype(jnp.float32)[None] + q[:, :, None, :])
    s = jnp.einsum("gbma,a->gbm", t, v)
    s = jnp.where(memory_mask[None] > 0, s, NEG)
    w = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("gbm,bme->gbe", w, memory.astype(jnp.float32))
    x = jnp.concatenate([emb, ctx], axis=-1)
    new_carry = []
    for layer in range(L):
        wi, wh, b = _gate_weights(cell_params[f"lstm{layer}"])
        c, h = carry[layer]
        c_new, h_new = _lstm_math(
            x, c.astype(jnp.float32), h.astype(jnp.float32),
            wi.astype(jnp.float32), wh.astype(jnp.float32),
            b.astype(jnp.float32),
        )
        new_carry.append((c_new.astype(c.dtype), h_new.astype(h.dtype)))
        x = h_new
    wo = cell_params["out_proj"]["kernel"].astype(jnp.float32)
    bo = cell_params["out_proj"]["bias"].astype(jnp.float32)
    logits = x @ wo + bo
    return tuple(new_carry), logits


def _kernel(*refs, num_layers: int, m_true: int):
    """Grid (batch-block i, lane g, vocab-block vb); weights grid-invariant.

    Ref layout (matching _fused_call's in_specs order):
      emb, [c_0, h_0, .., c_{L-1}, h_{L-1}], memory, proj, mask,
      wq, bq, v, [wi_0, wh_0, b_0, ..], wo, bo
      -> outputs: logits, [c_out_0, h_out_0, ..]; scratch: x_stash
    """
    L = num_layers
    it = iter(refs)
    emb_ref = next(it)
    carry_refs = [(next(it), next(it)) for _ in range(L)]
    mem_ref, proj_ref, mask_ref = next(it), next(it), next(it)
    wq_ref, bq_ref, v_ref = next(it), next(it), next(it)
    lstm_refs = [(next(it), next(it), next(it)) for _ in range(L)]
    wo_ref, bo_ref = next(it), next(it)
    logits_ref = next(it)
    carry_out_refs = [(next(it), next(it)) for _ in range(L)]
    x_scr = next(it)

    vb = pl.program_id(2)

    @pl.when(vb == 0)
    def _():
        h_top = carry_refs[L - 1][1][0].astype(jnp.float32)   # [Bb, H]
        q = (
            jnp.dot(h_top, wq_ref[:].astype(jnp.float32),
                    preferred_element_type=jnp.float32)
            + bq_ref[:].astype(jnp.float32)
        )                                                     # [Bb, A]
        t = jnp.tanh(proj_ref[:].astype(jnp.float32) + q[:, None, :])
        s = jnp.sum(t * v_ref[0].astype(jnp.float32)[None, None, :], axis=-1)
        s = jnp.where(mask_ref[:] > 0, s, NEG)                # [Bb, M]
        # alignment padding (cols >= m_true) leaves the softmax entirely;
        # merely-masked REAL slots stay in at -1e9 (reference semantics)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < m_true, s, -jnp.inf)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        w = p / jnp.sum(p, axis=-1, keepdims=True)
        ctx = jnp.sum(
            w[:, :, None] * mem_ref[:].astype(jnp.float32), axis=1
        )                                                     # [Bb, E]
        x = jnp.concatenate(
            [emb_ref[0].astype(jnp.float32), ctx], axis=-1
        )
        for layer in range(L):
            c_ref, h_ref = carry_refs[layer]
            wi_ref, wh_ref, b_ref = lstm_refs[layer]
            c_new, h_new = _lstm_math(
                x,
                c_ref[0].astype(jnp.float32),
                h_ref[0].astype(jnp.float32),
                wi_ref[:].astype(jnp.float32),
                wh_ref[:].astype(jnp.float32),
                b_ref[:].astype(jnp.float32),
            )
            c_out, h_out = carry_out_refs[layer]
            c_out[0] = c_new.astype(c_out.dtype)
            h_out[0] = h_new.astype(h_out.dtype)
            x = h_new
        x_scr[:] = x

    logits_ref[0] = (
        jnp.dot(x_scr[:], wo_ref[:].astype(jnp.float32),
                preferred_element_type=jnp.float32)
        + bo_ref[:].astype(jnp.float32)
    )


def _pad_to(x, axis, mult, value=0.0):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _fused_call(cell_params, carry, emb, memory, memory_proj, memory_mask,
                block_b: int, block_v: int, interpret: bool):
    L = _num_layers(cell_params)
    G, B, E = emb.shape
    M = memory.shape[1]
    Em = memory.shape[2]
    A = memory_proj.shape[2]
    H = carry[0][0].shape[-1]
    wo = cell_params["out_proj"]["kernel"]
    bo = cell_params["out_proj"]["bias"][None, :]
    V = wo.shape[-1]

    block_b = min(block_b, B) if B else block_b
    Bp = -(-B // block_b) * block_b
    block_v = min(block_v, -(-V // 128) * 128 if V > 128 else V)
    Vp = -(-V // block_v) * block_v
    Mp = -(-M // 128) * 128 if not interpret else M

    embp = _pad_to(emb, 1, block_b)
    carryp = [
        (_pad_to(c, 1, block_b), _pad_to(h, 1, block_b)) for c, h in carry
    ]
    memp = _pad_to(_pad_to(memory, 0, block_b), 1, Mp)
    projp = _pad_to(_pad_to(memory_proj, 0, block_b), 1, Mp)
    maskp = _pad_to(_pad_to(memory_mask, 0, block_b), 1, Mp)
    wop = _pad_to(wo, 1, block_v)
    bop = _pad_to(bo, 1, block_v)
    Mp = maskp.shape[1]

    att = cell_params["attention"]
    wq = att["query_proj"]["kernel"]
    bq = att["query_proj"]["bias"][None, :]
    vs = att["score"]["kernel"][:, 0][None, :]

    const = lambda i, g, vb: (0, 0)   # noqa: E731 — grid-invariant (resident)
    in_specs = [
        pl.BlockSpec((1, block_b, E), lambda i, g, vb: (g, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [embp]
    for c, h in carryp:
        for arr in (c, h):
            in_specs.append(
                pl.BlockSpec((1, block_b, H), lambda i, g, vb: (g, i, 0),
                             memory_space=pltpu.VMEM)
            )
            args.append(arr)
    in_specs += [
        pl.BlockSpec((block_b, Mp, Em), lambda i, g, vb: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, Mp, A), lambda i, g, vb: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, Mp), lambda i, g, vb: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((H, A), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, A), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, A), const, memory_space=pltpu.VMEM),
    ]
    args += [memp, projp, maskp, wq, bq, vs]
    for layer in range(L):
        wi, wh, b = _gate_weights(cell_params[f"lstm{layer}"])
        in_specs += [
            pl.BlockSpec(wi.shape, const, memory_space=pltpu.VMEM),
            pl.BlockSpec(wh.shape, const, memory_space=pltpu.VMEM),
            pl.BlockSpec(b.shape, const, memory_space=pltpu.VMEM),
        ]
        args += [wi, wh, b]
    in_specs += [
        pl.BlockSpec((H, block_v), lambda i, g, vb: (0, vb),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_v), lambda i, g, vb: (0, vb),
                     memory_space=pltpu.VMEM),
    ]
    args += [wop, bop]

    # inside a varying-axis-checked shard_map the outputs' vma must be
    # declared (same recipe as ops/attention_pallas.py); 0.4.x has no vma
    # parameter on ShapeDtypeStruct
    vma = frozenset()
    for x in (emb, memory, memory_proj, memory_mask, *jax.tree.leaves(carry)):
        vma = vma | vma_of(x)
    sds = (
        (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma
        else jax.ShapeDtypeStruct
    )
    out_shape = [sds((G, Bp, Vp), jnp.float32)]
    out_specs = [
        pl.BlockSpec((1, block_b, block_v), lambda i, g, vb: (g, i, vb),
                     memory_space=pltpu.VMEM)
    ]
    for c, h in carry:
        for arr in (c, h):
            out_shape.append(sds((G, Bp, H), arr.dtype))
            out_specs.append(
                pl.BlockSpec((1, block_b, H), lambda i, g, vb: (g, i, 0),
                             memory_space=pltpu.VMEM)
            )

    grid = (Bp // block_b, G, Vp // block_v)
    outs = pl.pallas_call(
        functools.partial(_kernel, num_layers=L, m_true=M),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((block_b, H), jnp.float32)],
        interpret=interpret,
    )(*args)
    logits = outs[0][:, :B, :V]
    flat = outs[1:]
    new_carry = tuple(
        (flat[2 * layer][:, :B], flat[2 * layer + 1][:, :B])
        for layer in range(L)
    )
    return new_carry, logits


def fused_decode_step(cell_params, carry, token, memory, memory_proj,
                      memory_mask, num_layers: int | None = None,
                      block_b: int = 32, block_v: int = 1024):
    """Fused decode step -> (new_carry, logits [G, B, V] f32).

    Args: ``cell_params`` — the DecoderCell param subtree
    (``params["params"]["cell"]``); ``carry`` — tuple over layers of
    (c, h), leaves [G, B, H]; ``token`` [G, B] int32; ``memory`` [B, M, E] /
    ``memory_proj`` [B, M, A] / ``memory_mask`` [B, M] shared by all G
    lanes. Inference-only: no VJP is defined (decode never takes gradients).
    """
    if num_layers is not None and num_layers != _num_layers(cell_params):
        raise ValueError(
            f"num_layers {num_layers} does not match the "
            f"{_num_layers(cell_params)} lstm layers in cell_params"
        )
    # the embed gather stays an XLA op (module docstring: keeping the [V, E]
    # table out of VMEM is what buys the other weights residency).
    # jnp.asarray: params may arrive as host numpy (a device_get'd
    # checkpoint), whose __getitem__ rejects traced token indices
    emb = jnp.asarray(cell_params["word_embed"]["embedding"])[token]
    interpret = jax.default_backend() != "tpu"
    if interpret and any(
        vma_of(x)
        for x in (emb, memory, memory_proj, memory_mask,
                  *jax.tree.leaves(carry))
    ):
        # Pallas interpret mode can't run under a varying-axis-checked
        # shard_map — fall back to the composite (CPU tests only; compiled
        # Mosaic on TPU runs the kernel in every context)
        return _reference(
            cell_params, carry, token, memory, memory_proj, memory_mask
        )
    return _fused_call(
        cell_params, carry, emb, memory, memory_proj, memory_mask,
        block_b, block_v, interpret,
    )
