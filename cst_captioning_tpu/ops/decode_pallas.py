"""Pallas TPU kernel: fused weight-stationary caption decode step.

One RL/eval decode step is, per lane ``g`` and batch row ``b`` (the exact
``DecoderCell.__call__`` math, models/decoder.py, dropout off — decode is
deterministic):

    q    = h_top @ Wq + bq                               # [B, A]
    s    = v . tanh(memory_proj + q[:, None, :])         # [B, M]
    ctx  = softmax_f32(where(mask, s, -1e9)) @ memory    # [B, E]
    x    = [word_emb(token), ctx]                        # [B, 2E]
    (c, h)_l = lstm_l(x) for each layer                  # [B, H]
    logits = h @ Wo + bo                                 # [B, V] f32

The XLA path lowers this to ~a dozen kernels per step, each re-reading its
operands from HBM; at round-5 dims the whole decode program ran at MFU
0.010 / bw_util 0.015 — latency-bound on dispatch, not on a resource. This
kernel runs the entire step as ONE ``pallas_call`` over a
``(batch-block, lane, vocab-block)`` grid in which every decoder weight has
a grid-invariant index map — Pallas fetches each weight block into VMEM
once and keeps it resident across the whole row grid (the weight-stationary
layout of TPU decode kernels, Ragged Paged Attention arXiv:2604.15464) —
and the memory bank block is fetched once per batch block and reused by all
1+K lanes. The output projection is blocked over the vocab axis
(``block_v``) so the full ``[H, V]`` matrix never has to fit VMEM; the
post-LSTM hidden is computed at the first vocab block and stashed in
scratch for the rest.

TWO kernels share that math:

- :func:`fused_decode_step` — the PR-4 per-step kernel: one launch per time
  step, weights resident across the row grid WITHIN the step. The embed
  gather and token selection stay outside (one XLA gather + argmax/
  categorical per step), so the XLA and Pallas impls share one RNG stream
  by construction. Still the kernel behind the greedy/sample loops.
- :func:`fused_decode_stride` — the multi-step stride kernel (see its
  section below): token selection and the next-token embedding lookup move
  IN-kernel, so weights stay resident across a whole stride of S time
  steps with ONE launch. RNG streams stay bit-identical because the Gumbel
  noise behind ``jax.random.categorical`` is precomputed outside from the
  ``rollout_step_keys`` streams and fed in as data. The fused RL decode
  (decoding/fused.py) drives this one.

Decode never takes gradients (the REINFORCE update teacher-forces through
its own path), so there is no VJP: differentiating the op raises.

Numerics: all compute in f32 regardless of the model dtype (scores, softmax,
gates); masked-but-real slots score -1e9 (a fully-masked row degrades to the
uniform softmax over its M real slots, reference semantics) while
block-alignment padding is EXCLUDED from the softmax entirely. Parity vs
the XLA step is pinned by the {f32, bf16} x {small, flagship-ish} sweep in
tests/test_ops_decode_pallas.py.

Off-TPU (CPU tests) the kernel runs in Pallas interpret mode automatically;
inside a varying-axis-checked shard_map in interpret mode it falls back to
the jnp composite (same caveat as ops/attention_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cst_captioning_tpu.compat import vma_of
from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.models.decoder import LSTM_GATE_ORDER

NEG = -1.0e9


def _num_layers(cell_params) -> int:
    n = sum(1 for k in cell_params if k.startswith("lstm"))
    if n == 0:
        raise ValueError("cell params carry no lstm<i> layers")
    return n


def _gate_weights(layer_params):
    """flax OptimizedLSTMCell per-gate Dense params -> (Wi [in, 4H],
    Wh [H, 4H], b [1, 4H]), concatenated in LSTM_GATE_ORDER — the same
    order the cell's own concatenated matmul splits on."""
    wi = jnp.concatenate(
        [layer_params[f"i{g}"]["kernel"] for g in LSTM_GATE_ORDER], axis=-1
    )
    wh = jnp.concatenate(
        [layer_params[f"h{g}"]["kernel"] for g in LSTM_GATE_ORDER], axis=-1
    )
    b = jnp.concatenate(
        [layer_params[f"h{g}"]["bias"] for g in LSTM_GATE_ORDER], axis=-1
    )
    return wi, wh, b[None, :]


def _lstm_math(x, c, h, wi, wh, b):
    """One OptimizedLSTMCell step in f32: gates split i|f|g|o."""
    gates = (
        jnp.dot(x, wi, preferred_element_type=jnp.float32)
        + jnp.dot(h, wh, preferred_element_type=jnp.float32)
        + b
    )
    i_, f_, g_, o_ = jnp.split(gates, 4, axis=-1)
    c_new = jax.nn.sigmoid(f_) * c + jax.nn.sigmoid(i_) * jnp.tanh(g_)
    h_new = jax.nn.sigmoid(o_) * jnp.tanh(c_new)
    return c_new, h_new


def _reference(cell_params, carry, token, memory, memory_proj, memory_mask,
               mem_lens=None, emb=None):
    """The decode step as a plain-jnp composite over the cell's param tree
    (f32 compute, like the kernel) — the interpret-mode shard_map fallback
    and the parity oracle's cross-check. ``mem_lens`` [B] excludes each
    row's memory columns >= its length from the softmax ENTIRELY (the
    per-row raggedness contract of the stride kernel below). ``emb``
    bypasses the embedding gather with pre-gathered rows — the
    vocab-sharded path (ops/decode_mp.py) gathers from its LOCAL embedding
    rows and psums, so a global-id gather here would be wrong there."""
    L = _num_layers(cell_params)
    if emb is None:
        emb = jnp.asarray(
            cell_params["word_embed"]["embedding"]
        )[token].astype(jnp.float32)
    else:
        emb = emb.astype(jnp.float32)
    wq = cell_params["attention"]["query_proj"]["kernel"].astype(jnp.float32)
    bq = cell_params["attention"]["query_proj"]["bias"].astype(jnp.float32)
    v = cell_params["attention"]["score"]["kernel"][:, 0].astype(jnp.float32)
    h_top = carry[-1][1].astype(jnp.float32)
    q = h_top @ wq + bq
    t = jnp.tanh(memory_proj.astype(jnp.float32)[None] + q[:, :, None, :])
    s = jnp.einsum("gbma,a->gbm", t, v)
    s = jnp.where(memory_mask[None] > 0, s, NEG)
    if mem_lens is not None:
        # rows keep >= 1 column so a fully-excluded row cannot NaN the
        # softmax (an unoccupied serving lane degrades to w=[1, 0, ..] over
        # zeroed memory — finite, and its frozen outputs never show it)
        lens = jnp.maximum(mem_lens.astype(jnp.int32), 1)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(col < lens[None, :, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("gbm,bme->gbe", w, memory.astype(jnp.float32))
    x = jnp.concatenate([emb, ctx], axis=-1)
    new_carry = []
    for layer in range(L):
        wi, wh, b = _gate_weights(cell_params[f"lstm{layer}"])
        c, h = carry[layer]
        c_new, h_new = _lstm_math(
            x, c.astype(jnp.float32), h.astype(jnp.float32),
            wi.astype(jnp.float32), wh.astype(jnp.float32),
            b.astype(jnp.float32),
        )
        new_carry.append((c_new.astype(c.dtype), h_new.astype(h.dtype)))
        x = h_new
    wo = cell_params["out_proj"]["kernel"].astype(jnp.float32)
    bo = cell_params["out_proj"]["bias"].astype(jnp.float32)
    logits = x @ wo + bo
    return tuple(new_carry), logits


def _kernel(*refs, num_layers: int, m_true: int):
    """Grid (batch-block i, lane g, vocab-block vb); weights grid-invariant.

    Ref layout (matching _fused_call's in_specs order):
      emb, [c_0, h_0, .., c_{L-1}, h_{L-1}], memory, proj, mask,
      wq, bq, v, [wi_0, wh_0, b_0, ..], wo, bo
      -> outputs: logits, [c_out_0, h_out_0, ..]; scratch: x_stash
    """
    L = num_layers
    it = iter(refs)
    emb_ref = next(it)
    carry_refs = [(next(it), next(it)) for _ in range(L)]
    mem_ref, proj_ref, mask_ref = next(it), next(it), next(it)
    wq_ref, bq_ref, v_ref = next(it), next(it), next(it)
    lstm_refs = [(next(it), next(it), next(it)) for _ in range(L)]
    wo_ref, bo_ref = next(it), next(it)
    logits_ref = next(it)
    carry_out_refs = [(next(it), next(it)) for _ in range(L)]
    x_scr = next(it)

    vb = pl.program_id(2)

    @pl.when(vb == 0)
    def _():
        h_top = carry_refs[L - 1][1][0].astype(jnp.float32)   # [Bb, H]
        q = (
            jnp.dot(h_top, wq_ref[:].astype(jnp.float32),
                    preferred_element_type=jnp.float32)
            + bq_ref[:].astype(jnp.float32)
        )                                                     # [Bb, A]
        t = jnp.tanh(proj_ref[:].astype(jnp.float32) + q[:, None, :])
        s = jnp.sum(t * v_ref[0].astype(jnp.float32)[None, None, :], axis=-1)
        s = jnp.where(mask_ref[:] > 0, s, NEG)                # [Bb, M]
        # alignment padding (cols >= m_true) leaves the softmax entirely;
        # merely-masked REAL slots stay in at -1e9 (reference semantics)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < m_true, s, -jnp.inf)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        w = p / jnp.sum(p, axis=-1, keepdims=True)
        ctx = jnp.sum(
            w[:, :, None] * mem_ref[:].astype(jnp.float32), axis=1
        )                                                     # [Bb, E]
        x = jnp.concatenate(
            [emb_ref[0].astype(jnp.float32), ctx], axis=-1
        )
        for layer in range(L):
            c_ref, h_ref = carry_refs[layer]
            wi_ref, wh_ref, b_ref = lstm_refs[layer]
            c_new, h_new = _lstm_math(
                x,
                c_ref[0].astype(jnp.float32),
                h_ref[0].astype(jnp.float32),
                wi_ref[:].astype(jnp.float32),
                wh_ref[:].astype(jnp.float32),
                b_ref[:].astype(jnp.float32),
            )
            c_out, h_out = carry_out_refs[layer]
            c_out[0] = c_new.astype(c_out.dtype)
            h_out[0] = h_new.astype(h_out.dtype)
            x = h_new
        x_scr[:] = x

    logits_ref[0] = (
        jnp.dot(x_scr[:], wo_ref[:].astype(jnp.float32),
                preferred_element_type=jnp.float32)
        + bo_ref[:].astype(jnp.float32)
    )


def _pad_to(x, axis, mult, value=0.0):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _fused_call(cell_params, carry, emb, memory, memory_proj, memory_mask,
                block_b: int, block_v: int, interpret: bool):
    L = _num_layers(cell_params)
    G, B, E = emb.shape
    M = memory.shape[1]
    Em = memory.shape[2]
    A = memory_proj.shape[2]
    H = carry[0][0].shape[-1]
    wo = cell_params["out_proj"]["kernel"]
    bo = cell_params["out_proj"]["bias"][None, :]
    V = wo.shape[-1]

    block_b = min(block_b, B) if B else block_b
    Bp = -(-B // block_b) * block_b
    block_v = min(block_v, -(-V // 128) * 128 if V > 128 else V)
    Vp = -(-V // block_v) * block_v
    Mp = -(-M // 128) * 128 if not interpret else M

    embp = _pad_to(emb, 1, block_b)
    carryp = [
        (_pad_to(c, 1, block_b), _pad_to(h, 1, block_b)) for c, h in carry
    ]
    memp = _pad_to(_pad_to(memory, 0, block_b), 1, Mp)
    projp = _pad_to(_pad_to(memory_proj, 0, block_b), 1, Mp)
    maskp = _pad_to(_pad_to(memory_mask, 0, block_b), 1, Mp)
    wop = _pad_to(wo, 1, block_v)
    bop = _pad_to(bo, 1, block_v)
    Mp = maskp.shape[1]

    att = cell_params["attention"]
    wq = att["query_proj"]["kernel"]
    bq = att["query_proj"]["bias"][None, :]
    vs = att["score"]["kernel"][:, 0][None, :]

    const = lambda i, g, vb: (0, 0)   # noqa: E731 — grid-invariant (resident)
    in_specs = [
        pl.BlockSpec((1, block_b, E), lambda i, g, vb: (g, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [embp]
    for c, h in carryp:
        for arr in (c, h):
            in_specs.append(
                pl.BlockSpec((1, block_b, H), lambda i, g, vb: (g, i, 0),
                             memory_space=pltpu.VMEM)
            )
            args.append(arr)
    in_specs += [
        pl.BlockSpec((block_b, Mp, Em), lambda i, g, vb: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, Mp, A), lambda i, g, vb: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, Mp), lambda i, g, vb: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((H, A), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, A), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, A), const, memory_space=pltpu.VMEM),
    ]
    args += [memp, projp, maskp, wq, bq, vs]
    for layer in range(L):
        wi, wh, b = _gate_weights(cell_params[f"lstm{layer}"])
        in_specs += [
            pl.BlockSpec(wi.shape, const, memory_space=pltpu.VMEM),
            pl.BlockSpec(wh.shape, const, memory_space=pltpu.VMEM),
            pl.BlockSpec(b.shape, const, memory_space=pltpu.VMEM),
        ]
        args += [wi, wh, b]
    in_specs += [
        pl.BlockSpec((H, block_v), lambda i, g, vb: (0, vb),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_v), lambda i, g, vb: (0, vb),
                     memory_space=pltpu.VMEM),
    ]
    args += [wop, bop]

    # inside a varying-axis-checked shard_map the outputs' vma must be
    # declared (same recipe as ops/attention_pallas.py); 0.4.x has no vma
    # parameter on ShapeDtypeStruct
    vma = frozenset()
    for x in (emb, memory, memory_proj, memory_mask, *jax.tree.leaves(carry)):
        vma = vma | vma_of(x)
    sds = (
        (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma
        else jax.ShapeDtypeStruct
    )
    out_shape = [sds((G, Bp, Vp), jnp.float32)]
    out_specs = [
        pl.BlockSpec((1, block_b, block_v), lambda i, g, vb: (g, i, vb),
                     memory_space=pltpu.VMEM)
    ]
    for c, h in carry:
        for arr in (c, h):
            out_shape.append(sds((G, Bp, H), arr.dtype))
            out_specs.append(
                pl.BlockSpec((1, block_b, H), lambda i, g, vb: (g, i, 0),
                             memory_space=pltpu.VMEM)
            )

    grid = (Bp // block_b, G, Vp // block_v)
    outs = pl.pallas_call(
        functools.partial(_kernel, num_layers=L, m_true=M),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((block_b, H), jnp.float32)],
        interpret=interpret,
    )(*args)
    logits = outs[0][:, :B, :V]
    flat = outs[1:]
    new_carry = tuple(
        (flat[2 * layer][:, :B], flat[2 * layer + 1][:, :B])
        for layer in range(L)
    )
    return new_carry, logits


def fused_decode_step(cell_params, carry, token, memory, memory_proj,
                      memory_mask, num_layers: int | None = None,
                      block_b: int = 32, block_v: int = 1024, emb=None):
    """Fused decode step -> (new_carry, logits [G, B, V] f32).

    Args: ``cell_params`` — the DecoderCell param subtree
    (``params["params"]["cell"]``); ``carry`` — tuple over layers of
    (c, h), leaves [G, B, H]; ``token`` [G, B] int32; ``memory`` [B, M, E] /
    ``memory_proj`` [B, M, A] / ``memory_mask`` [B, M] shared by all G
    lanes. ``emb`` [G, B, E] (optional) skips the internal embedding
    gather — the vocab-sharded caller (ops/decode_mp.py) supplies the
    psum-merged rows because its local table only holds a vocab slice.
    Inference-only: no VJP is defined (decode never takes gradients).
    """
    if num_layers is not None and num_layers != _num_layers(cell_params):
        raise ValueError(
            f"num_layers {num_layers} does not match the "
            f"{_num_layers(cell_params)} lstm layers in cell_params"
        )
    if emb is None:
        # the embed gather stays an XLA op (module docstring: keeping the
        # [V, E] table out of VMEM is what buys the other weights residency).
        # jnp.asarray: params may arrive as host numpy (a device_get'd
        # checkpoint), whose __getitem__ rejects traced token indices
        emb = jnp.asarray(cell_params["word_embed"]["embedding"])[token]
    interpret = jax.default_backend() != "tpu"
    # cell_params join the check: under the vocab-sharded shard_map
    # (ops/decode_mp.py) the activations are all invariant (emb arrives
    # psum-merged) but out_proj/word_embed vary over 'mp'
    if interpret and any(
        vma_of(x)
        for x in (emb, memory, memory_proj, memory_mask,
                  *jax.tree.leaves(carry),
                  *jax.tree.leaves(cell_params))
    ):
        # Pallas interpret mode can't run under a varying-axis-checked
        # shard_map — fall back to the composite (CPU tests only; compiled
        # Mosaic on TPU runs the kernel in every context)
        return _reference(
            cell_params, carry, token, memory, memory_proj, memory_mask,
            emb=emb,
        )
    return _fused_call(
        cell_params, carry, emb, memory, memory_proj, memory_mask,
        block_b, block_v, interpret,
    )


# ---- multi-step stride kernel: token selection moves INSIDE ------------------
#
# The per-step kernel above keeps weights resident for one time step; the
# stride kernel below keeps them resident across S steps by moving token
# selection and the next-token embedding lookup in-kernel, so the host
# dispatches ONE pallas_call per stride instead of one per step:
#
#   grid (batch-block i, lane g, step s, vocab-block vb) — s and vb are the
#   two inner (sequential) axes, so for each (i, g) the kernel walks S full
#   time steps while every decoder weight (grid-invariant index maps) and
#   the batch block's memory bank (invariant over g, s, vb) stay in VMEM.
#
# Selection semantics are EXACTLY the driving loop's (decoding/fused.py):
# lane 0 takes the first-index argmax of the untempered masked logits;
# lanes 1..K add precomputed Gumbel noise — jax.random.categorical's own
# Gumbel-max form, generated OUTSIDE from the [T, K] rollout_step_keys so
# the RNG streams stay bit-identical to the XLA path (the noise is data;
# only the argmax moved in-kernel). The blocked argmax keeps categorical's
# tie-break (lowest index wins: strictly-greater updates across vocab
# blocks, min-index within one). The chosen token's logprob comes from an
# online (max, sumexp) pair accumulated over the same vocab blocks.
#
# The next token's embedding never needs the [V, E] table resident: while
# vocab block vb streams through for the output projection, the embedding
# table block vb streams alongside it, and whenever a row's running argmax
# improves, that row one-hot-matmuls the candidate's embedding row out of
# the CURRENT table block into scratch (`pl.when(any(upd))` skips the
# matmul once the running max stops improving, which it quickly does). At
# the last vocab block the winner's embedding is already in scratch and
# becomes step s+1's input; finished rows feed PAD's embedding (stashed
# from block 0) — the exact frozen-token semantics of `step_outputs`.
#
# Finished-lane compaction hooks in through `n_active` (SMEM scalar): the
# driving loop packs batch columns that still have an unfinished lane into
# a dense prefix, and batch blocks entirely past the prefix skip attention,
# LSTM, projection and selection, writing only the frozen PAD/0 outputs and
# passing their carry through (a fully-finished column can never rejoin, so
# its stale carry is unobservable — the XLA path keeps stepping such rows,
# whose outputs are equally frozen). Per-lane raggedness inside an active
# block still steps (Ragged Paged Attention's per-page skipping is the
# natural next refinement); the compaction counters in the run report
# quantify exactly the column-level savings.

def _stride_kernel(*refs, num_layers: int, m_true: int, V: int, S: int,
                   temperature: float, min_len: int, block_v: int):
    L = num_layers
    it = iter(refs)
    t0_ref, nact_ref = next(it), next(it)
    emb0_ref, fin0_ref, lens_ref = next(it), next(it), next(it)
    carry_refs = [(next(it), next(it)) for _ in range(L)]
    mem_ref, proj_ref, mask_ref = next(it), next(it), next(it)
    wq_ref, bq_ref, v_ref = next(it), next(it), next(it)
    lstm_refs = [(next(it), next(it), next(it)) for _ in range(L)]
    wo_ref, bo_ref = next(it), next(it)
    embt_ref, noise_ref = next(it), next(it)
    tok_ref, lp_ref = next(it), next(it)
    carry_out_refs = [(next(it), next(it)) for _ in range(L)]
    x_scr, embc_scr, embn_scr, pade_scr = (
        next(it), next(it), next(it), next(it))
    bv_scr, bi_scr, sl_scr, lm_scr, ls_scr, fin_scr = (
        next(it), next(it), next(it), next(it), next(it), next(it))
    cs = [(next(it), next(it)) for _ in range(L)]

    i, g = pl.program_id(0), pl.program_id(1)
    s, vb = pl.program_id(2), pl.program_id(3)
    last_vb = vb == pl.num_programs(3) - 1
    bb = x_scr.shape[0]
    active = i * bb < nact_ref[0]

    @pl.when(active & (s == 0) & (vb == 0))
    def _():
        # per-(i, g) stride state lives in scratch; (re)seed it here
        embc_scr[:] = emb0_ref[0].astype(jnp.float32)
        fin_scr[:] = fin0_ref[0][:, None]
        for layer in range(L):
            cs[layer][0][:] = carry_refs[layer][0][0].astype(jnp.float32)
            cs[layer][1][:] = carry_refs[layer][1][0].astype(jnp.float32)
        # PAD's embedding row (PAD_ID == 0 lives in vocab block 0)
        pade_scr[:] = embt_ref[PAD_ID, :][None].astype(jnp.float32)

    # per-(lane-block, step) raggedness skip: once EVERY row of this lane's
    # batch block is finished, the remaining steps of the stride do no
    # attention/LSTM/projection/selection work — the finalize's frozen
    # branch (PAD/0 emission, PAD embedding feed) never reads the stale
    # selection scratch, and a fully-finished row's carry is unobservable
    # (compaction keeps such rows packed so whole blocks die together)
    live = active & jnp.any(fin_scr[:] == 0)

    @pl.when(live & (vb == 0))
    def _():
        # step s's attention + LSTM stack (the per-step kernel's math)
        h_top = cs[L - 1][1][:]
        q = (
            jnp.dot(h_top, wq_ref[:].astype(jnp.float32),
                    preferred_element_type=jnp.float32)
            + bq_ref[:].astype(jnp.float32)
        )
        t = jnp.tanh(proj_ref[:].astype(jnp.float32) + q[:, None, :])
        sc = jnp.sum(t * v_ref[0].astype(jnp.float32)[None, None, :], axis=-1)
        sc = jnp.where(mask_ref[:] > 0, sc, NEG)
        # per-ROW raggedness: each row's memory columns past ITS length
        # leave the softmax entirely (serving's paged gathers are ragged
        # per request; exp underflow makes the exclusion bit-exact vs the
        # -1e9 masking a padded-slab layout would apply — see module
        # docstring). Uniform-length callers pass lens == m_true per row.
        mcol = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        sc = jnp.where(mcol < lens_ref[:], sc, -jnp.inf)
        m = jnp.max(sc, axis=-1, keepdims=True)
        p = jnp.exp(sc - m)
        w = p / jnp.sum(p, axis=-1, keepdims=True)
        ctx = jnp.sum(w[:, :, None] * mem_ref[:].astype(jnp.float32), axis=1)
        x = jnp.concatenate([embc_scr[:], ctx], axis=-1)
        for layer in range(L):
            wi_ref, wh_ref, b_ref = lstm_refs[layer]
            c_new, h_new = _lstm_math(
                x, cs[layer][0][:], cs[layer][1][:],
                wi_ref[:].astype(jnp.float32),
                wh_ref[:].astype(jnp.float32),
                b_ref[:].astype(jnp.float32),
            )
            cs[layer][0][:] = c_new
            cs[layer][1][:] = h_new
            x = h_new
        x_scr[:] = x
        # reset the per-step online selection / logsumexp state (-inf is
        # safe: every vocab block holds >= 1 real column, so the running
        # max is finite from the first block on — no inf-inf NaN path)
        bv_scr[:] = jnp.full_like(bv_scr[:], -jnp.inf)
        bi_scr[:] = jnp.zeros_like(bi_scr[:])
        sl_scr[:] = jnp.zeros_like(sl_scr[:])
        lm_scr[:] = jnp.full_like(lm_scr[:], -jnp.inf)
        ls_scr[:] = jnp.zeros_like(ls_scr[:])

    @pl.when(live)
    def _():
        logits = (
            jnp.dot(x_scr[:], wo_ref[:].astype(jnp.float32),
                    preferred_element_type=jnp.float32)
            + bo_ref[:].astype(jnp.float32)
        )                                                   # [bb, block_v]
        col = vb * block_v + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1
        )
        # forbid_special + apply_min_len, in-kernel
        logits = jnp.where((col == PAD_ID) | (col == BOS_ID), NEG, logits)
        if min_len > 0:
            t_glob = t0_ref[0] + s
            logits = jnp.where(
                (t_glob < min_len) & (col == EOS_ID), NEG, logits
            )
        lm = jnp.where(col < V, logits, -jnp.inf)  # padding cols: excluded
        # online logsumexp over the untempered masked logits (selected_logprob)
        bm = jnp.max(lm, axis=-1, keepdims=True)
        m_new = jnp.maximum(lm_scr[:], bm)
        ls_scr[:] = (
            ls_scr[:] * jnp.exp(lm_scr[:] - m_new)
            + jnp.sum(jnp.exp(lm - m_new), axis=-1, keepdims=True)
        )
        lm_scr[:] = m_new
        # selection value: untempered argmax on lane 0, Gumbel-max draw on
        # the sampled lanes (noise precomputed from rollout_step_keys)
        sel = jnp.where(
            g == 0, lm, lm / temperature + noise_ref[0, 0]
        )
        bm_s = jnp.max(sel, axis=-1, keepdims=True)
        cand = jnp.min(
            jnp.where(sel == bm_s, col, 2**30), axis=-1, keepdims=True
        )                       # first-max tie-break: lowest column id wins
        upd = bm_s > bv_scr[:]  # strict >: the earliest block keeps ties
        cand_lm = jnp.sum(
            jnp.where(col == cand, lm, 0.0), axis=-1, keepdims=True
        )
        bv_scr[:] = jnp.where(upd, bm_s, bv_scr[:])
        bi_scr[:] = jnp.where(upd, cand, bi_scr[:])
        sl_scr[:] = jnp.where(upd, cand_lm, sl_scr[:])

        @pl.when(jnp.any(upd))
        def _():
            # candidate embedding: one-hot row-select out of the CURRENT
            # table block (an MXU matmul, not a gather); skipped entirely
            # once no row's running argmax improves
            onehot = (col == cand).astype(jnp.float32)
            cand_emb = jnp.dot(
                onehot, embt_ref[:].astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            embn_scr[:] = jnp.where(upd, cand_emb, embn_scr[:])

    @pl.when(active & last_vb)
    def _():
        # finalize step s: freeze finished rows (step_outputs semantics)
        fin = fin_scr[:] > 0
        tok = jnp.where(fin, jnp.int32(PAD_ID), bi_scr[:])
        lse = lm_scr[:] + jnp.log(ls_scr[:])
        lp = jnp.where(fin, 0.0, sl_scr[:] - lse)
        tok_ref[0, 0] = tok[:, 0]
        lp_ref[0, 0] = lp[:, 0]
        fin_scr[:] = jnp.logical_or(fin, tok == EOS_ID).astype(jnp.int32)
        embc_scr[:] = jnp.where(fin, pade_scr[:], embn_scr[:])

    @pl.when(active & (s == S - 1) & last_vb)
    def _():
        for layer in range(L):
            c_out, h_out = carry_out_refs[layer]
            c_out[0] = cs[layer][0][:].astype(c_out.dtype)
            h_out[0] = cs[layer][1][:].astype(h_out.dtype)

    # compacted-away blocks (every column fully finished): frozen outputs,
    # carry passthrough — no attention/LSTM/projection/selection work
    @pl.when(jnp.logical_not(active) & last_vb)
    def _():
        tok_ref[0, 0] = jnp.full((bb,), PAD_ID, jnp.int32)
        # frozen-row logprobs are f32 by the output contract
        lp_ref[0, 0] = jnp.zeros((bb,), jnp.float32)  # graftlint: disable=GL005

    @pl.when(jnp.logical_not(active) & (s == S - 1) & last_vb)
    def _():
        for layer in range(L):
            c_out, h_out = carry_out_refs[layer]
            c_out[0] = carry_refs[layer][0][0]
            h_out[0] = carry_refs[layer][1][0]


def _reference_stride(cell_params, carry, token, finished, memory,
                      memory_proj, memory_mask, noise, t0, *, steps: int,
                      temperature: float, min_len: int, mem_lens=None):
    """The stride kernel as a plain-jnp composite: S chained `_reference`
    steps with the driving loop's exact selection semantics (first-max
    argmax on lane 0, Gumbel-max on lanes 1..K from the provided noise,
    `selected_logprob` logprobs, `step_outputs` freezing) — the
    interpret-mode shard_map fallback and the parity oracle."""
    toks, lps = [], []
    for s in range(steps):
        carry, logits = _reference(
            cell_params, carry, token, memory, memory_proj, memory_mask,
            mem_lens=mem_lens,
        )
        neg = jnp.full_like(logits[..., :1], NEG)
        logits = (
            logits.at[..., PAD_ID].set(neg[..., 0])
            .at[..., BOS_ID].set(neg[..., 0])
        )
        if min_len > 0:
            blocked = logits.at[..., EOS_ID].set(NEG)
            logits = jnp.where(t0 + s < min_len, blocked, logits)
        g_nxt = jnp.argmax(logits[0], axis=-1)
        s_nxt = jnp.argmax(logits[1:] / temperature + noise[s], axis=-1)
        nxt = jnp.concatenate([g_nxt[None], s_nxt], axis=0).astype(jnp.int32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        lp = jnp.take_along_axis(logits, nxt[..., None], axis=-1)[..., 0] - lse
        nxt = jnp.where(finished, jnp.full_like(nxt, PAD_ID), nxt)
        lp = jnp.where(finished, jnp.zeros_like(lp), lp)
        finished = finished | (nxt == EOS_ID)
        toks.append(nxt)
        lps.append(lp)
        token = nxt
    return carry, jnp.stack(toks), jnp.stack(lps)


def _stride_call(cell_params, carry, emb0, finished, memory, memory_proj,
                 memory_mask, noise, t0, n_active, mem_lens, *, S: int,
                 temperature: float, min_len: int, block_b: int,
                 block_v: int, interpret: bool):
    L = _num_layers(cell_params)
    G, B, E = emb0.shape
    M = memory.shape[1]
    Em = memory.shape[2]
    A = memory_proj.shape[2]
    H = carry[0][0].shape[-1]
    wo = cell_params["out_proj"]["kernel"]
    bo = cell_params["out_proj"]["bias"][None, :]
    embt = jnp.asarray(cell_params["word_embed"]["embedding"])
    V = wo.shape[-1]

    block_b = min(block_b, B) if B else block_b
    Bp = -(-B // block_b) * block_b
    block_v = min(block_v, -(-V // 128) * 128 if V > 128 else V)
    Vp = -(-V // block_v) * block_v
    Mp = -(-M // 128) * 128 if not interpret else M

    emb0p = _pad_to(emb0, 1, block_b)
    # padded rows are born finished: their outputs freeze to PAD/0
    fin0p = _pad_to(finished.astype(jnp.int32), 1, block_b, value=1)
    # per-row memory lengths (serving's ragged paged gathers); uniform M
    # when the caller passes none. Clamped to >= 1 so a zero-length row
    # (unoccupied serving lane, padding) keeps a finite softmax — its
    # frozen outputs never observe the uniform-over-one-zero-slot weights
    if mem_lens is None:
        mem_lens = jnp.full((B,), M, jnp.int32)
    lensp = _pad_to(
        jnp.clip(mem_lens.astype(jnp.int32), 1, M)[:, None], 0, block_b,
        value=1,
    )
    carryp = [
        (_pad_to(c, 1, block_b), _pad_to(h, 1, block_b)) for c, h in carry
    ]
    memp = _pad_to(_pad_to(memory, 0, block_b), 1, Mp)
    projp = _pad_to(_pad_to(memory_proj, 0, block_b), 1, Mp)
    maskp = _pad_to(_pad_to(memory_mask, 0, block_b), 1, Mp)
    wop = _pad_to(wo, 1, block_v)
    bop = _pad_to(bo, 1, block_v)
    embtp = _pad_to(embt, 0, block_v)
    noisep = _pad_to(_pad_to(noise, 2, block_b), 3, block_v)
    Mp = maskp.shape[1]

    att = cell_params["attention"]
    wq = att["query_proj"]["kernel"]
    bq = att["query_proj"]["bias"][None, :]
    vs = att["score"]["kernel"][:, 0][None, :]

    smem = pl.BlockSpec((1,), lambda i, g, s, vb: (0,),
                        memory_space=pltpu.SMEM)
    const = lambda i, g, s, vb: (0, 0)   # noqa: E731 — grid-invariant
    in_specs = [smem, smem]
    args = [
        jnp.asarray(t0, jnp.int32).reshape(1),
        jnp.asarray(n_active, jnp.int32).reshape(1),
    ]
    in_specs += [
        pl.BlockSpec((1, block_b, E), lambda i, g, s, vb: (g, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_b), lambda i, g, s, vb: (g, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, 1), lambda i, g, s, vb: (i, 0),
                     memory_space=pltpu.VMEM),
    ]
    args += [emb0p, fin0p, lensp]
    for c, h in carryp:
        for arr in (c, h):
            in_specs.append(
                pl.BlockSpec((1, block_b, H), lambda i, g, s, vb: (g, i, 0),
                             memory_space=pltpu.VMEM)
            )
            args.append(arr)
    in_specs += [
        pl.BlockSpec((block_b, Mp, Em), lambda i, g, s, vb: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, Mp, A), lambda i, g, s, vb: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, Mp), lambda i, g, s, vb: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((H, A), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, A), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, A), const, memory_space=pltpu.VMEM),
    ]
    args += [memp, projp, maskp, wq, bq, vs]
    for layer in range(L):
        wi, wh, b = _gate_weights(cell_params[f"lstm{layer}"])
        in_specs += [
            pl.BlockSpec(wi.shape, const, memory_space=pltpu.VMEM),
            pl.BlockSpec(wh.shape, const, memory_space=pltpu.VMEM),
            pl.BlockSpec(b.shape, const, memory_space=pltpu.VMEM),
        ]
        args += [wi, wh, b]
    in_specs += [
        pl.BlockSpec((H, block_v), lambda i, g, s, vb: (0, vb),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_v), lambda i, g, s, vb: (0, vb),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_v, E), lambda i, g, s, vb: (vb, 0),
                     memory_space=pltpu.VMEM),
        # lane 0 draws no noise; its (unused) block aliases lane 1's so the
        # fetch is a repeat, not extra traffic
        pl.BlockSpec((1, 1, block_b, block_v),
                     lambda i, g, s, vb: (s, jnp.maximum(g - 1, 0), i, vb),
                     memory_space=pltpu.VMEM),
    ]
    args += [wop, bop, embtp, noisep]

    vma = frozenset()
    for x in (emb0, memory, memory_proj, memory_mask, finished, noise,
              *jax.tree.leaves(carry)):
        vma = vma | vma_of(x)
    sds = (
        (lambda sh, d: jax.ShapeDtypeStruct(sh, d, vma=vma)) if vma
        else jax.ShapeDtypeStruct
    )
    out_shape = [sds((S, G, Bp), jnp.int32), sds((S, G, Bp), jnp.float32)]
    out_specs = [
        pl.BlockSpec((1, 1, block_b), lambda i, g, s, vb: (s, g, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_b), lambda i, g, s, vb: (s, g, i),
                     memory_space=pltpu.VMEM),
    ]
    for c, h in carry:
        for arr in (c, h):
            out_shape.append(sds((G, Bp, H), arr.dtype))
            out_specs.append(
                pl.BlockSpec((1, block_b, H), lambda i, g, s, vb: (g, i, 0),
                             memory_space=pltpu.VMEM)
            )

    scratch = [
        pltpu.VMEM((block_b, H), jnp.float32),    # x_stash
        pltpu.VMEM((block_b, E), jnp.float32),    # current-step embedding
        pltpu.VMEM((block_b, E), jnp.float32),    # candidate embedding
        pltpu.VMEM((1, E), jnp.float32),          # PAD embedding
        pltpu.VMEM((block_b, 1), jnp.float32),    # running best sel value
        pltpu.VMEM((block_b, 1), jnp.int32),      # running best token
        pltpu.VMEM((block_b, 1), jnp.float32),    # its untempered logit
        pltpu.VMEM((block_b, 1), jnp.float32),    # online lse max
        pltpu.VMEM((block_b, 1), jnp.float32),    # online lse sumexp
        pltpu.VMEM((block_b, 1), jnp.int32),      # finished
    ]
    for _ in range(L):
        scratch += [
            pltpu.VMEM((block_b, H), jnp.float32),
            pltpu.VMEM((block_b, H), jnp.float32),
        ]

    grid = (Bp // block_b, G, S, Vp // block_v)
    outs = pl.pallas_call(
        functools.partial(
            _stride_kernel, num_layers=L, m_true=M, V=V, S=S,
            temperature=temperature, min_len=min_len, block_v=block_v,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)
    tokens = outs[0][:, :, :B]
    lps = outs[1][:, :, :B]
    flat = outs[2:]
    new_carry = tuple(
        (flat[2 * layer][:, :B], flat[2 * layer + 1][:, :B])
        for layer in range(L)
    )
    return new_carry, tokens, lps


def fused_decode_stride(cell_params, carry, token, finished, memory,
                        memory_proj, memory_mask, noise, t0, n_active=None,
                        *, steps: int, temperature: float = 1.0,
                        min_len: int = 0, num_layers: int | None = None,
                        block_b: int = 32, block_v: int = 1024,
                        mem_lens=None):
    """S fused decode steps with in-kernel token selection.

    -> ``(new_carry, tokens [S, G, B] int32, logprobs [S, G, B] f32)``.

    Args beyond :func:`fused_decode_step`'s: ``finished`` [G, B] bool (rows
    already past EOS — they emit PAD/0 and feed PAD forward), ``noise``
    [S, K, B, V] f32 Gumbel noise for the sampled lanes (generated from the
    exact ``rollout_step_keys`` streams by the driving loop — see
    ``decoding.common.gumbel_step_noise``), ``t0`` the global index of the
    stride's first step (for ``min_len`` masking), and ``n_active`` the
    compaction prefix length in batch columns (None/B = no compaction —
    every block steps). ``mem_lens`` [B] int32 gives each row's OWN memory
    length: columns past it leave the attention softmax entirely — the
    per-row raggedness contract serving's paged gathers rely on (a request
    holding fewer pages attends over exactly its own slots; None = the
    uniform M every offline caller has). Lane 0 is the greedy lane:
    untempered first-index argmax, no noise consumed. Inference-only, like
    the per-step kernel.
    """
    if num_layers is not None and num_layers != _num_layers(cell_params):
        raise ValueError(
            f"num_layers {num_layers} does not match the "
            f"{_num_layers(cell_params)} lstm layers in cell_params"
        )
    G, B = token.shape
    if G < 2:
        raise ValueError(
            "fused_decode_stride needs the (1+K)-lane layout with K >= 1 "
            f"sampled lanes; got G={G}"
        )
    if noise.shape[:3] != (steps, G - 1, B):
        raise ValueError(
            f"noise shape {noise.shape} does not match "
            f"[steps={steps}, K={G - 1}, B={B}, V]"
        )
    if n_active is None:
        n_active = B
    interpret = jax.default_backend() != "tpu"
    if interpret and any(
        vma_of(x)
        for x in (memory, memory_proj, memory_mask, finished, noise,
                  *jax.tree.leaves(carry))
    ):
        # Pallas interpret mode can't run under a varying-axis-checked
        # shard_map — the composite carries it (CPU tests only)
        return _reference_stride(
            cell_params, carry, token, finished, memory, memory_proj,
            memory_mask, noise, t0, steps=steps, temperature=temperature,
            min_len=min_len, mem_lens=mem_lens,
        )
    emb0 = jnp.asarray(cell_params["word_embed"]["embedding"])[token]
    return _stride_call(
        cell_params, carry, emb0, finished, memory, memory_proj, memory_mask,
        noise, t0, n_active, mem_lens, S=steps, temperature=temperature,
        min_len=min_len, block_b=block_b, block_v=block_v,
        interpret=interpret,
    )


# ---- paged stride kernel: page-table reads move INSIDE -----------------------
#
# The serving engine keeps each request's encoder memory in fixed-size HBM
# pages (serving/pages.py, the Ragged Paged Attention layout of arXiv
# 2604.15464) — but until now every stride first GATHERED the active lanes'
# pages into the dense [B, W, E] bank the stride kernel consumes: a full
# copy of all live memory per stride (read pool + write bank + kernel
# re-reads bank = 3x the bank bytes), and a hard cap of one batch's dense
# footprint on how large the pool can usefully grow. The paged variant
# moves the page-table reads INSIDE the kernel:
#
#   the [B, max_pages] int32 page table rides as a SCALAR-PREFETCH operand
#   (pltpu.PrefetchScalarGridSpec) so its entries are available to the
#   kernel before the grid body runs; the three pools stay in HBM as
#   unblocked ANY-space refs; and at each batch block's FIRST grid visit
#   (g == 0, s == 0, vb == 0) the kernel DMAs each row's pages
#   ``pool.at[table[row, p]]`` into a per-block VMEM slab scratch
#   [block_b, W, *] (start-all-then-wait-all async copies). Scratch
#   persists across the (g, s, vb) inner axes, so the slab is fetched
#   ONCE per stride per batch block — exactly the residency the dense
#   path's memory BlockSpec gave — and every later grid step runs the
#   UNCHANGED dense stride kernel math against the slab refs.
#
# Bit-exactness vs the dense-gather path is by construction: the gather
# (`jnp.take` per pool) and the DMA fill produce the same bytes in the
# same [row, slot] layout (page 0 is the shared zero page either way), and
# `_stride_kernel` then executes the identical program on them. Per-row
# `mem_lens` raggedness composes unchanged: columns past a row's length
# leave the softmax via the same -inf masking, so a row holding fewer
# pages attends over exactly its own slots and the zero-page tail is
# mathematically (not just numerically) excluded. Finished-block skipping
# also composes: a compacted-away block (i past the n_active prefix)
# skips the DMA fill along with all other work.

def _paged_stride_kernel(*refs, num_layers: int, page_size: int,
                         table_width: int, pad_m: int, V: int, S: int,
                         temperature: float, min_len: int, block_v: int):
    L = num_layers
    # the dense kernel's operand counts: 5 leading + 2L carry + 3 bank +
    # 3 attention + 3L lstm + 4 trailing inputs; 2 + 2L outputs
    n_in = 15 + 5 * L
    n_out = 2 + 2 * L
    tbl_ref = refs[0]                       # scalar prefetch: [Bp, width]
    ins = refs[1:1 + n_in]
    outs = refs[1 + n_in:1 + n_in + n_out]
    slab_mem, slab_proj, slab_mask, dma_sem = refs[
        1 + n_in + n_out:5 + n_in + n_out]
    inner_scratch = refs[5 + n_in + n_out:]

    nact_ref = ins[1]
    # the pools sit at the dense kernel's mem/proj/mask positions, but as
    # unblocked HBM refs ([N+1, P, E] / [N+1, P, A] / [N+1, P])
    mem_hbm, proj_hbm, mask_hbm = ins[5 + 2 * L:8 + 2 * L]

    i = pl.program_id(0)
    first = (
        (pl.program_id(1) == 0) & (pl.program_id(2) == 0)
        & (pl.program_id(3) == 0)
    )
    bb = slab_mem.shape[0]
    active = i * bb < nact_ref[0]
    W = table_width * page_size

    @pl.when(active & first)
    def _():
        if pad_m:
            # TPU lane-alignment tail past the true W slots: zero it so the
            # (exactly-zero-weighted) context sum never reads uninitialized
            # VMEM — 0 * garbage is only 0 when the garbage is finite
            tail = pl.ds(W, pad_m)
            slab_mem[:, tail, :] = jnp.zeros(
                (bb, pad_m, slab_mem.shape[2]), slab_mem.dtype
            )
            slab_proj[:, tail, :] = jnp.zeros(
                (bb, pad_m, slab_proj.shape[2]), slab_proj.dtype
            )
            slab_mask[:, tail] = jnp.zeros((bb, pad_m), slab_mask.dtype)
        copies = []
        for r in range(bb):
            for p in range(table_width):
                pg = tbl_ref[i * bb + r, p]
                dst = pl.ds(p * page_size, page_size)
                copies.append(pltpu.make_async_copy(
                    mem_hbm.at[pg], slab_mem.at[r, dst, :], dma_sem
                ))
                copies.append(pltpu.make_async_copy(
                    proj_hbm.at[pg], slab_proj.at[r, dst, :], dma_sem
                ))
                copies.append(pltpu.make_async_copy(
                    mask_hbm.at[pg], slab_mask.at[r, dst], dma_sem
                ))
        # start ALL page fetches before waiting on any: the DMA engine
        # overlaps them; program order only pins issue order
        for c in copies:
            c.start()
        for c in copies:
            c.wait()

    # the unchanged dense stride program, with the slab scratches standing
    # in for the dense bank's blocked refs — identical math on identical
    # bytes is the whole bit-exactness argument
    inner = (
        ins[:5 + 2 * L] + (slab_mem, slab_proj, slab_mask)
        + ins[8 + 2 * L:] + outs + inner_scratch
    )
    _stride_kernel(
        *inner, num_layers=L, m_true=W, V=V, S=S, temperature=temperature,
        min_len=min_len, block_v=block_v,
    )


def _gather_pages(mem_pool, proj_pool, mask_pool, table):
    """Dense [B, W, *] bank from pools + table — the XLA fallback and the
    parity oracle the paged kernel is pinned bit-exact against (page 0 is
    the shared zero page, so table padding gathers excluded slots)."""
    B, width = table.shape
    P = mem_pool.shape[1]
    flat = table.reshape(-1)
    mem = jnp.take(mem_pool, flat, axis=0).reshape(B, width * P, -1)
    proj = jnp.take(proj_pool, flat, axis=0).reshape(B, width * P, -1)
    mask = jnp.take(mask_pool, flat, axis=0).reshape(B, width * P)
    return mem, proj, mask


def _paged_stride_call(cell_params, carry, emb0, finished, mem_pool,
                       proj_pool, mask_pool, table, noise, t0, n_active,
                       mem_lens, *, S: int, temperature: float,
                       min_len: int, block_b: int, block_v: int,
                       interpret: bool):
    L = _num_layers(cell_params)
    G, B, E = emb0.shape
    P = mem_pool.shape[1]
    Em = mem_pool.shape[2]
    A = proj_pool.shape[2]
    width = table.shape[1]
    W = width * P
    H = carry[0][0].shape[-1]
    wo = cell_params["out_proj"]["kernel"]
    bo = cell_params["out_proj"]["bias"][None, :]
    embt = jnp.asarray(cell_params["word_embed"]["embedding"])
    V = wo.shape[-1]

    block_b = min(block_b, B) if B else block_b
    Bp = -(-B // block_b) * block_b
    block_v = min(block_v, -(-V // 128) * 128 if V > 128 else V)
    Vp = -(-V // block_v) * block_v
    Wp = -(-W // 128) * 128 if not interpret else W

    emb0p = _pad_to(emb0, 1, block_b)
    fin0p = _pad_to(finished.astype(jnp.int32), 1, block_b, value=1)
    if mem_lens is None:
        mem_lens = jnp.full((B,), W, jnp.int32)
    lensp = _pad_to(
        jnp.clip(mem_lens.astype(jnp.int32), 1, W)[:, None], 0, block_b,
        value=1,
    )
    carryp = [
        (_pad_to(c, 1, block_b), _pad_to(h, 1, block_b)) for c, h in carry
    ]
    # padded table rows point every slot at the shared zero page
    tablep = _pad_to(table.astype(jnp.int32), 0, block_b)
    wop = _pad_to(wo, 1, block_v)
    bop = _pad_to(bo, 1, block_v)
    embtp = _pad_to(embt, 0, block_v)
    noisep = _pad_to(_pad_to(noise, 2, block_b), 3, block_v)

    att = cell_params["attention"]
    wq = att["query_proj"]["kernel"]
    bq = att["query_proj"]["bias"][None, :]
    vs = att["score"]["kernel"][:, 0][None, :]

    # index maps gain the trailing scalar-prefetch ref (PrefetchScalarGridSpec
    # passes it after the grid indices); none of them consults it — the
    # table is read in-kernel, not at block-selection time
    smem = pl.BlockSpec((1,), lambda i, g, s, vb, tbl: (0,),
                        memory_space=pltpu.SMEM)
    const = lambda i, g, s, vb, tbl: (0, 0)   # noqa: E731 — grid-invariant
    in_specs = [smem, smem]
    args = [
        jnp.asarray(t0, jnp.int32).reshape(1),
        jnp.asarray(n_active, jnp.int32).reshape(1),
    ]
    in_specs += [
        pl.BlockSpec((1, block_b, E), lambda i, g, s, vb, tbl: (g, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_b), lambda i, g, s, vb, tbl: (g, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, 1), lambda i, g, s, vb, tbl: (i, 0),
                     memory_space=pltpu.VMEM),
    ]
    args += [emb0p, fin0p, lensp]
    for c, h in carryp:
        for arr in (c, h):
            in_specs.append(
                pl.BlockSpec((1, block_b, H),
                             lambda i, g, s, vb, tbl: (g, i, 0),
                             memory_space=pltpu.VMEM)
            )
            args.append(arr)
    in_specs += [
        # the pools stay whole in HBM; the kernel DMAs pages out by table id
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec((H, A), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, A), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, A), const, memory_space=pltpu.VMEM),
    ]
    args += [mem_pool, proj_pool, mask_pool, wq, bq, vs]
    for layer in range(L):
        wi, wh, b = _gate_weights(cell_params[f"lstm{layer}"])
        in_specs += [
            pl.BlockSpec(wi.shape, const, memory_space=pltpu.VMEM),
            pl.BlockSpec(wh.shape, const, memory_space=pltpu.VMEM),
            pl.BlockSpec(b.shape, const, memory_space=pltpu.VMEM),
        ]
        args += [wi, wh, b]
    in_specs += [
        pl.BlockSpec((H, block_v), lambda i, g, s, vb, tbl: (0, vb),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_v), lambda i, g, s, vb, tbl: (0, vb),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_v, E), lambda i, g, s, vb, tbl: (vb, 0),
                     memory_space=pltpu.VMEM),
        # lane 0 draws no noise; its (unused) block aliases lane 1's so the
        # fetch is a repeat, not extra traffic
        pl.BlockSpec((1, 1, block_b, block_v),
                     lambda i, g, s, vb, tbl:
                     (s, jnp.maximum(g - 1, 0), i, vb),
                     memory_space=pltpu.VMEM),
    ]
    args += [wop, bop, embtp, noisep]

    vma = frozenset()
    for x in (emb0, mem_pool, proj_pool, mask_pool, table, finished, noise,
              *jax.tree.leaves(carry)):
        vma = vma | vma_of(x)
    sds = (
        (lambda sh, d: jax.ShapeDtypeStruct(sh, d, vma=vma)) if vma
        else jax.ShapeDtypeStruct
    )
    out_shape = [sds((S, G, Bp), jnp.int32), sds((S, G, Bp), jnp.float32)]
    out_specs = [
        pl.BlockSpec((1, 1, block_b), lambda i, g, s, vb, tbl: (s, g, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_b), lambda i, g, s, vb, tbl: (s, g, i),
                     memory_space=pltpu.VMEM),
    ]
    for c, h in carry:
        for arr in (c, h):
            out_shape.append(sds((G, Bp, H), arr.dtype))
            out_specs.append(
                pl.BlockSpec((1, block_b, H),
                             lambda i, g, s, vb, tbl: (g, i, 0),
                             memory_space=pltpu.VMEM)
            )

    scratch = [
        # per-block page slabs, in the pools' OWN dtypes (the dense path
        # gathers without a cast, so the slab must hold the same bytes)
        pltpu.VMEM((block_b, Wp, Em), mem_pool.dtype),
        pltpu.VMEM((block_b, Wp, A), proj_pool.dtype),
        pltpu.VMEM((block_b, Wp), mask_pool.dtype),
        pltpu.SemaphoreType.DMA,
        # the dense kernel's own scratch, unchanged
        pltpu.VMEM((block_b, H), jnp.float32),    # x_stash
        pltpu.VMEM((block_b, E), jnp.float32),    # current-step embedding
        pltpu.VMEM((block_b, E), jnp.float32),    # candidate embedding
        pltpu.VMEM((1, E), jnp.float32),          # PAD embedding
        pltpu.VMEM((block_b, 1), jnp.float32),    # running best sel value
        pltpu.VMEM((block_b, 1), jnp.int32),      # running best token
        pltpu.VMEM((block_b, 1), jnp.float32),    # its untempered logit
        pltpu.VMEM((block_b, 1), jnp.float32),    # online lse max
        pltpu.VMEM((block_b, 1), jnp.float32),    # online lse sumexp
        pltpu.VMEM((block_b, 1), jnp.int32),      # finished
    ]
    for _ in range(L):
        scratch += [
            pltpu.VMEM((block_b, H), jnp.float32),
            pltpu.VMEM((block_b, H), jnp.float32),
        ]

    grid = (Bp // block_b, G, S, Vp // block_v)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    outs = pl.pallas_call(
        functools.partial(
            _paged_stride_kernel, num_layers=L, page_size=P,
            table_width=width, pad_m=Wp - W, V=V, S=S,
            temperature=temperature, min_len=min_len, block_v=block_v,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(tablep, *args)
    tokens = outs[0][:, :, :B]
    lps = outs[1][:, :, :B]
    flat = outs[2:]
    new_carry = tuple(
        (flat[2 * layer][:, :B], flat[2 * layer + 1][:, :B])
        for layer in range(L)
    )
    return new_carry, tokens, lps


def fused_decode_stride_paged(cell_params, carry, token, finished,
                              mem_pool, proj_pool, mask_pool, page_table,
                              noise, t0, n_active=None, *, steps: int,
                              temperature: float = 1.0, min_len: int = 0,
                              num_layers: int | None = None,
                              block_b: int = 32, block_v: int = 1024,
                              mem_lens=None):
    """:func:`fused_decode_stride` reading paged memory in-kernel.

    Same contract and returns, but the dense ``memory`` / ``memory_proj``
    / ``memory_mask`` bank is replaced by the page pools
    (``mem_pool [N+1, P, E]``, ``proj_pool [N+1, P, A]``,
    ``mask_pool [N+1, P]`` — row 0 is the shared zero page) plus a
    ``page_table [B, max_pages]`` int32 mapping each batch row to its pool
    rows (zero-page-padded past the row's own pages). The table rides as a
    scalar-prefetch operand and the kernel DMAs each batch block's pages
    from HBM into a VMEM slab once per stride — no dense [B, W, E] bank is
    ever materialized, so the pool may exceed one batch's dense footprint.
    Token- and logprob-bit-exact vs running :func:`fused_decode_stride`
    on the :func:`serving.pages.gather_bank` dense gather of the same
    pools (pinned in tests/test_ops_decode_pallas.py). ``mem_lens`` defaults to
    every row's full ``max_pages * P`` window; serving passes each row's
    true length. Inference-only, like the dense stride.
    """
    if num_layers is not None and num_layers != _num_layers(cell_params):
        raise ValueError(
            f"num_layers {num_layers} does not match the "
            f"{_num_layers(cell_params)} lstm layers in cell_params"
        )
    G, B = token.shape
    if G < 2:
        raise ValueError(
            "fused_decode_stride_paged needs the (1+K)-lane layout with "
            f"K >= 1 sampled lanes; got G={G}"
        )
    if noise.shape[:3] != (steps, G - 1, B):
        raise ValueError(
            f"noise shape {noise.shape} does not match "
            f"[steps={steps}, K={G - 1}, B={B}, V]"
        )
    if page_table.ndim != 2 or page_table.shape[0] != B:
        raise ValueError(
            f"page_table shape {page_table.shape} does not match "
            f"[B={B}, max_pages]"
        )
    if mem_pool.ndim != 3 or proj_pool.ndim != 3 or mask_pool.ndim != 2:
        raise ValueError(
            "pools must be [N+1, P, E] / [N+1, P, A] / [N+1, P]; got "
            f"{mem_pool.shape} / {proj_pool.shape} / {mask_pool.shape}"
        )
    if n_active is None:
        n_active = B
    interpret = jax.default_backend() != "tpu"
    if interpret and any(
        vma_of(x)
        for x in (mem_pool, proj_pool, mask_pool, page_table, finished,
                  noise, *jax.tree.leaves(carry))
    ):
        # Pallas interpret mode can't run under a varying-axis-checked
        # shard_map — gather the dense bank and run the composite (CPU
        # tests only; compiled Mosaic on TPU runs the kernel everywhere)
        memory, memory_proj, memory_mask = _gather_pages(
            mem_pool, proj_pool, mask_pool, page_table
        )
        return _reference_stride(
            cell_params, carry, token, finished, memory, memory_proj,
            memory_mask, noise, t0, steps=steps, temperature=temperature,
            min_len=min_len, mem_lens=mem_lens,
        )
    emb0 = jnp.asarray(cell_params["word_embed"]["embedding"])[token]
    return _paged_stride_call(
        cell_params, carry, emb0, finished, mem_pool, proj_pool, mask_pool,
        page_table, noise, t0, n_active, mem_lens, S=steps,
        temperature=temperature, min_len=min_len, block_b=block_b,
        block_v=block_v, interpret=interpret,
    )


# ---- beam step kernel: per-step top-k moves INSIDE ---------------------------
#
# The lane-batched beam search (decoding/beam.py, beam_impl="lanes") maps
# beams onto decode lanes, so its step is the per-step kernel above plus ONE
# extra reduction: the top-W candidate selection over (lane, vocab). The
# stride kernel's grid walks ALL steps of a lane before the next lane, which
# makes the beam's cross-lane hypothesis reorder impossible mid-stride —
# beams therefore ride a SINGLE-step launch (the reorder is a cross-lane
# gather the caller runs between launches, at the same seam where
# decoding/fused.py compacts finished columns), but the candidate selection
# itself moves in-kernel so the [G, B, V] logits never leave VMEM:
#
#   grid (batch-block i, lane g, vocab-block vb) — per vocab block the
#   kernel keeps (a) the stride kernel's online (max, sumexp) logsumexp and
#   (b) a running in-lane top-W over the raw masked logits, merged blockwise
#   (W max+mask passes — W is tiny). Raw-logit order equals logprob order
#   within a lane (the lse is one per-lane scalar subtracted uniformly), so
#   at the last vocab block the lane's W survivors become candidate totals
#   ``score + (logit - lse)`` — the exact `row_logprobs` association the XLA
#   beam scores with. Finished lanes contribute the closed-form PAD
#   continuation (score at PAD, score-1e9 at the next W-1 token ids), and a
#   cross-lane merge accumulated over g emits the global (total, flat) top-W
#   per row, ties broken toward the lower flat index like `lax.top_k`.
#
# Per-lane truncation to W is lossless: the global top-W takes at most W
# candidates from one lane, and in-lane ties keep the lowest column ids —
# the same order the flattened top_k would. (Known rounding edge: two
# DISTINCT raw logits whose totals round to equality at the f32 boundary
# candidate W could order differently than the reference's full sort; the
# parity suite has never observed it.) Requires W <= V so every lane can
# fill its candidate list.

def _beam_kernel(*refs, num_layers: int, m_true: int, V: int, W: int,
                 min_len: int, block_v: int):
    L = num_layers
    it = iter(refs)
    t_ref = next(it)
    emb_ref, fin_ref, sc_ref = next(it), next(it), next(it)
    carry_refs = [(next(it), next(it)) for _ in range(L)]
    mem_ref, proj_ref, mask_ref = next(it), next(it), next(it)
    wq_ref, bq_ref, v_ref = next(it), next(it), next(it)
    lstm_refs = [(next(it), next(it), next(it)) for _ in range(L)]
    wo_ref, bo_ref = next(it), next(it)
    tsc_ref, tfl_ref = next(it), next(it)
    carry_out_refs = [(next(it), next(it)) for _ in range(L)]
    x_scr, val_scr, idx_scr, lm_scr, ls_scr, cv_scr, cf_scr = (
        next(it), next(it), next(it), next(it), next(it), next(it), next(it))

    g, vb = pl.program_id(1), pl.program_id(2)
    G = pl.num_programs(1)
    last_vb = vb == pl.num_programs(2) - 1
    bb = x_scr.shape[0]

    @pl.when(vb == 0)
    def _():
        # lane g's attention + LSTM stack (the per-step kernel's math) and
        # carry write-out; then reset the per-lane selection state
        h_top = carry_refs[L - 1][1][0].astype(jnp.float32)
        q = (
            jnp.dot(h_top, wq_ref[:].astype(jnp.float32),
                    preferred_element_type=jnp.float32)
            + bq_ref[:].astype(jnp.float32)
        )
        t = jnp.tanh(proj_ref[:].astype(jnp.float32) + q[:, None, :])
        s = jnp.sum(t * v_ref[0].astype(jnp.float32)[None, None, :], axis=-1)
        s = jnp.where(mask_ref[:] > 0, s, NEG)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < m_true, s, -jnp.inf)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        w = p / jnp.sum(p, axis=-1, keepdims=True)
        ctx = jnp.sum(w[:, :, None] * mem_ref[:].astype(jnp.float32), axis=1)
        x = jnp.concatenate([emb_ref[0].astype(jnp.float32), ctx], axis=-1)
        for layer in range(L):
            c_ref, h_ref = carry_refs[layer]
            wi_ref, wh_ref, b_ref = lstm_refs[layer]
            c_new, h_new = _lstm_math(
                x, c_ref[0].astype(jnp.float32), h_ref[0].astype(jnp.float32),
                wi_ref[:].astype(jnp.float32), wh_ref[:].astype(jnp.float32),
                b_ref[:].astype(jnp.float32),
            )
            c_out, h_out = carry_out_refs[layer]
            c_out[0] = c_new.astype(c_out.dtype)
            h_out[0] = h_new.astype(h_out.dtype)
            x = h_new
        x_scr[:] = x
        # in-lane running top-W: -inf values under ids past any real column
        # (2**20 > any padded vocab id), so real candidates displace them
        val_scr[:] = jnp.full_like(val_scr[:], -jnp.inf)
        idx_scr[:] = 2**20 + jax.lax.broadcasted_iota(
            jnp.int32, idx_scr.shape, 1
        )
        lm_scr[:] = jnp.full_like(lm_scr[:], -jnp.inf)
        ls_scr[:] = jnp.zeros_like(ls_scr[:])

    logits = (
        jnp.dot(x_scr[:], wo_ref[:].astype(jnp.float32),
                preferred_element_type=jnp.float32)
        + bo_ref[:].astype(jnp.float32)
    )                                                   # [bb, block_v]
    col = vb * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    # forbid_special + apply_min_len, in-kernel (t is an SMEM scalar)
    logits = jnp.where((col == PAD_ID) | (col == BOS_ID), NEG, logits)
    if min_len > 0:
        logits = jnp.where(
            (t_ref[0] < min_len) & (col == EOS_ID), NEG, logits
        )
    lm = jnp.where(col < V, logits, -jnp.inf)  # padding cols: excluded
    # online logsumexp over the masked logits (the lane's row_logprobs lse)
    bm = jnp.max(lm, axis=-1, keepdims=True)
    m_new = jnp.maximum(lm_scr[:], bm)
    ls_scr[:] = (
        ls_scr[:] * jnp.exp(lm_scr[:] - m_new)
        + jnp.sum(jnp.exp(lm - m_new), axis=-1, keepdims=True)
    )
    lm_scr[:] = m_new
    # blocked in-lane top-W merge: union of this block's columns with the
    # running list (ids are globally unique — blocks cover disjoint column
    # ranges), W passes of (max, min-id-among-ties, mask-out) — `lax.top_k`
    # order: value descending, ties toward the lower id
    allv = jnp.concatenate([lm, val_scr[:]], axis=1)
    alli = jnp.concatenate([col, idx_scr[:]], axis=1)
    new_v, new_i = [], []
    for _ in range(W):
        mv = jnp.max(allv, axis=1, keepdims=True)
        pick = jnp.min(
            jnp.where(allv == mv, alli, 2**30), axis=1, keepdims=True
        )
        new_v.append(mv)
        new_i.append(pick)
        allv = jnp.where(alli == pick, -jnp.inf, allv)
    val_scr[:] = jnp.concatenate(new_v, axis=1)
    idx_scr[:] = jnp.concatenate(new_i, axis=1)

    @pl.when(last_vb)
    def _():
        # finalize lane g: W candidate (total, flat) pairs — live lanes
        # score their survivors in the row_logprobs association, finished
        # lanes emit the closed-form PAD continuation row's top-W
        lse = lm_scr[:] + jnp.log(ls_scr[:])            # [bb, 1]
        fin = fin_ref[0][:, None] > 0                   # [bb, 1]
        sc = sc_ref[0][:, None]                         # [bb, 1]
        wio = jax.lax.broadcasted_iota(jnp.int32, (bb, W), 1)
        live_tot = sc + (val_scr[:] - lse)
        live_flat = g * V + idx_scr[:]
        fin_tot = sc + jnp.where(wio == 0, 0.0, NEG)
        fin_flat = g * V + wio                          # PAD, then ids 1..W-1
        tot = jnp.where(fin, fin_tot, live_tot)
        flat = jnp.where(fin, fin_flat, live_flat)

        @pl.when(g == 0)
        def _():
            cv_scr[:] = tot
            cf_scr[:] = flat

        @pl.when(g > 0)
        def _():
            # cross-lane merge: top-W of the 2W union, ties toward the
            # lower flat index (flats are unique across lanes)
            av = jnp.concatenate([cv_scr[:], tot], axis=1)
            ai = jnp.concatenate([cf_scr[:], flat], axis=1)
            mv_l, mi_l = [], []
            for _ in range(W):
                mv = jnp.max(av, axis=1, keepdims=True)
                pick = jnp.min(
                    jnp.where(av == mv, ai, 2**30), axis=1, keepdims=True
                )
                mv_l.append(mv)
                mi_l.append(pick)
                av = jnp.where(ai == pick, -jnp.inf, av)
            cv_scr[:] = jnp.concatenate(mv_l, axis=1)
            cf_scr[:] = jnp.concatenate(mi_l, axis=1)

        @pl.when(g == G - 1)
        def _():
            tsc_ref[:] = cv_scr[:]
            tfl_ref[:] = cf_scr[:]


def _reference_beam_topk(cell_params, carry, token, finished, scores,
                         memory, memory_proj, memory_mask, *, t,
                         min_len: int):
    """The beam step + candidate selection as a plain-jnp composite: one
    `_reference` step, `row_logprobs` scoring, PAD continuation for finished
    lanes, one `top_k` over the flattened W*V candidates — the interpret-
    mode shard_map fallback and the kernel's parity oracle."""
    new_carry, logits = _reference(
        cell_params, carry, token, memory, memory_proj, memory_mask
    )
    neg = jnp.full_like(logits[..., :1], NEG)
    logits = (
        logits.at[..., PAD_ID].set(neg[..., 0])
        .at[..., BOS_ID].set(neg[..., 0])
    )
    if min_len > 0:
        blocked = logits.at[..., EOS_ID].set(NEG)
        logits = jnp.where(t < min_len, blocked, logits)
    W, B = token.shape
    V = logits.shape[-1]
    logp = logits - jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    logp = logp.transpose(1, 0, 2)                      # [B, W, V]
    pad_row = jnp.full((V,), NEG).at[PAD_ID].set(0.0)
    cont = jnp.where(finished.T[:, :, None], pad_row[None, None, :], logp)
    total = scores.T[:, :, None] + cont
    top_scores, flat = jax.lax.top_k(total.reshape(B, W * V), W)
    return new_carry, top_scores, flat.astype(jnp.int32)


def _beam_call(cell_params, carry, emb, finished, scores, memory,
               memory_proj, memory_mask, t, *, min_len: int, block_b: int,
               block_v: int, interpret: bool):
    L = _num_layers(cell_params)
    G, B, E = emb.shape
    M = memory.shape[1]
    Em = memory.shape[2]
    A = memory_proj.shape[2]
    H = carry[0][0].shape[-1]
    wo = cell_params["out_proj"]["kernel"]
    bo = cell_params["out_proj"]["bias"][None, :]
    V = wo.shape[-1]

    block_b = min(block_b, B) if B else block_b
    Bp = -(-B // block_b) * block_b
    block_v = min(block_v, -(-V // 128) * 128 if V > 128 else V)
    Vp = -(-V // block_v) * block_v
    Mp = -(-M // 128) * 128 if not interpret else M

    embp = _pad_to(emb, 1, block_b)
    # padded rows are born finished with score 0 — their candidate rows are
    # sliced off below, never merged into a real row's top-W (the merge is
    # per batch row)
    finp = _pad_to(finished.astype(jnp.int32), 1, block_b, value=1)
    scp = _pad_to(scores.astype(jnp.float32), 1, block_b)
    carryp = [
        (_pad_to(c, 1, block_b), _pad_to(h, 1, block_b)) for c, h in carry
    ]
    memp = _pad_to(_pad_to(memory, 0, block_b), 1, Mp)
    projp = _pad_to(_pad_to(memory_proj, 0, block_b), 1, Mp)
    maskp = _pad_to(_pad_to(memory_mask, 0, block_b), 1, Mp)
    wop = _pad_to(wo, 1, block_v)
    bop = _pad_to(bo, 1, block_v)
    Mp = maskp.shape[1]

    att = cell_params["attention"]
    wq = att["query_proj"]["kernel"]
    bq = att["query_proj"]["bias"][None, :]
    vs = att["score"]["kernel"][:, 0][None, :]

    smem = pl.BlockSpec((1,), lambda i, g, vb: (0,), memory_space=pltpu.SMEM)
    const = lambda i, g, vb: (0, 0)   # noqa: E731 — grid-invariant (resident)
    in_specs = [smem]
    args = [jnp.asarray(t, jnp.int32).reshape(1)]
    in_specs += [
        pl.BlockSpec((1, block_b, E), lambda i, g, vb: (g, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_b), lambda i, g, vb: (g, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_b), lambda i, g, vb: (g, i),
                     memory_space=pltpu.VMEM),
    ]
    args += [embp, finp, scp]
    for c, h in carryp:
        for arr in (c, h):
            in_specs.append(
                pl.BlockSpec((1, block_b, H), lambda i, g, vb: (g, i, 0),
                             memory_space=pltpu.VMEM)
            )
            args.append(arr)
    in_specs += [
        pl.BlockSpec((block_b, Mp, Em), lambda i, g, vb: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, Mp, A), lambda i, g, vb: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, Mp), lambda i, g, vb: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((H, A), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, A), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, A), const, memory_space=pltpu.VMEM),
    ]
    args += [memp, projp, maskp, wq, bq, vs]
    for layer in range(L):
        wi, wh, b = _gate_weights(cell_params[f"lstm{layer}"])
        in_specs += [
            pl.BlockSpec(wi.shape, const, memory_space=pltpu.VMEM),
            pl.BlockSpec(wh.shape, const, memory_space=pltpu.VMEM),
            pl.BlockSpec(b.shape, const, memory_space=pltpu.VMEM),
        ]
        args += [wi, wh, b]
    in_specs += [
        pl.BlockSpec((H, block_v), lambda i, g, vb: (0, vb),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_v), lambda i, g, vb: (0, vb),
                     memory_space=pltpu.VMEM),
    ]
    args += [wop, bop]

    vma = frozenset()
    for x in (emb, memory, memory_proj, memory_mask, finished, scores,
              *jax.tree.leaves(carry)):
        vma = vma | vma_of(x)
    sds = (
        (lambda sh, d: jax.ShapeDtypeStruct(sh, d, vma=vma)) if vma
        else jax.ShapeDtypeStruct
    )
    W = G
    out_shape = [sds((Bp, W), jnp.float32), sds((Bp, W), jnp.int32)]
    out_specs = [
        pl.BlockSpec((block_b, W), lambda i, g, vb: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, W), lambda i, g, vb: (i, 0),
                     memory_space=pltpu.VMEM),
    ]
    for c, h in carry:
        for arr in (c, h):
            out_shape.append(sds((G, Bp, H), arr.dtype))
            out_specs.append(
                pl.BlockSpec((1, block_b, H), lambda i, g, vb: (g, i, 0),
                             memory_space=pltpu.VMEM)
            )

    grid = (Bp // block_b, G, Vp // block_v)
    outs = pl.pallas_call(
        functools.partial(
            _beam_kernel, num_layers=L, m_true=M, V=V, W=W,
            min_len=min_len, block_v=block_v,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_b, H), jnp.float32),    # x_stash
            pltpu.VMEM((block_b, W), jnp.float32),    # in-lane top-W values
            pltpu.VMEM((block_b, W), jnp.int32),      # in-lane top-W col ids
            pltpu.VMEM((block_b, 1), jnp.float32),    # online lse max
            pltpu.VMEM((block_b, 1), jnp.float32),    # online lse sumexp
            pltpu.VMEM((block_b, W), jnp.float32),    # cross-lane totals
            pltpu.VMEM((block_b, W), jnp.int32),      # cross-lane flat ids
        ],
        interpret=interpret,
    )(*args)
    top_scores = outs[0][:B]
    top_flat = outs[1][:B]
    flat = outs[2:]
    new_carry = tuple(
        (flat[2 * layer][:, :B], flat[2 * layer + 1][:, :B])
        for layer in range(L)
    )
    return new_carry, top_scores, top_flat


def fused_beam_step(cell_params, carry, token, finished, scores, memory,
                    memory_proj, memory_mask, *, t, min_len: int = 0,
                    num_layers: int | None = None, block_b: int = 32,
                    block_v: int = 1024):
    """Fused beam step: decode + in-kernel top-W candidate selection.

    -> ``(new_carry, top_scores [B, W] f32, top_flat [B, W] int32)`` — the
    per-row global top-W over all (lane, token) candidates, ``flat = lane *
    V + token`` exactly like the XLA beam's flattened ``top_k``. The caller
    (decoding/beam.py) derives parent/token from ``flat`` and performs the
    hypothesis reorder between launches.

    Args beyond :func:`fused_decode_step`'s: ``finished`` [W, B] bool lanes
    already past EOS (they contribute the PAD continuation row),
    ``scores`` [W, B] f32 running hypothesis scores, ``t`` the global step
    index (traced; for ``min_len`` masking). Requires beam width <= vocab
    (section comment). Inference-only, like the other decode kernels.
    """
    if num_layers is not None and num_layers != _num_layers(cell_params):
        raise ValueError(
            f"num_layers {num_layers} does not match the "
            f"{_num_layers(cell_params)} lstm layers in cell_params"
        )
    W, B = token.shape
    V = cell_params["out_proj"]["kernel"].shape[-1]
    if W > V:
        raise ValueError(
            f"fused_beam_step needs beam width <= vocab to fill every "
            f"lane's candidate list; got W={W} > V={V}"
        )
    interpret = jax.default_backend() != "tpu"
    if interpret and any(
        vma_of(x)
        for x in (memory, memory_proj, memory_mask, finished, scores,
                  *jax.tree.leaves(carry))
    ):
        # Pallas interpret mode can't run under a varying-axis-checked
        # shard_map — the composite carries it (CPU tests only)
        return _reference_beam_topk(
            cell_params, carry, token, finished, scores, memory,
            memory_proj, memory_mask, t=t, min_len=min_len,
        )
    emb = jnp.asarray(cell_params["word_embed"]["embedding"])[token]
    return _beam_call(
        cell_params, carry, emb, finished, scores, memory, memory_proj,
        memory_mask, t, min_len=min_len, block_b=block_b, block_v=block_v,
        interpret=interpret,
    )
