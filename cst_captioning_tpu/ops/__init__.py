"""Hand-written TPU kernels (Pallas) for the hot ops.

XLA's fusions cover this model well (SURVEY.md §2: "the TPU build's native
layer is XLA itself plus optional Pallas kernels"); this package holds the
optional kernels where explicit VMEM blocking beats the default — currently
the long-context additive-attention context (flash-style online softmax over
the frame axis).
"""

from cst_captioning_tpu.ops.attention_pallas import fused_additive_attention

__all__ = ["fused_additive_attention"]
