"""Hand-written TPU kernels (Pallas) for the hot ops.

XLA's fusions cover this model well (SURVEY.md §2: "the TPU build's native
layer is XLA itself plus optional Pallas kernels"); this package holds the
optional kernels where explicit VMEM blocking beats the default:

- the long-context additive-attention context (flash-style online softmax
  over the frame axis, ``model.attention_impl="pallas"``);
- the weight-stationary fused decode step (attention + LSTM stack + output
  projection in one launch, ``model.decode_impl="pallas"`` — README
  "Decode fast path");
- the vocab-sharded stride/beam variants for flagship-XL model parallelism
  (ops/decode_mp.py — README "Model parallelism").
"""

from cst_captioning_tpu.ops.attention_pallas import fused_additive_attention
from cst_captioning_tpu.ops.decode_mp import mp_beam_step, mp_decode_stride
from cst_captioning_tpu.ops.decode_pallas import fused_decode_step

__all__ = [
    "fused_additive_attention",
    "fused_decode_step",
    "mp_beam_step",
    "mp_decode_stride",
]
