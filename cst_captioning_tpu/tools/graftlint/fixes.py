"""graftlint autofix engine: apply the mechanical repairs rules emit.

Rules attach a :class:`~.core.Fix` (span-precise :class:`~.core.Edit`\\ s +
a one-line description) to findings whose repair is provably mechanical —
GL013's ``np.asarray(x)`` → ``jax.device_get(x)``, GL011's carry-init
dtype literal, GL005's f32 literal when a ``dtype`` parameter is in scope.
This module turns those into file rewrites, plus the two repair classes no
rule owns: stale inline ``# graftlint: disable=`` suppressions and stale
``graftlint.baseline`` entries (``--check-stale`` reports them; ``--fix``
now removes them).

Safety ladder, in order:

1. **Plan, don't stream** — all edits for a file are collected first;
   overlapping fixes are REFUSED (first-come by source position wins, the
   rest are reported as skipped), never merged or guessed about.
2. **Re-parse** — the rewritten source must still parse; a syntax error
   reverts the whole file and reports every one of its fixes as skipped.
3. **Re-lint** — the CLI re-runs the lint after writing and fails if any
   autofixable finding survives: applying ``--fix`` twice is a no-op, and
   that idempotence is part of the contract (pinned in
   tests/test_graftlint.py).

``--fix --dry-run`` prints the unified diff instead of writing;
``--fix-check`` is the CI mode — it fails while any autofixable finding
is unfixed, without touching the tree.
"""

from __future__ import annotations

import ast
import difflib
import os
import tokenize
from dataclasses import dataclass, field
from io import StringIO

from cst_captioning_tpu.tools.graftlint.core import (
    _SUPPRESS_RE,
    Baseline,
    Edit,
    Finding,
    LintResult,
)


class OverlappingEditsError(ValueError):
    """Two edits claim overlapping spans — the engine refuses to guess
    which rewrite wins (the caller skips the later fix instead)."""


# ---- span-precise edit application ------------------------------------------

def _line_starts(source: str) -> list[int]:
    starts = [0]
    for i, ch in enumerate(source):
        if ch == "\n":
            starts.append(i + 1)
    return starts


def _span(source: str, starts: list[int], edit: Edit) -> tuple[int, int]:
    def offset(line: int, col: int) -> int:
        if line < 1 or line > len(starts):
            raise ValueError(f"edit line {line} out of range")
        return starts[line - 1] + col

    a = offset(edit.line, edit.col)
    b = offset(edit.end_line, edit.end_col)
    if b < a or b > len(source):
        raise ValueError(f"bad edit span {edit}")
    return a, b


def apply_edits(source: str, edits: list[Edit]) -> str:
    """Apply non-overlapping edits to ``source`` in one pass.

    Edits are sorted by start offset; any pair whose spans overlap (a
    zero-width insertion exactly at another edit's boundary is fine)
    raises :class:`OverlappingEditsError` — refusal, not resolution.
    """
    starts = _line_starts(source)
    spans = sorted(
        (( *_span(source, starts, e), e) for e in edits),
        key=lambda t: (t[0], t[1]),
    )
    prev_end = -1
    for a, b, e in spans:
        if a < prev_end:
            raise OverlappingEditsError(
                f"edit at {e.line}:{e.col} overlaps a previous edit"
            )
        prev_end = b
    out = source
    for a, b, e in reversed(spans):
        out = out[:a] + e.replacement + out[b:]
    return out


def edits_overlap(source: str, accepted: list[Edit],
                  candidate: list[Edit]) -> bool:
    """Would ``candidate`` overlap any already-accepted edit?"""
    starts = _line_starts(source)
    acc = [_span(source, starts, e) for e in accepted]
    for e in candidate:
        a, b = _span(source, starts, e)
        for (x, y) in acc:
            if a < y and x < b:
                return True
            if a == x and b == y:
                return True  # identical span: still two writers
    return False


# ---- stale-suppression removal ----------------------------------------------

def suppression_edits(source: str,
                      stale: list[dict]) -> list[tuple[Edit, str]]:
    """Edits removing (or trimming) the inline ``# graftlint: disable=``
    comments that ``--check-stale`` reported as dead.

    ``stale`` entries carry the TARGET line (the line the suppression
    applies to) and the dead rule id. A comment whose every id is dead is
    removed whole (its entire line when nothing else is on it); a comment
    with live ids left is rewritten without the dead ones.
    """
    by_line: dict[int, set[str]] = {}
    for s in stale:
        by_line.setdefault(int(s["line"]), set()).add(s["rule"])
    out: list[tuple[Edit, str]] = []
    lines = source.splitlines()
    try:
        tokens = list(tokenize.generate_tokens(StringIO(source).readline))
    except tokenize.TokenError:
        return []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        kind = m.group(1)
        target = tok.start[0] + (1 if kind.endswith("next-line") else 0)
        dead = by_line.get(target)
        if not dead:
            continue
        ids = [s.strip() for s in m.group(2).split(",") if s.strip()]
        live = [i for i in ids if i not in dead]
        row = tok.start[0]
        line_text = lines[row - 1] if row <= len(lines) else ""
        if live:
            # trim just the dead ids, keep the comment
            a = tok.start[1] + m.start(2)
            b = tok.start[1] + m.end(2)
            out.append((
                Edit(line=row, col=a, end_line=row, end_col=b,
                     replacement=",".join(live)),
                f"drop stale id(s) {sorted(dead & set(ids))} from the "
                f"suppression on line {row}",
            ))
            continue
        before = line_text[: tok.start[1]]
        if before.strip():
            # code shares the line: remove the comment and the padding
            # separating it from the code
            a = len(before.rstrip())
            out.append((
                Edit(line=row, col=a, end_line=row, end_col=len(line_text),
                     replacement=""),
                f"remove the stale suppression comment on line {row}",
            ))
        else:
            # the comment owns the line: remove the line entirely
            out.append((
                Edit(line=row, col=0, end_line=row + 1, end_col=0,
                     replacement="")
                if row < len(lines) or source.endswith("\n")
                else Edit(line=row, col=0, end_line=row,
                          end_col=len(line_text), replacement=""),
                f"remove the stale suppression line {row}",
            ))
    return out


# ---- the per-run fix plan ----------------------------------------------------

@dataclass
class FileFix:
    path: str                         # absolute
    relpath: str
    old_source: str
    new_source: str
    applied: list[str] = field(default_factory=list)   # descriptions
    skipped: list[str] = field(default_factory=list)   # reason strings

    def diff(self) -> str:
        return "".join(difflib.unified_diff(
            self.old_source.splitlines(keepends=True),
            self.new_source.splitlines(keepends=True),
            fromfile=f"a/{self.relpath}", tofile=f"b/{self.relpath}",
        ))


@dataclass
class FixPlan:
    files: list[FileFix] = field(default_factory=list)
    # (finding-or-label, reason) pairs the plan refused
    skipped: list[tuple[str, str]] = field(default_factory=list)
    stale_baseline_removed: int = 0
    baseline: Baseline | None = None   # rewritten baseline, when changed

    @property
    def applied_count(self) -> int:
        return sum(len(f.applied) for f in self.files)


def plan_fixes(result: LintResult, root: str,
               baseline: Baseline | None = None) -> FixPlan:
    """Turn a lint result into a concrete, conflict-free rewrite plan.

    Per file: fixable findings' edits are accepted in source order, each
    refused (with a reason) if it would overlap an accepted one; stale
    suppression comments are removed alongside. The rewritten source must
    re-parse or the whole file is reverted. Stale baseline entries are
    dropped from the (returned, not yet saved) baseline."""
    plan = FixPlan()

    by_path: dict[str, list[Finding]] = {}
    for f in result.fixable:
        by_path.setdefault(f.path, []).append(f)
    supp_by_path: dict[str, list[dict]] = {}
    for s in result.unused_suppressions:
        supp_by_path.setdefault(s["path"], []).append(s)

    for relpath in sorted(set(by_path) | set(supp_by_path)):
        path = os.path.join(root, relpath)
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            plan.skipped.append((relpath, f"unreadable: {e}"))
            continue
        file_fix = FileFix(path=path, relpath=relpath, old_source=source,
                           new_source=source)
        accepted: list[Edit] = []
        for finding in sorted(by_path.get(relpath, ()),
                              key=lambda f: (f.line, f.col)):
            fix = finding.fix
            assert fix is not None
            try:
                clash = edits_overlap(source, accepted, list(fix.edits))
            except ValueError as e:
                file_fix.skipped.append(
                    f"{finding.rule} at {relpath}:{finding.line}: "
                    f"bad edit span ({e})"
                )
                continue
            if clash:
                file_fix.skipped.append(
                    f"{finding.rule} at {relpath}:{finding.line}: "
                    "overlaps an earlier fix — refused, re-run --fix "
                    "after applying"
                )
                continue
            accepted.extend(fix.edits)
            file_fix.applied.append(
                f"{finding.rule} {relpath}:{finding.line}: "
                f"{fix.description}"
            )
        for edit, desc in suppression_edits(
            source, supp_by_path.get(relpath, [])
        ):
            if edits_overlap(source, accepted, [edit]):
                file_fix.skipped.append(
                    f"stale suppression at {relpath}:{edit.line}: "
                    "overlaps an earlier fix — refused"
                )
                continue
            accepted.extend([edit])
            file_fix.applied.append(f"{relpath}:{edit.line}: {desc}")
        if not accepted:
            if file_fix.skipped:
                plan.skipped.extend(("", s) for s in file_fix.skipped)
            continue
        new_source = apply_edits(source, accepted)
        try:
            ast.parse(new_source, filename=relpath)
        except SyntaxError as e:
            plan.skipped.append((
                relpath,
                f"fixed source no longer parses ({e.msg} at line "
                f"{e.lineno}) — file reverted, nothing applied",
            ))
            continue
        file_fix.new_source = new_source
        plan.files.append(file_fix)
        plan.skipped.extend((relpath, s) for s in file_fix.skipped)

    if baseline is not None and result.stale_baseline:
        remaining = []
        removed = 0
        stale_by_key = {
            (e["rule"], e["path"], e["context"]): int(e.get("unfired", 0))
            for e in result.stale_baseline
        }
        for e in baseline.entries:
            key = (e["rule"], e["path"], e["context"])
            unfired = stale_by_key.get(key, 0)
            if unfired <= 0:
                remaining.append(e)
                continue
            count = int(e.get("count", 1))
            take = min(count, unfired)
            stale_by_key[key] = unfired - take
            removed += take
            if count - take > 0:
                remaining.append(dict(e, count=count - take))
        if removed:
            plan.stale_baseline_removed = removed
            plan.baseline = Baseline(entries=remaining, path=baseline.path)
    return plan


def write_plan(plan: FixPlan) -> None:
    """Apply a plan to disk: rewrite each fixed file, save the trimmed
    baseline. (Dry-run callers print :meth:`FileFix.diff` instead.)"""
    for file_fix in plan.files:
        with open(file_fix.path, "w", encoding="utf-8") as fh:
            fh.write(file_fix.new_source)
    if plan.baseline is not None:
        plan.baseline.save()
