"""graftlint: JAX/TPU-aware static analysis for this repo.

Usage: ``python -m cst_captioning_tpu.tools.graftlint [paths]`` — see
:mod:`cst_captioning_tpu.tools.graftlint.cli` for flags, ``--list-rules``
for the rule table, and the README "Static analysis" section for rationale,
suppression syntax (``# graftlint: disable=GL00X``), baseline workflow,
and the ``--fix`` / ``--fix-check`` autofix modes (:mod:`fixes`).
"""

from cst_captioning_tpu.tools.graftlint.core import (
    Baseline,
    Edit,
    FileContext,
    Finding,
    Fix,
    LintResult,
    ProjectRule,
    Rule,
    all_rules,
    find_repo_root,
    lint_paths,
    register,
)
from cst_captioning_tpu.tools.graftlint.project import ProjectIndex

__all__ = [
    "Baseline",
    "Edit",
    "FileContext",
    "Finding",
    "Fix",
    "LintResult",
    "ProjectIndex",
    "ProjectRule",
    "Rule",
    "all_rules",
    "find_repo_root",
    "lint_paths",
    "register",
]
