"""graftlint whole-program pass: the project index.

Pass 1 of the two-pass engine (see :mod:`core`): every module under the lint
paths is summarized into a :class:`ProjectIndex` — a project-wide symbol
table that the interprocedural ``ProjectRule``s (GL013/GL014/GL015) and the
mesh-aware per-file rules (GL012, GL007) query during pass 2.

What the index knows:

- **modules** — dotted-name → :class:`ModuleSummary` (path, import-alias
  map, function table), with suffix-based lookup so both package-absolute
  (``cst_captioning_tpu.rl.scst.rollout``) and fixture-local (``producer.f``)
  callee names resolve.
- **function summaries** — per top-level function/method: which parameters
  are consumed as PRNG keys (directly or transitively through callees),
  whether the return value's provenance traces to device arrays (jnp/lax/
  random producers, traced functions, device-returning callees — resolved
  by a global fixpoint over the call graph), and whether a generator yields
  device-placed values (the ``prefetch_to_device`` pattern: stages via
  ``jax.device_put``, then yields).
- **mesh declaration** — the axes and PARAM_PARTITION_RULES families
  declared by ``<root>/cst_captioning_tpu/train/mesh.py``, scraped once per
  run (GL012's old module-level cache is gone: a long-lived test session
  re-scrapes whenever the index is rebuilt, and the on-disk cache below is
  mtime-keyed).
- **axis environments** — every def (nested ones included, unlike the
  function table above) is scanned for named-axis *bindings*
  (``shard_map``/``vmap(axis_name=)``/``pmap(axis_name=)`` applications,
  with ``axis_names=`` literals when spelled, all declared mesh axes
  otherwise) and for collective calls with literal axis names; an abstract
  interpretation over the call graph then computes, per function, the set
  of axes bound in at least one reachable calling context. GL016 reads the
  result: a collective over a *declared* mesh axis that no reachable caller
  binds is out of scope at runtime, something GL012's literal-vs-mesh check
  cannot know.
- **donation facts** — which argument positions a function donates when
  called (``@partial(jax.jit, donate_argnums=literal)``), whether calling
  it returns a donating callable (the ``make_*_step`` factory pattern), and
  which of its own params it forwards into a donated position of a
  donating callee (a *wrapper* whose donation an outer ``jit`` would
  silently drop) — all propagated through the same fixpoint for GL017.
- **shape-sharding environment** — per-function abstract shape/dtype/
  sharding facts: array dims recorded at literal constructors
  (``jnp.zeros((4, 128))``) and ``.shape``-unpacking sites, dtype
  provenance through ``astype``/``dtype=`` bindings (the bf16-on-the-wire
  casts in ``parallel/comms.py``), PartitionSpec literal bindings, and a
  per-host taint: whether a value (and hence any shape or wire dtype
  derived from it) depends on this process's identity
  (``jax.process_index()``, ``len(jax.local_devices())``,
  ``process_index``-conditional branches). Results of
  ``process_allgather``-style collectives are globally consistent and
  CLEANSE the taint. Whether a function's *return* has a host-dependent
  shape propagates through the same cross-module fixpoint; GL019 reads
  the result at every collective site reachable from ``train/multihost.py``
  or the comms bucket path (``index.multihost_reach``).
- **on-disk summary cache** — ``<root>/.graftlint_cache.json`` keyed by
  ``(mtime, size)`` per file, so repeat ``lint.sh`` runs skip re-parsing
  unchanged modules in pass 1. Summaries are cached PRE-fixpoint; the
  cross-module fixpoint is recomputed every run (it is global and cheap).
  The schema version gates the whole cache: adding summary fields bumps
  ``_CACHE_VERSION`` and an old cache file is discarded wholesale (a cold
  start, never a half-read).

Everything here is stdlib-``ast`` only — no JAX import, no backend init.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from dataclasses import dataclass, field

# ---- shared AST helpers (canonical home; rules.py re-exports) ---------------

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# call-position names that trace their function arguments into XLA programs
_TRACERS = {
    "jit", "pjit", "shard_map", "scan", "while_loop", "fori_loop", "cond",
    "switch", "vmap", "pmap", "grad", "value_and_grad", "vjp", "jvp",
    "checkpoint", "remat", "custom_vjp", "custom_jvp", "associative_scan",
}


def _dotted(node: ast.AST) -> str:
    """'jax.lax.scan' for a Name/Attribute chain, '' when not one."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _last(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _decorator_traces(dec: ast.AST) -> bool:
    """True for @jax.jit / @pjit / @functools.partial(jax.jit, ...) style."""
    if isinstance(dec, ast.Call):
        d = _dotted(dec.func)
        if _last(d) == "partial" and dec.args:
            return _last(_dotted(dec.args[0])) in _TRACERS
        return _last(d) in _TRACERS
    return _last(_dotted(dec)) in _TRACERS


# dotted-prefix bases whose call results live on device
_DEVICE_BASES = (
    "jax.numpy.", "jax.lax.", "jax.random.", "jax.nn.", "jax.scipy.",
)
_DEVICE_EXACT = {"jax.device_put", "jax.make_array_from_process_local_data"}
# results known to be HOST values (the explicit-readback spelling)
_HOST_EXACT = {"jax.device_get", "float", "int", "len", "str", "bool"}
_HOST_BASES = ("numpy.",)

# jax.random consumers: a key passed here is spent
_KEY_CONSUMERS = {
    "categorical", "normal", "uniform", "bernoulli", "gumbel", "choice",
    "permutation", "randint", "bits", "exponential", "laplace",
    "truncated_normal", "dirichlet", "beta", "gamma", "poisson", "shuffle",
}

# collective -> positional index of its axis-name argument (canonical home;
# GL012 and the axis-environment scan share it)
COLLECTIVE_AXIS_POS = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "all_to_all": 1, "ppermute": 1, "pshuffle": 1,
    "pbroadcast": 1, "pcast": 1, "axis_index": 0,
}
COLLECTIVE_AXIS_KWARGS = ("axis_name",)

# call-position names that bind named axes for the function they wrap
_AXIS_BINDERS = {"shard_map", "vmap", "pmap"}

# resolved dotted calls whose RESULT differs per host (per-process): the
# seeds of the GL019 host-taint. jax.devices()/jax.process_count() are
# deliberately absent — they are globally consistent.
PER_HOST_CALLS = {
    "jax.process_index",
    "jax.local_device_count",
    "jax.local_devices",
    "jax.addressable_devices",
}
# resolved dotted calls whose result is GLOBALLY CONSISTENT even when fed
# per-host values: the collective itself synchronizes, so its result (and
# anything derived from it, e.g. a gathered-lengths ``.max()``) is safe to
# size buffers with. These cleanse the host taint.
GLOBALLY_CONSISTENT_CALLS = {
    "jax.experimental.multihost_utils.process_allgather",
    "jax.experimental.multihost_utils.broadcast_one_to_all",
    "multihost_utils.process_allgather",
    "multihost_utils.broadcast_one_to_all",
}
# array constructors whose FIRST argument (or ``shape=``) is the shape —
# a host-tainted dim expression here makes the array's shape per-host
_SHAPE_CTORS = {"zeros", "ones", "full", "empty"}

# PartitionSpec constructor names (resolved) for the pspec-binding scrape
_PSPEC_TYPES = {
    "jax.sharding.PartitionSpec",
    "jax.experimental.pjit.PartitionSpec",
    "jax.interpreters.pxla.PartitionSpec",
}

_DONATE_KWARGS = ("donate_argnums", "donate_argnames")


def _literal_str_tuple(node: ast.AST | None) -> tuple[str, ...] | None:
    """('data', 'seq') for a string constant or tuple/list/set of string
    constants; None when absent or not fully literal (never guess)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _literal_int_tuple(node: ast.AST | None) -> tuple[int, ...] | None:
    """(0, 2) for an int constant or tuple/list of int constants; None for
    anything dynamic — ``(0,) if donate else ()`` stays out of scope."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def donation_of_call(call: ast.Call) -> tuple[int, ...] | None:
    """Donated argnums of a ``jax.jit``/``pjit`` call node, when literal.

    Returns None when the call is not a jit, carries no donate kwargs, or
    the donation expression is dynamic. ``donate_argnames`` cannot be
    resolved without the target's signature — callers that have it resolve
    names themselves; here only ``donate_argnums`` literals count."""
    if _last(_dotted(call.func)) not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _literal_int_tuple(kw.value)
    return None


def _decorator_donation(dec: ast.AST,
                        params: list[str]) -> tuple[int, ...] | None:
    """Donated argnums declared by a jit decorator, when literal.

    ``@partial(jax.jit, donate_argnums=(0,))`` and the direct-call form;
    ``donate_argnames`` resolves against ``params`` (the decorated def's
    own signature is in hand). Dynamic expressions -> None."""
    if not isinstance(dec, ast.Call):
        return None
    d = _dotted(dec.func)
    is_jit = _last(d) in ("jit", "pjit") or (
        _last(d) == "partial" and dec.args
        and _last(_dotted(dec.args[0])) in ("jit", "pjit")
    )
    if not is_jit:
        return None
    for kw in dec.keywords:
        if kw.arg == "donate_argnums":
            return _literal_int_tuple(kw.value)
        if kw.arg == "donate_argnames":
            names = _literal_str_tuple(kw.value)
            if names is None:
                return None
            try:
                return tuple(params.index(n) for n in names)
            except ValueError:
                return None
    return None


def module_name_for(relpath: str) -> str:
    """Dotted module name for a repo-relative posix path."""
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def import_aliases(tree: ast.Module, module_name: str) -> dict[str, str]:
    """Local name -> canonical dotted target, from the module's imports.

    ``import numpy as np`` -> ``{'np': 'numpy'}``; ``from jax.sharding
    import PartitionSpec as P`` -> ``{'P': 'jax.sharding.PartitionSpec'}``;
    relative imports resolve against ``module_name``'s package.
    """
    pkg = module_name.split(".")[:-1]
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    top = a.name.split(".", 1)[0]
                    out[top] = top
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base_parts = pkg[: len(pkg) - (node.level - 1)]
                base = ".".join(
                    base_parts + ([node.module] if node.module else [])
                )
            else:
                base = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                target = f"{base}.{a.name}" if base else a.name
                out[a.asname or a.name] = target
    return out


def resolve_dotted(dotted: str, aliases: dict[str, str]) -> str:
    """Expand the first segment of a dotted name through the alias map."""
    if not dotted:
        return dotted
    first, _, rest = dotted.partition(".")
    base = aliases.get(first)
    if base is None:
        return dotted
    return f"{base}.{rest}" if rest else base


# ---- per-function summaries -------------------------------------------------

@dataclass
class CallSite:
    """One call to a (possibly cross-module) function, with the caller
    params forwarded at each argument position — the call-graph edge."""

    callee: str                      # resolved dotted name
    lineno: int
    arg_params: list[str | None] = field(default_factory=list)
    kw_params: dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "CallSite":
        return cls(**d)


@dataclass
class FunctionSummary:
    """What callers may rely on about one function, without reading it."""

    qualname: str                    # module-relative, e.g. "Trainer.fit"
    lineno: int
    params: list[str] = field(default_factory=list)
    # params consumed directly as PRNG keys (arg0 / key= of a jax.random
    # consumer) — transitive consumption is added by the index fixpoint
    key_params_consumed: list[str] = field(default_factory=list)
    # where each consumed param is spent: param -> "jax.random.normal" or
    # (post-fixpoint) the consuming callee's dotted name
    key_consumed_via: dict[str, str] = field(default_factory=dict)
    returns_device: bool = False
    device_reason: str = ""          # human chain: why the return is device
    yields_device: bool = False
    traced: bool = False             # jit/pjit-decorated
    # callees whose result this function returns (pre-fixpoint pending set)
    returns_calls: list[str] = field(default_factory=list)
    calls: list[CallSite] = field(default_factory=list)
    # -- donation facts (GL017) --
    # arg positions THIS function donates when called (a literal
    # @partial(jax.jit, donate_argnums=...) decoration)
    donated_argnums: list[int] = field(default_factory=list)
    # calling this function returns a callable donating these positions
    # (the jitted-step factory pattern); fixpoint propagates through
    # factories-of-factories via returns_calls
    returns_donating: list[int] = field(default_factory=list)
    # own param positions forwarded into a donated position of a donating
    # callee — a wrapper whose donation an outer jit() would silently drop;
    # the human chain for each position lives in forwards_donated_via
    forwards_donated: list[int] = field(default_factory=list)
    forwards_donated_via: dict[str, str] = field(default_factory=dict)
    # -- shape-sharding environment (GL019 substrate, cache schema v5) --
    # local var -> abstract dims recorded at a literal constructor binding
    # (ints, ".shape"-derived tokens like "memory.shape[0]", or "?" for a
    # dim the walker cannot resolve)
    array_dims: dict[str, list] = field(default_factory=dict)
    # local var -> the ".shape" source it unpacks ("B, M, E = memory.shape"
    # records B -> "memory.shape[0]", ...)
    dim_vars: dict[str, str] = field(default_factory=dict)
    # local var -> dtype name bound via astype(...)/dtype= (dtype
    # provenance: the comms bf16-on-the-wire cast records "bfloat16")
    dtype_env: dict[str, str] = field(default_factory=dict)
    # local var -> PartitionSpec literal axes (None entries for replicated
    # dims), from ``spec = P('data', None)``-style bindings
    pspec_vars: dict[str, list] = field(default_factory=dict)
    # abstract dims of the returned expression, when derivable
    return_dims: list | None = None
    return_dtype: str = ""
    # the return value's SHAPE (or wire dtype) depends on per-host values —
    # seeded intraprocedurally, propagated through returns_calls by the
    # fixpoint (like returns_device)
    returns_host_shape: bool = False
    host_shape_reason: str = ""
    # the return VALUE differs per host (e.g. host_shard() returning
    # process_index) — callers sizing buffers with it inherit the taint
    returns_host_value: bool = False
    host_value_reason: str = ""

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["calls"] = [c.to_dict() for c in self.calls]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FunctionSummary":
        d = dict(d)
        d["calls"] = [CallSite.from_dict(c) for c in d.get("calls", [])]
        return cls(**d)


@dataclass
class AxisFuncInfo:
    """Axis-relevant view of ONE def — nested defs included, each its own
    entry (unlike the function table, which stops at methods): the
    collectives it calls with literal axis names, its direct callees, and
    its lexical parent. The index's axis fixpoint runs over these."""

    qualname: str                    # dot-joined path, e.g. "make_step.step"
    lineno: int
    parent: str = ""                 # lexical parent qualname ("" = module)
    # (collective name, literal axis, lineno, col)
    collectives: list = field(default_factory=list)
    # resolved dotted callee names called directly in this def's body
    calls: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "AxisFuncInfo":
        d = dict(d)
        d["collectives"] = [tuple(c) for c in d.get("collectives", [])]
        return cls(**d)


@dataclass
class AxisBinding:
    """One named-axis binding application: ``shard_map(target, ...)`` /
    ``vmap(target, axis_name=...)`` / ``pmap(target, axis_name=...)``.
    ``axes is None`` means "every declared mesh axis" (a ``shard_map``
    with no literal ``axis_names=`` — the mesh argument is dynamic, and
    shard_map makes all of its axes manual)."""

    owner: str                       # enclosing def qualname ("" = module)
    target: str                      # alias-resolved dotted name of bound fn
    axes: list | None = None
    lineno: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "AxisBinding":
        return cls(**d)


@dataclass
class ModuleSummary:
    module: str                      # dotted name
    relpath: str
    mtime: float = 0.0
    size: int = 0
    aliases: dict[str, str] = field(default_factory=dict)
    functions: dict[str, FunctionSummary] = field(default_factory=dict)
    axis_funcs: dict[str, AxisFuncInfo] = field(default_factory=dict)
    axis_bindings: list[AxisBinding] = field(default_factory=list)
    parse_error: bool = False

    def to_dict(self) -> dict:
        return {
            "module": self.module,
            "relpath": self.relpath,
            "mtime": self.mtime,
            "size": self.size,
            "aliases": self.aliases,
            "functions": {
                k: f.to_dict() for k, f in self.functions.items()
            },
            "axis_funcs": {
                k: a.to_dict() for k, a in self.axis_funcs.items()
            },
            "axis_bindings": [b.to_dict() for b in self.axis_bindings],
            "parse_error": self.parse_error,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ModuleSummary":
        out = cls(
            module=d["module"], relpath=d["relpath"],
            mtime=d.get("mtime", 0.0), size=d.get("size", 0),
            aliases=dict(d.get("aliases", {})),
            parse_error=bool(d.get("parse_error", False)),
        )
        out.functions = {
            k: FunctionSummary.from_dict(f)
            for k, f in d.get("functions", {}).items()
        }
        out.axis_funcs = {
            k: AxisFuncInfo.from_dict(a)
            for k, a in d.get("axis_funcs", {}).items()
        }
        out.axis_bindings = [
            AxisBinding.from_dict(b) for b in d.get("axis_bindings", [])
        ]
        return out


# repo-local wrapper names whose results are also globally consistent (they
# sit directly on process_allgather); matched by last segment because the
# lazy in-function imports defeat full dotted resolution at some call sites
GLOBALLY_CONSISTENT_LASTS = {
    "process_allgather", "broadcast_one_to_all", "allgather_pyobj",
    "broadcast_pyobj", "allgather_to_host", "global_scalar_mean",
    "global_weighted_mean",
}

class HostTaint:
    """Abstract shape/dtype/per-host-taint environment over ONE function
    body, in source order.

    Tracks, per local name: whether its VALUE differs per host (seeded by
    :data:`PER_HOST_CALLS`, spread by containment, cleansed by
    :data:`GLOBALLY_CONSISTENT_CALLS`), whether its SHAPE or wire dtype
    does (constructor dims / ``astype`` / ragged slice bounds built from
    per-host values), abstract array dims at literal constructors,
    ``.shape``-unpack dim sources, dtype provenance, and PartitionSpec
    literal bindings.

    Used twice: the pass-1 summarizer runs it WITHOUT cross-module
    resolution (``lookup=None``) to seed the cached per-function facts;
    GL019 re-runs it at rule time with ``lookup`` wired to the project
    index, so calls to functions whose summaries say
    ``returns_host_value``/``returns_host_shape`` taint their results."""

    def __init__(self, aliases: dict[str, str], lookup=None,
                 may_host: bool = True):
        self.aliases = aliases
        self.lookup = lookup       # dotted -> FunctionSummary | None
        # cheap pass-1 gate: a module that never names a per-host API (and
        # has no index to resolve callees through) cannot seed host taint,
        # so the per-bind taint walks can short-circuit
        self.may_host = may_host or lookup is not None
        self.host_vals: dict[str, str] = {}
        self.host_shapes: dict[str, str] = {}
        self.var_dims: dict[str, list] = {}
        self.dim_vars: dict[str, str] = {}
        self.dtype_env: dict[str, str] = {}
        self.pspec_vars: dict[str, list] = {}

    # -- queries --------------------------------------------------------

    def _callee(self, resolved: str):
        if self.lookup is None or not resolved or \
                resolved.startswith(("jax.", "numpy.")):
            return None
        return self.lookup(resolved)

    def value_taint(self, expr: ast.AST | None) -> str:
        """Why ``expr``'s VALUE differs per host ('' = no known reason)."""
        if expr is None or not self.may_host:
            return ""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNC_NODES + (ast.Lambda,)):
                continue  # separate scope
            if isinstance(node, ast.Call):
                resolved = resolve_dotted(_dotted(node.func), self.aliases)
                if resolved in PER_HOST_CALLS:
                    return f"{resolved}()"
                if resolved in GLOBALLY_CONSISTENT_CALLS or \
                        _last(resolved) in GLOBALLY_CONSISTENT_LASTS:
                    continue  # synchronized result: args don't leak out
                target = self._callee(resolved)
                if target is not None and target.returns_host_value:
                    return (f"{resolved}() → "
                            f"{target.host_value_reason or 'per-host value'}")
            if isinstance(node, ast.Name) and node.id in self.host_vals:
                return self.host_vals[node.id]
            stack.extend(ast.iter_child_nodes(node))
        return ""

    def shape_taint(self, expr: ast.AST | None) -> str:
        """Why ``expr``'s SHAPE or wire dtype differs per host ('' = no
        provable reason — unknown shapes stay quiet, never guess)."""
        if expr is None or not self.may_host:
            return ""
        if isinstance(expr, ast.Name):
            return self.host_shapes.get(expr.id, "")
        if isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                t = self.shape_taint(e)
                if t:
                    return t
            return ""
        if isinstance(expr, ast.BinOp):
            return self.shape_taint(expr.left) or \
                self.shape_taint(expr.right)
        if isinstance(expr, ast.IfExp):
            t = self.value_taint(expr.test)
            if t:
                return f"shape chosen by a branch on {t}"
            return self.shape_taint(expr.body) or \
                self.shape_taint(expr.orelse)
        if isinstance(expr, ast.Subscript):
            sl = expr.slice
            bounds: list = []
            if isinstance(sl, ast.Slice):
                bounds = [b for b in (sl.lower, sl.upper, sl.step)
                          if b is not None]
            for b in bounds:
                t = self.value_taint(b)
                if t:
                    return f"ragged slice bound from {t}"
            return self.shape_taint(expr.value)
        if isinstance(expr, ast.Call):
            resolved = resolve_dotted(_dotted(expr.func), self.aliases)
            last = _last(resolved)
            if last in _SHAPE_CTORS:
                shape_arg = expr.args[0] if expr.args else None
                for kw in expr.keywords:
                    if kw.arg == "shape":
                        shape_arg = kw.value
                t = self.value_taint(shape_arg)
                if t:
                    return f"constructor shape built from {t}"
                return self._dtype_kwarg_taint(expr)
            if last == "astype":
                t = self.value_taint(expr.args[0]) if expr.args else ""
                if t:
                    return f"wire dtype chosen by {t}"
                if isinstance(expr.func, ast.Attribute):
                    return self.shape_taint(expr.func.value)
                return ""
            if last == "reshape":
                for a in expr.args:
                    t = self.value_taint(a)
                    if t:
                        return f"reshaped to a size from {t}"
                if isinstance(expr.func, ast.Attribute):
                    return self.shape_taint(expr.func.value)
                return ""
            if last in ("concatenate", "stack", "vstack", "hstack",
                        "asarray", "array"):
                for a in expr.args:
                    t = self.shape_taint(a)
                    if t:
                        return t
                return self._dtype_kwarg_taint(expr)
            if last == "pad":
                for a in expr.args[1:]:
                    t = self.value_taint(a)
                    if t:
                        return f"pad widths from {t}"
                return self.shape_taint(expr.args[0]) if expr.args else ""
            target = self._callee(resolved)
            if target is not None and target.returns_host_shape:
                return (f"{resolved}() returns a per-host shape "
                        f"({target.host_shape_reason or 'see its body'})")
            return ""
        return ""

    def _dtype_kwarg_taint(self, call: ast.Call) -> str:
        for kw in call.keywords:
            if kw.arg == "dtype":
                t = self.value_taint(kw.value)
                if t:
                    return f"dtype chosen by {t}"
        return ""

    def dims_of(self, expr: ast.AST | None) -> list | None:
        """Abstract dims of ``expr``: ints for literal constructor dims,
        ``.shape``-derived tokens for named dims, '?' for unresolved,
        'host:<why>' for per-host dims. None = not an array the walker
        can size."""
        if expr is None:
            return None
        if isinstance(expr, ast.Name):
            return self.var_dims.get(expr.id)
        if isinstance(expr, ast.Call):
            resolved = resolve_dotted(_dotted(expr.func), self.aliases)
            last = _last(resolved)
            if last in _SHAPE_CTORS or last == "reshape":
                if last == "reshape":
                    shape_elts = list(expr.args)
                else:
                    shape_arg = expr.args[0] if expr.args else None
                    for kw in expr.keywords:
                        if kw.arg == "shape":
                            shape_arg = kw.value
                    if shape_arg is None:
                        return None
                    if isinstance(shape_arg, (ast.Tuple, ast.List)):
                        shape_elts = list(shape_arg.elts)
                    else:
                        shape_elts = [shape_arg]
                out: list = []
                for e in shape_elts:
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, int
                    ):
                        out.append(e.value)
                    elif isinstance(e, ast.Name) and e.id in self.dim_vars:
                        out.append(self.dim_vars[e.id])
                    else:
                        t = self.value_taint(e)
                        out.append(f"host:{t}" if t else "?")
                return out
            if last in ("astype", "asarray", "array") and expr.args:
                base = expr.args[0] if last != "astype" else (
                    expr.func.value
                    if isinstance(expr.func, ast.Attribute) else None
                )
                return self.dims_of(base)
            target = self._callee(resolved)
            if target is not None and target.return_dims is not None:
                return list(target.return_dims)
            return None
        return None

    def dtype_of(self, expr: ast.AST | None) -> str:
        """Dtype name bound by ``expr`` ('' = unknown): ``x.astype(d)``,
        a ``dtype=`` constructor kwarg, or a callee's return dtype."""
        if expr is None:
            return ""
        if isinstance(expr, ast.Name):
            return self.dtype_env.get(expr.id, "")
        if not isinstance(expr, ast.Call):
            return ""
        resolved = resolve_dotted(_dotted(expr.func), self.aliases)
        last = _last(resolved)
        if last == "astype" and expr.args:
            return self._dtype_name(expr.args[0])
        for kw in expr.keywords:
            if kw.arg == "dtype":
                return self._dtype_name(kw.value)
        target = self._callee(resolved)
        if target is not None and target.return_dtype:
            return target.return_dtype
        return ""

    def _dtype_name(self, node: ast.AST) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        d = _last(_dotted(node))
        return d if d else ""

    def pspec_of(self, expr: ast.AST | None) -> list | None:
        """PartitionSpec literal axes, or None when not a spec literal."""
        if not isinstance(expr, ast.Call):
            return None
        resolved = resolve_dotted(_dotted(expr.func), self.aliases)
        if resolved not in _PSPEC_TYPES:
            return None
        out: list = []
        for a in expr.args:
            if isinstance(a, ast.Constant):
                out.append(a.value if isinstance(a.value, str) else None)
            elif isinstance(a, (ast.Tuple, ast.List)):
                out.append([
                    e.value for e in a.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                ])
            else:
                out.append("?")
        return out

    # -- binding --------------------------------------------------------

    def bind(self, names: list[str], value: ast.AST) -> None:
        """Rebind ``names`` (pure Name/tuple targets only — mutations like
        ``x[i] = v`` must not clear what is known about ``x``)."""
        vt = self.value_taint(value)
        st = self.shape_taint(value)
        dims = self.dims_of(value)
        dt = self.dtype_of(value)
        pspec = self.pspec_of(value)
        shape_src = ""
        if isinstance(value, ast.Attribute) and value.attr == "shape":
            shape_src = _dotted(value.value) or "<expr>"
        for n in names:
            self.host_vals.pop(n, None)
            self.host_shapes.pop(n, None)
            self.var_dims.pop(n, None)
            self.dim_vars.pop(n, None)
            self.dtype_env.pop(n, None)
            self.pspec_vars.pop(n, None)
            if vt:
                self.host_vals[n] = vt
            if st:
                self.host_shapes[n] = st
            if dims is not None:
                self.var_dims[n] = dims
            if dt:
                self.dtype_env[n] = dt
            if pspec is not None:
                self.pspec_vars[n] = pspec
        if shape_src and len(names) > 1:
            # B, M, E = memory.shape — each name is a dim of the source
            for i, n in enumerate(names):
                self.dim_vars[n] = f"{shape_src}.shape[{i}]"
        elif len(names) == 1 and isinstance(value, ast.Subscript) and \
                isinstance(value.value, ast.Attribute) and \
                value.value.attr == "shape" and \
                isinstance(value.slice, ast.Constant) and \
                isinstance(value.slice.value, int):
            # n = x.shape[0]
            src = _dotted(value.value.value) or "<expr>"
            self.dim_vars[names[0]] = f"{src}.shape[{value.slice.value}]"

    def taint_branch_stores(self, stmts: list[ast.stmt],
                            reason: str) -> None:
        """Names assigned under a per-host-conditional branch get BOTH
        taints: their value and (potentially) their shape now depend on
        which host is running."""
        why = f"assigned under a branch on {reason}"
        work: list[ast.AST] = list(stmts)
        while work:
            node = work.pop()
            if isinstance(node, _FUNC_NODES + (ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store
            ):
                self.host_vals.setdefault(node.id, why)
                self.host_shapes.setdefault(node.id, why)
            work.extend(ast.iter_child_nodes(node))


class _FunctionSummarizer:
    """Single in-order walk of one function body (nested defs excluded:
    they are separate scopes, summarized — when top-level — on their own)."""

    def __init__(self, fn: ast.AST, qualname: str, aliases: dict[str, str],
                 may_host: bool = True):
        self.fn = fn
        self.aliases = aliases
        args = fn.args
        params = [a.arg for a in args.posonlyargs + args.args
                  + args.kwonlyargs]
        if args.vararg:
            params.append(args.vararg.arg)
        if args.kwarg:
            params.append(args.kwarg.arg)
        self.summary = FunctionSummary(
            qualname=qualname, lineno=fn.lineno, params=params,
            traced=any(_decorator_traces(d) for d in fn.decorator_list),
        )
        for dec in fn.decorator_list:
            donated = _decorator_donation(dec, params)
            if donated:
                self.summary.donated_argnums = sorted(set(donated))
                break
        # local provenance: name -> reason string ("" = device, why)
        self.device_vars: dict[str, str] = {}
        # name -> pending callee (result of an unresolved call)
        self.pending_vars: dict[str, str] = {}
        # name -> donated argnums of the donating jit bound to it
        self.donating_vars: dict[str, tuple[int, ...]] = {}
        self.has_device_put = False
        self.yields_any = False
        # shape-sharding environment (GL019 substrate), local-only here:
        # cross-module resolution happens in the fixpoint / at rule time
        self.shapes = HostTaint(aliases, may_host=may_host)

    def run(self) -> FunctionSummary:
        for stmt in self.fn.body:
            self._stmt(stmt)
        if self.summary.traced:
            self.summary.returns_device = True
            self.summary.device_reason = "jit-traced function"
        if self.yields_any and self.has_device_put and not \
                self.summary.yields_device:
            # the prefetch pattern: stages via device_put, yields the result
            # through a queue the walker cannot see through
            self.summary.yields_device = True
            self.summary.device_reason = (
                self.summary.device_reason
                or "generator stages values via jax.device_put"
            )
        # export the shape-sharding environment (capped: the cache must
        # stay small, and huge functions bound the fixpoint's working set)
        env = self.shapes
        self.summary.array_dims = dict(list(env.var_dims.items())[:32])
        self.summary.dim_vars = dict(list(env.dim_vars.items())[:32])
        self.summary.dtype_env = dict(list(env.dtype_env.items())[:32])
        self.summary.pspec_vars = dict(list(env.pspec_vars.items())[:32])
        return self.summary

    # -- statement walk, in source order --------------------------------

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, _FUNC_NODES + (ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.Assign):
            self._visit_expr(node.value)
            self._bind(node.targets, node.value)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if node.value is not None:
                self._visit_expr(node.value)
                self._bind([node.target], node.value)
        elif isinstance(node, ast.Return) and node.value is not None:
            self._visit_expr(node.value)
            self._note_return(node.value)
        elif isinstance(node, ast.Expr):
            self._visit_expr(node.value)
        elif isinstance(node, ast.For):
            self._visit_expr(node.iter)
            for stmt in node.body + node.orelse:
                self._stmt(stmt)
            return
        elif isinstance(node, ast.If):
            self._visit_expr(node.test)
            for stmt in node.body + node.orelse:
                self._stmt(stmt)
            # anything assigned under a per-host conditional (e.g. an
            # `if jax.process_index() == 0:` branch) is per-host itself
            reason = self.shapes.value_taint(node.test)
            if reason:
                self.shapes.taint_branch_stores(
                    node.body + node.orelse, reason
                )
            return
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._visit_expr(child)
                else:
                    self._stmt(child)
            return

    def _bind(self, targets: list[ast.AST], value: ast.AST) -> None:
        names: list[str] = []
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    names.append(sub.id)
        # shape env rebinds only on pure name targets: `x[i] = v` mutates
        # x's contents, not its shape, and must not clear what is known
        rebinds: list[str] = []
        for t in targets:
            if isinstance(t, ast.Name):
                rebinds.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    inner = e.value if isinstance(e, ast.Starred) else e
                    if isinstance(inner, ast.Name):
                        rebinds.append(inner.id)
        if rebinds:
            self.shapes.bind(rebinds, value)
        prov, reason, pending = self._provenance(value)
        donated = donation_of_call(value) if isinstance(value, ast.Call) \
            else None
        for n in names:
            self.device_vars.pop(n, None)
            self.pending_vars.pop(n, None)
            self.donating_vars.pop(n, None)
            if prov:
                self.device_vars[n] = reason
            elif pending:
                self.pending_vars[n] = pending
            if donated:
                self.donating_vars[n] = donated

    def _note_return(self, expr: ast.AST) -> None:
        prov, reason, pending = self._provenance(expr)
        if prov and not self.summary.returns_device:
            self.summary.returns_device = True
            self.summary.device_reason = reason
        elif pending and pending not in self.summary.returns_calls:
            self.summary.returns_calls.append(pending)
        donated = None
        if isinstance(expr, ast.Call):
            donated = donation_of_call(expr)
        elif isinstance(expr, ast.Name):
            donated = self.donating_vars.get(expr.id)
        if donated:
            self.summary.returns_donating = sorted(
                set(self.summary.returns_donating) | set(donated)
            )
        # shape-sharding return facts (first reason wins)
        if not self.summary.returns_host_shape:
            st = self.shapes.shape_taint(expr)
            if st:
                self.summary.returns_host_shape = True
                self.summary.host_shape_reason = st
        if not self.summary.returns_host_value:
            vt = self.shapes.value_taint(expr)
            if vt:
                self.summary.returns_host_value = True
                self.summary.host_value_reason = vt
        if self.summary.return_dims is None:
            dims = self.shapes.dims_of(expr)
            if dims is not None:
                self.summary.return_dims = dims
        if not self.summary.return_dtype:
            self.summary.return_dtype = self.shapes.dtype_of(expr)

    # -- expression analysis --------------------------------------------

    def _visit_expr(self, expr: ast.AST) -> None:
        """Record key consumption, call-graph edges, and yields inside an
        expression (single traversal)."""
        for node in ast.walk(expr):
            if isinstance(node, (ast.Yield, ast.YieldFrom)):
                self.yields_any = True
                if isinstance(node, ast.Yield) and node.value is not None:
                    prov, reason, _ = self._provenance(node.value)
                    if prov:
                        self.summary.yields_device = True
                        self.summary.device_reason = reason
                continue
            if not isinstance(node, ast.Call):
                continue
            resolved = resolve_dotted(_dotted(node.func), self.aliases)
            if resolved in ("jax.device_put",):
                self.has_device_put = True
            if isinstance(node.func, ast.Name) and \
                    node.func.id in self.donating_vars:
                # forwarding an own param into a donated position of a
                # locally-built donating jit: this function is a donation
                # WRAPPER — an outer jit() around it drops the donation
                for pos in self.donating_vars[node.func.id]:
                    if pos < len(node.args) and isinstance(
                        node.args[pos], ast.Name
                    ) and node.args[pos].id in self.summary.params:
                        own = self.summary.params.index(node.args[pos].id)
                        if own not in self.summary.forwards_donated:
                            self.summary.forwards_donated.append(own)
                            self.summary.forwards_donated_via[str(own)] = (
                                f"a jit(donate_argnums=...) bound to "
                                f"{node.func.id!r} (argument {pos})"
                            )
            base, _, attr = resolved.rpartition(".")
            if base == "jax.random" and attr in _KEY_CONSUMERS:
                key_arg = node.args[0] if node.args else None
                for kw in node.keywords:
                    if kw.arg == "key":
                        key_arg = kw.value
                if isinstance(key_arg, ast.Name) and \
                        key_arg.id in self.summary.params:
                    if key_arg.id not in self.summary.key_params_consumed:
                        self.summary.key_params_consumed.append(key_arg.id)
                        self.summary.key_consumed_via[key_arg.id] = resolved
            elif resolved and not resolved.startswith(("jax.", "numpy.")):
                # a call-graph edge for a possibly-indexed callee; param-
                # level arg forwarding recorded for the key fixpoint
                arg_params = [
                    a.id if isinstance(a, ast.Name)
                    and a.id in self.summary.params else None
                    for a in node.args
                ]
                kw_params = {
                    kw.arg: kw.value.id for kw in node.keywords
                    if kw.arg and isinstance(kw.value, ast.Name)
                    and kw.value.id in self.summary.params
                }
                if any(p for p in arg_params) or kw_params:
                    self.summary.calls.append(CallSite(
                        callee=resolved, lineno=node.lineno,
                        arg_params=arg_params, kw_params=kw_params,
                    ))

    def _provenance(self, expr: ast.AST) -> tuple[bool, str, str]:
        """-> (is_device, reason, pending_callee). Conservative: params and
        unknown expressions have no provenance (never guess)."""
        if isinstance(expr, ast.Name):
            if expr.id in self.device_vars:
                return True, self.device_vars[expr.id], ""
            if expr.id in self.pending_vars:
                return False, "", self.pending_vars[expr.id]
            return False, "", ""
        if isinstance(expr, (ast.Attribute, ast.Subscript)):
            # a field/slice of a device value is a device value
            prov, reason, pending = self._provenance(expr.value)
            return prov, reason, pending
        if isinstance(expr, ast.Call):
            # jax.jit(f)(x)-style: the inner call's last segment is a tracer
            if isinstance(expr.func, ast.Call):
                inner = resolve_dotted(_dotted(expr.func.func), self.aliases)
                if _last(inner) in _TRACERS:
                    return True, f"result of {inner}(...)", ""
            resolved = resolve_dotted(_dotted(expr.func), self.aliases)
            if not resolved:
                return False, "", ""
            if resolved in _HOST_EXACT or resolved.startswith(_HOST_BASES):
                return False, "", ""
            if resolved in _DEVICE_EXACT or \
                    resolved.startswith(_DEVICE_BASES):
                return True, f"result of {resolved}(...)", ""
            if resolved.startswith("jax."):
                return False, "", ""
            return False, "", resolved  # pending on an indexed callee
        if isinstance(expr, ast.BinOp):
            sides = (expr.left, expr.right)
        elif isinstance(expr, (ast.Tuple, ast.List)):
            sides = tuple(expr.elts)
        elif isinstance(expr, ast.IfExp):
            sides = (expr.body, expr.orelse)
        else:
            return False, "", ""
        first_pending = ""
        for side in sides:
            prov, reason, pending = self._provenance(side)
            if prov:
                return True, reason, ""
            if pending and not first_pending:
                first_pending = pending
        return False, "", first_pending


# a module whose source never names one of these cannot seed per-host
# taint locally — the summarizer's taint walks short-circuit there
_PER_HOST_TOKENS = ("process_index", "local_device", "addressable_devices")


def summarize_module(tree: ast.Module, relpath: str,
                     source: str = "") -> ModuleSummary:
    """Pass-1 summary of one parsed module (pure function of the AST;
    ``source``, when given, only gates the host-taint walks cheaply)."""
    module = module_name_for(relpath)
    aliases = import_aliases(tree, module)
    out = ModuleSummary(module=module, relpath=relpath, aliases=aliases)
    may_host = any(t in source for t in _PER_HOST_TOKENS) if source \
        else True

    def visit(body: list[ast.stmt], prefix: str) -> None:
        for node in body:
            if isinstance(node, _FUNC_NODES):
                qual = f"{prefix}{node.name}"
                out.functions[qual] = _FunctionSummarizer(
                    node, qual, aliases, may_host=may_host
                ).run()
            elif isinstance(node, ast.ClassDef):
                visit(node.body, f"{prefix}{node.name}.")

    visit(tree.body, "")
    out.axis_funcs, out.axis_bindings = scan_axis_info(tree, aliases)
    return out


def def_qualnames(tree: ast.Module) -> dict[int, str]:
    """id(def node) -> dot-joined qualname, for EVERY def (nested included,
    classes joined without a marker: ``Trainer.fit.step``) — the naming
    scheme the axis tables use. Rules resolve an AST site back to its
    axis-environment entry through this map."""
    out: dict[int, str] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                qual = f"{prefix}{child.name}"
                out[id(child)] = qual
                visit(child, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def scan_axis_info(
    tree: ast.Module, aliases: dict[str, str]
) -> tuple[dict[str, AxisFuncInfo], list[AxisBinding]]:
    """Collect, for one module: every def's literal-axis collectives and
    direct callees (:class:`AxisFuncInfo`, nested defs included), plus the
    named-axis binding applications (:class:`AxisBinding`). Pure AST."""
    funcs: dict[str, AxisFuncInfo] = {}
    bindings: list[AxisBinding] = []

    def stored_names(func: ast.AST) -> set:
        """Names assigned anywhere in THIS def's body (nested defs have
        their own scope and are skipped) — a rebind makes a string-default
        axis parameter unresolvable, so it must drop out of the env."""
        out: set = set()
        work = list(ast.iter_child_nodes(func))
        while work:
            n = work.pop()
            if isinstance(n, _FUNC_NODES) or isinstance(n, ast.ClassDef):
                continue
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                out.add(n.id)
            work.extend(ast.iter_child_nodes(n))
        return out

    def func_env(node: ast.AST, env: dict) -> dict:
        """Axis environment for one def: inherited name -> axis-string
        entries (closure capture), minus every name this def's parameters
        or assignments shadow, plus this def's own ``axis``-suffixed
        parameters with NON-EMPTY string defaults (the ``axis="data"``
        factory spelling; the empty-string default means "no data axis"
        in the SP factories and resolves to nothing)."""
        args = node.args
        all_args = args.posonlyargs + args.args + args.kwonlyargs + [
            a for a in (args.vararg, args.kwarg) if a is not None
        ]
        stores = stored_names(node)
        shadowed = {a.arg for a in all_args} | stores
        child = {k: v for k, v in env.items() if k not in shadowed}
        pos = args.posonlyargs + args.args
        pairs = list(
            zip(pos[len(pos) - len(args.defaults):], args.defaults)
        ) + [
            (a, d) for a, d in zip(args.kwonlyargs, args.kw_defaults)
            if d is not None
        ]
        for arg, default in pairs:
            if (
                arg.arg.endswith("axis")
                and arg.arg not in stores
                and isinstance(default, ast.Constant)
                and isinstance(default.value, str)
                and default.value
            ):
                child[arg.arg] = default.value
        return child

    def axis_values(arg, env: dict) -> tuple:
        """Axis strings an axis argument resolves to: literals as before,
        plus bare names (or tuple/list elements) that resolve through the
        string-default parameter env; anything else resolves to ()."""
        lit = _literal_str_tuple(arg)
        if lit is not None:
            return lit
        if isinstance(arg, ast.Name):
            val = env.get(arg.id)
            return (val,) if val else ()
        if isinstance(arg, (ast.Tuple, ast.List)):
            out = []
            for e in arg.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append(e.value)
                elif isinstance(e, ast.Name) and env.get(e.id):
                    out.append(env[e.id])
                else:
                    return ()  # one opaque element -> the whole arg is
            return tuple(out)
        return ()

    def binder_axes(call: ast.Call):
        """-> tuple of axes, None (= all mesh axes), or False (no named
        binding here)."""
        name = _last(_dotted(call.func))
        if name == "shard_map":
            for kw in call.keywords:
                if kw.arg == "axis_names":
                    axes = _literal_str_tuple(kw.value)
                    if axes:
                        return axes
                    return None  # dynamic axis_names: assume all mesh axes
            return None
        # vmap/pmap bind one named axis only when axis_name= is spelled
        for kw in call.keywords:
            if kw.arg == "axis_name":
                axes = _literal_str_tuple(kw.value)
                if axes:
                    return axes
        return False

    def handle_call(call: ast.Call, owner: str, env: dict) -> None:
        name = _last(_dotted(call.func))
        pos = COLLECTIVE_AXIS_POS.get(name)
        if pos is not None and owner:
            axis_arg = None
            for kw in call.keywords:
                if kw.arg in COLLECTIVE_AXIS_KWARGS:
                    axis_arg = kw.value
            if axis_arg is None and len(call.args) > pos:
                axis_arg = call.args[pos]
            for ax in axis_values(axis_arg, env):
                funcs[owner].collectives.append(
                    (name, ax, call.lineno, call.col_offset)
                )
        if name in _AXIS_BINDERS and call.args:
            axes = binder_axes(call)
            if axes is not False:
                target = resolve_dotted(_dotted(call.args[0]), aliases)
                if target:
                    bindings.append(AxisBinding(
                        owner=owner, target=target,
                        axes=list(axes) if axes is not None else None,
                        lineno=call.lineno,
                    ))
        elif owner:
            resolved = resolve_dotted(_dotted(call.func), aliases)
            if resolved and not resolved.startswith(("jax.", "numpy.")) \
                    and resolved not in funcs[owner].calls:
                funcs[owner].calls.append(resolved)

    # explicit stack (not recursion): this walk visits every node of every
    # module on a cold run — call overhead is the budget's margin
    stack: list[tuple[ast.AST, str, str, dict]] = [(tree, "", "", {})]
    while stack:
        node, owner, prefix, env = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                qual = f"{prefix}{child.name}"
                funcs[qual] = AxisFuncInfo(
                    qualname=qual, lineno=child.lineno, parent=owner,
                )
                stack.append((child, qual, f"{qual}.", func_env(child, env)))
            elif isinstance(child, ast.ClassDef):
                stack.append((child, owner, f"{prefix}{child.name}.", env))
            else:
                if isinstance(child, ast.Call):
                    handle_call(child, owner, env)
                stack.append((child, owner, prefix, env))
    return funcs, bindings


# ---- mesh declaration (GL012/GL015/GL007 shared scrape) ---------------------

@dataclass
class MeshDecl:
    """What train/mesh.py declares: the single source of truth the
    sharding-surface rules check literals against."""

    axes: frozenset = frozenset({"data", "seq"})
    families: tuple = ()             # ((family, regex), ...)
    contract: str = ""               # SHARDING_CONTRACT value, if declared
    found: bool = False

    def to_dict(self) -> dict:
        return {"axes": sorted(self.axes), "families": list(self.families),
                "contract": self.contract, "found": self.found}

    @classmethod
    def from_dict(cls, d: dict) -> "MeshDecl":
        return cls(
            axes=frozenset(d.get("axes", ("data", "seq"))),
            families=tuple(tuple(f) for f in d.get("families", ())),
            contract=d.get("contract", ""), found=bool(d.get("found")),
        )


MESH_RELPATH = "cst_captioning_tpu/train/mesh.py"
SUBMESH_RELPATH = "cst_captioning_tpu/parallel/submesh.py"
# GL019 seed modules: collectives reachable from these are cross-host
# rendezvous points where every participating host must agree
MULTIHOST_SEED_RELPATHS = (
    "cst_captioning_tpu/train/multihost.py",
    "cst_captioning_tpu/parallel/comms.py",
)


def _axis_param_defaults(tree: ast.Module) -> set[str]:
    """Axis names declared as string defaults of ``*axis``-suffixed
    function parameters (the ``axis="data"`` factory spelling)."""
    axes: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, _FUNC_NODES):
            continue
        args = node.args
        pos = args.posonlyargs + args.args
        pairs = list(
            zip(pos[len(pos) - len(args.defaults):], args.defaults)
        ) + [
            (a, d) for a, d in zip(args.kwonlyargs, args.kw_defaults)
            if d is not None
        ]
        for arg, default in pairs:
            if arg.arg.endswith("axis") and isinstance(
                default, ast.Constant
            ) and isinstance(default.value, str) and default.value:
                axes.add(default.value)
    return axes


def scrape_submesh_axes(tree: ast.Module) -> MeshDecl:
    """SubmeshPlan axis declarations from ``parallel/submesh.py`` —
    axes only, NO default fallback: an empty result merges into the
    mesh decl as a no-op instead of widening it."""
    return MeshDecl(
        axes=frozenset(_axis_param_defaults(tree)), families=(),
        contract="", found=True,
    )


def scrape_mesh_decl(tree: ast.Module) -> MeshDecl:
    """Mesh axes (string defaults of ``*axis`` function parameters), the
    regex rule families of EVERY ``*PARTITION_RULES`` table (the canonical
    one plus the flagship-XL per-axis tables, e.g.
    ``MP_PARAM_PARTITION_RULES``), and the SHARDING_CONTRACT path."""
    axes: set[str] = set(_axis_param_defaults(tree))
    families: list[tuple[str, str]] = []
    contract = ""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            names = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if any(n.endswith("PARTITION_RULES") for n in names):
                for elt in getattr(node.value, "elts", []):
                    parts = getattr(elt, "elts", [])
                    if len(parts) >= 2 and isinstance(
                        parts[0], ast.Constant
                    ) and isinstance(parts[1], ast.Constant):
                        families.append(
                            (str(parts[0].value), str(parts[1].value))
                        )
            if "SHARDING_CONTRACT" in names and isinstance(
                node.value, ast.Constant
            ):
                contract = str(node.value.value)
    return MeshDecl(
        axes=frozenset(axes) if axes else MeshDecl.axes,
        families=tuple(families), contract=contract, found=True,
    )


# ---- the index --------------------------------------------------------------

CACHE_NAME = ".graftlint_cache.json"
# v3: axis-environment tables (axis_funcs/axis_bindings) + donation facts
# (donated_argnums/returns_donating/forwards_donated) joined the summaries.
# v4: collective axes resolve through string-default ``*axis`` parameters
# (the ``axis="data"`` factory spelling), not just call-site literals.
# v5: shape-sharding environment joined the summaries (array_dims /
# dim_vars / dtype_env / pspec_vars / return_dims / return_dtype /
# returns_host_shape / returns_host_value), and parallel/submesh.py axis
# declarations are scraped alongside train/mesh.py.
# v6: the mesh scrape collects families from EVERY *PARTITION_RULES table
# (flagship-XL adds MP_PARAM_PARTITION_RULES), and the 'mp' axis joins the
# declared set via make_mesh's ``mp_axis="mp"`` default.
# A version mismatch discards the cache wholesale — cold start, never a
# half-read of the old schema.
_CACHE_VERSION = 6
_FIXPOINT_MAX_ROUNDS = 25


@dataclass
class IndexStats:
    files: int = 0
    summarized: int = 0
    cached: int = 0


class ProjectIndex:
    """Project-wide symbol table + call-graph summaries (pass 1)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.modules: dict[str, ModuleSummary] = {}      # dotted name ->
        self.by_relpath: dict[str, ModuleSummary] = {}
        self.mesh = MeshDecl()
        self.stats = IndexStats()
        # dotted function name ("<module>.<qual>") -> summary
        self.functions: dict[str, FunctionSummary] = {}
        self._suffix_cache: dict[str, str | None] = {}
        self._fn_by_last: dict[str, list[str]] | None = None
        # axis-environment tables: "<module>.<qualname>" (nested defs
        # included) -> info / may-bound axes / has-known-calling-context
        self.axis_funcs: dict[str, AxisFuncInfo] = {}
        self.axis_env: dict[str, frozenset] = {}
        self.axis_context: dict[str, bool] = {}
        self._axis_suffix_cache: dict[tuple[str, str], str | None] = {}
        self._axis_by_last: dict[str, list[str]] | None = None
        self.donation_names: frozenset = frozenset()
        self.key_consumer_names: frozenset = frozenset()
        # defs reachable (via resolved call edges) from train/multihost.py
        # or the comms bucket path — GL019's scope
        self.multihost_reach: frozenset = frozenset()
        # (source, tree) for files parsed THIS run (cache misses): pass 2
        # adopts them instead of re-parsing
        self.parsed: dict[str, tuple[str, ast.Module]] = {}

    # -- build ----------------------------------------------------------

    @classmethod
    def build(cls, files: list[str], root: str,
              cache_path: str | None = None) -> "ProjectIndex":
        """Summarize ``files`` (absolute paths) under ``root``; reuse the
        mtime-keyed on-disk cache at ``cache_path`` (default
        ``<root>/.graftlint_cache.json``; pass '' to disable caching)."""
        index = cls(root)
        if cache_path is None:
            cache_path = os.path.join(index.root, CACHE_NAME)
        cache = _load_cache(cache_path) if cache_path else {}
        entries = cache.get("files", {})
        dirty = False

        todo = list(files)
        # force mesh/submesh axis declarations into every index, however
        # narrow the linted path set is
        for decl_rel in (MESH_RELPATH, SUBMESH_RELPATH):
            decl_path = os.path.join(index.root, decl_rel)
            if os.path.exists(decl_path) and not any(
                os.path.abspath(p) == decl_path for p in todo
            ):
                todo.append(decl_path)

        submesh_axes: set[str] = set()
        for path in todo:
            relpath = os.path.relpath(path, index.root).replace(os.sep, "/")
            try:
                st = os.stat(path)
            except OSError:
                continue
            index.stats.files += 1
            ent = entries.get(relpath)
            if ent and ent.get("mtime") == st.st_mtime and \
                    ent.get("size") == st.st_size:
                summary = ModuleSummary.from_dict(ent["summary"])
                mesh = MeshDecl.from_dict(ent["mesh"]) if "mesh" in ent \
                    else None
                index.stats.cached += 1
            else:
                summary, mesh, parsed = _summarize_path(path, relpath)
                if parsed is not None:
                    index.parsed[relpath] = parsed
                entries[relpath] = {
                    "mtime": st.st_mtime, "size": st.st_size,
                    "summary": summary.to_dict(),
                }
                if mesh is not None:
                    entries[relpath]["mesh"] = mesh.to_dict()
                index.stats.summarized += 1
                dirty = True
            summary.mtime, summary.size = st.st_mtime, st.st_size
            index.modules[summary.module] = summary
            index.by_relpath[relpath] = summary
            if relpath == MESH_RELPATH and mesh is not None:
                index.mesh = mesh
            elif relpath == SUBMESH_RELPATH and mesh is not None:
                submesh_axes |= set(mesh.axes)

        if submesh_axes - set(index.mesh.axes):
            # merge AFTER the file loop: iteration order must not decide
            # whether submesh axes land before or after the mesh decl
            index.mesh = MeshDecl(
                axes=frozenset(set(index.mesh.axes) | submesh_axes),
                families=index.mesh.families,
                contract=index.mesh.contract,
                found=index.mesh.found,
            )

        for module in index.modules.values():
            for qual, fn in module.functions.items():
                index.functions[f"{module.module}.{qual}"] = fn
            for qual, info in module.axis_funcs.items():
                index.axis_funcs[f"{module.module}.{qual}"] = info
        index._fixpoint()
        index._axis_fixpoint()
        # cheap pre-filter for GL017: the last segments of every function
        # carrying a donation fact — callers only pay a lookup when a
        # callee's bare name can possibly match one
        index.donation_names = frozenset(
            name.rsplit(".", 1)[-1]
            for name, fn in index.functions.items()
            if fn.donated_argnums or fn.forwards_donated
            or fn.returns_donating
        )
        # same trick for GL014: last segments of key-consuming functions
        index.key_consumer_names = frozenset(
            name.rsplit(".", 1)[-1]
            for name, fn in index.functions.items()
            if fn.key_params_consumed
        )
        if cache_path and dirty:
            _save_cache(cache_path, {"version": _CACHE_VERSION,
                                     "files": entries})
        return index

    # -- lookups --------------------------------------------------------

    def lookup(self, dotted: str) -> tuple[str, FunctionSummary] | None:
        """Resolve an already-alias-expanded dotted callee name to its
        (full indexed name, summary).

        Exact match first, then a unique-suffix match (fixture-local flat
        imports: ``producer.f`` matches ``tests.fixtures.….producer.f``);
        ambiguous suffixes resolve to nothing — never guess.
        """
        if not dotted:
            return None
        hit = self.functions.get(dotted)
        if hit is not None:
            return dotted, hit
        if dotted not in self._suffix_cache:
            by_last = self._fn_by_last
            if by_last is None:
                by_last = {}
                for k in self.functions:
                    by_last.setdefault(_last(k), []).append(k)
                self._fn_by_last = by_last
            suffix = "." + dotted
            matches = [k for k in by_last.get(_last(dotted), ())
                       if k.endswith(suffix)]
            self._suffix_cache[dotted] = (
                matches[0] if len(matches) == 1 else None
            )
        key = self._suffix_cache[dotted]
        return (key, self.functions[key]) if key else None

    def lookup_function(self, dotted: str) -> FunctionSummary | None:
        hit = self.lookup(dotted)
        return hit[1] if hit else None

    def lookup_from(self, module: str,
                    dotted: str) -> tuple[str, FunctionSummary] | None:
        """Like :meth:`lookup`, but same-module names win first: a bare
        local call (``decode(...)``) must resolve to THIS module's def,
        never suffix-match a same-named function elsewhere."""
        if module and dotted:
            local = f"{module}.{dotted}"
            hit = self.functions.get(local)
            if hit is not None:
                return local, hit
        return self.lookup(dotted)

    def module_of(self, relpath: str) -> str:
        mod = self.by_relpath.get(relpath)
        return mod.module if mod is not None else module_name_for(relpath)

    def aliases_for(self, relpath: str, tree: ast.Module) -> dict[str, str]:
        """Import-alias map for a file — from its module summary when the
        file was indexed, recomputed from ``tree`` otherwise."""
        mod = self.by_relpath.get(relpath)
        if mod is not None and not mod.parse_error:
            return mod.aliases
        return import_aliases(tree, module_name_for(relpath))

    def _axis_lookup(self, module: str, dotted: str) -> str | None:
        """Resolve a callee/binding-target name to its axis-table entry:
        module-local exact first, then unique suffix (same-module matches
        preferred — a bare nested name like ``body`` resolves to THIS
        module's ``make_step.body``, never another module's)."""
        if not dotted:
            return None
        key = (module, dotted)
        if key not in self._axis_suffix_cache:
            hit: str | None = None
            local = f"{module}.{dotted}"
            if dotted in self.axis_funcs:
                hit = dotted           # already a full indexed name
            elif local in self.axis_funcs:
                hit = local
            else:
                # bucket by last segment: the suffix scan only ever walks
                # same-named entries, not the whole table
                by_last = self._axis_by_last
                if by_last is None:
                    by_last = {}
                    for k in self.axis_funcs:
                        by_last.setdefault(_last(k), []).append(k)
                    self._axis_by_last = by_last
                suffix = "." + dotted
                matches = [k for k in by_last.get(_last(dotted), ())
                           if k.endswith(suffix)]
                same_mod = [m for m in matches
                            if m.startswith(module + ".")]
                pool = same_mod or matches
                hit = pool[0] if len(pool) == 1 else None
            self._axis_suffix_cache[key] = hit
        return self._axis_suffix_cache[key]

    def axis_env_of(self, module: str,
                    qualname: str) -> tuple[frozenset, bool]:
        """(may-bound axes, has-known-calling-context) for one def. The
        axis set is the union over every known binding application and
        call path reaching the def; the flag is False when the tree shows
        NO way to reach it (an entry point — its runtime context is
        unknowable, so axis rules stay quiet)."""
        full = f"{module}.{qualname}"
        return (self.axis_env.get(full, frozenset()),
                self.axis_context.get(full, False))

    # -- cross-module fixpoint ------------------------------------------

    def _fixpoint(self) -> None:
        """Propagate device-return provenance and PRNG-key consumption
        through the call graph until stable."""
        owner_module = {
            f"{m.module}.{qual}": m.module
            for m in self.modules.values() for qual in m.functions
        }
        for _ in range(_FIXPOINT_MAX_ROUNDS):
            changed = False
            for name, fn in self.functions.items():
                mod = owner_module.get(name, "")
                # returns_device via a returned callee result
                if not fn.returns_device:
                    for callee in fn.returns_calls:
                        hit = self.lookup_from(mod, callee)
                        target = hit[1] if hit else None
                        if target is not None and target.returns_device:
                            fn.returns_device = True
                            fn.device_reason = (
                                f"returns {callee}(...) → "
                                f"{target.device_reason or 'device value'}"
                            )
                            changed = True
                            break
                # host-shape/value facts through returned callee results
                # (GL019: `return host_shard()` is as per-host as the
                # callee's own body)
                for callee in fn.returns_calls:
                    if fn.returns_host_shape and fn.returns_host_value:
                        break
                    hit = self.lookup_from(mod, callee)
                    target = hit[1] if hit else None
                    if target is None:
                        continue
                    if target.returns_host_shape and \
                            not fn.returns_host_shape:
                        fn.returns_host_shape = True
                        fn.host_shape_reason = (
                            f"returns {callee}(...) → "
                            f"{target.host_shape_reason or 'per-host shape'}"
                        )
                        changed = True
                    if target.returns_host_value and \
                            not fn.returns_host_value:
                        fn.returns_host_value = True
                        fn.host_value_reason = (
                            f"returns {callee}(...) → "
                            f"{target.host_value_reason or 'per-host value'}"
                        )
                        changed = True
                    if fn.return_dims is None and \
                            target.return_dims is not None:
                        fn.return_dims = list(target.return_dims)
                        changed = True
                # returns_donating through factory-of-factory returns
                for callee in fn.returns_calls:
                    hit = self.lookup_from(mod, callee)
                    target = hit[1] if hit else None
                    if target is not None and target.returns_donating:
                        merged = sorted(set(fn.returns_donating)
                                        | set(target.returns_donating))
                        if merged != fn.returns_donating:
                            fn.returns_donating = merged
                            changed = True
                # transitive key consumption through consuming callees,
                # and donation forwarding through wrapper callees
                for site in fn.calls:
                    hit = self.lookup_from(mod, site.callee)
                    target = hit[1] if hit else None
                    if target is None:
                        continue
                    donated_pos = set(target.donated_argnums) | set(
                        target.forwards_donated
                    )
                    for i, p in enumerate(site.arg_params):
                        if p is None:
                            continue
                        if i in donated_pos:
                            own = fn.params.index(p)
                            if own not in fn.forwards_donated:
                                fn.forwards_donated.append(own)
                                via = target.forwards_donated_via.get(
                                    str(i), ""
                                )
                                chain = f"{site.callee}() (argument {i}"
                                chain += f", via {via})" if via else ")"
                                fn.forwards_donated_via[str(own)] = chain
                                changed = True
                        if p in fn.key_params_consumed:
                            continue
                        if target.key_params_consumed and \
                                i < len(target.params) and \
                                target.params[i] in \
                                target.key_params_consumed:
                            fn.key_params_consumed.append(p)
                            fn.key_consumed_via[p] = site.callee
                            changed = True
                    for kw, p in site.kw_params.items():
                        if kw in target.params and \
                                target.params.index(kw) in donated_pos \
                                and fn.params.index(p) not in \
                                fn.forwards_donated:
                            own = fn.params.index(p)
                            fn.forwards_donated.append(own)
                            fn.forwards_donated_via[str(own)] = (
                                f"{site.callee}() (argument {kw!r})"
                            )
                            changed = True
                        if p in fn.key_params_consumed:
                            continue
                        if kw in target.key_params_consumed:
                            fn.key_params_consumed.append(p)
                            fn.key_consumed_via[p] = site.callee
                            changed = True
            if not changed:
                return

    def _axis_fixpoint(self) -> None:
        """Abstract interpretation over the axis tables: compute, per def,
        the union of named axes bound on at least one reachable path
        (binding applications seed, call edges and lexical nesting
        propagate). Monotone over a finite axis universe — terminates."""
        env: dict[str, set] = {k: set() for k in self.axis_funcs}
        ctx: dict[str, bool] = {k: False for k in self.axis_funcs}
        mesh_axes = set(self.mesh.axes)

        bind_edges: list[tuple[str | None, str, set]] = []
        call_edges: list[tuple[str, str]] = []
        lex_edges: list[tuple[str, str]] = []
        reach: set[str] = set()
        for mod in self.modules.values():
            if mod.relpath in MULTIHOST_SEED_RELPATHS:
                reach.update(
                    f"{mod.module}.{qual}" for qual in mod.axis_funcs
                )
            for b in mod.axis_bindings:
                t = self._axis_lookup(mod.module, b.target)
                if t is None:
                    continue
                owner = f"{mod.module}.{b.owner}" if b.owner else None
                owner = owner if owner in env else None
                axes = set(b.axes) if b.axes is not None else mesh_axes
                bind_edges.append((owner, t, axes))
                ctx[t] = True
            for qual, info in mod.axis_funcs.items():
                full = f"{mod.module}.{qual}"
                if info.parent:
                    parent = f"{mod.module}.{info.parent}"
                    if parent in env:
                        lex_edges.append((parent, full))
                for callee in info.calls:
                    t = self._axis_lookup(mod.module, callee)
                    if t is not None and t != full:
                        call_edges.append((full, t))
                        ctx[t] = True

        for _ in range(_FIXPOINT_MAX_ROUNDS):
            changed = False
            for owner, t, axes in bind_edges:
                add = axes | (env[owner] if owner else set())
                if add - env[t]:
                    env[t] |= add
                    changed = True
            for caller, t in call_edges:
                if env[caller] - env[t]:
                    env[t] |= env[caller]
                    changed = True
            for parent, child in lex_edges:
                if env[parent] - env[child]:
                    env[child] |= env[parent]
                    changed = True
            if not changed:
                break
        self.axis_env = {k: frozenset(v) for k, v in env.items()}
        self.axis_context = ctx
        # forward closure over the same resolved edges: a helper a seed
        # module calls (transitively) runs at the same rendezvous points
        for _ in range(_FIXPOINT_MAX_ROUNDS):
            before = len(reach)
            for caller, t in call_edges:
                if caller in reach:
                    reach.add(t)
            for parent, child in lex_edges:
                if parent in reach:
                    reach.add(child)
            if len(reach) == before:
                break
        self.multihost_reach = frozenset(reach)


def _summarize_path(
    path: str, relpath: str
) -> tuple[ModuleSummary, MeshDecl | None, tuple[str, ast.Module] | None]:
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            source = f.read()
        tree = ast.parse(source, filename=relpath)
    except (OSError, SyntaxError):
        return ModuleSummary(
            module=module_name_for(relpath), relpath=relpath,
            parse_error=True,
        ), None, None
    summary = summarize_module(tree, relpath, source=source)
    if relpath == MESH_RELPATH:
        mesh = scrape_mesh_decl(tree)
    elif relpath == SUBMESH_RELPATH:
        mesh = scrape_submesh_axes(tree)
    else:
        mesh = None
    return summary, mesh, (source, tree)


def _load_cache(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if isinstance(data, dict) and data.get("version") == _CACHE_VERSION:
            return data
    except (OSError, ValueError):
        pass
    return {}


def _save_cache(path: str, data: dict) -> None:
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
            f.flush()
            os.fsync(f.fileno())  # durable before the rename publishes it
        os.replace(tmp, path)
    except OSError:
        pass  # caching is best-effort; never fail the lint over it
