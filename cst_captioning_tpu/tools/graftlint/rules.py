"""graftlint rules: the JAX/TPU hazards this codebase has actually hit.

Each rule's ``rationale`` is one line of "why this is a bug here"; the README
"Static analysis" section is generated from these strings (keep them short).

Rule ids are stable (baseline fingerprints and inline suppressions reference
them); add new rules with new ids, never renumber.
"""

from __future__ import annotations

import ast
import json
import os
import re

from cst_captioning_tpu.tools.graftlint.core import (
    Edit,
    FileContext,
    Finding,
    Fix,
    ProjectRule,
    Rule,
    register,
)
from cst_captioning_tpu.tools.graftlint.project import (
    _FUNC_NODES,
    _TRACERS,
    _decorator_traces,
    _dotted,
    _last,
    COLLECTIVE_AXIS_KWARGS,
    COLLECTIVE_AXIS_POS,
    HostTaint,
    MULTIHOST_SEED_RELPATHS,
    ProjectIndex,
    def_qualnames,
    donation_of_call,
    resolve_dotted,
)

# ---- shared AST helpers (canonical defs live in project.py) -----------------

_HOT_PACKAGES = (
    "cst_captioning_tpu/train/", "cst_captioning_tpu/rl/",
    "cst_captioning_tpu/decoding/",
)


def _is_tracer_call(call: ast.Call) -> bool:
    d = _dotted(call.func)
    return bool(d) and not d.startswith(("self.", "cls.")) and _last(d) in _TRACERS


def traced_node_ids(ctx: FileContext) -> set[int]:
    """ids of every AST node lexically inside a traced function.

    A function counts as traced when decorated by a tracer (``@jax.jit``,
    ``@functools.partial(jax.jit, ...)``) or passed by name (or as an inline
    lambda) into a tracer call (``jax.jit(f)``, ``jax.lax.scan(body, ...)``,
    ``shard_map(step, ...)``). Functions nested inside traced functions are
    traced too (they run under the same trace).
    """
    cached = ctx._cache.get("traced_ids")
    if cached is not None:
        return cached

    name_defs: dict[str, list[ast.AST]] = {}
    for node in ctx.nodes_of(*_FUNC_NODES):
        name_defs.setdefault(node.name, []).append(node)

    entries: list[ast.AST] = []
    for node in ctx.nodes_of(*_FUNC_NODES):
        if any(_decorator_traces(d) for d in node.decorator_list):
            entries.append(node)
    for node in ctx.nodes_of(ast.Call):
        if _is_tracer_call(node):
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                if isinstance(arg, ast.Lambda):
                    entries.append(arg)
                elif isinstance(arg, ast.Name) and arg.id in name_defs:
                    entries.extend(name_defs[arg.id])

    ids: set[int] = set()
    for entry in entries:
        for node in ast.walk(entry):
            ids.add(id(node))
    ctx._cache["traced_ids"] = ids
    return ids


def _in_package(ctx: FileContext) -> bool:
    return ctx.relpath.startswith("cst_captioning_tpu/")


def _is_test_file(ctx: FileContext) -> bool:
    base = os.path.basename(ctx.relpath)
    return base.startswith("test_") or ctx.relpath.startswith("tests/")


# ---- GL001: host sync on the device hot path --------------------------------

_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_SYNC_DOTTED = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get", "device_get", "jax.block_until_ready",
}


def _sync_call(node: ast.AST) -> str | None:
    """Name of the host-sync primitive a call node invokes, else None."""
    if not isinstance(node, ast.Call):
        return None
    if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_ATTRS:
        return f".{node.func.attr}()"
    d = _dotted(node.func)
    if d in _SYNC_DOTTED:
        return d
    if d == "float":
        return "float()"
    return None


@register
class HostSyncRule(Rule):
    id = "GL001"
    name = "host-sync-in-hot-path"
    severity = "error"
    rationale = (
        "a device_get/.item()/float()/np.asarray inside a traced function "
        "(or unconditionally inside a per-step loop) serializes the dispatch "
        "pipeline — the device idles while the host blocks on the transfer"
    )

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        traced = traced_node_ids(ctx)
        for node in ctx.nodes_of(ast.Call):
            prim = _sync_call(node)
            if prim and id(node) in traced:
                out.append(ctx.finding(
                    self, node,
                    f"host-sync call {prim} inside a jit/scan-traced "
                    "function: the trace either fails at runtime or (via a "
                    "constant-folded host value) hides a device round-trip",
                ))
        if self._loop_scope(ctx):
            out.extend(self._check_step_loops(ctx, traced))
        return out

    @staticmethod
    def _loop_scope(ctx: FileContext) -> bool:
        """The per-step-loop heuristic only applies where loop bodies drive
        jitted steps: jax-importing modules of the train/rl/decoding
        packages. Host-side modules (the numpy reward scorer, metrics, data)
        and benchmarks/tests sync deliberately — measuring or asserting IS
        a readback."""
        if not ctx.relpath.startswith(_HOT_PACKAGES):
            return False
        return any(
            isinstance(n, ast.Import) and any(
                a.name == "jax" or a.name.startswith("jax.")
                for a in n.names
            )
            or isinstance(n, ast.ImportFrom) and (n.module or "").split(
                "."
            )[0] == "jax"
            for n in ctx.nodes_of(ast.Import, ast.ImportFrom)
        )

    def _check_step_loops(self, ctx: FileContext,
                          traced: set[int]) -> list[Finding]:
        """Flag syncs that run on EVERY iteration of a for/while loop: direct
        statements and `if` tests, but not gated `if` bodies (logging every N
        steps is a deliberate, amortized sync)."""
        out: dict[tuple[int, int, str], Finding] = {}
        for loop in ctx.nodes_of(ast.For, ast.While):
            if id(loop) in traced:
                continue  # the traced-scope pass above already covers these
            stack: list[ast.AST] = list(loop.body)
            while stack:
                node = stack.pop()
                if isinstance(node, _FUNC_NODES + (ast.Lambda,)):
                    continue  # closures run on their own schedule
                if isinstance(node, ast.If):
                    stack.extend(ast.walk(node.test))
                    continue
                prim = _sync_call(node)
                # int() of a device scalar (step counters read off the train
                # state, metrics dict entries) is the sneakiest per-step
                # sync; only the loop pass flags it — inside a trace int()
                # is a plain shape computation, and int() of host strings/
                # counters is everyday Python, so gate on the argument
                # LOOKING like device state
                if prim is None and isinstance(node, ast.Call) \
                        and _dotted(node.func) == "int" and node.args:
                    try:
                        arg_src = ast.unparse(node.args[0])
                    except Exception:  # pragma: no cover - defensive
                        arg_src = ""
                    if re.search(r"state|\bstep\b|metrics|\bm\[", arg_src):
                        prim = "int()"
                if prim:
                    key = (node.lineno, node.col_offset, prim)
                    if key not in out:
                        out[key] = ctx.finding(
                            self, node,
                            f"per-step host sync: {prim} runs every "
                            "iteration of this step loop, blocking dispatch "
                            "of the next step; defer the readback "
                            "(accumulate device values, convert once per "
                            "epoch) or gate it behind a log-every-N branch",
                            severity="warning",
                        )
                for child in ast.iter_child_nodes(node):
                    stack.append(child)
        return list(out.values())


# ---- GL002: PRNG key reuse --------------------------------------------------

_KEY_CONSUMERS = {
    "categorical", "normal", "uniform", "bernoulli", "gumbel", "choice",
    "permutation", "randint", "bits", "exponential", "laplace", "truncated_normal",
    "dirichlet", "beta", "gamma", "poisson", "shuffle",
}
_KEY_BASES = {"jax.random", "random", "jrandom", "jr"}


@register
class KeyReuseRule(Rule):
    id = "GL002"
    name = "prng-key-reuse"
    severity = "error"
    rationale = (
        "passing one key to two jax.random consumers yields CORRELATED "
        "draws — in SCST the K rollouts stop exploring independently and "
        "the REINFORCE baseline silently biases"
    )

    def applies(self, ctx: FileContext) -> bool:
        # tests reuse keys deliberately (determinism assertions); a file
        # that never spells a jax.random base cannot consume a key
        return not _is_test_file(ctx) and "random" in ctx.source

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ctx.nodes_of(*_FUNC_NODES):
            out.extend(self._check_function(ctx, node))
        return out

    def _check_function(self, ctx: FileContext, fn: ast.AST) -> list[Finding]:
        # events in source order: key consumptions and name (re)bindings,
        # nested functions excluded (separate scopes, analyzed on their own)
        events: list[tuple[int, int, str, str, ast.AST]] = []

        def visit(node, depth=0):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_NODES + (ast.Lambda,)):
                    continue
                if isinstance(child, ast.Call):
                    d = _dotted(child.func)
                    base, _, attr = d.rpartition(".")
                    if base in _KEY_BASES and attr in _KEY_CONSUMERS and child.args:
                        key_expr = child.args[0]
                        try:
                            key_src = ast.unparse(key_expr)
                        except Exception:  # pragma: no cover - defensive
                            key_src = ""
                        if key_src:
                            events.append((
                                child.lineno, child.col_offset,
                                "consume", key_src, child,
                            ))
                if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                      ast.For, ast.withitem, ast.NamedExpr)):
                    for name in _bound_names(child):
                        events.append((
                            getattr(child, "lineno", 0),
                            getattr(child, "col_offset", 0),
                            "bind", name, child,
                        ))
                visit(child, depth + 1)

        visit(fn)
        events.sort(key=lambda e: (e[0], e[1]))

        live: dict[str, ast.AST] = {}  # key expr -> first consuming call
        out: list[Finding] = []
        for _, _, kind, payload, node in events:
            if kind == "bind":
                # any key expression mentioning the rebound name is refreshed
                for expr in [e for e in live
                             if re.search(rf"\b{re.escape(payload)}\b", e)]:
                    del live[expr]
            else:
                if payload in live:
                    first = live[payload]
                    out.append(ctx.finding(
                        self, node,
                        f"PRNG key {payload!r} already consumed by a "
                        f"jax.random call on line {first.lineno}; split or "
                        "fold_in before reusing it (identical keys give "
                        "identical draws)",
                    ))
                else:
                    live[payload] = node
        return out


def _bound_names(node: ast.AST) -> list[str]:
    targets: list[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    elif isinstance(node, ast.For):
        targets = [node.target]
    elif isinstance(node, ast.withitem) and node.optional_vars is not None:
        targets = [node.optional_vars]
    elif isinstance(node, ast.NamedExpr):
        targets = [node.target]
    out: list[str] = []
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                out.append(sub.id)
    return out


# ---- GL003: Python control flow on traced values ----------------------------

_TENSOR_BASES = {"jnp", "jax.numpy", "lax", "jax.lax", "jax.nn"}


@register
class TracedBranchRule(Rule):
    id = "GL003"
    name = "python-branch-on-traced-value"
    severity = "error"
    rationale = (
        "`if`/`while` on a jnp/lax value inside a traced function raises "
        "ConcretizationTypeError at best — or, when the value is accidentally "
        "concrete, silently burns one retrace per Python branch outcome"
    )

    def check(self, ctx: FileContext) -> list[Finding]:
        traced = traced_node_ids(ctx)
        out: list[Finding] = []
        for fn in ctx.nodes_of(*_FUNC_NODES):
            if id(fn) not in traced:
                continue
            tensor_names: set[str] = set()
            for node in ast.iter_child_nodes(fn):
                out.extend(self._scan(ctx, node, tensor_names))
        # dedupe (nested traced functions are walked once per enclosing entry)
        seen: set[tuple[int, int]] = set()
        uniq = []
        for f in out:
            if (f.line, f.col) not in seen:
                seen.add((f.line, f.col))
                uniq.append(f)
        return uniq

    def _scan(self, ctx, node, tensor_names, depth=0) -> list[Finding]:
        out: list[Finding] = []
        if isinstance(node, ast.Assign) and self._is_tensor_expr(
            node.value, tensor_names
        ):
            tensor_names.update(_bound_names(node))
        if isinstance(node, (ast.If, ast.While)) and self._is_tensor_expr(
            node.test, tensor_names
        ):
            kind = "if" if isinstance(node, ast.If) else "while"
            out.append(ctx.finding(
                self, node,
                f"Python `{kind}` on a traced jnp/lax value: use jnp.where / "
                "lax.cond / lax.while_loop (or hoist the decision to static "
                "config) so the branch stays inside the XLA program",
            ))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES + (ast.Lambda,)):
                continue
            out.extend(self._scan(ctx, child, tensor_names, depth + 1))
        return out

    @staticmethod
    def _is_tensor_expr(expr: ast.AST, tensor_names: set[str]) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                base = _dotted(node.func).rpartition(".")[0]
                if base in _TENSOR_BASES:
                    return True
            if isinstance(node, ast.Name) and node.id in tensor_names:
                return True
        return False


# ---- GL004: train/update steps jitted without donation ----------------------

_STEP_NAME = re.compile(r"(step|update)", re.IGNORECASE)
_DONATE_KWARGS = {"donate_argnums", "donate_argnames"}


@register
class DonationRule(Rule):
    id = "GL004"
    name = "jit-step-without-donation"
    severity = "warning"
    rationale = (
        "jitting a train/update step without donate_argnums double-buffers "
        "params + optimizer state in HBM — the exact memory ceiling "
        "BASELINE.md hit at batch 1024; donation must be an explicit choice"
    )

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        enclosing: dict[int, str] | None = None  # lazy: most files don't jit
        for node in ctx.nodes_of(*_FUNC_NODES):
            # a *train* step carries mutable state (params/optimizer);
            # decode/eval "step" functions don't, and donating their
            # inputs buys nothing — require a state-like parameter
            has_state = any(
                "state" in a.arg for a in node.args.args + node.args.kwonlyargs
            )
            for dec in node.decorator_list:
                if has_state and self._jit_without_donation(dec) \
                        and _STEP_NAME.search(node.name):
                    out.append(self._finding(ctx, dec, node.name))
        for node in ctx.nodes_of(ast.Call):
            if _last(_dotted(node.func)) in (
                "jit", "pjit"
            ):
                if any(kw.arg in _DONATE_KWARGS for kw in node.keywords):
                    continue
                target = ""
                if node.args and isinstance(node.args[0], ast.Name):
                    target = node.args[0].id
                if enclosing is None:
                    enclosing = _enclosing_function_names(ctx)
                owner = enclosing.get(id(node), "")
                subject = target if _STEP_NAME.search(target) else (
                    owner if _STEP_NAME.search(owner) else ""
                )
                if subject:
                    out.append(self._finding(ctx, node, subject))
        return out

    @staticmethod
    def _jit_without_donation(dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            d = _dotted(dec.func)
            if _last(d) == "partial" and dec.args and _last(
                _dotted(dec.args[0])
            ) in ("jit", "pjit"):
                return not any(kw.arg in _DONATE_KWARGS for kw in dec.keywords)
            if _last(d) in ("jit", "pjit"):
                return not any(kw.arg in _DONATE_KWARGS for kw in dec.keywords)
            return False
        return _last(_dotted(dec)) in ("jit", "pjit")

    def _finding(self, ctx, node, subject) -> Finding:
        return ctx.finding(
            self, node,
            f"{subject!r} looks like a train/update step but is jitted "
            "without donate_argnums/donate_argnames: its input state "
            "double-buffers in HBM. Pass donation explicitly (an empty "
            "tuple is fine when replay semantics are wanted)",
        )


def _enclosing_function_names(ctx: FileContext) -> dict[int, str]:
    """node id -> name of the nearest enclosing function ('' at module)."""
    cached = ctx._cache.get("enclosing_fn")
    if cached is not None:
        return cached
    out: dict[int, str] = {}

    def walk(node, owner):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                out[id(child)] = owner
                walk(child, child.name)
            else:
                out[id(child)] = owner
                walk(child, owner)

    walk(ctx.tree, "")
    ctx._cache["enclosing_fn"] = out
    return out


# ---- GL005: float32 literals in bf16-annotated modules ----------------------

_CREATORS = {
    "zeros": 1, "ones": 1, "empty": 1, "array": 1, "asarray": 1,
    "full": 2, "full_like": 2,
}


@register
class F32LiteralRule(Rule):
    id = "GL005"
    name = "f32-literal-in-bf16-module"
    severity = "warning"
    rationale = (
        "an explicit float32 array literal in a bf16 compute module upcasts "
        "every op it touches off the MXU fast path; route dtypes through "
        "cfg.dtype or mark the f32 accumulation intentional"
    )

    # the packages whose code executes under the model's compute dtype;
    # tests/benches build f32 INPUT data on purpose (the model casts), so
    # merely containing the string "bfloat16" does not put a file in scope
    _SCOPE = (
        "cst_captioning_tpu/models/", "cst_captioning_tpu/ops/",
        "cst_captioning_tpu/losses/", "cst_captioning_tpu/parallel/",
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.relpath.startswith(self._SCOPE)

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        param_scopes: dict[int, frozenset] | None = None  # lazy: rare rule
        for node in ctx.nodes_of(ast.Call):
            d = _dotted(node.func)
            base, _, attr = d.rpartition(".")
            if base not in ("jnp", "jax.numpy", "np", "numpy"):
                continue
            if attr not in _CREATORS:
                continue
            dtype = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dtype = kw.value
            pos = _CREATORS[attr]
            if dtype is None and len(node.args) > pos:
                dtype = node.args[pos]
            if dtype is not None and self._is_f32(dtype):
                fix = None
                if param_scopes is None:
                    param_scopes = _enclosing_param_sets(ctx)
                if "dtype" in param_scopes.get(id(node), frozenset()):
                    # the enclosing function already routes a dtype: the
                    # mechanical fix is to use it (the dtype the caller
                    # chose — exactly what the literal was overriding)
                    fix = Fix(
                        edits=(Edit.from_node(dtype, "dtype"),),
                        description=(
                            "route the literal through the enclosing "
                            "function's `dtype` parameter"
                        ),
                    )
                out.append(ctx.finding(
                    self, node,
                    f"float32 literal via {d}(...) in a bf16-annotated "
                    "module: pass the module's compute dtype (cfg.dtype) or "
                    "suppress with a comment when f32 accumulation is the "
                    "point",
                    fix=fix,
                ))
        return out

    @staticmethod
    def _is_f32(node: ast.AST) -> bool:
        if isinstance(node, ast.Constant) and node.value == "float32":
            return True
        return _dotted(node) in (
            "jnp.float32", "np.float32", "numpy.float32", "jax.numpy.float32",
        )


def _enclosing_param_sets(ctx: FileContext) -> dict[int, frozenset]:
    """id(node) -> parameter names of the nearest enclosing function
    (including outer functions' params — closures see them). Cached."""
    cached = ctx._cache.get("enclosing_params")
    if cached is not None:
        return cached
    out: dict[int, frozenset] = {}

    def walk(node: ast.AST, params: frozenset) -> None:
        for child in ast.iter_child_nodes(node):
            child_params = params
            if isinstance(child, _FUNC_NODES):
                args = child.args
                own = {a.arg for a in args.posonlyargs + args.args
                       + args.kwonlyargs}
                if args.vararg:
                    own.add(args.vararg.arg)
                if args.kwarg:
                    own.add(args.kwarg.arg)
                child_params = params | own
            out[id(child)] = child_params
            walk(child, child_params)

    walk(ctx.tree, frozenset())
    ctx._cache["enclosing_params"] = out
    return out


# ---- GL006: heavyweight imports / module-level device work ------------------

_FORBIDDEN_IMPORTS = {
    "torch", "torchvision", "tensorflow", "keras", "theano", "pandas",
    "matplotlib", "sklearn", "pycocoevalcap", "nltk",
}
# module-scope calls that initialize the backend / touch devices at import
_DEVICE_CALLS = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.default_backend", "jax.device_put",
    "jax.process_index", "jax.process_count",
}
_DEVICE_PREFIXES = ("jax.random.",)


@register
class HeavyImportRule(Rule):
    id = "GL006"
    name = "heavy-import-or-import-side-effect"
    severity = "error"
    rationale = (
        "hot-path packages must stay importable in milliseconds with no "
        "backend init: a stray torch/tensorflow import or module-level "
        "jax.devices() makes every CLI, test, and subprocess pay for it"
    )

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ctx.nodes_of(ast.Import, ast.ImportFrom):
            mods: list[str] = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mods = [node.module]
            for mod in mods:
                top = mod.split(".", 1)[0]
                if top in _FORBIDDEN_IMPORTS:
                    out.append(ctx.finding(
                        self, node,
                        f"forbidden heavyweight import {mod!r}: this "
                        "codebase is jax+numpy only (no network to install "
                        "extras; host metrics stay in metrics/)",
                    ))
        out.extend(self._module_scope_device_work(ctx))
        return out

    def _module_scope_device_work(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for stmt in ctx.tree.body:
            if isinstance(stmt, _FUNC_NODES + (ast.ClassDef,)):
                continue
            if _is_main_guard(stmt):
                continue
            for node in ast.walk(stmt):
                if isinstance(node, _FUNC_NODES + (ast.Lambda,)):
                    # defs nested in module-level if/try: bodies run later
                    continue
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if d in _DEVICE_CALLS or d.startswith(_DEVICE_PREFIXES):
                    out.append(ctx.finding(
                        self, node,
                        f"module-level {d}(...) initializes the JAX backend "
                        "at import time: importing this file grabs the TPU "
                        "(or pays CPU-client startup) before any CLI flag or "
                        "env guard can run; move it under main() or a "
                        "__main__ guard",
                    ))
        return out


def _is_main_guard(stmt: ast.AST) -> bool:
    return (
        isinstance(stmt, ast.If)
        and isinstance(stmt.test, ast.Compare)
        and isinstance(stmt.test.left, ast.Name)
        and stmt.test.left.id == "__name__"
    )


# ---- GL007: partition-rule coverage vs the sharding contract ----------------

@register
class PartitionCoverageRule(Rule):
    """Anchors findings on the PARAM_PARTITION_RULES tuple of the file
    being linted, so it parses the families from ``ctx.tree`` itself; the
    project index carries the same declaration (``index.mesh.families``,
    via :func:`~.project.scrape_mesh_decl`) for rules that need it without
    node anchors (GL012/GL015 use the axes half)."""

    id = "GL007"
    name = "partition-rule-coverage"
    severity = "error"
    rationale = (
        "a PartitionSpec rule regex that matches no param (or a param no "
        "rule covers) means a model refactor silently changed the sharded "
        "layout; the contract dump pins the param tree the rules were "
        "written against"
    )

    def applies(self, ctx: FileContext) -> bool:
        return "PARAM_PARTITION_RULES" in ctx.source

    def check(self, ctx: FileContext) -> list[Finding]:
        rules_node = None
        contract_rel = None
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign):
                names = _bound_names(node)
                if "PARAM_PARTITION_RULES" in names:
                    rules_node = node
                if "SHARDING_CONTRACT" in names and isinstance(
                    node.value, ast.Constant
                ):
                    contract_rel = str(node.value.value)
        if rules_node is None:
            return []

        regexes: list[tuple[str, str, ast.AST]] = []  # (family, pattern, node)
        for elt in getattr(rules_node.value, "elts", []):
            parts = getattr(elt, "elts", [])
            if len(parts) >= 2 and isinstance(parts[0], ast.Constant) \
                    and isinstance(parts[1], ast.Constant):
                regexes.append((str(parts[0].value), str(parts[1].value), elt))

        out: list[Finding] = []
        if contract_rel is None:
            return [ctx.finding(
                self, rules_node,
                "PARAM_PARTITION_RULES defined without a SHARDING_CONTRACT "
                "path: the rules cannot be cross-checked against the param "
                "tree",
            )]
        contract_path = contract_rel if os.path.isabs(contract_rel) else \
            os.path.join(ctx.root, contract_rel)
        if not os.path.exists(contract_path):
            return [ctx.finding(
                self, rules_node,
                f"sharding contract {contract_rel!r} not found: run "
                "`python scripts/check_shardings.py --write` to dump the "
                "param tree",
                severity="info",
            )]
        try:
            with open(contract_path, encoding="utf-8") as f:
                params = list(json.load(f)["params"])
        except (OSError, ValueError, KeyError) as e:
            return [ctx.finding(
                self, rules_node,
                f"sharding contract {contract_rel!r} unreadable: {e}",
            )]

        unruled = set(params)
        for family, pattern, node in regexes:
            try:
                rx = re.compile(pattern)
            except re.error as e:
                out.append(ctx.finding(
                    self, node,
                    f"partition rule {family!r} has an invalid regex: {e}",
                ))
                continue
            matched = [p for p in params if rx.fullmatch(p)]
            if not matched:
                out.append(ctx.finding(
                    self, node,
                    f"partition rule {family!r} ({pattern!r}) matches no "
                    "parameter in the contract dump — the param family it "
                    "was written for was renamed or removed",
                ))
            unruled.difference_update(matched)
        for p in sorted(unruled):
            out.append(ctx.finding(
                self, rules_node,
                f"parameter {p!r} (from the contract dump) matches no "
                "partition rule: add a rule for its family so its (future) "
                "sharding is an explicit decision",
            ))
        return out


# ---- GL008: TPU-only test imports without the slow marker -------------------

_TPU_ONLY_PREFIXES = (
    "cst_captioning_tpu.ops",
    "jax.experimental.pallas",
    "jax.experimental.mosaic",
)


@register
class TpuTestMarkerRule(Rule):
    id = "GL008"
    name = "tpu-test-without-slow-marker"
    severity = "warning"
    rationale = (
        "tier-1 runs `-m 'not slow'` on CPU everywhere; a test importing "
        "TPU-only kernel modules must either run in interpret mode "
        "(baseline it, with the reason) or carry @pytest.mark.slow"
    )

    def applies(self, ctx: FileContext) -> bool:
        return _is_test_file(ctx) and os.path.basename(
            ctx.relpath
        ).startswith("test_")

    def check(self, ctx: FileContext) -> list[Finding]:
        tpu_import = None
        tpu_mod = ""
        for node in ctx.nodes_of(ast.Import, ast.ImportFrom):
            mods: list[str] = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mods = [node.module]
            for mod in mods:
                if mod.startswith(_TPU_ONLY_PREFIXES):
                    tpu_import, tpu_mod = node, mod
                    break
            if tpu_import is not None:
                break
        if tpu_import is None:
            return []

        if self._module_marked_slow(ctx.tree):
            return []
        unmarked = [
            fn.name for fn in ctx.nodes_of(*_FUNC_NODES)
            if fn.name.startswith("test_") and not self._marked_slow(fn)
        ]
        if not unmarked:
            return []
        return [ctx.finding(
            self, tpu_import,
            f"imports TPU-only module {tpu_mod!r} but {len(unmarked)} test "
            "function(s) lack @pytest.mark.slow "
            f"({', '.join(unmarked[:4])}{'…' if len(unmarked) > 4 else ''}); "
            "mark them slow, or baseline this file with the reason it is "
            "CPU-safe (e.g. Pallas interpret mode)",
        )]

    @staticmethod
    def _marked_slow(fn: ast.AST) -> bool:
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _dotted(target).endswith("mark.slow"):
                return True
        return False

    @staticmethod
    def _module_marked_slow(tree: ast.Module) -> bool:
        for node in tree.body:
            if isinstance(node, ast.Assign) and "pytestmark" in _bound_names(
                node
            ):
                for sub in ast.walk(node.value):
                    if _dotted(sub).endswith("mark.slow"):
                        return True
        return False


# ---- GL009: silently swallowed broad exceptions -----------------------------

@register
class SwallowedExceptionRule(Rule):
    id = "GL009"
    name = "silent-exception-swallow"
    severity = "warning"
    rationale = (
        "a bare `except Exception: pass/continue` in package code hides "
        "corrupt checkpoints and I/O failures without a trace — log a "
        "structured event (or narrow the exception type) before falling back"
    )

    def applies(self, ctx: FileContext) -> bool:
        # package code only: tests/benches swallow on purpose when asserting
        # failure modes, and scripts print their own diagnostics
        return ctx.relpath.startswith("cst_captioning_tpu/")

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ctx.nodes_of(ast.Try):
            for handler in node.handlers:
                if not self._broad(handler.type):
                    continue
                if not all(self._silent(stmt) for stmt in handler.body):
                    continue  # the handler logs/recovers — that's the fix
                caught = (
                    "bare except" if handler.type is None
                    else _last(_dotted(handler.type)) or "Exception"
                )
                out.append(ctx.finding(
                    self, handler,
                    f"{caught} swallowed silently (body is only "
                    "pass/continue): a corrupt checkpoint or failed I/O "
                    "vanishes without a structured event — log which "
                    "operation failed and why before falling back",
                ))
        return out

    @classmethod
    def _broad(cls, type_node) -> bool:
        """True for ``except:``, ``except (Base)Exception``, or a tuple
        containing one — narrow types are a deliberate contract."""
        if type_node is None:
            return True
        if isinstance(type_node, ast.Tuple):
            return any(cls._broad(elt) for elt in type_node.elts)
        return _last(_dotted(type_node)) in ("Exception", "BaseException")

    @staticmethod
    def _silent(stmt: ast.stmt) -> bool:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            return True
        # a lone string/ellipsis expression is documentation, not handling
        return isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Constant
        )


# ---- GL010: ad-hoc timing / bare print in package hot paths -----------------

# CLIs print their reports and the linter prints its findings — both are
# user-facing stdout by design, not hot-path instrumentation
_GL010_EXCLUDED = (
    "cst_captioning_tpu/cli/", "cst_captioning_tpu/tools/",
)


@register
class AdHocTimingRule(Rule):
    id = "GL010"
    name = "adhoc-timing-or-print-in-hot-path"
    severity = "warning"
    rationale = (
        "hand-rolled time.time() deltas and bare print() in package code "
        "are invisible to run reports and traces: time windows belong in "
        "obs.span / obs.metrics, messages in EventLogger.log / obs.event"
    )

    def applies(self, ctx: FileContext) -> bool:
        # package code only (tests/benches/scripts measure and print on
        # purpose), minus the user-facing CLI/tooling surfaces
        return _in_package(ctx) and not ctx.relpath.startswith(_GL010_EXCLUDED)

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ctx.nodes_of(ast.Call):
            d = _dotted(node.func)
            if d == "time.time":
                out.append(ctx.finding(
                    self, node,
                    "raw time.time() in package code: wrap the window in "
                    "obs.span(...) (or feed an obs.metrics histogram via "
                    "time.perf_counter) so the duration reaches the run "
                    "report and Perfetto trace; wall-clock event timestamps "
                    "belong to EventLogger/obs",
                ))
            elif d == "print":
                out.append(ctx.finding(
                    self, node,
                    "bare print() in package code: route it through "
                    "EventLogger.log / obs.event so the message lands in "
                    "the structured event stream instead of a scrollback "
                    "buffer",
                ))
        return out


# ---- GL011: scan-carry dtype drift ------------------------------------------

# jnp array constructors whose dtype is the literal `dtype=` kw (or the f32
# default when omitted) — the only leaves the rule can reason about without
# a type system
_GL011_CTORS = {"zeros", "ones", "full", "empty"}
_GL011_DTYPE_KW_CTORS = {"array", "asarray", "arange"}


@register
class ScanCarryDtypeRule(Rule):
    id = "GL011"
    name = "scan-carry-dtype-drift"
    severity = "error"
    rationale = (
        "a lax.scan / while_loop body whose carry comes back in a "
        "different dtype than its init fails jaxpr type-checking at best — "
        "and at worst silently widens/narrows an accumulator every "
        "iteration (f32 init + bf16-cast update); keep the carry dtype "
        "loop-invariant"
    )

    def applies(self, ctx: FileContext) -> bool:
        return True

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        defs: dict[str, ast.AST] = {}
        assigns: dict[str, ast.AST] = {}
        for node in ctx.nodes_of(*_FUNC_NODES):
            defs[node.name] = node
        for node in ctx.nodes_of(ast.Assign):
            if len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    # last write wins — good enough for the literal inits
                    # this rule reasons about
                    assigns[tgt.id] = node.value
        for node in ctx.nodes_of(ast.Call):
            kind = _last(_dotted(node.func))
            if kind == "scan" and len(node.args) >= 2:
                body_arg, init_arg = node.args[0], node.args[1]
            elif kind == "while_loop" and len(node.args) >= 3:
                body_arg, init_arg = node.args[1], node.args[2]
            else:
                continue
            body = self._resolve(body_arg, defs)
            if body is None:
                continue
            init_leaves = self._leaves(init_arg, assigns)
            for ret in self._carry_returns(body, kind):
                ret_leaves = self._leaves(ret, assigns)
                if len(init_leaves) != len(ret_leaves):
                    continue  # structure unknown — out of scope
                for (d_init, init_leaf), (d_ret, leaf) in zip(
                    init_leaves, ret_leaves
                ):
                    if d_init and d_ret and d_init != d_ret:
                        out.append(ctx.finding(
                            self, leaf,
                            f"scan/while carry leaf returns dtype "
                            f"{d_ret!r} but its init is {d_init!r}: the "
                            "carry dtype must be loop-invariant — cast the "
                            "init (or drop the per-iteration cast) so "
                            "input and output types agree",
                            fix=self._init_dtype_fix(
                                init_leaf, d_ret, assigns
                            ),
                        ))
        return out

    @classmethod
    def _init_dtype_fix(cls, init_node, d_ret: str, assigns) -> Fix | None:
        """Rewrite the init's dtype LITERAL to the dtype the body already
        returns — the mechanical half of the rule's prescription (the
        body's dtype is what the computation produces; the init is the
        stale literal). Spelled in the literal's own style; inits without
        an explicit literal stay manual."""
        if isinstance(init_node, ast.Name) and init_node.id in assigns:
            init_node = assigns[init_node.id]
        if not isinstance(init_node, ast.Call):
            return None
        dtype_node = None
        if isinstance(init_node.func, ast.Attribute) and \
                init_node.func.attr == "astype" and init_node.args:
            dtype_node = init_node.args[0]
        else:
            for kw in init_node.keywords:
                if kw.arg == "dtype":
                    dtype_node = kw.value
            if dtype_node is None:
                name = _last(_dotted(init_node.func))
                if name in ("zeros", "ones", "empty") and \
                        len(init_node.args) >= 2:
                    dtype_node = init_node.args[1]
        if dtype_node is None or not d_ret.isidentifier():
            return None
        if isinstance(dtype_node, ast.Constant) and isinstance(
            dtype_node.value, str
        ):
            replacement = repr(d_ret)
        else:
            d = _dotted(dtype_node)
            if not d:
                return None
            base = d.rpartition(".")[0]
            replacement = f"{base}.{d_ret}" if base else d_ret
        return Fix(
            edits=(Edit.from_node(dtype_node, replacement),),
            description=(
                f"rewrite the carry init's dtype literal to {d_ret!r} "
                "(the dtype the body returns) so the carry dtype is "
                "loop-invariant"
            ),
        )

    @staticmethod
    def _resolve(arg, defs):
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            return defs.get(arg.id)
        return None

    @staticmethod
    def _carry_returns(body, kind) -> list[ast.AST]:
        """Carry expressions returned by a scan/while body.

        scan bodies return ``(carry, y)`` — the carry is element 0;
        while bodies return the whole carry. Lambdas return their body."""
        rets: list[ast.AST] = []
        if isinstance(body, ast.Lambda):
            exprs = [body.body]
        else:
            # this function's own returns only — skip nested defs/lambdas
            exprs = []
            stack = list(body.body)
            while stack:
                n = stack.pop()
                if isinstance(n, _FUNC_NODES + (ast.Lambda,)):
                    continue
                if isinstance(n, ast.Return) and n.value is not None:
                    exprs.append(n.value)
                stack.extend(ast.iter_child_nodes(n))
        for expr in exprs:
            if kind == "scan":
                if isinstance(expr, ast.Tuple) and len(expr.elts) == 2:
                    rets.append(expr.elts[0])
            else:
                rets.append(expr)
        return rets

    @classmethod
    def _leaves(cls, expr, assigns) -> list[tuple[str | None, ast.AST]]:
        """Flatten one tuple level into (dtype-or-None, node) leaves."""
        if isinstance(expr, ast.Name) and expr.id in assigns:
            expr = assigns[expr.id]
        if isinstance(expr, ast.Tuple):
            return [
                (cls._dtype_of(e, assigns), e) for e in expr.elts
            ]
        return [(cls._dtype_of(expr, assigns), expr)]

    @classmethod
    def _dtype_of(cls, expr, assigns) -> str | None:
        """Literal dtype of an expression, when statically evident."""
        if isinstance(expr, ast.Name) and expr.id in assigns:
            expr = assigns[expr.id]
        if not isinstance(expr, ast.Call):
            return None
        if isinstance(expr.func, ast.Attribute) and expr.func.attr == "astype":
            return cls._dtype_name(expr.args[0]) if expr.args else None
        name = _last(_dotted(expr.func))
        for kw in expr.keywords:
            if kw.arg == "dtype":
                return cls._dtype_name(kw.value)
        if name in _GL011_CTORS:
            # second positional arg of zeros/ones/full(shape[, fill], dtype)
            # is the dtype for zeros/ones; full's is the fill value
            if name in ("zeros", "ones", "empty") and len(expr.args) >= 2:
                return cls._dtype_name(expr.args[1])
            return "float32"  # jnp default
        return None

    @staticmethod
    def _dtype_name(node) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        d = _last(_dotted(node))
        return d or None


# ---- GL012: collective-axis-name typos --------------------------------------

# canonical table lives in project.py (the axis-environment scan shares it)
_GL012_COLLECTIVES = COLLECTIVE_AXIS_POS
_GL012_AXIS_KWARGS = COLLECTIVE_AXIS_KWARGS


def _enclosing_def_quals(ctx: FileContext) -> dict[int, str]:
    """id(any node) -> qualname of the nearest enclosing def (the axis
    tables' naming scheme), '' at module/class scope. Cached per file."""
    cached = ctx._cache.get("enclosing_def_quals")
    if cached is not None:
        return cached
    quals = def_qualnames(ctx.tree)
    out: dict[int, str] = {}

    def walk(node: ast.AST, owner: str) -> None:
        for child in ast.iter_child_nodes(node):
            child_owner = quals.get(id(child), owner)
            out[id(child)] = child_owner
            walk(child, child_owner)

    walk(ctx.tree, "")
    ctx._cache["enclosing_def_quals"] = out
    return out


@register
class CollectiveAxisRule(ProjectRule):
    id = "GL012"
    name = "collective-axis-name-typo"
    severity = "error"
    rationale = (
        "a psum/pmean/all_gather over a misspelled mesh axis name only "
        "fails at trace time deep inside shard_map (unbound axis) — or, "
        "with nested meshes, silently reduces over the WRONG axis; literal "
        "axis names are checked against the axes train/mesh.py declares, "
        "plus any axis the call path visibly binds (vmap/pmap axis_name) — "
        "GL016 owns the scoped is-it-actually-bound check"
    )

    def applies(self, ctx: FileContext) -> bool:
        # package code only: tests/fixtures spell fake axes on purpose
        return _in_package(ctx)

    def check_project(self, ctx: FileContext,
                      index: ProjectIndex) -> list[Finding]:
        # the mesh-axes scrape lives on the project index now: rebuilt
        # whenever mesh.py's (mtime, size) changes, so a long-lived test
        # session can never lint against stale axes
        out: list[Finding] = []
        allowed = index.mesh.axes
        module = index.module_of(ctx.relpath)
        enclosing: dict[int, str] | None = None  # built on first collective
        for node in ctx.nodes_of(ast.Call):
            name = _last(_dotted(node.func))
            pos = _GL012_COLLECTIVES.get(name)
            if pos is None:
                continue
            if enclosing is None:
                enclosing = _enclosing_def_quals(ctx)
            axis_arg = None
            for kw in node.keywords:
                if kw.arg in _GL012_AXIS_KWARGS:
                    axis_arg = kw.value
            if axis_arg is None and len(node.args) > pos:
                axis_arg = node.args[pos]
            for axis in self._axis_literals(axis_arg):
                if axis in allowed:
                    continue
                # an axis some reachable caller BINDS (vmap(axis_name=)/
                # pmap) is not a typo even though the mesh never declares
                # it — the axis-environment pass (GL016's substrate) knows
                qual = enclosing.get(id(node), "")
                if qual:
                    env, _ = index.axis_env_of(module, qual)
                    if axis in env:
                        continue
                out.append(ctx.finding(
                    self, node,
                    f"{name}(...) over axis {axis!r}, which is not a "
                    "mesh axis train/mesh.py declares "
                    f"({', '.join(sorted(allowed))}) nor an axis any "
                    "reachable caller binds: a typo here is an "
                    "unbound-axis trace error at best and a wrong-axis "
                    "reduction at worst",
                ))
        return out

    @staticmethod
    def _axis_literals(node) -> list[str]:
        """String-literal axis names in an axis argument (a constant or a
        tuple/list of constants); dynamic expressions are out of scope."""
        if node is None:
            return []
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            return [
                e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
        return []


# ---- GL013: implicit host transfers on device-provenance values -------------

# numpy calls that force a device->host transfer when handed a device array
_GL013_NP_SINKS = {
    "asarray", "array", "ascontiguousarray", "copy", "mean", "sum", "max",
    "min", "abs", "concatenate", "stack", "vstack", "hstack", "where",
    "argmax", "argmin", "argsort", "sort", "unique", "square", "sqrt",
    "clip", "dot", "einsum", "std", "var", "median", "prod", "all", "any",
    "allclose", "array_equal", "count_nonzero", "save", "savez",
}
# jnp re-wraps of an already-device value: at best a no-op, at worst a
# hidden dtype-cast copy — and historically the spelling that smuggled a
# per-step host re-wrap of prefetched batches into the hot loop
_GL013_JNP_SINKS = {"jax.numpy.asarray", "jax.numpy.array"}

_GL013_EXCLUDED = ("cst_captioning_tpu/tools/",)


class _DeviceFlow:
    """In-order local dataflow over one function body (pass 2 of GL013):
    tracks which names hold device-resident values and the interprocedural
    path that made them so, querying the project index for callee return
    provenance and device-yielding generators."""

    def __init__(self, rule: "ImplicitTransferRule", ctx: FileContext,
                 index: ProjectIndex, aliases: dict[str, str]):
        self.rule = rule
        self.ctx = ctx
        self.index = index
        self.aliases = aliases
        self.module = index.module_of(ctx.relpath)
        self.device_vars: dict[str, str] = {}   # name -> provenance chain
        self.findings: list[Finding] = []
        self._reported: set[tuple[int, int]] = set()
        # the spelling of jax.device_get THIS file can use (autofix): the
        # plain `import jax` alias, or a direct `from jax import device_get`
        self.device_get_spelling = ""
        for local, target in aliases.items():
            if target == "jax":
                self.device_get_spelling = f"{local}.device_get"
                break
            if target == "jax.device_get" and not self.device_get_spelling:
                self.device_get_spelling = local

    # -- provenance ------------------------------------------------------

    def provenance(self, expr: ast.AST) -> str | None:
        """Why ``expr`` is device-resident (a human-readable chain), or
        None when its provenance is unknown — never guess."""
        if isinstance(expr, ast.Name):
            return self.device_vars.get(expr.id)
        if isinstance(expr, (ast.Attribute, ast.Subscript, ast.Starred)):
            return self.provenance(expr.value)
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Call):
                inner = resolve_dotted(
                    _dotted(expr.func.func), self.aliases
                )
                if _last(inner) in _TRACERS:
                    return f"result of {inner}(...)"
            resolved = resolve_dotted(_dotted(expr.func), self.aliases)
            if not resolved:
                return None
            from cst_captioning_tpu.tools.graftlint.project import (
                _DEVICE_BASES, _DEVICE_EXACT, _HOST_BASES, _HOST_EXACT,
            )
            if resolved in _HOST_EXACT or resolved.startswith(_HOST_BASES):
                return None
            if resolved in _DEVICE_EXACT or \
                    resolved.startswith(_DEVICE_BASES):
                return f"result of {resolved}(...)"
            if resolved.startswith("jax."):
                return None
            hit = self.index.lookup_from(self.module, resolved)
            if hit is not None and hit[1].returns_device:
                name, summary = hit
                return f"returns from {name}() [{summary.device_reason}]"
            return None
        if isinstance(expr, ast.BinOp):
            return self.provenance(expr.left) or \
                self.provenance(expr.right)
        if isinstance(expr, (ast.Tuple, ast.List)):
            for elt in expr.elts:
                chain = self.provenance(elt)
                if chain:
                    return chain
            return None
        if isinstance(expr, ast.IfExp):
            return self.provenance(expr.body) or \
                self.provenance(expr.orelse)
        return None

    # -- statement walk --------------------------------------------------

    def run(self, body: list[ast.stmt]) -> list[Finding]:
        for stmt in body:
            self._stmt(stmt)
        return self.findings

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, _FUNC_NODES + (ast.Lambda, ast.ClassDef)):
            return  # separate scopes, analyzed on their own
        if isinstance(node, ast.Assign):
            self._sinks(node.value)
            self._bind(node.targets, node.value)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if node.value is not None:
                self._sinks(node.value)
                self._bind([node.target], node.value)
        elif isinstance(node, ast.For):
            self._sinks(node.iter)
            self._bind_loop_target(node)
            for stmt in node.body + node.orelse:
                self._stmt(stmt)
        elif isinstance(node, ast.If):
            # exclusive branches: a binding in one arm must not leak into
            # the other; after the join, only names device in BOTH arms
            # stay device (must-analysis — never guess)
            self._sinks(node.test)
            before = dict(self.device_vars)
            for stmt in node.body:
                self._stmt(stmt)
            after_body = self.device_vars
            self.device_vars = before if not node.orelse else dict(before)
            for stmt in node.orelse:
                self._stmt(stmt)
            after_else = self.device_vars
            self.device_vars = {
                k: v for k, v in after_body.items() if k in after_else
            }
        elif isinstance(node, ast.Try):
            before = dict(self.device_vars)
            for stmt in node.body + node.orelse:
                self._stmt(stmt)
            after_body = self.device_vars
            for handler in node.handlers:
                self.device_vars = dict(before)
                for stmt in handler.body:
                    self._stmt(stmt)
                after_body = {
                    k: v for k, v in after_body.items()
                    if k in self.device_vars
                }
            self.device_vars = after_body
            for stmt in node.finalbody:
                self._stmt(stmt)
        elif isinstance(node, ast.expr):
            self._sinks(node)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._sinks(child)
                else:
                    self._stmt(child)

    def _bind(self, targets: list[ast.AST], value: ast.AST) -> None:
        chain = self.provenance(value)
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    if chain:
                        self.device_vars[sub.id] = chain
                    else:
                        self.device_vars.pop(sub.id, None)

    def _bind_loop_target(self, node: ast.For) -> None:
        if not isinstance(node.iter, ast.Call):
            return
        resolved = resolve_dotted(_dotted(node.iter.func), self.aliases)
        hit = self.index.lookup_from(self.module, resolved)
        if hit is None or not hit[1].yields_device:
            return
        name, summary = hit
        chain = f"yielded by {name}() [{summary.device_reason}]"
        for sub in ast.walk(node.target):
            if isinstance(sub, ast.Name):
                self.device_vars[sub.id] = chain

    # -- sink detection --------------------------------------------------

    def _sinks(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            # .tolist() on a device value
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "tolist" and not node.args:
                chain = self.provenance(node.func.value)
                if chain:
                    self._report(
                        node,
                        ".tolist() forces a blocking device→host "
                        f"transfer: the receiver is device-resident "
                        f"({self._describe(node.func.value)} ← {chain})",
                    )
                continue
            resolved = resolve_dotted(_dotted(node.func), self.aliases)
            if not resolved or not node.args:
                continue
            base, _, attr = resolved.rpartition(".")
            sink = None
            if base == "numpy" and attr in _GL013_NP_SINKS:
                sink = f"np.{attr}"
            elif resolved in _GL013_JNP_SINKS:
                sink = f"jnp.{attr}"
            if sink is None:
                continue
            chain = self.provenance(node.args[0])
            if not chain:
                continue
            if sink.startswith("np."):
                msg = (
                    f"{sink}(...) on a device-resident value forces an "
                    "implicit device→host transfer"
                )
                hint = (
                    "read it back explicitly with jax.device_get (one "
                    "visible sync) or keep the math in jnp"
                )
            else:
                msg = (
                    f"{sink}(...) re-wraps a value that is already on "
                    "device — at best a no-op, at worst a hidden copy/cast"
                )
                hint = "drop the conversion (or make the cast explicit)"
            self._report(
                node,
                f"{msg}: {self._describe(node.args[0])} ← {chain}; {hint}",
                fix=self._asarray_fix(node, attr)
                if sink.startswith("np.") else None,
            )

    def _asarray_fix(self, node: ast.Call, attr: str) -> Fix | None:
        """``np.asarray(x)`` -> ``jax.device_get(x)``: both return a host
        numpy array of the same values, but device_get is the EXPLICIT
        readback spelling (behavior-identical, sanitizer-legal). Only the
        bare single-argument form is mechanical; dtype=/copy= kwargs
        change semantics and stay manual. A file with no jax import in
        scope gets ``import jax`` inserted alongside the rewrite — the
        dedup in check_project keeps that insertion on one finding only
        (identical spans read as two writers to the fix engine)."""
        if attr != "asarray":
            return None
        if len(node.args) != 1 or node.keywords:
            return None
        spelling = self.device_get_spelling or "jax.device_get"
        edits = [Edit.from_node(node.func, spelling)]
        description = (
            f"replace {self._describe(node.func)}(...) with "
            f"{spelling}(...) — the same host readback, made explicit"
        )
        if not self.device_get_spelling:
            edits.append(self._import_jax_edit())
            description += " (inserting the missing `import jax`)"
        return Fix(edits=tuple(edits), description=description)

    def _import_jax_edit(self) -> Edit:
        """Zero-width insertion of ``import jax`` where the file's layout
        dictates: after the last ``__future__`` import (those must stay
        first), else grouped onto the first top-level import, else after
        the module docstring, else line 1. Never anchors on a non-import
        statement's ``lineno`` — that would land between a decorator and
        its def."""
        body = self.ctx.tree.body
        line = 1
        i = 0
        if body and isinstance(body[0], ast.Expr) and isinstance(
                body[0].value, ast.Constant) and isinstance(
                body[0].value.value, str):
            line = int(body[0].end_lineno or body[0].lineno) + 1
            i = 1
        futures = [
            s for s in body
            if isinstance(s, ast.ImportFrom) and s.module == "__future__"
        ]
        if futures:
            line = int(futures[-1].end_lineno or futures[-1].lineno) + 1
        else:
            first_import = next(
                (s for s in body[i:]
                 if isinstance(s, (ast.Import, ast.ImportFrom))), None,
            )
            if first_import is not None:
                line = first_import.lineno
        return Edit(line=line, col=0, end_line=line, end_col=0,
                    replacement="import jax\n")

    def _describe(self, expr: ast.AST) -> str:
        try:
            src = ast.unparse(expr)
        except Exception:  # pragma: no cover - defensive
            src = "<expr>"
        return src if len(src) <= 40 else src[:37] + "…"

    def _report(self, node: ast.AST, message: str,
                fix: Fix | None = None) -> None:
        key = (node.lineno, node.col_offset)
        if key not in self._reported:
            self._reported.add(key)
            self.findings.append(
                self.ctx.finding(self.rule, node, message, fix=fix)
            )


@register
class ImplicitTransferRule(ProjectRule):
    id = "GL013"
    name = "implicit-host-transfer"
    severity = "warning"
    rationale = (
        "np.asarray/.tolist()/np.* math on a value whose provenance traces "
        "to device arrays (a traced-fn result, a prefetched batch) is a "
        "hidden blocking device→host transfer — even two calls deep in "
        "another module; the sanitizer gate (scripts/sanitize.sh) enforces "
        "the same claim at runtime via jax.transfer_guard"
    )

    def applies(self, ctx: FileContext) -> bool:
        # package code only (benches/tests/scripts read back on purpose),
        # minus the linter itself
        return _in_package(ctx) and not ctx.relpath.startswith(
            _GL013_EXCLUDED
        )

    def check_project(self, ctx: FileContext,
                      index: ProjectIndex) -> list[Finding]:
        aliases = index.aliases_for(ctx.relpath, ctx.tree)
        traced = traced_node_ids(ctx)
        out: list[Finding] = []
        # module scope + every non-traced function, each a fresh dataflow
        # (traced scopes belong to GL001: inside a trace these calls are a
        # trace error, not a quiet transfer)
        scopes: list[list[ast.stmt]] = [ctx.tree.body]
        for node in ctx.nodes_of(*_FUNC_NODES):
            if id(node) not in traced:
                scopes.append(node.body)
        for body in scopes:
            out.extend(_DeviceFlow(self, ctx, index, aliases).run(body))
        self._dedup_import_edits(out)
        return out

    @staticmethod
    def _is_import_edit(e: Edit) -> bool:
        return (e.replacement == "import jax\n" and e.line == e.end_line
                and e.col == 0 and e.end_col == 0)

    @classmethod
    def _dedup_import_edits(cls, findings: list[Finding]) -> None:
        """Several findings in one import-less file each want the same
        zero-width ``import jax`` insertion; the fix engine refuses
        identical spans as two writers, so only the FIRST fixable finding
        in source order (the order plan_fixes accepts edits) keeps it —
        the rest are rebuilt without the insertion, and one ``--fix``
        pass lands the import exactly once."""
        kept = False
        for f in sorted(
            (f for f in findings if f.fix is not None),
            key=lambda f: (f.line, f.col),
        ):
            if not any(cls._is_import_edit(e) for e in f.fix.edits):
                continue
            if kept:
                f.fix.edits = tuple(
                    e for e in f.fix.edits if not cls._is_import_edit(e)
                )
            kept = True


# ---- GL014: cross-function PRNG key reuse -----------------------------------

@register
class CrossFunctionKeyReuseRule(ProjectRule):
    id = "GL014"
    name = "cross-function-prng-key-reuse"
    severity = "error"
    rationale = (
        "a key handed to a callee that CONSUMES it (directly or further "
        "down the call graph) and then reused by the caller draws the same "
        "randomness twice — GL002 past function boundaries, resolved "
        "through the project call graph"
    )

    def applies(self, ctx: FileContext) -> bool:
        # tests reuse keys deliberately (determinism assertions)
        return not _is_test_file(ctx)

    def check_project(self, ctx: FileContext,
                      index: ProjectIndex) -> list[Finding]:
        aliases = index.aliases_for(ctx.relpath, ctx.tree)
        module = index.module_of(ctx.relpath)
        out: list[Finding] = []
        for node in ctx.nodes_of(*_FUNC_NODES):
            out.extend(
                self._check_function(ctx, index, aliases, module, node)
            )
        return out

    def _check_function(self, ctx: FileContext, index: ProjectIndex,
                        aliases: dict[str, str], module: str,
                        fn: ast.AST) -> list[Finding]:
        # events in source order, nested scopes excluded (same walk shape
        # as GL002; the new event kind is "a callee spent this key")
        events: list[tuple[int, int, str, str, ast.AST, str]] = []

        def visit(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_NODES + (ast.Lambda,)):
                    continue
                if isinstance(child, ast.Call):
                    self._call_events(child, index, aliases, module, events)
                if isinstance(child, (ast.Assign, ast.AugAssign,
                                      ast.AnnAssign, ast.For, ast.withitem,
                                      ast.NamedExpr)):
                    for name in _bound_names(child):
                        events.append((
                            getattr(child, "lineno", 0),
                            getattr(child, "col_offset", 0),
                            "bind", name, child, "",
                        ))
                visit(child)

        visit(fn)
        events.sort(key=lambda e: (e[0], e[1]))

        live: dict[str, tuple[ast.AST, str]] = {}
        out: list[Finding] = []
        for _, _, kind, payload, node, info in events:
            if kind == "bind":
                for expr in [e for e in live
                             if re.search(rf"\b{re.escape(payload)}\b", e)]:
                    del live[expr]
                continue
            if payload in live:
                first_node, first_info = live[payload]
                # pure-local double consumption is GL002's finding; this
                # rule owns the pairs a single-file engine cannot see
                if kind == "consume-callee" or first_info:
                    where = (
                        f"consumed by {first_info}" if first_info
                        else "consumed by a jax.random call"
                    )
                    use = (
                        f"passing it to {info}" if info
                        else "this jax.random call"
                    )
                    out.append(ctx.finding(
                        self, node,
                        f"PRNG key {payload!r} was already {where} on line "
                        f"{first_node.lineno}; {use} reuses it — split or "
                        "fold_in first (identical keys give identical "
                        "draws)",
                    ))
            else:
                live[payload] = (node, info)
        return out

    @staticmethod
    def _call_events(call: ast.Call, index: ProjectIndex,
                     aliases: dict[str, str], module: str,
                     events: list) -> None:
        resolved = resolve_dotted(_dotted(call.func), aliases)
        if not resolved:
            return
        base, _, attr = resolved.rpartition(".")
        if base == "jax.random" and attr in _KEY_CONSUMERS:
            key_arg = call.args[0] if call.args else None
            for kw in call.keywords:
                if kw.arg == "key":
                    key_arg = kw.value
            src = _unparse(key_arg)
            if src:
                events.append((call.lineno, call.col_offset,
                               "consume-local", src, call, ""))
            return
        if resolved.startswith(("jax.", "numpy.")):
            return
        if _last(resolved) not in index.key_consumer_names:
            return  # no key-consuming function anywhere shares the name
        hit = index.lookup_from(module, resolved)
        if hit is None or not hit[1].key_params_consumed:
            return
        name, summary = hit
        for param in summary.key_params_consumed:
            arg = None
            try:
                pos = summary.params.index(param)
            except ValueError:
                pos = -1
            if 0 <= pos < len(call.args):
                arg = call.args[pos]
            for kw in call.keywords:
                if kw.arg == param:
                    arg = kw.value
            src = _unparse(arg)
            if src:
                via = summary.key_consumed_via.get(param, "")
                info = f"{name}() (parameter {param!r}"
                info += f", spent via {via})" if via else ")"
                events.append((call.lineno, call.col_offset,
                               "consume-callee", src, call, info))


def _unparse(node: ast.AST | None) -> str:
    if node is None:
        return ""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - defensive
        return ""


# ---- GL015: sharding-spec drift vs the mesh declaration ---------------------

_GL015_SPEC_TYPES = {
    "jax.sharding.PartitionSpec",
    "jax.experimental.pjit.PartitionSpec",
    "jax.interpreters.pxla.PartitionSpec",
}


@register
class ShardingSpecDriftRule(ProjectRule):
    id = "GL015"
    name = "sharding-spec-drift"
    severity = "error"
    rationale = (
        "a PartitionSpec/NamedSharding axis literal that is not a mesh "
        "axis train/mesh.py declares shards over an axis that does not "
        "exist — an unbound-axis error at jit time, or (after a mesh "
        "rename) a silently replicated array that was meant to be sharded; "
        "every spec literal in the package resolves against the shared "
        "project index's mesh declaration"
    )

    def applies(self, ctx: FileContext) -> bool:
        # package code only: tests spell fake axes on purpose
        return _in_package(ctx) and not ctx.relpath.startswith(
            "cst_captioning_tpu/tools/"
        )

    def check_project(self, ctx: FileContext,
                      index: ProjectIndex) -> list[Finding]:
        aliases = index.aliases_for(ctx.relpath, ctx.tree)
        allowed = index.mesh.axes
        out: list[Finding] = []
        for node in ctx.nodes_of(ast.Call):
            resolved = resolve_dotted(_dotted(node.func), aliases)
            if resolved not in _GL015_SPEC_TYPES:
                continue
            for axis, anchor in self._axis_literals(node):
                if axis not in allowed:
                    out.append(ctx.finding(
                        self, anchor,
                        f"PartitionSpec axis {axis!r} is not a mesh axis "
                        "train/mesh.py declares "
                        f"({', '.join(sorted(allowed))}): the spec drifted "
                        "from the mesh declaration — rename the axis or "
                        "declare it in make_mesh",
                    ))
        return out

    @staticmethod
    def _axis_literals(call: ast.Call) -> list[tuple[str, ast.AST]]:
        out: list[tuple[str, ast.AST]] = []
        for arg in call.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append((arg.value, arg))
            elif isinstance(arg, (ast.Tuple, ast.List)):
                for elt in arg.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        out.append((elt.value, elt))
        return out


# ---- GL016: collective over a declared-but-unbound axis ---------------------

@register
class CollectiveAxisScopeRule(ProjectRule):
    id = "GL016"
    name = "collective-axis-unbound-in-scope"
    severity = "error"
    rationale = (
        "a psum/pmean/all_gather over a mesh axis that NO reachable "
        "calling context binds (no shard_map/vmap(axis_name=)/pmap on any "
        "call path) is an unbound-axis error the moment it runs — GL012 "
        "cannot see this (the axis IS declared); the per-function axis "
        "environments propagated through the call-graph fixpoint can, "
        "including for helpers called from inside a shard_map body"
    )

    def applies(self, ctx: FileContext) -> bool:
        # package code only: tests/fixtures spell unbound axes on purpose
        return _in_package(ctx) and not ctx.relpath.startswith(
            "cst_captioning_tpu/tools/"
        )

    def check_project(self, ctx: FileContext,
                      index: ProjectIndex) -> list[Finding]:
        mod = index.by_relpath.get(ctx.relpath)
        if mod is None or mod.parse_error:
            return []
        allowed = index.mesh.axes
        out: list[Finding] = []
        for qual, info in mod.axis_funcs.items():
            if not info.collectives:
                continue
            env, has_context = index.axis_env_of(mod.module, qual)
            if not has_context:
                # no binding application and no in-tree caller: an entry
                # point whose runtime context is unknowable — never guess
                continue
            for prim, axis, line, col in info.collectives:
                if axis not in allowed:
                    continue  # undeclared axis: that finding is GL012's
                if axis in env:
                    continue
                bound = ", ".join(sorted(env)) if env else "no named axes"
                out.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=ctx.relpath, line=line, col=col,
                    message=(
                        f"{prim}(...) over axis {axis!r} inside "
                        f"{qual}(): the axis is declared by train/mesh.py "
                        "but NOT bound in any reachable calling context "
                        f"(known callers bind {bound}) — at runtime this "
                        "is an unbound-axis error; bind it on the call "
                        "path (shard_map axis_names= / vmap(axis_name=)) "
                        "or route the axis in as a parameter"
                    ),
                    context=ctx.line_text(line),
                ))
        return out


# ---- GL017: interprocedural donation hazards --------------------------------

class _DonationFlow:
    """Source-order walk of one function body tracking (a) names bound to
    DONATING callables — a literal ``jax.jit(..., donate_argnums=...)``,
    or a factory whose summary says it returns one — and (b) buffer names
    donated through them. A later read of a donated name is a
    use-after-donate: at runtime the buffer is deleted and the read
    raises. Loop bodies are walked twice so a donation on iteration one
    is visible to reads on iteration two (the classic un-rebound
    ``new_state = step(state, b)`` train-loop bug).

    Buffers and callees can both be ATTRIBUTE-rooted: ``self._buf`` donated
    through ``self._write`` (a ``self.``/``cls.``-stripped method resolved
    via the project index) is tracked under its dotted name, so the
    donate-and-rebind ring-buffer idiom
    ``self._buf = self._write(self._buf, x)`` stays clean while a missing
    rebind flags (rl/async_scst.py's RolloutRing is the in-tree shape)."""

    def __init__(self, rule: "DonationFlowRule", ctx: FileContext,
                 index: ProjectIndex, aliases: dict[str, str]):
        self.rule = rule
        self.ctx = ctx
        self.index = index
        self.aliases = aliases
        self.module = index.module_of(ctx.relpath)
        # callable name (dotted, e.g. "step" / "self._admit_fn") ->
        # (donated argnums, human label)
        self.donating: dict[str, tuple[tuple[int, ...], str]] = {}
        # buffer name -> (donation line, human label)
        self.donated: dict[str, tuple[int, str]] = {}
        self.findings: list[Finding] = []
        self._reported: set[tuple[int, int]] = set()

    # -- resolution ------------------------------------------------------

    def _callable_donation(
        self, call: ast.Call
    ) -> tuple[tuple[int, ...], str] | None:
        """Donated argnums of the callable a call goes through, resolved
        locally (a tracked binding) or through the project index (a
        donating def / a wrapper forwarding into one)."""
        dotted = _dotted(call.func)
        local = self.donating.get(dotted)
        if local is not None:
            return local
        # attribute-rooted callees: `self._write(...)` resolves to the
        # enclosing (or any unique) class's method — the index keys are
        # `module.Class.method`, which `self.`/`cls.` can never prefix
        if dotted.startswith(("self.", "cls.")):
            dotted = dotted.split(".", 1)[1]
        if _last(dotted) not in self.index.donation_names:
            return None  # no donating function anywhere shares the name
        resolved = resolve_dotted(dotted, self.aliases)
        if not resolved or resolved.startswith(("jax.", "numpy.")):
            return None
        hit = self.index.lookup_from(self.module, resolved)
        if hit is None:
            return None
        name, summary = hit
        positions = sorted(
            set(summary.donated_argnums) | set(summary.forwards_donated)
        )
        if not positions:
            return None
        vias = [
            summary.forwards_donated_via.get(str(p)) for p in positions
        ]
        via = next((v for v in vias if v), "")
        label = f"{name}() (donates argument(s) {positions}"
        label += f", via {via})" if via else ")"
        return tuple(positions), label

    def _factory_donation(self, call: ast.Call) -> tuple | None:
        dotted = _dotted(call.func)
        if dotted.startswith(("self.", "cls.")):
            dotted = dotted.split(".", 1)[1]
        if _last(dotted) not in self.index.donation_names:
            return None
        resolved = resolve_dotted(dotted, self.aliases)
        if not resolved or resolved.startswith(("jax.", "numpy.")):
            return None
        hit = self.index.lookup_from(self.module, resolved)
        if hit is None or not hit[1].returns_donating:
            return None
        name = hit[0]
        return (
            tuple(hit[1].returns_donating),
            f"the donating jit returned by {name}()",
        )

    # -- statement walk --------------------------------------------------

    def run(self, body: list[ast.stmt]) -> list[Finding]:
        for stmt in body:
            self._stmt(stmt)
        return self.findings

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, _FUNC_NODES + (ast.Lambda, ast.ClassDef)):
            return  # separate scopes
        if isinstance(node, ast.Assign):
            self._expr(node.value)
            self._bind(node.targets, node.value)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if node.value is not None:
                self._expr(node.value)
                self._bind([node.target], node.value)
        elif isinstance(node, (ast.For, ast.While)):
            if isinstance(node, ast.For):
                self._expr(node.iter)
            else:
                self._expr(node.test)
            for _ in range(2):  # donations are loop-carried
                for stmt in node.body:
                    self._stmt(stmt)
            for stmt in node.orelse:
                self._stmt(stmt)
        elif isinstance(node, ast.If):
            self._expr(node.test)
            before_donated = dict(self.donated)
            before_donating = dict(self.donating)
            for stmt in node.body:
                self._stmt(stmt)
            after_body_donated = self.donated
            after_body_donating = self.donating
            self.donated = dict(before_donated)
            self.donating = dict(before_donating)
            for stmt in node.orelse:
                self._stmt(stmt)
            # may-join: a donation on either arm poisons later reads
            self.donated = {**after_body_donated, **self.donated}
            self.donating = {**after_body_donating, **self.donating}
        elif isinstance(node, ast.expr):
            self._expr(node)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._expr(child)
                else:
                    self._stmt(child)

    def _bind(self, targets: list[ast.AST], value: ast.AST) -> None:
        donating = None
        if isinstance(value, ast.Call):
            argnums = donation_of_call(value)
            if argnums:
                donating = (argnums, "a jax.jit(donate_argnums=...) "
                                     "built here")
            else:
                donating = self._factory_donation(value)
        for t in targets:
            # rebinding refreshes both roles of every name it touches
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    self.donated.pop(sub.id, None)
                    self.donating.pop(sub.id, None)
            dotted = _dotted(t)
            if dotted:
                self.donated.pop(dotted, None)
                self.donating.pop(dotted, None)
                if donating is not None:
                    self.donating[dotted] = donating

    def _expr(self, expr: ast.AST) -> None:
        # one walk: reads of already-donated buffers are reported BEFORE
        # this expression's own donations land (the canonical
        # `state = step(state, b)` donate-and-rebind must not self-flag)
        calls: list[ast.Call] = []
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                calls.append(node)
                continue
            read = None
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ):
                read = node.id
            elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                # attribute-rooted buffers (`self._state`-style) donate and
                # read under their dotted name
                read = _dotted(node)
            if read and read in self.donated:
                line, label = self.donated[read]
                self._report(
                    node,
                    f"buffer {read!r} was donated on line {line} "
                    f"(to {label}) and is read again here: donation "
                    "deletes the buffer, so this read raises at runtime "
                    "— reorder the read before the donating call, or "
                    "rebind the name to the call's result",
                )
        for node in calls:
            don = self._callable_donation(node)
            if don is None:
                continue
            positions, label = don
            for pos in positions:
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                if isinstance(arg, ast.Name):
                    name = arg.id
                elif isinstance(arg, ast.Attribute):
                    name = _dotted(arg)
                else:
                    continue
                if name and name not in self.donated:
                    self.donated[name] = (node.lineno, label)

    def _report(self, node: ast.AST, message: str) -> None:
        key = (node.lineno, node.col_offset)
        if key not in self._reported:
            self._reported.add(key)
            self.findings.append(
                self.ctx.finding(self.rule, node, message)
            )


@register
class DonationFlowRule(ProjectRule):
    id = "GL017"
    name = "interprocedural-donation-hazard"
    severity = "error"
    rationale = (
        "donation facts propagate through wrapper helpers: a buffer "
        "handed to a jit(donate_argnums=...) — even through a factory or "
        "a forwarding wrapper in another module — is DELETED, so a later "
        "read raises at runtime; and an outer jit() around a wrapper "
        "whose callee donates silently drops the donation (the GL004 "
        "memory ceiling comes back with no finding at the wrapper site)"
    )

    def applies(self, ctx: FileContext) -> bool:
        # package code only (tests exercise donation errors on purpose),
        # minus the linter itself
        return _in_package(ctx) and not ctx.relpath.startswith(
            "cst_captioning_tpu/tools/"
        )

    def check_project(self, ctx: FileContext,
                      index: ProjectIndex) -> list[Finding]:
        aliases = index.aliases_for(ctx.relpath, ctx.tree)
        out: list[Finding] = []
        # (a) use-after-donate, one flow per function scope
        for node in ctx.nodes_of(*_FUNC_NODES):
            out.extend(
                _DonationFlow(self, ctx, index, aliases).run(node.body)
            )
        # (b) outer jit() that silently drops a wrapped donation
        out.extend(self._dropped_donation(ctx, index, aliases))
        return out

    def _dropped_donation(self, ctx: FileContext, index: ProjectIndex,
                          aliases: dict[str, str]) -> list[Finding]:
        module = index.module_of(ctx.relpath)
        out: list[Finding] = []
        candidates: list[tuple[str, ast.AST]] = []
        for node in ctx.nodes_of(ast.Call):
            if _last(_dotted(node.func)) in ("jit", "pjit"):
                if any(kw.arg in _DONATE_KWARGS for kw in node.keywords):
                    continue  # donation (even dynamic) was a decision
                if node.args and isinstance(node.args[0], ast.Name):
                    candidates.append((node.args[0].id, node))
        for node in ctx.nodes_of(*_FUNC_NODES):
            for dec in node.decorator_list:
                if DonationRule._jit_without_donation(dec):
                    candidates.append((node.name, dec))
                    break
        for target, anchor in candidates:
            if _last(target) not in index.donation_names:
                continue
            hit = index.lookup_from(module, resolve_dotted(target, aliases))
            if hit is None or not hit[1].forwards_donated:
                continue
            name, summary = hit
            pos = summary.forwards_donated[0]
            param = summary.params[pos] if pos < len(summary.params) \
                else f"#{pos}"
            via = summary.forwards_donated_via.get(str(pos), "")
            chain = f" via {via}" if via else ""
            out.append(ctx.finding(
                self, anchor,
                f"jit() wraps {target!r} without donation, but "
                f"{name}() forwards its parameter {param!r} into a "
                f"donated position{chain}: under an outer jit the inner "
                "donate_argnums is ignored, so the buffer silently "
                "double-buffers again — donate at THIS jit (or drop the "
                "inner donation)",
                severity="warning",
            ))
        return out


# ---- GL018: regex partition-rule table coverage and shadowing ---------------

_DEFAULT_CONTRACT = "scripts/shardings_contract.json"


def _delete_element_fix(ctx: FileContext, elt: ast.AST,
                        description: str) -> Fix:
    """Span-delete one tuple/list element plus its trailing comma; when
    the element owns its line(s) outright, take the whole lines so no
    blank husk is left behind."""
    start_line, start_col = elt.lineno, elt.col_offset
    end_line = int(elt.end_lineno or elt.lineno)
    end_col = int(elt.end_col_offset or elt.col_offset)
    tail = ctx.lines[end_line - 1][end_col:] if end_line <= len(ctx.lines) \
        else ""
    i = 0
    while i < len(tail) and tail[i] in " \t":
        i += 1
    if i < len(tail) and tail[i] == ",":
        i += 1
        end_col += i
        tail = tail[i:]
    prefix = ctx.lines[start_line - 1][:start_col]
    if not prefix.strip() and not tail.strip() and end_line < len(ctx.lines):
        return Fix(edits=(Edit(line=start_line, col=0,
                               end_line=end_line + 1, end_col=0,
                               replacement=""),),
                   description=description)
    return Fix(edits=(Edit(line=start_line, col=start_col,
                           end_line=end_line, end_col=end_col,
                           replacement=""),),
               description=description)


@register
class PartitionTableShadowingRule(Rule):
    """GL007 generalized to EVERY ``*PARTITION_RULES`` regex table (the
    flagship-XL refactor introduces per-subsystem tables): coverage and
    first-match-wins shadowing against the sharding contract.

    Coverage findings (rule matches nothing / param matched by nothing)
    are skipped for the canonical ``PARAM_PARTITION_RULES`` table — GL007
    owns those there — but shadowing is checked everywhere: a row whose
    every contract match is already claimed by earlier rows can never be
    selected, and deleting it is provably behavior-identical (the
    autofix)."""

    id = "GL018"
    name = "partition-rule-shadowing"
    severity = "error"
    rationale = (
        "first-match-wins regex rule tables rot silently: a later rule "
        "fully shadowed by earlier ones is dead code that reads like a "
        "live sharding decision, and in non-canonical tables a rule "
        "matching nothing (or a param matched by nothing) means the "
        "table drifted from the contract dump"
    )

    def applies(self, ctx: FileContext) -> bool:
        return "PARTITION_RULES" in ctx.source and not _is_test_file(ctx)

    def check(self, ctx: FileContext) -> list[Finding]:
        contract_rel = None
        tables: list[tuple[str, ast.Assign]] = []
        for node in ctx.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for name in _bound_names(node):
                if name.endswith("PARTITION_RULES"):
                    tables.append((name, node))
                if name == "SHARDING_CONTRACT" and isinstance(
                    node.value, ast.Constant
                ):
                    contract_rel = str(node.value.value)
        if not tables:
            return []
        if contract_rel is None:
            contract_rel = _DEFAULT_CONTRACT
        contract_path = contract_rel if os.path.isabs(contract_rel) else \
            os.path.join(ctx.root, contract_rel)
        try:
            with open(contract_path, encoding="utf-8") as f:
                params = list(json.load(f)["params"])
        except (OSError, ValueError, KeyError):
            return []  # no readable contract: GL007 reports the canonical
            # table's missing contract; nothing is checkable here

        out: list[Finding] = []
        for table_name, node in tables:
            out.extend(self._check_table(ctx, table_name, node, params))
        return out

    def _check_table(self, ctx: FileContext, table_name: str,
                     node: ast.Assign, params: list[str]) -> list[Finding]:
        canonical = table_name == "PARAM_PARTITION_RULES"
        elts = getattr(node.value, "elts", [])
        rows: list[tuple[str, str, ast.AST]] = []
        for elt in elts:
            parts = getattr(elt, "elts", [])
            if len(parts) >= 2 and isinstance(parts[0], ast.Constant) \
                    and isinstance(parts[1], ast.Constant):
                rows.append((str(parts[0].value), str(parts[1].value), elt))
        if not rows or len(rows) != len(elts):
            # dynamically-built (or partially literal) table: single-file
            # analysis provably cannot check it — never guess
            return []
        out: list[Finding] = []
        claimed: set[str] = set()
        unruled = set(params)
        for family, pattern, elt in rows:
            try:
                rx = re.compile(pattern)
            except re.error as e:
                if not canonical:  # GL007 reports this on the canonical
                    out.append(ctx.finding(
                        self, elt,
                        f"{table_name} rule {family!r} has an invalid "
                        f"regex: {e}",
                    ))
                continue
            matched = {p for p in params if rx.fullmatch(p)}
            unruled -= matched
            if not matched:
                if not canonical:
                    out.append(ctx.finding(
                        self, elt,
                        f"{table_name} rule {family!r} ({pattern!r}) "
                        "matches no parameter in the contract dump — the "
                        "family it was written for was renamed or removed",
                    ))
            elif matched <= claimed:
                out.append(ctx.finding(
                    self, elt,
                    f"{table_name} rule {family!r} ({pattern!r}) is fully "
                    "shadowed: every contract param it matches is already "
                    "claimed by an earlier rule, so under first-match-wins "
                    "this row can never be selected — it is dead code that "
                    "reads like a live sharding decision",
                    fix=_delete_element_fix(
                        ctx, elt,
                        f"delete dead {table_name} rule {family!r} "
                        "(fully shadowed by earlier rules)",
                    ),
                ))
            claimed |= matched
        if not canonical:
            for p in sorted(unruled):
                out.append(ctx.finding(
                    self, node,
                    f"parameter {p!r} (from the contract dump) matches no "
                    f"{table_name} rule: add a rule for its family so its "
                    "sharding is an explicit decision",
                ))
        return out


# ---- GL019: cross-host collective operand drift -----------------------------

_GL019_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "pbroadcast", "pcast", "ppermute",
    "process_allgather", "broadcast_one_to_all",
}


class _DriftFlow:
    """Source-order walk of one scope, mirroring the pass-1 summarizer's
    statement order but with the project index plugged into the
    :class:`~.project.HostTaint` environment, so calls to functions whose
    summaries carry host facts (``returns_host_shape`` /
    ``returns_host_value``, propagated by the fixpoint) taint their
    results here. At every collective call site the operand's abstract
    shape/wire-dtype is checked for per-host dependence."""

    def __init__(self, rule: Rule, ctx: FileContext, index: ProjectIndex,
                 aliases: dict[str, str], module: str):
        self.rule = rule
        self.ctx = ctx
        self.aliases = aliases
        self.index = index
        self.module = module
        self.env = HostTaint(aliases, lookup=self._lookup)
        self.findings: list[Finding] = []

    def _lookup(self, dotted: str):
        hit = self.index.lookup_from(self.module, dotted)
        return hit[1] if hit else None

    def run(self, body: list[ast.stmt]) -> list[Finding]:
        for stmt in body:
            self._stmt(stmt)
        return self.findings

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, _FUNC_NODES + (ast.Lambda, ast.ClassDef)):
            return  # separate scopes, each gets its own flow
        if isinstance(node, ast.Assign):
            self._expr(node.value)
            self._bind(node.targets, node.value)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if node.value is not None:
                self._expr(node.value)
                self._bind([node.target], node.value)
        elif isinstance(node, ast.If):
            self._expr(node.test)
            for stmt in node.body + node.orelse:
                self._stmt(stmt)
            reason = self.env.value_taint(node.test)
            if reason:
                self.env.taint_branch_stores(node.body + node.orelse,
                                             reason)
        elif isinstance(node, ast.For):
            self._expr(node.iter)
            for stmt in node.body + node.orelse:
                self._stmt(stmt)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._expr(child)
                else:
                    self._stmt(child)

    def _bind(self, targets: list[ast.AST], value: ast.AST) -> None:
        names: list[str] = []
        for t in targets:
            if isinstance(t, ast.Name):
                names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    inner = e.value if isinstance(e, ast.Starred) else e
                    if isinstance(inner, ast.Name):
                        names.append(inner.id)
        if names:
            self.env.bind(names, value)

    def _expr(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            resolved = resolve_dotted(_dotted(node.func), self.aliases)
            if _last(resolved) not in _GL019_COLLECTIVES:
                continue
            operand = node.args[0]
            reason = self.env.shape_taint(operand)
            if not reason:
                continue
            opname = _unparse(operand) or "operand"
            self.findings.append(self.ctx.finding(
                self.rule, node,
                f"{_last(resolved)}(...) operand {opname!r} has a "
                f"per-host shape or wire dtype ({reason}): every "
                "participating host must pass identically-shaped, "
                "identically-typed operands to a collective, or the pod "
                "deadlocks with no traceback — derive the size/dtype "
                "from globally-consistent values (process_allgather the "
                "lengths first, pad to the gathered max)",
            ))


@register
class CollectiveOperandDriftRule(ProjectRule):
    id = "GL019"
    name = "cross-host-collective-operand-drift"
    severity = "error"
    rationale = (
        "a collective whose operand shape or wire dtype depends on "
        "per-host values (len(local_devices), a process_index-"
        "conditional branch, a ragged bucket tail) hangs the whole pod "
        "at the rendezvous with no traceback; the shape-sharding "
        "environment proves per-host dependence at every collective "
        "reachable from train/multihost.py or the comms bucket path"
    )

    def applies(self, ctx: FileContext) -> bool:
        # package code only, minus the linter itself
        return _in_package(ctx) and not ctx.relpath.startswith(
            "cst_captioning_tpu/tools/"
        )

    def check_project(self, ctx: FileContext,
                      index: ProjectIndex) -> list[Finding]:
        mod = index.by_relpath.get(ctx.relpath)
        if mod is None or mod.parse_error:
            return []
        seeded = ctx.relpath in MULTIHOST_SEED_RELPATHS
        if not seeded and not any(
            q.startswith(f"{mod.module}.")
            for q in index.multihost_reach
        ):
            return []
        aliases = index.aliases_for(ctx.relpath, ctx.tree)
        quals = def_qualnames(ctx.tree)
        out: list[Finding] = []
        for node in ctx.nodes_of(*_FUNC_NODES):
            full = f"{mod.module}.{quals.get(id(node), node.name)}"
            if not seeded and full not in index.multihost_reach:
                continue
            flow = _DriftFlow(self, ctx, index, aliases, mod.module)
            out.extend(flow.run(node.body))
        if seeded:
            # module-level collectives in a seed module are in scope too
            flow = _DriftFlow(self, ctx, index, aliases, mod.module)
            out.extend(flow.run(ctx.tree.body))
        return out


# ---- GL020: Pallas kernel contract lint -------------------------------------

# VMEM is ~16 MiB/core; a kernel whose resident blocks + scratch exceed
# it fails to fit long before the compiler says anything useful
_GL020_VMEM_BUDGET = 16 * 1024 * 1024
_GL020_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
}


@register
class PallasContractRule(Rule):
    """Single-file, purely structural checks over ``pl.pallas_call``
    sites: index-map arity vs grid rank, block-shape/grid divisibility
    (the ``grid=(M // bm,)`` + ``BlockSpec((bm, ...))`` pairing — a block
    dim paired with a floor-divided grid dim must reuse the same divisor
    unless the kernel body visibly guards with ``pl.when``), and a
    resolvable-only VMEM footprint estimate. Opaque specs (built by
    helpers, unpacked from tuples) are skipped — single-file analysis
    provably cannot see them, so it never guesses."""

    id = "GL020"
    name = "pallas-kernel-contract"
    severity = "error"
    rationale = (
        "BlockSpec contracts live only in comments and runtime asserts "
        "today: an index map whose arity drifts from the grid rank fails "
        "deep in lowering, a block shape that stops dividing a reshaped "
        "grid dim silently reads garbage in the tail block unless "
        "pl.when-guarded, and a kernel whose blocks + scratch exceed the "
        "~16 MiB VMEM budget fails to fit at compile time"
    )

    def applies(self, ctx: FileContext) -> bool:
        return "pallas_call" in ctx.source and not _is_test_file(ctx)

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        name_defs = {
            node.name: node for node in ctx.nodes_of(*_FUNC_NODES)
        }
        for call in ctx.nodes_of(ast.Call):
            if _last(_dotted(call.func)) != "pallas_call":
                continue
            out.extend(self._check_site(ctx, call, name_defs))
        return out

    # -- per-site ---------------------------------------------------------

    def _check_site(self, ctx: FileContext, call: ast.Call,
                    name_defs: dict) -> list[Finding]:
        env = self._local_env(ctx, call)
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        nsp = 0
        gs = self._resolve(kw.get("grid_spec"), env)
        if isinstance(gs, ast.Call) and _last(_dotted(gs.func)) in (
                "GridSpec", "PrefetchScalarGridSpec"):
            # grid_spec sites carry grid/specs/scratch inside the spec
            # call; PrefetchScalarGridSpec additionally appends its
            # num_scalar_prefetch operands to every index map's argument
            # list (after the grid indices)
            kw = dict(kw)
            for k in gs.keywords:
                if k.arg in ("grid", "in_specs", "out_specs",
                             "scratch_shapes", "num_scalar_prefetch"):
                    kw[k.arg] = k.value
            nsp = self._int_of(kw.pop("num_scalar_prefetch", None),
                               env) or 0
        grid = self._resolve(kw.get("grid"), env)
        if not isinstance(grid, ast.Tuple):
            return []  # no literal grid: nothing checkable single-file
        rank = len(grid.elts)
        # grid dim -> divisor token when the extent is `X // d`
        divisors: list[str | None] = []
        for elt in grid.elts:
            e = self._resolve(elt, env)
            if isinstance(e, ast.BinOp) and isinstance(e.op, ast.FloorDiv):
                divisors.append(_unparse(e.right))
            else:
                divisors.append(None)
        guarded = self._kernel_has_when(call, env, name_defs)

        out: list[Finding] = []
        specs: list[ast.AST] = []
        for key in ("in_specs", "out_specs"):
            v = self._resolve(kw.get(key), env)
            if isinstance(v, (ast.Tuple, ast.List)):
                specs.extend(v.elts)
            elif v is not None:
                specs.append(v)
        block_bytes = 0
        resolvable = True
        for spec in specs:
            spec = self._resolve(spec, env)
            if not isinstance(spec, ast.Call) or \
                    _last(_dotted(spec.func)) != "BlockSpec":
                resolvable = False
                continue  # opaque spec: provably cannot analyze it here
            shape_node = self._resolve(
                spec.args[0] if spec.args else None, env
            )
            imap = self._resolve(
                spec.args[1] if len(spec.args) > 1 else None, env
            )
            mem_space = None
            for k in spec.keywords:
                if k.arg == "index_map":
                    imap = self._resolve(k.value, env)
                elif k.arg == "memory_space":
                    mem_space = _last(_dotted(k.value))
            if isinstance(imap, ast.Lambda):
                arity = len(imap.args.args) + len(imap.args.posonlyargs)
                if arity != rank + nsp:
                    expect = (
                        f"the grid rank ({rank}) plus the "
                        f"{nsp} scalar-prefetch ref(s)" if nsp else
                        f"the grid rank ({rank})"
                    )
                    out.append(ctx.finding(
                        self, spec,
                        f"BlockSpec index map takes {arity} argument(s) "
                        f"but pallas passes {rank + nsp}: one program "
                        "index per grid dim"
                        + (", then each scalar-prefetch ref" if nsp
                           else "")
                        + f" — keep the lambda arity equal to {expect}",
                    ))
                elif not guarded and isinstance(imap.body, ast.Tuple):
                    out.extend(self._divisibility(
                        ctx, spec, shape_node, imap, divisors, env
                    ))
            if shape_node is None and mem_space == "ANY":
                # unblocked whole-array HBM ref (the kernel DMAs slices
                # itself): nothing resident in VMEM
                nbytes = 0
            else:
                nbytes = self._block_nbytes(
                    shape_node, env, dtype="float32"
                )
            if nbytes is None:
                resolvable = False
            else:
                block_bytes += nbytes
        scratch_bytes = self._scratch_nbytes(
            self._resolve(kw.get("scratch_shapes"), env), env
        )
        if scratch_bytes is None:
            resolvable = False
            scratch_bytes = 0
        total = block_bytes + scratch_bytes
        if resolvable and specs and total > _GL020_VMEM_BUDGET:
            out.append(ctx.finding(
                self, call,
                f"estimated VMEM footprint {total / 2**20:.1f} MiB "
                "(resident blocks + scratch at declared dtypes) exceeds "
                f"the ~{_GL020_VMEM_BUDGET // 2**20} MiB per-core budget: "
                "shrink the block shapes or spill stages to HBM",
                severity="warning",
            ))
        return out

    def _divisibility(self, ctx: FileContext, spec: ast.AST,
                      shape_node: ast.AST | None, imap: ast.Lambda,
                      divisors: list, env: dict) -> list[Finding]:
        """Block dim j paired (via a bare index-map param) with grid dim k
        whose extent is `X // d` must BE d (or 1): anything else walks the
        array with a stride the grid was not built for."""
        if not isinstance(shape_node, (ast.Tuple, ast.List)):
            return []
        params = [a.arg for a in imap.args.posonlyargs + imap.args.args]
        out: list[Finding] = []
        for j, idx_expr in enumerate(imap.body.elts):
            if not isinstance(idx_expr, ast.Name) or \
                    idx_expr.id not in params:
                continue  # derived index (e.g. jnp.maximum(g-1, 0)):
                # the mapping is deliberate, not a stride contract
            k = params.index(idx_expr.id)
            if k >= len(divisors) or divisors[k] is None:
                continue
            if j >= len(shape_node.elts):
                continue
            dim = self._resolve(shape_node.elts[j], env)
            dim_txt = _unparse(shape_node.elts[j])
            if isinstance(dim, ast.Constant) and dim.value == 1:
                continue
            if dim_txt == divisors[k] or _unparse(dim) == divisors[k]:
                continue
            out.append(ctx.finding(
                self, spec,
                f"BlockSpec block dim {j} ({dim_txt!r}) indexes grid dim "
                f"{k}, whose extent is divided by {divisors[k]!r}: the "
                "block dim and the grid divisor must be the same value "
                "(or the kernel must guard the tail with pl.when/"
                "masking), otherwise the last block reads out of bounds",
            ))
        return out

    # -- resolution helpers ----------------------------------------------

    def _local_env(self, ctx: FileContext, call: ast.Call) -> dict:
        """name -> value node for single-Name assigns (and int parameter
        defaults) of the def enclosing ``call``; module scope otherwise."""
        cache = ctx._cache.setdefault("gl020_envs", {})
        owner = None
        for node in ctx.nodes_of(*_FUNC_NODES):
            if node.lineno <= call.lineno <= (node.end_lineno or 0):
                if owner is None or node.lineno > owner.lineno:
                    owner = node  # innermost enclosing def
        key = id(owner) if owner is not None else 0
        if key in cache:
            return cache[key]
        env: dict[str, ast.AST] = {}
        if owner is not None:
            args = owner.args
            pos = args.posonlyargs + args.args
            for a, d in zip(pos[len(pos) - len(args.defaults):],
                            args.defaults):
                env[a.arg] = d
            for a, d in zip(args.kwonlyargs, args.kw_defaults):
                if d is not None:
                    env[a.arg] = d
        body = owner.body if owner is not None else ctx.tree.body
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNC_NODES + (ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                env[node.targets[0].id] = node.value
            stack.extend(ast.iter_child_nodes(node))
        cache[key] = env
        return env

    @staticmethod
    def _resolve(node: ast.AST | None, env: dict,
                 depth: int = 4) -> ast.AST | None:
        while depth > 0 and isinstance(node, ast.Name) and node.id in env:
            node = env[node.id]
            depth -= 1
        return node

    def _kernel_has_when(self, call: ast.Call, env: dict,
                         name_defs: dict) -> bool:
        kernel = call.args[0] if call.args else None
        if isinstance(kernel, ast.Call) and \
                _last(_dotted(kernel.func)) == "partial" and kernel.args:
            kernel = kernel.args[0]
        if isinstance(kernel, ast.Name):
            fn = name_defs.get(kernel.id)
            if fn is None:
                return True  # unknown kernel body: assume it guards
            return any(
                isinstance(n, ast.Call) and _last(_dotted(n.func)) == "when"
                for n in ast.walk(fn)
            )
        return True  # lambda/opaque kernel: never guess

    def _int_of(self, node: ast.AST | None, env: dict) -> int | None:
        node = self._resolve(node, env)
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return node.value
        if isinstance(node, ast.BinOp):
            lhs = self._int_of(node.left, env)
            rhs = self._int_of(node.right, env)
            if lhs is None or rhs is None:
                return None
            if isinstance(node.op, ast.Mult):
                return lhs * rhs
            if isinstance(node.op, ast.Add):
                return lhs + rhs
            if isinstance(node.op, ast.Sub):
                return lhs - rhs
            if isinstance(node.op, ast.FloorDiv) and rhs:
                return lhs // rhs
        return None

    def _block_nbytes(self, shape_node: ast.AST | None, env: dict,
                      dtype: str) -> int | None:
        if not isinstance(shape_node, (ast.Tuple, ast.List)):
            return None
        total = _GL020_DTYPE_BYTES.get(dtype, 4)
        for elt in shape_node.elts:
            if isinstance(self._resolve(elt, env), ast.Constant) and \
                    self._resolve(elt, env).value is None:
                continue  # None block dim: whole-axis, sized elsewhere
            v = self._int_of(elt, env)
            if v is None:
                return None
            total *= v
        return total

    def _scratch_nbytes(self, node: ast.AST | None,
                        env: dict) -> int | None:
        """Total bytes of ``scratch_shapes=[pltpu.VMEM(shape, dtype),…]``;
        None = present but unresolvable, 0 = absent."""
        if node is None:
            return 0
        if not isinstance(node, (ast.Tuple, ast.List)):
            return None
        total = 0
        for elt in node.elts:
            elt = self._resolve(elt, env)
            if isinstance(elt, ast.Attribute) and \
                    "SemaphoreType" in _dotted(elt):
                continue  # DMA/REGULAR semaphore: no VMEM footprint
            if not isinstance(elt, ast.Call) or \
                    _last(_dotted(elt.func)) not in ("VMEM", "SMEM"):
                return None
            shape = self._resolve(
                elt.args[0] if elt.args else None, env
            )
            dtype = _last(_dotted(elt.args[1])) if len(elt.args) > 1 \
                else "float32"
            n = self._block_nbytes(shape, env, dtype=dtype or "float32")
            if n is None:
                return None
            total += n
        return total
