"""graftlint CLI.

    python -m cst_captioning_tpu.tools.graftlint [paths...] [--json]
        [--baseline PATH | --no-baseline] [--write-baseline]
        [--rules GL001,GL002] [--root DIR] [--list-rules]
        [--check-stale] [--timings] [--budget SECONDS] [--no-cache]

Exit codes: 0 = no new error/warning findings (info and baselined findings
never gate), 1 = new findings / stale baseline or suppressions with
--check-stale / budget exceeded with --budget, 2 = usage error.

``--check-stale`` additionally fails the run when a ``graftlint.baseline``
entry no longer fires or an inline ``# graftlint: disable=GLxxx`` suppresses
nothing — dead grandfathers silently re-open the door for a finding to come
back. The runtime counterpart of the static GL001/GL013 transfer claims is
``scripts/sanitize.sh``, which runs a tier-1 subset under
``pytest --sanitize`` (``jax.transfer_guard("disallow")`` + debug_nans).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from cst_captioning_tpu.tools.graftlint.core import (
    BASELINE_NAME,
    Baseline,
    all_rules,
    find_repo_root,
    lint_paths,
)

_DEFAULT_PATHS = ("cst_captioning_tpu", "tests", "scripts")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: "
                         f"{' '.join(_DEFAULT_PATHS)} under --root, plus "
                         "repo-level bench*.py)")
    ap.add_argument("--root", default="",
                    help="repo root (default: auto-detected from cwd)")
    ap.add_argument("--baseline", default="",
                    help=f"baseline file (default: <root>/{BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather every current finding into the "
                         "baseline file (reasons preserved by fingerprint) "
                         "and exit 0")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report on stdout")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--check-stale", action="store_true",
                    help="also fail on baseline entries that no longer fire "
                         "and on unused inline disable= suppressions "
                         "(requires the full rule set and a baseline)")
    ap.add_argument("--timings", action="store_true",
                    help="print the per-pass timing line (index build vs "
                         "rule run) on stderr")
    ap.add_argument("--budget", type=float, default=0.0, metavar="SECONDS",
                    help="fail (exit 1) when index build + rule run exceed "
                         "this wall-clock budget")
    ap.add_argument("--no-cache", action="store_true",
                    help="skip the on-disk project-summary cache "
                         "(<root>/.graftlint_cache.json)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in sorted(all_rules().values(), key=lambda r: r.id):
            print(f"{rule.id} {rule.name} [{rule.severity}]")
            print(f"    {rule.rationale}")
        return 0

    root = os.path.abspath(args.root) if args.root else find_repo_root(
        os.getcwd()
    )
    paths = list(args.paths)
    if not paths:
        paths = [
            os.path.join(root, p) for p in _DEFAULT_PATHS
            if os.path.exists(os.path.join(root, p))
        ]
        paths += [
            os.path.join(root, n) for n in sorted(os.listdir(root))
            if n.startswith("bench") and n.endswith(".py")
        ]
    if not paths:
        print("graftlint: nothing to lint", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    baseline = None if args.no_baseline else Baseline.load(baseline_path)

    rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()] or None
    if args.check_stale and (rule_ids is not None or baseline is None):
        print("graftlint: --check-stale needs the full rule set and a "
              "baseline (drop --rules / --no-baseline)", file=sys.stderr)
        return 2
    try:
        result = lint_paths(
            paths, root, baseline=baseline, rule_ids=rule_ids,
            cache_path="" if args.no_cache else None,
        )
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    total_seconds = result.index_seconds + result.rules_seconds
    if args.timings:
        stats = result.index_stats
        print(
            f"graftlint: index {result.index_seconds:.3f}s "
            f"({stats.get('files', 0)} files, "
            f"{stats.get('summarized', 0)} summarized, "
            f"{stats.get('cached', 0)} cached) + rules "
            f"{result.rules_seconds:.3f}s = {total_seconds:.3f}s",
            file=sys.stderr,
        )

    if args.write_baseline:
        old = Baseline.load(baseline_path)
        new = Baseline.from_findings(result.findings, old=old)
        new.save(baseline_path)
        print(
            f"graftlint: baselined {len(result.findings)} finding(s) into "
            f"{os.path.relpath(baseline_path, root)} — fill in each "
            "`reason` before committing",
            file=sys.stderr,
        )
        return 0

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        for f in result.findings:
            print(f.render())
        n_new, n_base = len(result.new), len(result.findings) - len(result.new)
        print(
            f"graftlint: {result.files_checked} file(s), "
            f"{len(result.findings)} finding(s) "
            f"({n_new} new, {n_base} baselined)",
            file=sys.stderr,
        )

    failed = bool(result.gating)
    if args.check_stale:
        for e in result.stale_baseline:
            print(
                f"graftlint: stale baseline entry: {e['rule']} at "
                f"{e['path']} ({e['context']!r}) no longer fires "
                f"({e['unfired']} unfired) — remove it from "
                f"{BASELINE_NAME}",
                file=sys.stderr,
            )
            failed = True
        for s in result.unused_suppressions:
            print(
                f"graftlint: unused suppression: {s['path']}:{s['line']} "
                f"disables {s['rule']} but nothing fires there — remove "
                "the comment",
                file=sys.stderr,
            )
            failed = True
    if args.budget and total_seconds > args.budget:
        print(
            f"graftlint: pass took {total_seconds:.3f}s, over the "
            f"{args.budget:.1f}s budget — the index cache or a rule "
            "regressed",
            file=sys.stderr,
        )
        failed = True
    return 1 if failed else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
