"""graftlint CLI.

    python -m cst_captioning_tpu.tools.graftlint [paths...] [--json]
        [--baseline PATH | --no-baseline] [--write-baseline]
        [--rules GL001,GL002] [--root DIR] [--list-rules]
        [--check-stale] [--timings] [--budget SECONDS] [--no-cache]
        [--fix [--dry-run]] [--fix-check] [--changed-only]

Exit codes: 0 = no new error/warning findings (info and baselined findings
never gate), 1 = new findings / stale baseline or suppressions with
--check-stale / budget exceeded with --budget / unfixed autofixable
findings with --fix-check / fixes skipped or surviving with --fix,
2 = usage error.

``--check-stale`` additionally fails the run when a ``graftlint.baseline``
entry no longer fires or an inline ``# graftlint: disable=GLxxx`` suppresses
nothing — dead grandfathers silently re-open the door for a finding to come
back.

``--fix`` applies the mechanical repairs rules attach to findings (see
:mod:`fixes`) plus stale-suppression/baseline removal, re-parses every
rewritten file, then RE-LINTS and fails unless the tree is fix-clean —
so applying ``--fix`` twice is always a no-op. ``--fix --dry-run`` prints
the unified diff without writing. ``--fix-check`` is the CI spelling: it
fails while any autofixable finding is unfixed, touching nothing.

``--changed-only`` is the pre-commit fast path: pass 1 still indexes the
whole tree (so cross-module rules keep their whole-program knowledge and
the warm cache makes it cheap), but pass 2 runs only on files git reports
as changed vs HEAD (plus untracked). It is exclusive with the
authoritative gates (``--fix``/``--fix-check``/``--write-baseline``/
``--check-stale``), which need full-tree findings.

The runtime counterpart of the static GL001/GL013 transfer claims is
``scripts/sanitize.sh``, which runs a tier-1 subset under
``pytest --sanitize`` (``jax.transfer_guard("disallow")`` + debug_nans).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from cst_captioning_tpu.tools.graftlint.core import (
    BASELINE_NAME,
    Baseline,
    all_rules,
    find_repo_root,
    lint_paths,
)

_DEFAULT_PATHS = ("cst_captioning_tpu", "tests", "scripts")


def _git_changed_files(root: str) -> list[str] | None:
    """Absolute paths of .py files changed vs HEAD (tracked diffs plus
    untracked files, .gitignore respected). ``None`` when ``root`` is not
    a git checkout — the caller turns that into a usage error rather than
    silently linting nothing."""
    rels: list[str] = []
    for cmd in (
        ["git", "-C", root, "diff", "--name-only", "HEAD", "--"],
        ["git", "-C", root, "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            out = subprocess.run(
                cmd, capture_output=True, text=True, timeout=10,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if out.returncode != 0:
            return None
        rels += out.stdout.splitlines()
    seen: set[str] = set()
    files: list[str] = []
    for rel in rels:
        rel = rel.strip()
        if not rel.endswith(".py") or rel in seen:
            continue
        seen.add(rel)
        path = os.path.join(root, rel)
        if os.path.isfile(path):  # deletions show in the diff too
            files.append(path)
    return files


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: "
                         f"{' '.join(_DEFAULT_PATHS)} under --root, plus "
                         "repo-level bench*.py)")
    ap.add_argument("--root", default="",
                    help="repo root (default: auto-detected from cwd)")
    ap.add_argument("--baseline", default="",
                    help=f"baseline file (default: <root>/{BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather every current finding into the "
                         "baseline file (reasons preserved by fingerprint) "
                         "and exit 0")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report on stdout")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--check-stale", action="store_true",
                    help="also fail on baseline entries that no longer fire "
                         "and on unused inline disable= suppressions "
                         "(requires the full rule set and a baseline)")
    ap.add_argument("--timings", action="store_true",
                    help="print the per-pass timing line (index build vs "
                         "rule run) on stderr")
    ap.add_argument("--budget", type=float, default=0.0, metavar="SECONDS",
                    help="fail (exit 1) when index build + rule run exceed "
                         "this wall-clock budget")
    ap.add_argument("--no-cache", action="store_true",
                    help="skip the on-disk project-summary cache "
                         "(<root>/.graftlint_cache.json)")
    ap.add_argument("--fix", action="store_true",
                    help="apply the mechanical fixes rules attach to NEW "
                         "findings (plus stale suppression/baseline "
                         "removal), re-parse, re-lint, and fail unless "
                         "the tree ends fix-clean")
    ap.add_argument("--dry-run", action="store_true",
                    help="with --fix: print the unified diff instead of "
                         "writing files")
    ap.add_argument("--fix-check", action="store_true",
                    help="CI mode: fail (exit 1) while any autofixable "
                         "finding is unfixed; never writes")
    ap.add_argument("--changed-only", action="store_true",
                    help="fast pre-commit path: build the full whole-program "
                         "index as usual, but run pass 2 only on files git "
                         "reports as changed (diff vs HEAD + untracked); "
                         "exclusive with --fix/--fix-check/--write-baseline/"
                         "--check-stale, which need full-tree findings")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in sorted(all_rules().values(), key=lambda r: r.id):
            print(f"{rule.id} {rule.name} [{rule.severity}]")
            print(f"    {rule.rationale}")
        return 0

    root = os.path.abspath(args.root) if args.root else find_repo_root(
        os.getcwd()
    )
    paths = list(args.paths)
    if not paths:
        paths = [
            os.path.join(root, p) for p in _DEFAULT_PATHS
            if os.path.exists(os.path.join(root, p))
        ]
        paths += [
            os.path.join(root, n) for n in sorted(os.listdir(root))
            if n.startswith("bench") and n.endswith(".py")
        ]
    if not paths:
        print("graftlint: nothing to lint", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    baseline = None if args.no_baseline else Baseline.load(baseline_path)

    rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()] or None
    if args.check_stale and (rule_ids is not None or baseline is None):
        print("graftlint: --check-stale needs the full rule set and a "
              "baseline (drop --rules / --no-baseline)", file=sys.stderr)
        return 2
    if args.fix and args.fix_check:
        print("graftlint: --fix and --fix-check are exclusive (apply or "
              "gate, not both)", file=sys.stderr)
        return 2
    if args.dry_run and not args.fix:
        print("graftlint: --dry-run only means something with --fix",
              file=sys.stderr)
        return 2
    only_files = None
    if args.changed_only:
        for flag, on in (("--fix", args.fix), ("--fix-check", args.fix_check),
                         ("--write-baseline", args.write_baseline),
                         ("--check-stale", args.check_stale)):
            if on:
                print(f"graftlint: --changed-only and {flag} are exclusive "
                      "— the authoritative gates need full-tree findings",
                      file=sys.stderr)
                return 2
        only_files = _git_changed_files(root)
        if only_files is None:
            print("graftlint: --changed-only needs a git checkout at "
                  f"{root}", file=sys.stderr)
            return 2
        if not only_files:
            print("graftlint: --changed-only: no changed .py files, "
                  "nothing to lint", file=sys.stderr)
            return 0
    try:
        result = lint_paths(
            paths, root, baseline=baseline, rule_ids=rule_ids,
            cache_path="" if args.no_cache else None,
            only_files=only_files,
        )
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    fix_failed = False
    if args.fix:
        result, rc = _run_fix(
            args, paths, root, rule_ids, baseline_path, result
        )
        if args.dry_run:
            return rc
        fix_failed = rc != 0

    total_seconds = result.index_seconds + result.rules_seconds
    if args.timings:
        stats = result.index_stats
        print(
            f"graftlint: index {result.index_seconds:.3f}s "
            f"({stats.get('files', 0)} files, "
            f"{stats.get('summarized', 0)} summarized, "
            f"{stats.get('cached', 0)} cached) + rules "
            f"{result.rules_seconds:.3f}s = {total_seconds:.3f}s",
            file=sys.stderr,
        )

    if args.write_baseline:
        old = Baseline.load(baseline_path)
        new = Baseline.from_findings(result.findings, old=old)
        new.save(baseline_path)
        print(
            f"graftlint: baselined {len(result.findings)} finding(s) into "
            f"{os.path.relpath(baseline_path, root)} — fill in each "
            "`reason` before committing",
            file=sys.stderr,
        )
        return 0

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        for f in result.findings:
            print(f.render())
        n_new, n_base = len(result.new), len(result.findings) - len(result.new)
        print(
            f"graftlint: {result.files_checked} file(s), "
            f"{len(result.findings)} finding(s) "
            f"({n_new} new, {n_base} baselined)",
            file=sys.stderr,
        )

    failed = bool(result.gating) or fix_failed
    if args.fix_check:
        for f in result.fixable:
            print(
                f"graftlint: autofixable: {f.path}:{f.line} {f.rule} — "
                f"{f.fix.description}; run `--fix` to apply",
                file=sys.stderr,
            )
            failed = True
    if args.check_stale:
        for e in result.stale_baseline:
            print(
                f"graftlint: stale baseline entry: {e['rule']} at "
                f"{e['path']} ({e['context']!r}) no longer fires "
                f"({e['unfired']} unfired) — remove it from "
                f"{BASELINE_NAME}",
                file=sys.stderr,
            )
            failed = True
        for s in result.unused_suppressions:
            print(
                f"graftlint: unused suppression: {s['path']}:{s['line']} "
                f"disables {s['rule']} but nothing fires there — remove "
                "the comment",
                file=sys.stderr,
            )
            failed = True
    if args.budget and total_seconds > args.budget:
        print(
            f"graftlint: pass took {total_seconds:.3f}s, over the "
            f"{args.budget:.1f}s budget — the index cache or a rule "
            "regressed",
            file=sys.stderr,
        )
        failed = True
    return 1 if failed else 0


_FIX_MAX_ROUNDS = 5


def _run_fix(args, paths, root, rule_ids, baseline_path, result):
    """Apply fixes until the tree is fix-clean (or no progress), re-linting
    after every write — the idempotence proof. Returns the post-fix lint
    result and an exit code (0 = converged clean)."""
    from cst_captioning_tpu.tools.graftlint.fixes import (
        plan_fixes,
        write_plan,
    )

    baseline = None if args.no_baseline else Baseline.load(baseline_path)
    if args.dry_run:
        plan = plan_fixes(result, root, baseline=baseline)
        for file_fix in plan.files:
            print(file_fix.diff(), end="")
        _print_fix_summary(plan, dry=True)
        return result, 0

    rounds = 0
    while result.fixable or result.unused_suppressions or \
            result.stale_baseline:
        if rounds >= _FIX_MAX_ROUNDS:
            print(
                "graftlint: --fix did not converge after "
                f"{_FIX_MAX_ROUNDS} rounds — a fixer is not idempotent",
                file=sys.stderr,
            )
            return result, 1
        plan = plan_fixes(result, root, baseline=baseline)
        if plan.applied_count == 0 and plan.stale_baseline_removed == 0:
            unfixed = len(result.fixable)
            if unfixed:
                print(
                    f"graftlint: {unfixed} autofixable finding(s) could "
                    "not be applied (see skips above)",
                    file=sys.stderr,
                )
                _print_fix_summary(plan, dry=False)
                return result, 1
            break  # only unused suppressions with no comment found: done
        write_plan(plan)
        _print_fix_summary(plan, dry=False)
        rounds += 1
        # the idempotence proof: re-lint the same paths from disk
        baseline = None if args.no_baseline else Baseline.load(
            baseline_path
        )
        result = lint_paths(
            paths, root, baseline=baseline, rule_ids=rule_ids,
            cache_path="" if args.no_cache else None,
        )
    rc = 0
    if result.fixable:
        print(
            f"graftlint: {len(result.fixable)} autofixable finding(s) "
            "survived --fix — a fixer regressed",
            file=sys.stderr,
        )
        rc = 1
    return result, rc


def _print_fix_summary(plan, dry: bool) -> None:
    verb = "would fix" if dry else "fixed"
    for file_fix in plan.files:
        for line in file_fix.applied:
            print(f"graftlint: {verb}: {line}", file=sys.stderr)
    for _, reason in plan.skipped:
        if reason:
            print(f"graftlint: skipped: {reason}", file=sys.stderr)
    if plan.stale_baseline_removed:
        print(
            f"graftlint: {verb}: removed {plan.stale_baseline_removed} "
            "stale baseline entr(y/ies)",
            file=sys.stderr,
        )


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
