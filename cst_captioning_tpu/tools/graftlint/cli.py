"""graftlint CLI.

    python -m cst_captioning_tpu.tools.graftlint [paths...] [--json]
        [--baseline PATH | --no-baseline] [--write-baseline]
        [--rules GL001,GL002] [--root DIR] [--list-rules]

Exit codes: 0 = no new error/warning findings (info and baselined findings
never gate), 1 = new findings, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from cst_captioning_tpu.tools.graftlint.core import (
    BASELINE_NAME,
    Baseline,
    all_rules,
    find_repo_root,
    lint_paths,
)

_DEFAULT_PATHS = ("cst_captioning_tpu", "tests", "scripts")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: "
                         f"{' '.join(_DEFAULT_PATHS)} under --root, plus "
                         "repo-level bench*.py)")
    ap.add_argument("--root", default="",
                    help="repo root (default: auto-detected from cwd)")
    ap.add_argument("--baseline", default="",
                    help=f"baseline file (default: <root>/{BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather every current finding into the "
                         "baseline file (reasons preserved by fingerprint) "
                         "and exit 0")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report on stdout")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in sorted(all_rules().values(), key=lambda r: r.id):
            print(f"{rule.id} {rule.name} [{rule.severity}]")
            print(f"    {rule.rationale}")
        return 0

    root = os.path.abspath(args.root) if args.root else find_repo_root(
        os.getcwd()
    )
    paths = list(args.paths)
    if not paths:
        paths = [
            os.path.join(root, p) for p in _DEFAULT_PATHS
            if os.path.exists(os.path.join(root, p))
        ]
        paths += [
            os.path.join(root, n) for n in sorted(os.listdir(root))
            if n.startswith("bench") and n.endswith(".py")
        ]
    if not paths:
        print("graftlint: nothing to lint", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    baseline = None if args.no_baseline else Baseline.load(baseline_path)

    rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()] or None
    try:
        result = lint_paths(paths, root, baseline=baseline, rule_ids=rule_ids)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        old = Baseline.load(baseline_path)
        new = Baseline.from_findings(result.findings, old=old)
        new.save(baseline_path)
        print(
            f"graftlint: baselined {len(result.findings)} finding(s) into "
            f"{os.path.relpath(baseline_path, root)} — fill in each "
            "`reason` before committing",
            file=sys.stderr,
        )
        return 0

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        for f in result.findings:
            print(f.render())
        n_new, n_base = len(result.new), len(result.findings) - len(result.new)
        print(
            f"graftlint: {result.files_checked} file(s), "
            f"{len(result.findings)} finding(s) "
            f"({n_new} new, {n_base} baselined)",
            file=sys.stderr,
        )
    return 1 if result.gating else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
