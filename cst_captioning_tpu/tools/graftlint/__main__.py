import sys

from cst_captioning_tpu.tools.graftlint.cli import main

sys.exit(main())
