"""graftlint framework: findings, rule registry, suppressions, baseline.

A stdlib-``ast`` static-analysis pass specialised for the JAX/TPU hazards of
this codebase (host syncs inside traced code, PRNG key reuse, tracer-leak
branches, missing buffer donation, dtype drift, heavyweight imports,
partition-rule coverage). No third-party dependencies: the sandbox has no
network and the linter must run wherever the tests run.

Layers:

- :class:`Finding`        — one diagnostic, with a line-content fingerprint
  that survives unrelated line-number drift; may carry a :class:`Fix`.
- :class:`Fix`            — span-precise source edits repairing a finding
  mechanically (applied by :mod:`fixes` under ``--fix``).
- :class:`Rule`           — registry-registered check over a
  :class:`FileContext`; per-rule id / severity / docs.
- suppressions            — ``# graftlint: disable=GL001[,GL002|all]`` on the
  offending line, or ``# graftlint: disable-next-line=...`` on the line above.
- baseline                — a repo-root ``graftlint.baseline`` JSON of
  grandfathered fingerprints (with a human ``reason`` each); matched findings
  are reported but do not fail the run.

The CLI lives in :mod:`cst_captioning_tpu.tools.graftlint.cli`.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Callable, Iterable, Iterator

SEVERITIES = ("error", "warning", "info")

# rule id the framework itself emits for unparseable files
PARSE_ERROR_RULE = "GL000"

BASELINE_NAME = "graftlint.baseline"

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable(?:-next-line)?)\s*=\s*([A-Za-z0-9_,\s]+)"
)

# directory names never descended into. "fixtures" keeps the deliberately
# lint-dirty GL013/14/15 fixture pairs under tests/fixtures/ out of the
# repo gate — tests lint them by passing the fixture directory explicitly
# (os.walk only filters SUBdirectories of the given path).
_SKIP_DIRS = {
    ".git", "__pycache__", ".pytest_cache", "node_modules", ".claude",
    "fixtures",
}


@dataclass(frozen=True)
class Edit:
    """One span-precise replacement: ``[start, end)`` in (1-based line,
    0-based col) coordinates, the same frame ``ast`` nodes report."""

    line: int
    col: int
    end_line: int
    end_col: int
    replacement: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_node(cls, node: ast.AST, replacement: str) -> "Edit":
        return cls(
            line=node.lineno, col=node.col_offset,
            end_line=int(node.end_lineno or node.lineno),
            end_col=int(node.end_col_offset or node.col_offset),
            replacement=replacement,
        )


@dataclass
class Fix:
    """A mechanical repair for one finding: non-overlapping edits plus a
    one-line human description (printed by ``--fix`` / the JSON report).
    Rules only emit a Fix when the rewrite is provably behavior-identical
    (or restores the invariant the finding names) — never a guess."""

    edits: tuple[Edit, ...]
    description: str

    def to_dict(self) -> dict:
        return {
            "description": self.description,
            "edits": [e.to_dict() for e in self.edits],
        }


@dataclass
class Finding:
    """One diagnostic. ``context`` (the stripped source line) + rule + path
    form the baseline fingerprint, so renumbering lines doesn't unbaseline."""

    rule: str
    severity: str
    path: str            # repo-root-relative, posix separators
    line: int
    col: int
    message: str
    context: str
    baselined: bool = False
    fix: Fix | None = None

    def fingerprint(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.context)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fix"] = self.fix.to_dict() if self.fix is not None else None
        return d

    def render(self) -> str:
        tag = " (baselined)" if self.baselined else ""
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{self.severity}: {self.message}{tag}"
        )


@dataclass
class FileContext:
    """Parsed view of one file, shared by every rule."""

    path: str            # absolute
    relpath: str         # repo-root-relative, posix
    root: str            # repo root (absolute)
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)
    # line -> set of rule ids (or "all") suppressed on that line
    suppressions: dict[int, set[str]] = field(default_factory=dict)
    # populated lazily by rules that need it (see rules._traced_functions)
    _cache: dict = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, root: str,
              source: str | None = None,
              tree: ast.Module | None = None) -> "FileContext":
        """Parse ``path`` — or adopt an already-parsed (source, tree) pair
        (pass 1 of the two-pass driver parses every file anyway; re-parsing
        in pass 2 would double the lint's dominant cost)."""
        if source is None:
            with open(path, encoding="utf-8", errors="replace") as f:
                source = f.read()
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        if tree is None:
            tree = ast.parse(source, filename=relpath)  # may raise SyntaxError
        ctx = cls(path=path, relpath=relpath, root=root, source=source,
                  tree=tree, lines=source.splitlines())
        ctx.suppressions = _collect_suppressions(source)
        return ctx

    def walk_nodes(self) -> list:
        """Flat list of every AST node, cached: rules iterate the whole
        tree a dozen times per file — one traversal, not fifteen."""
        cached = self._cache.get("all_nodes")
        if cached is None:
            cached = list(ast.walk(self.tree))
            self._cache["all_nodes"] = cached
        return cached

    def nodes_of(self, *types: type) -> list:
        """All nodes of the given AST types, from a one-time type-bucketed
        index over :meth:`walk_nodes` — most rules only look at ``Call``
        or def nodes, and fifteen full-tree isinstance scans per file were
        the dominant pass-2 cost. Within one type, walk order is kept;
        multiple types concatenate (callers that need interleaved source
        order still use :meth:`walk_nodes`)."""
        by_type = self._cache.get("nodes_by_type")
        if by_type is None:
            by_type = {}
            for node in self.walk_nodes():
                by_type.setdefault(type(node), []).append(node)
            self._cache["nodes_by_type"] = by_type
        if len(types) == 1:
            return by_type.get(types[0], [])
        out: list = []
        for t in types:
            out.extend(by_type.get(t, ()))
        return out

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: "Rule", node: ast.AST, message: str,
                severity: str | None = None,
                fix: Fix | None = None) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            rule=rule.id,
            severity=severity or rule.severity,
            path=self.relpath,
            line=line,
            col=col,
            message=message,
            context=self.line_text(line),
            fix=fix,
        )

    def suppressed(self, f: Finding) -> bool:
        ids = self.suppressions.get(f.line, set())
        return "all" in ids or f.rule in ids


def _collect_suppressions(source: str) -> dict[int, set[str]]:
    """Map line -> suppressed rule ids from ``# graftlint:`` comments."""
    out: dict[int, set[str]] = {}
    if "graftlint:" not in source:
        return out  # skip the tokenizer: most files carry no suppressions
    try:
        tokens = tokenize.generate_tokens(StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            kind, ids = m.group(1), {
                s.strip() for s in m.group(2).split(",") if s.strip()
            }
            line = tok.start[0] + (1 if kind.endswith("next-line") else 0)
            out.setdefault(line, set()).update(ids)
    except tokenize.TokenError:
        pass
    return out


# ---- rule registry ----------------------------------------------------------

class Rule:
    """Base rule. Subclasses set ``id``/``name``/``severity``/``rationale``
    and implement :meth:`check`; registration is via :func:`register`."""

    id: str = ""
    name: str = ""
    severity: str = "warning"
    rationale: str = ""

    def applies(self, ctx: FileContext) -> bool:
        return True

    def check(self, ctx: FileContext) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


class ProjectRule(Rule):
    """A rule that sees past the file: :meth:`check_project` receives the
    pass-1 :class:`~.project.ProjectIndex` alongside the per-file context.
    Findings still anchor to lines of ``ctx`` (and per-line suppressions /
    the baseline apply unchanged) — the index only widens what the rule can
    *know*, not where it reports."""

    def check(self, ctx: FileContext) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError("ProjectRule runs via check_project")

    def check_project(self, ctx: FileContext,
                      index) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


_REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    rule = cls()
    if not rule.id or rule.severity not in SEVERITIES:
        raise ValueError(f"bad rule registration: {cls.__name__}")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id}")
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> dict[str, Rule]:
    # import the rule module on first use so registration is one-time
    from cst_captioning_tpu.tools.graftlint import rules  # noqa: F401

    return dict(_REGISTRY)


# ---- baseline ---------------------------------------------------------------

@dataclass
class Baseline:
    """Grandfathered findings: fingerprint -> allowed count (+ a reason)."""

    entries: list[dict] = field(default_factory=list)
    path: str = ""

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict) or "entries" not in data:
            raise ValueError(f"{path}: not a graftlint baseline file")
        return cls(entries=list(data["entries"]), path=path)

    def save(self, path: str | None = None) -> None:
        path = path or self.path
        data = {
            "version": 1,
            "comment": (
                "Grandfathered graftlint findings. Each entry carries a "
                "`reason` saying why the finding is intentional; remove the "
                "entry when the code site is fixed. Regenerate with "
                "`python -m cst_captioning_tpu.tools.graftlint "
                "--write-baseline` (which preserves reasons by fingerprint)."
            ),
            "entries": sorted(
                self.entries,
                key=lambda e: (e["path"], e["rule"], e["context"]),
            ),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2)
            f.write("\n")

    def _counts(self) -> dict[tuple[str, str, str], int]:
        out: dict[tuple[str, str, str], int] = {}
        for e in self.entries:
            key = (e["rule"], e["path"], e["context"])
            out[key] = out.get(key, 0) + int(e.get("count", 1))
        return out

    def apply(self, findings: list[Finding]) -> None:
        """Mark findings covered by the baseline, first-come first-served
        per fingerprint (extra occurrences stay new)."""
        budget = self._counts()
        for f in findings:
            key = f.fingerprint()
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                f.baselined = True

    def stale_entries(self, findings: list[Finding]) -> list[dict]:
        """Entries whose fingerprint no longer fires (or fires fewer times
        than its grandfathered count): the code site was fixed, so the
        grandfather must go too — a stale entry silently re-opens the door
        for the finding to come back."""
        budget = self._counts()
        for f in findings:
            key = f.fingerprint()
            if budget.get(key, 0) > 0:
                budget[key] -= 1
        stale, seen = [], set()
        for e in self.entries:
            key = (e["rule"], e["path"], e["context"])
            if budget.get(key, 0) > 0 and key not in seen:
                seen.add(key)
                stale.append(dict(e, unfired=budget[key]))
        return stale

    @classmethod
    def from_findings(cls, findings: list[Finding],
                      old: "Baseline | None" = None) -> "Baseline":
        """Baseline every (non-suppressed) finding; reasons carried over from
        ``old`` by fingerprint, placeholder otherwise."""
        reasons: dict[tuple[str, str, str], str] = {}
        if old is not None:
            for e in old.entries:
                reasons[(e["rule"], e["path"], e["context"])] = e.get(
                    "reason", ""
                )
        grouped: dict[tuple[str, str, str], dict] = {}
        for f in findings:
            key = f.fingerprint()
            if key in grouped:
                grouped[key]["count"] += 1
            else:
                grouped[key] = {
                    "rule": f.rule,
                    "path": f.path,
                    "context": f.context,
                    "count": 1,
                    "reason": reasons.get(
                        key, "TODO: justify or fix this finding"
                    ),
                }
        return cls(entries=list(grouped.values()),
                   path=old.path if old is not None else "")


# ---- driver -----------------------------------------------------------------

def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into a sorted, deduped .py file list."""
    seen: set[str] = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            if p.endswith(".py") and p not in seen:
                seen.add(p)
                yield p
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for n in sorted(names):
                if n.endswith(".py"):
                    fp = os.path.join(root, n)
                    if fp not in seen:
                        seen.add(fp)
                        yield fp


@dataclass
class LintResult:
    findings: list[Finding]
    files_checked: int
    # --check-stale surfaces: baseline entries that no longer fire, and
    # inline `# graftlint: disable=` ids that suppressed nothing
    stale_baseline: list[dict] = field(default_factory=list)
    unused_suppressions: list[dict] = field(default_factory=list)
    # per-pass wall-clock (index build vs rule run) for the lint.sh budget
    index_seconds: float = 0.0
    rules_seconds: float = 0.0
    index_stats: dict = field(default_factory=dict)

    @property
    def new(self) -> list[Finding]:
        return [f for f in self.findings if not f.baselined]

    @property
    def gating(self) -> list[Finding]:
        """New findings that fail the run (info never gates)."""
        return [f for f in self.new if f.severity in ("error", "warning")]

    @property
    def fixable(self) -> list[Finding]:
        """NEW findings carrying a mechanical fix (``--fix`` applies them;
        ``--fix-check`` fails while any exist). Baselined findings are
        intentional — their fixes are never applied."""
        return [f for f in self.new if f.fix is not None]

    def to_json(self) -> dict:
        counts = {"total": len(self.findings),
                  "new": len(self.new),
                  "baselined": len(self.findings) - len(self.new),
                  "by_rule": {}}
        for f in self.findings:
            counts["by_rule"][f.rule] = counts["by_rule"].get(f.rule, 0) + 1
        fixes = {"autofixable": len(self.fixable), "by_rule": {}}
        for f in self.fixable:
            fixes["by_rule"][f.rule] = fixes["by_rule"].get(f.rule, 0) + 1
        # stale suppressions/baseline entries are repaired by --fix too
        fixes["stale_suppressions"] = len(self.unused_suppressions)
        fixes["stale_baseline"] = len(self.stale_baseline)
        return {
            "version": 1,
            "tool": "graftlint",
            "files_checked": self.files_checked,
            "counts": counts,
            "findings": [f.to_dict() for f in self.findings],
            "fixes": fixes,
            "stale_baseline": self.stale_baseline,
            "unused_suppressions": self.unused_suppressions,
            "timings": {
                "index_seconds": round(self.index_seconds, 4),
                "rules_seconds": round(self.rules_seconds, 4),
                **self.index_stats,
            },
        }


def find_repo_root(start: str) -> str:
    """Nearest ancestor containing a baseline file, .git, or the package."""
    d = os.path.abspath(start)
    while True:
        if (
            os.path.exists(os.path.join(d, BASELINE_NAME))
            or os.path.isdir(os.path.join(d, ".git"))
            or os.path.isdir(os.path.join(d, "cst_captioning_tpu"))
        ):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.abspath(start)
        d = parent


def lint_paths(
    paths: Iterable[str],
    root: str,
    baseline: Baseline | None = None,
    rule_ids: Iterable[str] | None = None,
    on_file: Callable[[str], None] | None = None,
    cache_path: str | None = None,
    only_files: Iterable[str] | None = None,
) -> LintResult:
    """Two-pass driver. Pass 1 builds the whole-program
    :class:`~.project.ProjectIndex` over every file (reusing the mtime-keyed
    on-disk summary cache — ``cache_path=''`` disables it); pass 2 runs the
    per-file rules unchanged plus the :class:`ProjectRule`s against the
    index. Suppression usage and baseline hit-counts are tracked so
    ``--check-stale`` can report dead grandfathers and dead disables.

    ``only_files`` (absolute paths) limits PASS 2 to a subset of the
    files — the index still covers everything, so cross-module rules keep
    their whole-program knowledge. This is ``--changed-only``'s fast
    path: warm index + a handful of changed files."""
    from cst_captioning_tpu.tools.graftlint.project import ProjectIndex

    rules = all_rules()
    if rule_ids is not None:
        unknown = set(rule_ids) - set(rules)
        if unknown:
            raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
        rules = {k: v for k, v in rules.items() if k in set(rule_ids)}

    files = list(iter_py_files(paths))
    t0 = time.perf_counter()
    index = ProjectIndex.build(files, root, cache_path=cache_path)
    index_seconds = time.perf_counter() - t0

    pass2_files = files
    if only_files is not None:
        wanted = {os.path.abspath(p) for p in only_files}
        pass2_files = [p for p in files if os.path.abspath(p) in wanted]

    findings: list[Finding] = []
    # (relpath, line) -> rule ids whose suppression actually fired there
    used_supp: dict[tuple[str, int], set[str]] = {}
    all_supp: list[tuple[str, int, set[str]]] = []
    t0 = time.perf_counter()
    for path in pass2_files:
        if on_file is not None:
            on_file(path)
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        pre = index.parsed.get(relpath)
        try:
            ctx = FileContext.parse(
                path, root,
                source=pre[0] if pre else None,
                tree=pre[1] if pre else None,
            )
        except SyntaxError as e:
            findings.append(Finding(
                rule=PARSE_ERROR_RULE,
                severity="error",
                path=os.path.relpath(path, root).replace(os.sep, "/"),
                line=int(e.lineno or 1),
                col=int(e.offset or 0),
                message=f"syntax error: {e.msg}",
                context="",
            ))
            continue
        for line, ids in ctx.suppressions.items():
            all_supp.append((ctx.relpath, line, ids))
        for rule in rules.values():
            if not rule.applies(ctx):
                continue
            if isinstance(rule, ProjectRule):
                checked = rule.check_project(ctx, index)
            else:
                checked = rule.check(ctx)
            for f in checked:
                if ctx.suppressed(f):
                    used_supp.setdefault(
                        (ctx.relpath, f.line), set()
                    ).add(f.rule)
                else:
                    findings.append(f)
    rules_seconds = time.perf_counter() - t0

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result = LintResult(
        findings=findings,
        files_checked=len(pass2_files),
        index_seconds=index_seconds,
        rules_seconds=rules_seconds,
        index_stats=dataclasses.asdict(index.stats),
    )
    if baseline is not None:
        baseline.apply(findings)
        result.stale_baseline = baseline.stale_entries(findings)
    # an "unused" suppression id is only meaningful when its rule ran
    ran = set(rules)
    for relpath, line, ids in sorted(all_supp):
        hit = used_supp.get((relpath, line), set())
        for rid in sorted(ids):
            if rid == "all":
                if not hit:
                    result.unused_suppressions.append(
                        {"path": relpath, "line": line, "rule": "all"}
                    )
            elif rid in ran:
                if rid not in hit:
                    result.unused_suppressions.append(
                        {"path": relpath, "line": line, "rule": rid}
                    )
            elif rule_ids is None:
                # not a registered rule id at all: a typo'd disable that
                # can never suppress anything
                result.unused_suppressions.append(
                    {"path": relpath, "line": line, "rule": rid}
                )
    return result
