"""Developer tooling that ships with the package (no runtime dependencies).

``tools.graftlint`` is the JAX/TPU-aware static-analysis pass; it is wired
into tier-1 via tests/test_graftlint.py and scripts/lint.sh.
"""
