"""Loss layer: masked XE, consensus-weighted XE, REINFORCE.

Rebuilds the reference's ``CrossEntropyCriterion`` / ``RewardCriterion``
(SURVEY.md §2 rows 5-6) as pure jittable functions.
"""

from cst_captioning_tpu.losses.losses import (
    masked_cross_entropy,
    reinforce_loss,
    sequence_log_probs,
)

__all__ = ["masked_cross_entropy", "reinforce_loss", "sequence_log_probs"]
