"""Pure loss functions (all f32, all mask-aware, all jittable).

- :func:`masked_cross_entropy` — the reference's ``CrossEntropyCriterion``:
  token-masked sequence XE, normalized by total token count; the ``weights``
  argument is the WXE variant (per-caption consensus weight multiplying that
  caption's token losses, CST paper §3.2).
- :func:`reinforce_loss` — the reference's ``RewardCriterion``:
  ``-(reward - baseline) * logprob`` over sampled tokens, masked and
  normalized the same way (advantage is per-sequence, broadcast over steps).
- :func:`sequence_log_probs` — gather per-token logprobs of given sequences
  from logits (used to re-score sampled rollouts differentiably in the RL
  update, SURVEY.md §7 step 5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def sequence_log_probs(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """logits [B, T, V], tokens [B, T] -> per-token logprobs [B, T] (f32)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]


def masked_cross_entropy(
    logits: jnp.ndarray,           # [B, T, V]
    labels: jnp.ndarray,           # [B, T] int
    mask: jnp.ndarray,             # [B, T] 1/0 on real tokens (incl. EOS)
    weights: jnp.ndarray | None = None,   # [B] per-caption consensus weights
    label_smoothing: float = 0.0,
) -> jnp.ndarray:
    """Masked (optionally consensus-weighted) sequence XE, mean over tokens."""
    logits = logits.astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    if label_smoothing > 0.0:
        V = logits.shape[-1]
        soft = optax.smooth_labels(jax.nn.one_hot(labels, V), label_smoothing)
        per_tok = optax.softmax_cross_entropy(logits, soft)
    else:
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    if weights is not None:
        mask = mask * weights.astype(jnp.float32)[:, None]
    return jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def reinforce_loss(
    log_probs: jnp.ndarray,        # [B, T] per-token logprobs of sampled seqs
    mask: jnp.ndarray,             # [B, T] 1/0 on sampled tokens (incl. EOS)
    advantage: jnp.ndarray,        # [B] reward - baseline (host-computed)
) -> jnp.ndarray:
    """REINFORCE: -E[advantage * logp], masked, mean over tokens.

    ``advantage`` is treated as a constant (stop_gradient): gradients flow
    only through ``log_probs``.
    """
    mask = mask.astype(jnp.float32)
    adv = jax.lax.stop_gradient(advantage.astype(jnp.float32))[:, None]
    loss = -(adv * log_probs.astype(jnp.float32) * mask)
    return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1.0)
