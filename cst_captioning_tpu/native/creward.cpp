// Fast consensus-reward kernel: CIDEr-D + smoothed sentence BLEU-4.
//
// The RL phase's host-side bottleneck (SURVEY.md §3.2 / §7 "RL step
// throughput"): scoring B×K sampled captions against per-video reference
// pools every step. Pure-Python scoring costs ~900ms per 320-row batch —
// 80% of the SCST step. This kernel does the same arithmetic over interned
// token ids with FNV-style 64-bit gram hashes, multi-threaded, GIL-free.
//
// Hot-path layout (r4): reference pools are flattened ONCE at add_video time
// into hash-sorted flat arrays; each hypothesis row builds its (deduped,
// sorted) gram lists in per-worker reusable buffers and every dot product /
// clipped match is a two-pointer merge-join — zero hash-map construction or
// lookup per row outside the shared read-only df table. On captions (≤ ~30
// tokens) this is ~3x the throughput of the original per-row unordered_map
// implementation, which matters because the reward competes with dispatch
// for the host core that the pipelined epoch hides it under.
//
// Semantics are EXACTLY the Python oracles (cst_captioning_tpu.metrics):
//   - CIDEr-D: tf-idf n-gram cosine with hyp counts clipped to the ref's,
//     gaussian length penalty exp(-(lh-lr)^2 / (2*sigma^2)), mean over
//     n=1..4 and refs, ×10 (metrics/cider.py::CiderD).
//   - BLEU-4: clipped precision vs max ref counts, +1 smoothing for n>1,
//     brevity penalty vs closest ref length (metrics/bleu.py::sentence_bleu).
// Parity is pinned by tests/test_rl.py (C++ path vs Python oracles).
//
// Tokens are *interned word ids* built by the Python wrapper from the union
// of reference words and the model vocab, so OOV reference words keep their
// string identity (id-space scoring stays equivalent to string-space).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread creward.cpp -o libcreward.so

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int MAX_N = 4;

inline uint64_t hash_gram(const int32_t* toks, int n) {
    // splitmix64-style mixing over up to 4 token ids; low collision odds
    // (~1e-13 for 1M grams) and deterministic across platforms.
    uint64_t h = 0x9e3779b97f4a7c15ull ^ (uint64_t)n;
    for (int i = 0; i < n; ++i) {
        uint64_t x = (uint64_t)(uint32_t)toks[i] + 0x9e3779b97f4a7c15ull;
        x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
        x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
        h ^= (x ^ (x >> 31)) + 0x9e3779b97f4a7c15ull + (h << 6) + (h >> 2);
    }
    return h;
}

using GramCounts = std::unordered_map<uint64_t, int>;

// n-gram counts of one token sequence, all orders 1..4 (build-time only).
void count_grams(const int32_t* toks, int len, GramCounts out[MAX_N]) {
    for (int n = 1; n <= MAX_N; ++n) {
        GramCounts& m = out[n - 1];
        for (int i = 0; i + n <= len; ++i) {
            ++m[hash_gram(toks + i, n)];
        }
    }
}

// hash-sorted flat (gram -> weight) vector: the merge-join operand
struct FlatVec {
    std::vector<uint64_t> h;
    std::vector<double> w;
};

// hash-sorted flat (gram -> count) vector
struct FlatCounts {
    std::vector<uint64_t> h;
    std::vector<int> c;
};

struct RefVec {
    FlatVec vec[MAX_N];
    double norm[MAX_N] = {0, 0, 0, 0};
    int len = 0;
};

struct VideoStats {
    std::vector<RefVec> cider;            // per reference
    FlatCounts bleu_max[MAX_N];           // elementwise max ref counts
    std::vector<int> ref_lens;
};

struct Ctx {
    double log_ndoc = 1.0;
    double sigma = 6.0;
    std::unordered_map<uint64_t, double> df;
    std::vector<VideoStats> videos;
    int32_t eos_id = 2, pad_id = 0, bos_id = 1;
};

inline double idf(const Ctx& c, uint64_t gram) {
    auto it = c.df.find(gram);
    double d = it == c.df.end() ? 0.0 : it->second;
    return c.log_ndoc - std::log(d > 1.0 ? d : 1.0);
}

// effective hypothesis length: tokens up to (excluding) EOS/PAD, skipping BOS
int effective_row(const int32_t* row, int T, const Ctx& c, int32_t* out) {
    int n = 0;
    for (int t = 0; t < T; ++t) {
        int32_t tok = row[t];
        if (tok == c.eos_id || tok == c.pad_id) break;
        if (tok == c.bos_id) continue;
        out[n++] = tok;
    }
    return n;
}

// one hypothesis's grams of one order: deduped, then hash-sorted, with
// per-gram tf-idf weights. Buffers are reused across rows (no allocation in
// the steady state — capacity grows to the max gram count once).
struct HypOrder {
    std::vector<uint64_t> h;
    std::vector<int> c;
    std::vector<double> w;     // tf * idf, filled after sorting
    std::vector<int> order;    // sort permutation scratch
    std::vector<uint64_t> h2;  // permutation-apply scratch
    std::vector<int> c2;

    void build(const int32_t* toks, int len, int n) {
        h.clear();
        c.clear();
        for (int i = 0; i + n <= len; ++i) {
            uint64_t hh = hash_gram(toks + i, n);
            // linear dedup: caption-order gram counts are tiny (<= ~30)
            size_t j = 0, sz = h.size();
            for (; j < sz; ++j) {
                if (h[j] == hh) {
                    ++c[j];
                    break;
                }
            }
            if (j == sz) {
                h.push_back(hh);
                c.push_back(1);
            }
        }
        // sort (h, c) by hash via a permutation (arrays are tiny)
        size_t sz = h.size();
        order.resize(sz);
        for (size_t i = 0; i < sz; ++i) order[i] = (int)i;
        std::sort(order.begin(), order.end(),
                  [&](int a, int b) { return h[a] < h[b]; });
        w.resize(sz);
        h2.resize(sz);
        c2.resize(sz);
        for (size_t i = 0; i < sz; ++i) {
            h2[i] = h[order[i]];
            c2[i] = c[order[i]];
        }
        h.swap(h2);
        c.swap(c2);
    }

    double weigh(const Ctx& ctx) {   // fills w, returns l2 norm
        double norm = 0.0;
        for (size_t i = 0; i < h.size(); ++i) {
            double ww = (double)c[i] * idf(ctx, h[i]);
            w[i] = ww;
            norm += ww * ww;
        }
        return std::sqrt(norm);
    }
};

double cider_d_one(const Ctx& c, const VideoStats& vs, HypOrder hyp[MAX_N],
                   const double hnorm[MAX_N], int hyp_len) {
    double per_n[MAX_N] = {0, 0, 0, 0};
    for (const RefVec& rv : vs.cider) {
        double pen = std::exp(-((double)(hyp_len - rv.len) * (hyp_len - rv.len)) /
                              (2.0 * c.sigma * c.sigma));
        for (int n = 0; n < MAX_N; ++n) {
            double denom = hnorm[n] * rv.norm[n];
            if (denom <= 0) continue;
            const HypOrder& ho = hyp[n];
            const FlatVec& fv = rv.vec[n];
            double dot = 0.0;
            size_t i = 0, j = 0, hs = ho.h.size(), rs = fv.h.size();
            while (i < hs && j < rs) {          // sorted merge-join
                uint64_t a = ho.h[i], b = fv.h[j];
                if (a == b) {
                    double hw = ho.w[i], rw = fv.w[j];
                    dot += (hw < rw ? hw : rw) * rw;
                    ++i;
                    ++j;
                } else if (a < b) {
                    ++i;
                } else {
                    ++j;
                }
            }
            per_n[n] += pen * dot / denom;
        }
    }
    double nref = vs.cider.empty() ? 1.0 : (double)vs.cider.size();
    double mean = 0.0;
    for (int n = 0; n < MAX_N; ++n) mean += per_n[n] / nref;
    return mean / MAX_N * 10.0;
}

double bleu4_one(const Ctx&, const VideoStats& vs, const HypOrder hyp[MAX_N],
                 int hyp_len) {
    if (hyp_len == 0 || vs.ref_lens.empty()) return 0.0;
    // closest ref length (ties -> smaller)
    int best = vs.ref_lens[0];
    for (int rl : vs.ref_lens) {
        int da = std::abs(rl - hyp_len), db = std::abs(best - hyp_len);
        if (da < db || (da == db && rl < best)) best = rl;
    }
    double bp = hyp_len >= best ? 1.0 : std::exp(1.0 - (double)best / hyp_len);
    double log_p = 0.0, score = 0.0;
    for (int n = 1; n <= MAX_N; ++n) {
        long matched = 0, total = 0;
        const HypOrder& ho = hyp[n - 1];
        const FlatCounts& maxc = vs.bleu_max[n - 1];
        size_t i = 0, j = 0, hs = ho.h.size(), rs = maxc.h.size();
        for (size_t k = 0; k < hs; ++k) total += ho.c[k];
        while (i < hs && j < rs) {              // sorted merge-join
            uint64_t a = ho.h[i], b = maxc.h[j];
            if (a == b) {
                matched += ho.c[i] < maxc.c[j] ? ho.c[i] : maxc.c[j];
                ++i;
                ++j;
            } else if (a < b) {
                ++i;
            } else {
                ++j;
            }
        }
        double p;
        if (n == 1) p = total ? (double)matched / total : 0.0;
        else p = total ? (matched + 1.0) / (total + 1.0) : 0.0;
        if (p == 0.0) return 0.0;
        log_p += std::log(p);
        score = bp * std::exp(log_p / n);
    }
    return score;
}

// flatten an unordered map into a hash-sorted FlatVec/FlatCounts
void flatten_vec(const std::unordered_map<uint64_t, double>& m, FlatVec& out) {
    out.h.reserve(m.size());
    for (const auto& kv : m) out.h.push_back(kv.first);
    std::sort(out.h.begin(), out.h.end());
    out.w.resize(out.h.size());
    for (size_t i = 0; i < out.h.size(); ++i) out.w[i] = m.at(out.h[i]);
}

void flatten_counts(const GramCounts& m, FlatCounts& out) {
    out.h.reserve(m.size());
    for (const auto& kv : m) out.h.push_back(kv.first);
    std::sort(out.h.begin(), out.h.end());
    out.c.resize(out.h.size());
    for (size_t i = 0; i < out.h.size(); ++i) out.c[i] = m.at(out.h[i]);
}

}  // namespace

extern "C" {

void* crw_create(double log_ndoc, double sigma, int32_t pad_id, int32_t bos_id,
                 int32_t eos_id) {
    Ctx* c = new Ctx();
    c->log_ndoc = log_ndoc;
    c->sigma = sigma;
    c->pad_id = pad_id;
    c->bos_id = bos_id;
    c->eos_id = eos_id;
    return c;
}

void crw_free(void* h) { delete (Ctx*)h; }

// df entries: n_grams grams; gram i occupies gram_lens[i] ids in `tokens`
// (concatenated), with document frequency counts[i].
void crw_set_df(void* h, const int32_t* tokens, const int32_t* gram_lens,
                const double* counts, int64_t n_grams) {
    Ctx* c = (Ctx*)h;
    c->df.reserve((size_t)n_grams * 2);
    int64_t off = 0;
    for (int64_t i = 0; i < n_grams; ++i) {
        int n = gram_lens[i];
        c->df[hash_gram(tokens + off, n)] = counts[i];
        off += n;
    }
}

// add one video's reference pool: ref i occupies ref_lens[i] ids in `tokens`.
// Returns the video index used by crw_score.
int32_t crw_add_video(void* h, const int32_t* tokens, const int32_t* ref_lens,
                      int32_t n_refs) {
    Ctx* c = (Ctx*)h;
    c->videos.emplace_back();
    VideoStats& vs = c->videos.back();
    GramCounts bleu_max[MAX_N];
    int64_t off = 0;
    for (int32_t r = 0; r < n_refs; ++r) {
        int len = ref_lens[r];
        GramCounts counts[MAX_N];
        count_grams(tokens + off, len, counts);
        // CIDEr vectors (built in a map, flattened hash-sorted for the
        // per-row merge-joins)
        vs.cider.emplace_back();
        RefVec& rv = vs.cider.back();
        rv.len = len;
        for (int n = 0; n < MAX_N; ++n) {
            std::unordered_map<uint64_t, double> vec;
            vec.reserve(counts[n].size() * 2);
            for (const auto& kv : counts[n]) {
                double w = (double)kv.second * idf(*c, kv.first);
                vec[kv.first] = w;
                rv.norm[n] += w * w;
            }
            rv.norm[n] = std::sqrt(rv.norm[n]);
            flatten_vec(vec, rv.vec[n]);
        }
        // BLEU max counts
        for (int n = 0; n < MAX_N; ++n)
            for (const auto& kv : counts[n]) {
                int& slot = bleu_max[n][kv.first];
                if (kv.second > slot) slot = kv.second;
            }
        vs.ref_lens.push_back(len);
        off += len;
    }
    for (int n = 0; n < MAX_N; ++n) flatten_counts(bleu_max[n], vs.bleu_max[n]);
    return (int32_t)(c->videos.size() - 1);
}

// score n_rows hypotheses (rows of length T, interned ids, EOS-terminated)
// against videos[video_idx[i]]; out[i] = cw*CIDErD + bw*BLEU4*10.
void crw_score(void* h, const int32_t* video_idx, const int32_t* rows,
               int64_t n_rows, int32_t T, double cider_w, double bleu_w,
               int32_t n_threads, float* out) {
    Ctx* c = (Ctx*)h;
    if (n_threads < 1) n_threads = 1;
    auto worker = [&](int64_t lo, int64_t hi) {
        std::vector<int32_t> buf(T);
        HypOrder hyp[MAX_N];   // reused across rows: no steady-state mallocs
        for (int64_t i = lo; i < hi; ++i) {
            const VideoStats& vs = c->videos[video_idx[i]];
            int len = effective_row(rows + i * T, T, *c, buf.data());
            double hnorm[MAX_N] = {0, 0, 0, 0};
            for (int n = 0; n < MAX_N; ++n) {
                hyp[n].build(buf.data(), len, n + 1);
                if (cider_w != 0.0) hnorm[n] = hyp[n].weigh(*c);  // df lookups
            }
            double r = 0.0;
            if (cider_w != 0.0)
                r += cider_w * cider_d_one(*c, vs, hyp, hnorm, len);
            if (bleu_w != 0.0)
                r += bleu_w * bleu4_one(*c, vs, hyp, len) * 10.0;
            // scores are computed in double but cross the ABI as float32:
            // callers comparing against a float64 oracle (the Python
            // CiderD scorer) must budget ~1e-7 relative tolerance for this
            // narrowing — pinned by the parity tests in
            // tests/test_metrics_cider.py
            out[i] = (float)r;
        }
    };
    if (n_threads == 1 || n_rows < 64) {
        worker(0, n_rows);
        return;
    }
    std::vector<std::thread> threads;
    int64_t chunk = (n_rows + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
        int64_t lo = t * chunk, hi = lo + chunk < n_rows ? lo + chunk : n_rows;
        if (lo >= hi) break;
        threads.emplace_back(worker, lo, hi);
    }
    for (auto& th : threads) th.join();
}

}  // extern "C"
