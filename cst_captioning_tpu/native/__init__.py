"""Native host kernels (C++ via ctypes; auto-built, pure-Python fallback).

The reference has no first-party native code (its jars are JVM metric tools,
SURVEY.md §2 "native components"); this framework's native layer accelerates
the RL reward host path, per the SURVEY's design note: "implement a small C++
extension … with a pure-numpy fallback".
"""

from cst_captioning_tpu.native.build import load_creward

__all__ = ["load_creward"]
