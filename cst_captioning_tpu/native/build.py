"""Build + load the creward shared library (ctypes; no pybind11 needed).

Compiles ``creward.cpp`` with g++ on first use into the package directory and
memoizes the handle. Every failure path (no compiler, compile error, load
error) returns None so callers fall back to the pure-Python scorer.

The binary name embeds a hash of the source (``libcreward-<sha>.so``), so a
stale prebuilt library can never shadow newer source — git clones don't
preserve mtimes, making mtime staleness checks unreliable. Binaries are never
committed (.gitignore'd); the library is always built from source on the
machine that uses it.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "creward.cpp")

_lock = threading.Lock()
_cached: "ctypes.CDLL | None | bool" = False  # False = not attempted yet


def _lib_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_DIR, f"libcreward-{digest}.so")


def _compile(lib_path: str) -> bool:
    tmp = f"{lib_path}.{os.getpid()}.tmp"  # per-process: builders can't collide
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", tmp,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        if proc.returncode != 0:
            return False
        # sweep dead binaries from previous source revisions
        for old in os.listdir(_DIR):
            if old.startswith("libcreward-") and old.endswith(".so"):
                if os.path.join(_DIR, old) != lib_path:
                    try:
                        os.unlink(os.path.join(_DIR, old))
                    except OSError:
                        pass
        os.replace(tmp, lib_path)  # atomic publish
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32p = ctypes.POINTER(ctypes.c_int32)
    f64p = ctypes.POINTER(ctypes.c_double)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.crw_create.restype = ctypes.c_void_p
    lib.crw_create.argtypes = [ctypes.c_double, ctypes.c_double,
                               ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
    lib.crw_free.argtypes = [ctypes.c_void_p]
    lib.crw_set_df.argtypes = [ctypes.c_void_p, i32p, i32p, f64p, ctypes.c_int64]
    lib.crw_add_video.restype = ctypes.c_int32
    lib.crw_add_video.argtypes = [ctypes.c_void_p, i32p, i32p, ctypes.c_int32]
    lib.crw_score.argtypes = [
        ctypes.c_void_p, i32p, i32p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_double, ctypes.c_double, ctypes.c_int32, f32p,
    ]
    return lib


def load_creward() -> "ctypes.CDLL | None":
    """Load (building if needed) the reward kernel; None -> use Python path."""
    global _cached
    with _lock:
        if _cached is not False:
            return _cached
        lib = None
        try:
            path = _lib_path()
            if not os.path.exists(path):
                if not _compile(path):
                    _cached = None
                    return None
            lib = _bind(ctypes.CDLL(path))
        except OSError:
            lib = None
        _cached = lib
        return lib
