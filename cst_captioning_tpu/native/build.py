"""Build + load the creward shared library (ctypes; no pybind11 needed).

Compiles ``creward.cpp`` with g++ on first use into the package directory and
memoizes the handle. Every failure path (no compiler, compile error, load
error) returns None so callers fall back to the pure-Python scorer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "creward.cpp")
_LIB = os.path.join(_DIR, "libcreward.so")

_lock = threading.Lock()
_cached: "ctypes.CDLL | None | bool" = False  # False = not attempted yet


def _compile() -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", _LIB,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        return proc.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32p = ctypes.POINTER(ctypes.c_int32)
    f64p = ctypes.POINTER(ctypes.c_double)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.crw_create.restype = ctypes.c_void_p
    lib.crw_create.argtypes = [ctypes.c_double, ctypes.c_double,
                               ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
    lib.crw_free.argtypes = [ctypes.c_void_p]
    lib.crw_set_df.argtypes = [ctypes.c_void_p, i32p, i32p, f64p, ctypes.c_int64]
    lib.crw_add_video.restype = ctypes.c_int32
    lib.crw_add_video.argtypes = [ctypes.c_void_p, i32p, i32p, ctypes.c_int32]
    lib.crw_score.argtypes = [
        ctypes.c_void_p, i32p, i32p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_double, ctypes.c_double, ctypes.c_int32, f32p,
    ]
    return lib


def load_creward() -> "ctypes.CDLL | None":
    """Load (building if needed) the reward kernel; None -> use Python path."""
    global _cached
    with _lock:
        if _cached is not False:
            return _cached
        lib = None
        try:
            if not os.path.exists(_LIB) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            ):
                if not _compile():
                    _cached = None
                    return None
            lib = _bind(ctypes.CDLL(_LIB))
        except OSError:
            lib = None
        _cached = lib
        return lib
