"""Dataclass configs for model / data / training / RL / eval / mesh.

Design notes (TPU-first):

- Everything that reaches a jitted function is static and hashable, so configs
  are frozen dataclasses — they can be closed over by ``jax.jit`` without
  retracing hazards.
- Token id conventions are fixed framework-wide: PAD=0, BOS=1, EOS=2, UNK=3.
  PAD=0 lets masks be computed as ``labels != 0`` on device, and keeps padded
  positions out of every loss/metric without extra bookkeeping.
- ``modalities`` is an ordered mapping name -> raw feature dim (e.g.
  ``{"resnet": 2048, "c3d": 500}``), mirroring the reference's multi-h5
  feature list but with the dims carried in config so model init needs no
  data peek.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Mapping

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
UNK_ID = 3
NUM_SPECIAL_TOKENS = 4


def _freeze_modalities(m: Mapping[str, int]) -> tuple[tuple[str, int], ...]:
    return tuple((str(k), int(v)) for k, v in m.items())


@dataclass(frozen=True)
class ModelConfig:
    """Caption model shape (reference ``model.py::CaptionModel`` capability)."""

    vocab_size: int = 512
    # ordered (name, raw_dim) pairs; tuple-of-tuples so the config is hashable.
    modalities: tuple[tuple[str, int], ...] = (("resnet", 2048),)
    d_embed: int = 512          # word embedding + per-modality frame embedding dim
    d_hidden: int = 512         # LSTM hidden size
    encoder: str = "meanpool"   # "meanpool" | "temporal_attention"
    d_att: int = 256            # additive-attention projection dim
    num_layers: int = 1         # LSTM layers (reference uses 1)
    dropout: float = 0.5
    max_len: int = 30           # max caption length incl. EOS
    max_frames: int = 60        # frame-axis padding length
    dtype: str = "bfloat16"     # compute dtype for MXU-friendly matmuls
    param_dtype: str = "float32"
    # sequence/context parallelism (SURVEY.md §5 long-context row): when set
    # to a mesh axis name, the model must run inside shard_map with the FRAME
    # axis of feats/masks sharded over that axis; the only frame-crossing
    # reductions (attention softmax, carry-init pooling) become collective
    # (pmax/psum over ICI), so videos longer than one chip's HBM still train
    # and decode. "" = single-device frame axis (the default).
    seq_axis: str = ""
    # temporal-attention context implementation: "xla" (the fused composite
    # XLA compiles, default) or "pallas" (ops/attention_pallas.py — blockwise
    # online softmax over the frame axis; parity-tested. Measured on v5e:
    # XLA ties or beats it (within ±10%) at every M up to 8192 — see
    # BENCH_ATTENTION.json — so "xla" is recommended everywhere; the kernel
    # is long-context insurance)
    attention_impl: str = "xla"
    # decode-step implementation for the greedy/sampling/fused RL decode
    # loops (README "Decode fast path"): "xla" (the composite the loops'
    # lane-batched step compiles to, default) or "pallas"
    # (ops/decode_pallas.py — one fused kernel per step: attention + LSTM
    # stack + output projection with the decoder weights resident in VMEM
    # across the row grid). Decode is inference-only (REINFORCE gradients go
    # through the teacher-forced update path), so the kernel has no VJP;
    # parity-swept against the XLA step in tests/test_ops_decode_pallas.py,
    # benchmarked by bench_decode.py (BENCH_DECODE.json)
    decode_impl: str = "xla"
    # fused RL decode stride: steps per driving-loop iteration (and per
    # pallas_call when decode_impl="pallas" — the multi-step kernel keeps
    # decoder weights VMEM-resident across the whole stride). 1 = the
    # per-step loop (the PR-4 behavior, kept as the exactness baseline).
    # Token/logprob-exact for every S by construction (pinned in
    # tests/test_decoding.py); larger strides coarsen the EOS early-exit
    # granularity, so S should stay well under the typical caption length
    decode_stride: int = 8
    # finished-lane compaction between strides: gather batch columns that
    # still have an unfinished lane into a dense prefix so the stride kernel
    # skips whole blocks of finished rows (XLA steps full width — the
    # compute win is the kernel's; the permutation round-trip is
    # token-exact either way). No-op at decode_stride=1 — compaction only
    # pays between strides. Off = step every row until the global exit
    decode_compact: bool = True

    def __post_init__(self):
        if isinstance(self.modalities, Mapping):
            object.__setattr__(self, "modalities", _freeze_modalities(self.modalities))
        else:
            object.__setattr__(
                self, "modalities", tuple((str(k), int(v)) for k, v in self.modalities)
            )
        if self.encoder not in ("meanpool", "temporal_attention"):
            raise ValueError(f"unknown encoder: {self.encoder!r}")
        if self.attention_impl not in ("xla", "pallas"):
            raise ValueError(
                f"unknown attention_impl: {self.attention_impl!r} "
                "(expected 'xla' or 'pallas')"
            )
        if self.decode_impl not in ("xla", "pallas"):
            raise ValueError(
                f"unknown decode_impl: {self.decode_impl!r} "
                "(expected 'xla' or 'pallas')"
            )
        if self.decode_stride < 1:
            raise ValueError(
                f"decode_stride {self.decode_stride} must be >= 1"
            )
        if self.decode_impl == "pallas" and self.seq_axis:
            # the kernel's in-VMEM softmax is single-device; a frame-sharded
            # memory bank needs the collective softmax path
            raise ValueError(
                "decode_impl='pallas' cannot run with a frame-sharded "
                "memory bank (seq_axis set) — the kernel's attention "
                "softmax is not collective"
            )

    @property
    def modality_names(self) -> tuple[str, ...]:
        return tuple(name for name, _ in self.modalities)

    @property
    def modality_dims(self) -> dict[str, int]:
        return dict(self.modalities)


@dataclass(frozen=True)
class DataConfig:
    """Dataset wiring (reference ``dataloader.py`` capability)."""

    dataset: str = "synthetic"          # "msvd" | "msrvtt" | "synthetic"
    feature_files: tuple[tuple[str, str], ...] = ()  # (modality, h5 path)
    info_json: str = ""                 # vocab + splits + tokenized captions
    consensus_weights: str = ""         # WXE per-caption weights (npz), optional
    cider_df: str = ""                  # precomputed CIDEr-D document freqs, optional
    batch_size: int = 64                # global batch (split across data axis)
    seq_per_vid: int = 1                # caption rows sampled per video (XE)
    shuffle_seed: int = 0
    prefetch: int = 2                   # device prefetch depth
    # keep every video's (padded) features in host RAM after the first h5
    # read: repeat epochs skip h5py entirely. Opt-in — full MSR-VTT
    # ResNet+C3D at 28 frames is ~2 GB of f32; size it to the host.
    # Cached arrays come back READ-ONLY (in-place mutation raises instead of
    # silently poisoning later epochs); the uncached path returns fresh
    # writable arrays — consumers that mutate features must copy first
    cache_features: bool = False

    def __post_init__(self):
        if isinstance(self.feature_files, Mapping):
            object.__setattr__(
                self,
                "feature_files",
                tuple((str(k), str(v)) for k, v in self.feature_files.items()),
            )


@dataclass(frozen=True)
class TrainConfig:
    """Optimization loop (reference ``train.py`` capability)."""

    optimizer: str = "adam"
    lr: float = 1e-4
    lr_decay: float = 0.5               # multiplicative decay factor
    lr_decay_every: int = 3             # epochs between decays (0 = constant)
    grad_clip: float = 5.0              # global-norm clip
    epochs: int = 30
    seed: int = 1234
    weight_decay: float = 0.0
    label_smoothing: float = 0.0
    loss: str = "xe"                    # "xe" | "wxe"

    def __post_init__(self):
        if self.on_divergence not in ("off", "skip_batch", "rollback", "abort"):
            raise ValueError(
                f"unknown on_divergence policy {self.on_divergence!r} "
                "(expected 'off', 'skip_batch', 'rollback', or 'abort')"
            )
        if self.ckpt_every_steps < 0 or self.keep_ckpts < 1 or self.max_rollbacks < 0:
            raise ValueError(
                "resilience knobs out of range: ckpt_every_steps >= 0, "
                "keep_ckpts >= 1, max_rollbacks >= 0 required "
                f"(got {self.ckpt_every_steps}, {self.keep_ckpts}, "
                f"{self.max_rollbacks})"
            )
        if self.elastic not in ("strict", "degraded"):
            raise ValueError(
                f"unknown elastic mode {self.elastic!r} "
                "(expected 'strict' or 'degraded')"
            )
        if self.health_interval_s <= 0 or self.peer_timeout_s <= 0:
            raise ValueError(
                "health knobs out of range: health_interval_s > 0 and "
                f"peer_timeout_s > 0 required (got {self.health_interval_s}, "
                f"{self.peer_timeout_s})"
            )
        if self.health_sim_hosts < 0:
            raise ValueError(
                f"health_sim_hosts {self.health_sim_hosts} must be >= 0 "
                "(0 = the real process count)"
            )
        if self.comm_dtype not in ("f32", "bf16"):
            raise ValueError(
                f"unknown comm_dtype {self.comm_dtype!r} "
                "(expected 'f32' or 'bf16')"
            )
        if self.comm_bucket_mb < 0:
            raise ValueError(
                f"comm_bucket_mb {self.comm_bucket_mb} must be >= 0 "
                "(0 = one message per leaf)"
            )
        if self.recorder_steps < 0:
            raise ValueError(
                f"recorder_steps {self.recorder_steps} must be >= 0 "
                "(0 = flight recorder off)"
            )
        if self.spike_mode not in ("fixed", "adaptive"):
            raise ValueError(
                f"unknown spike_mode {self.spike_mode!r} "
                "(expected 'fixed' or 'adaptive')"
            )
        if self.spike_mode == "adaptive":
            if self.spike_factor <= 0:
                raise ValueError(
                    "spike_mode='adaptive' needs spike_factor > 0 — the "
                    "factor is the adaptive bound's ceiling clamp"
                )
            if not 0 < self.spike_factor_min <= self.spike_factor:
                raise ValueError(
                    f"spike_factor_min {self.spike_factor_min} must be in "
                    f"(0, spike_factor={self.spike_factor}]"
                )
        if self.rl_topology not in ("sync", "decoupled"):
            raise ValueError(
                f"unknown rl_topology {self.rl_topology!r} "
                "(expected 'sync' or 'decoupled')"
            )
    # per-step JSONL events (loss/reward + grad_norm every N steps; 0 = off,
    # keeping logs to per-epoch summaries)
    log_every_steps: int = 0
    eval_every_epochs: int = 1
    ckpt_dir: str = "checkpoints"
    resume: str = ""                    # "", "auto", or explicit ckpt path
    # observability (SURVEY.md §5 rows 1-2; obs/ package)
    profile_dir: str = ""               # jax.profiler trace output dir ("" = off)
    profile_steps: int = 10             # steps to trace (after the compile step)
    debug_nans: bool = False            # jax_debug_nans sanitizer mode
    # unified obs subsystem (spans + metrics + run report, README
    # "Observability"): off by default — every span/counter call in the hot
    # paths degrades to a no-op. Snapshot cadence rides log_every_steps.
    obs: bool = False
    obs_dir: str = ""                   # run dir ("" = <ckpt_dir>/obs)
    # ---- resilience (resilience/ package; README "Preemption-safe training")
    # mid-epoch step_<n> checkpoint interval, in steps (0 = epoch-end saves
    # only; SIGTERM-triggered saves happen regardless)
    ckpt_every_steps: int = 0
    keep_ckpts: int = 3                 # keep-last-K rotation for step_* ckpts
    # divergence sentinel policy: "off" | "skip_batch" (on-device guard
    # excludes the non-finite update, run continues) | "rollback" (restore
    # last-good checkpoint, re-randomize data order) | "abort"
    on_divergence: str = "skip_batch"
    # loss-spike sentinel: flag a finite loss > factor * median(recent
    # window); 0 = NaN/inf detection only
    spike_factor: float = 0.0
    # "fixed" = the factor-of-median bound above, untouched. "adaptive" =
    # the anomaly detector's EWMA moments set the bound (mean + z*std,
    # clamped to [spike_factor_min, spike_factor] x median — never looser
    # than fixed; catches slow ramps the fixed factor misses). Requires
    # spike_factor > 0; shares the detector's loss Ewma when `anomaly` is on
    spike_mode: str = "fixed"
    spike_factor_min: float = 1.5       # adaptive bound's floor clamp
    max_rollbacks: int = 2              # rollback budget per run before aborting
    # ---- elastic multi-host resilience (resilience/health.py; README
    # "Elastic training"): off by default — the hot loops then carry zero
    # extra work (the peer-loss poll is gated on `health`)
    health: bool = False                # run the heartbeat/watchdog monitor
    health_dir: str = ""                # heartbeat dir ("" = <ckpt_dir>/health)
    health_interval_s: float = 0.5      # watchdog beat/poll cadence
    peer_timeout_s: float = 5.0         # heartbeat staleness before a strike
    # consecutive stale polls (the debounce) before a peer is declared lost
    health_misses: int = 2
    # chaos/test only: pretend the cluster has N hosts (this process is host
    # 0, the phantoms die only via the partial_preempt fault); 0 = the real
    # jax.process_count()
    health_sim_hosts: int = 0
    # on peer loss after the drain+save: "strict" aborts (raise PeerLost;
    # the restarted full-mesh run resumes bit-exactly) | "degraded"
    # rendezvous the survivors, rebuild a shrunk data mesh, reshard from the
    # drained checkpoint, and continue with per-host batch rescaling
    elastic: str = "strict"
    # grow-back direction (only meaningful with elastic="degraded"): a
    # degraded run polls for generation-stamped rejoin markers at batch
    # boundaries and re-admits a validated recovered host — drain, full-mesh
    # rendezvous, reshard state from the SURVIVORS (never the rejoiner's
    # stale checkpoint), continue the epoch remainder. False = a degraded
    # run stays degraded (the pre-regrow ratchet-down behavior)
    elastic_regrow: bool = True
    # a cross-host collective slower than this emits a dcn_stall event +
    # counter (the DCN-stall span around the multihost barrier/broadcast)
    dcn_stall_s: float = 2.0
    # ---- gradient communication (parallel/comms.py; README "Gradient
    # communication"): how the data-parallel factories allreduce grads.
    # Target payload per collective in MiB — the grad tree coalesces into
    # family-ordered contiguous buckets of at most this many WIRE bytes and
    # one psum runs per bucket (0 = one psum per leaf). Bit-identical to the
    # per-leaf spelling at f32 — psum is elementwise
    comm_bucket_mb: float = 4.0
    # "f32" (bit-exact default) | "bf16": grads ride the wire in bfloat16,
    # halving bytes; params/optimizer moments stay f32 (master accumulation)
    comm_dtype: str = "f32"
    # overlap the grad reduction with the backward scan: each rl.update_chunks
    # chunk's psum starts while the next chunk's backward runs (double-
    # buffered carry). Needs rl.update_chunks >= 2; trades (chunks+1)x wire
    # bytes for latency hiding — see the README section before enabling
    comm_overlap: bool = False
    # ---- flight recorder + anomaly detection (obs/recorder.py, obs/anomaly.py;
    # README "Observability"): ring capacity in steps for the black-box
    # per-step record buffer (0 = off; requires `obs`). On divergence/
    # rollback/chaos/preemption the ring dumps as a postmortem bundle under
    # the obs dir, rendered by `cli.obs_report --postmortem <bundle>`
    recorder_steps: int = 0
    # online EWMA z-score + stall detection over the recorder's loss/
    # grad-norm/reward/step-time streams; verdicts land inline in the ring
    # records and as `anomaly` events + obs.anomaly.<kind> counters
    anomaly: bool = False
    # ---- RL actor/learner topology (rl/async_scst.py; README "Decoupled
    # actor/learner RL"): "sync" (default) = today's synchronous loop,
    # bit-identical to the pre-topology trainer. "decoupled" = the data mesh
    # splits into actor and learner submeshes (rl.actor_fraction) — actors
    # run the fused decode continuously into a device-resident rollout ring
    # (rl.rollout_depth), learners consume it with the existing rl_update
    # factories, and params broadcast actor-ward on the rl.staleness_bound
    # schedule. Decoupled with depth 1 / bound 0 / actor = full mesh is the
    # strict replay mode, pinned bit-identical to "sync"
    rl_topology: str = "sync"


@dataclass(frozen=True)
class RLConfig:
    """CST / self-critical phase (reference RL loop, SURVEY.md §3.2)."""

    enabled: bool = False
    num_rollouts: int = 5               # K Monte-Carlo samples per clip
    baseline: str = "greedy"            # "greedy" (SCST) | "scb" (self-consensus) | "none"
    reward_cider_weight: float = 1.0
    reward_bleu4_weight: float = 0.0
    temperature: float = 1.0
    lr: float = 2e-5                    # RL phase LR (fresh optimizer on handoff)
    epochs: int = 20
    init_from: str = ""                 # XE checkpoint to start from
    # True (default): the two-stage pipelined epoch — per iteration the
    # dispatch order is update(i-2) -> decode(i) -> host-score(i-1), so a
    # full device step stays queued while the host computes the consensus
    # reward and the device never idles on it. The decoded policy is one
    # update stale (identical to a plain decode-then-score loop — update
    # i-1 cannot be ready before decode i without blocking). False: strict
    # on-policy SCST, decode -> score -> update serialized per batch,
    # exactly the reference's loop (SURVEY.md §3.2); measured reward-curve
    # delta between the modes is recorded in BASELINE.md
    pipelined: bool = True
    # host threads for the native consensus-reward scorer; 0 = all cores
    # (os.cpu_count()). The reward is the host hot path the pipeline hides —
    # size it to the machine, not a hardcoded cap
    reward_threads: int = 0
    # scale applied to sentence-BLEU4 (in [0,1]) before mixing with CIDEr-D
    # (x10 scale) in the consensus reward: reward = w_c*CIDErD +
    # w_b*BLEU4*scale. Default 10.0 puts both terms on a like scale —
    # UNVERIFIED interpretation of the reference's convention (BASELINE.md
    # "Mixed-reward BLEU4 scale"); exposed so it can be matched when the
    # reference becomes readable
    reward_bleu4_scale: float = 10.0
    # gradient accumulation over the K rollout axis in the REINFORCE update:
    # the update teacher-forces K*B sequences at once, which caps the batch
    # size under HBM; update_chunks=C (dividing K) re-runs forward+backward
    # on K/C rollouts at a time — the same total gradient up to float
    # summation order, NOT bit-equal to the fused path (1 = fused)
    update_chunks: int = 1
    # ---- decoupled actor/learner knobs (train.rl_topology="decoupled";
    # rl/async_scst.py, README "Decoupled actor/learner RL") ----
    # device-resident rollout ring depth in batches: actors decode up to
    # this many batches ahead of the learner (2 = the double buffer).
    # Depth 1 serializes actor and learner — with staleness_bound 0 and a
    # full-mesh actor that is the strict schedule replaying "sync" bit-for-bit
    rollout_depth: int = 2
    # max learner updates a rollout's params may lag at consumption time; a
    # staler rollout is dropped and re-decoded (recounted) under the actor's
    # current params with the entry's stored RNG key, so the drop/recount
    # sequence is deterministic run-to-run
    staleness_bound: int = 1
    # fraction of the data-axis devices handed to the actor submesh (the
    # remainder learn); both sides are clamped to >= 1 device, and a 1-device
    # mesh (or mesh=None) runs both roles on the same device
    actor_fraction: float = 0.5
    # ---- online serving-as-actor knobs (rl/online.py; README "Online RL
    # from served traffic") ----
    # completed served requests buffered per learner batch before the
    # batch enters the rollout ring (the online analogue of
    # data.batch_size; a trailing partial buffer waits for more traffic)
    online_batch_size: int = 4
    # learner updates between param publishes into the live CaptionService
    # (1 = publish after every update). The publish is version-stamped with
    # the learner's update counter and applies at the service's next stride
    # boundary — drain-free, with in-flight requests pinned to their
    # admission version
    swap_every: int = 1


@dataclass(frozen=True)
class EvalConfig:
    """Evaluation (reference ``test.py`` capability)."""

    beam_size: int = 5
    max_len: int = 30
    min_len: int = 0              # suppress EOS for the first N steps (0 = off)
    length_penalty: float = 0.0         # 0 = pure sum-logprob (reference behavior)
    split: str = "test"
    # selector names understood by metrics.scorer.CaptionScorer
    metrics: tuple[str, ...] = ("Bleu", "ROUGE_L", "METEOR_approx", "CIDEr", "CIDEr-D")
    results_json: str = ""
    # "lanes" = beam-on-decode-lanes fast path, "reference" = the sequential
    # bit-parity oracle (decoding/beam.py; token- and score-bit-exact pair)
    beam_impl: str = "lanes"
    # NPAD anytime mode (arXiv 1605.03835): >0 decodes greedy + this many
    # noise-perturbed lanes and answers with the best-sum-logprob lane
    # INSTEAD of beam search — the latency-budget eval answer (0 = off)
    npad_lanes: int = 0
    npad_temperature: float = 1.0
    npad_seed: int = 0
    # two-stage eval pipeline: device decodes batch i+1 while a worker pool
    # tokenizes batch i's captions on the host; metric tables stay
    # bit-identical to the serial path (eval/evaluator.py)
    pipelined: bool = True
    score_workers: int = 4        # tokenizer threads feeding the drain

    def __post_init__(self):
        if self.beam_impl not in ("lanes", "reference"):
            raise ValueError(
                f"eval.beam_impl must be 'lanes' or 'reference', got "
                f"{self.beam_impl!r}"
            )
        if self.npad_lanes < 0:
            raise ValueError(
                f"eval.npad_lanes {self.npad_lanes} must be >= 0 (0 = off)"
            )
        if self.npad_lanes and self.npad_temperature <= 0:
            raise ValueError(
                f"eval.npad_temperature {self.npad_temperature} must be > 0"
            )
        if self.score_workers < 1:
            raise ValueError(
                f"eval.score_workers {self.score_workers} must be >= 1"
            )


@dataclass(frozen=True)
class MeshConfig:
    """Device mesh (replaces torch.nn.DataParallel / NCCL, SURVEY.md §2).

    Axis names are chosen so a future multi-host ('dcn', 'data') hierarchy can
    be layered in without changing call sites.
    """

    data_axis: str = "data"
    num_devices: int = 0                # 0 = all visible devices
    # >1: 2-D ('data','seq') mesh — the FRAME axis shards over 'seq' with the
    # collective attention softmax (long-context path, SURVEY.md §5); must
    # divide num_devices and model.max_frames
    seq_devices: int = 1
    # >1: 2-D ('data','mp') mesh — flagship-XL model parallelism: the vocab
    # head / embedding (and the training-side LSTM gates) shard over 'mp'
    # per train/mesh.MP_PARAM_PARTITION_RULES; must divide the device count
    # and model.vocab_size / model.d_hidden. Exclusive with seq_devices > 1.
    mp_devices: int = 1


@dataclass(frozen=True)
class ExperimentConfig:
    name: str = "experiment"
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    rl: RLConfig = field(default_factory=RLConfig)
    eval: EvalConfig = field(default_factory=EvalConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)

    def __post_init__(self):
        if self.model.attention_impl == "pallas" and self.mesh.seq_devices > 1:
            # the sequence-parallel path uses the collective softmax and
            # would silently override the kernel — fail loudly instead
            raise ValueError(
                "attention_impl='pallas' is not implemented for the "
                "sequence-parallel ('seq_devices > 1') path; use one or the "
                "other"
            )
        if self.model.decode_impl == "pallas" and self.mesh.seq_devices > 1:
            # the decode kernel fuses its own (single-device) attention
            # softmax — it cannot express the collective 'seq' softmax
            raise ValueError(
                "decode_impl='pallas' is not implemented for the "
                "sequence-parallel ('seq_devices > 1') path; use one or the "
                "other"
            )
        if self.rl.enabled and (
            self.rl.update_chunks < 1
            or self.rl.num_rollouts % self.rl.update_chunks
        ):
            # catch at config time, not at the first RL step after a
            # potentially multi-hour XE phase
            raise ValueError(
                f"rl.update_chunks {self.rl.update_chunks} must be >= 1 and "
                f"divide rl.num_rollouts {self.rl.num_rollouts}"
            )
        if self.rl.reward_threads < 0:
            raise ValueError(
                f"rl.reward_threads {self.rl.reward_threads} must be >= 0 "
                "(0 = all cores)"
            )
        if self.train.comm_overlap and self.rl.update_chunks < 2:
            # overlap hides the psum behind the NEXT chunk's backward — with
            # one chunk there is nothing to hide behind
            raise ValueError(
                "train.comm_overlap requires rl.update_chunks >= 2 (the "
                f"chunk boundary is the overlap seam; got "
                f"{self.rl.update_chunks})"
            )
        if self.train.rl_topology == "decoupled":
            if self.rl.rollout_depth < 1:
                raise ValueError(
                    f"rl.rollout_depth {self.rl.rollout_depth} must be >= 1 "
                    "for train.rl_topology='decoupled'"
                )
            if self.rl.staleness_bound < 0:
                raise ValueError(
                    f"rl.staleness_bound {self.rl.staleness_bound} must be "
                    ">= 0 (0 = strict on-policy consumption)"
                )
            if not 0.0 < self.rl.actor_fraction < 1.0:
                raise ValueError(
                    f"rl.actor_fraction {self.rl.actor_fraction} must be in "
                    "(0, 1) — both submeshes need at least one device's share"
                )
            if self.mesh.seq_devices > 1:
                # the SP trainer's decode/update live inside one shard_map
                # over ('data','seq'); splitting 'data' under it needs a
                # submesh-aware SP story first
                raise ValueError(
                    "train.rl_topology='decoupled' is not implemented for "
                    "the sequence-parallel ('seq_devices > 1') path"
                )
        if self.rl.online_batch_size < 1:
            raise ValueError(
                f"rl.online_batch_size {self.rl.online_batch_size} must be "
                ">= 1 (served requests per online learner batch)"
            )
        if self.rl.swap_every < 1:
            raise ValueError(
                f"rl.swap_every {self.rl.swap_every} must be >= 1 (learner "
                "updates between param publishes into the serving engine)"
            )
        if self.mesh.seq_devices > 1 and (
            self.train.comm_dtype != "f32" or self.train.comm_overlap
        ):
            # the SP factories take grads OUTSIDE shard_map (the collective
            # transposes already produce global grads) — there is no grad
            # allreduce to compress or overlap
            raise ValueError(
                "train.comm_dtype='bf16' / train.comm_overlap are not "
                "implemented for the sequence-parallel ('seq_devices > 1') "
                "path: its gradients are computed outside shard_map and "
                "never ride a grad allreduce"
            )
        if self.mesh.mp_devices < 1:
            raise ValueError(
                f"mesh.mp_devices {self.mesh.mp_devices} must be >= 1 "
                "(1 = no model parallelism)"
            )
        if self.mesh.mp_devices > 1:
            if self.mesh.seq_devices > 1:
                # both want the second mesh dimension; a 3-D
                # ('data','seq','mp') composition needs an SP-aware vocab
                # shard story first (ROADMAP flagship-XL residuals)
                raise ValueError(
                    "mesh.mp_devices > 1 cannot compose with the "
                    "sequence-parallel ('seq_devices > 1') path yet — "
                    "pick one second mesh axis"
                )
            if self.model.vocab_size % self.mesh.mp_devices:
                raise ValueError(
                    f"mesh.mp_devices {self.mesh.mp_devices} must divide "
                    f"model.vocab_size {self.model.vocab_size} (the vocab "
                    "head and embedding shard in equal slices)"
                )
            if self.model.d_hidden % self.mesh.mp_devices:
                raise ValueError(
                    f"mesh.mp_devices {self.mesh.mp_devices} must divide "
                    f"model.d_hidden {self.model.d_hidden} (the LSTM gate "
                    "matrices shard in equal columns)"
                )
            if (self.mesh.num_devices
                    and self.mesh.num_devices % self.mesh.mp_devices):
                raise ValueError(
                    f"mesh.mp_devices {self.mesh.mp_devices} must divide "
                    f"mesh.num_devices {self.mesh.num_devices} (the mesh "
                    "is a dense data x mp grid)"
                )

    # ---- serialization ----------------------------------------------------

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, default=list)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ExperimentConfig":
        def build(tp, val):
            if val is None:
                return tp()
            fields = {f.name: f for f in dataclasses.fields(tp)}
            kwargs = {}
            for k, v in val.items():
                if k not in fields:
                    raise KeyError(f"{tp.__name__}: unknown field {k!r}")
                if isinstance(v, list):
                    v = tuple(tuple(x) if isinstance(x, list) else x for x in v)
                kwargs[k] = v
            return tp(**kwargs)

        return cls(
            name=d.get("name", "experiment"),
            model=build(ModelConfig, d.get("model")),
            data=build(DataConfig, d.get("data")),
            train=build(TrainConfig, d.get("train")),
            rl=build(RLConfig, d.get("rl")),
            eval=build(EvalConfig, d.get("eval")),
            mesh=build(MeshConfig, d.get("mesh")),
        )

    @classmethod
    def from_json(cls, s: str) -> "ExperimentConfig":
        return cls.from_dict(json.loads(s))

    def override(self, **dotted: Any) -> "ExperimentConfig":
        """Apply ``section__field=value`` overrides (CLI escape hatch).

        ``cfg.override(model__d_hidden=1024, rl__enabled=True)``
        """
        out = self
        for key, value in dotted.items():
            section, _, fname = key.partition("__")
            if not fname:
                out = dataclasses.replace(out, **{section: value})
                continue
            sub = getattr(out, section)
            out = dataclasses.replace(
                out, **{section: dataclasses.replace(sub, **{fname: value})}
            )
        return out
