"""Named presets — the Makefile-equivalent experiment recipes.

The five presets reproduce, one-for-one, the capability configs recorded by the
driver in ``BASELINE.json`` (the acceptance surface of the rebuild; the
reference expressed these as Makefile targets over ``opts.py`` flags,
SURVEY.md §2 rows 1/12):

1. ``msvd_xe_meanpool``      — MSVD, ResNet-152 mean-pool, 1-layer LSTM, XE.
2. ``msrvtt_xe_attention``   — MSR-VTT, ResNet-152 + C3D, temporal attention, XE.
3. ``msrvtt_scst``           — MSR-VTT CST fine-tune: greedy baseline + CIDEr-D (SCST).
4. ``msrvtt_cst_consensus``  — MSR-VTT weighted-consensus reward (CIDEr-D + BLEU4),
                               5 Monte-Carlo rollouts, self-consensus (SCB) baseline.
5. ``msrvtt_eval_beam5``     — MSR-VTT eval: beam search (beam=5) + COCO metrics.

Paper CST variant names map onto presets as: XE -> 1/2; CST_GT_None/SCST -> 3;
CST_MS_SCB -> 4 (with ``rl.baseline="scb"``); WXE is preset 2 with
``train.loss="wxe"``.
"""

from __future__ import annotations

import dataclasses

from cst_captioning_tpu.config.config import (
    DataConfig,
    EvalConfig,
    ExperimentConfig,
    ModelConfig,
    RLConfig,
    TrainConfig,
)

# MSR-VTT-scale vocab (reference builds ~8-11k word vocab after thresholding);
# synthetic/test runs override this downward.
_MSVD_VOCAB = 4000
_MSRVTT_VOCAB = 9000


def _msvd_xe_meanpool() -> ExperimentConfig:
    return ExperimentConfig(
        name="msvd_xe_meanpool",
        model=ModelConfig(
            vocab_size=_MSVD_VOCAB,
            modalities=(("resnet", 2048),),
            encoder="meanpool",
            d_embed=512,
            d_hidden=512,
            max_len=30,
            max_frames=28,
        ),
        data=DataConfig(dataset="msvd", batch_size=64),
        train=TrainConfig(loss="xe", lr=1e-4, epochs=50),
    )


def _msrvtt_xe_attention() -> ExperimentConfig:
    return ExperimentConfig(
        name="msrvtt_xe_attention",
        model=ModelConfig(
            vocab_size=_MSRVTT_VOCAB,
            modalities=(("resnet", 2048), ("c3d", 500)),
            encoder="temporal_attention",
            d_embed=512,
            d_hidden=512,
            d_att=256,
            max_len=30,
            max_frames=28,
        ),
        data=DataConfig(dataset="msrvtt", batch_size=64),
        train=TrainConfig(loss="xe", lr=1e-4, epochs=50),
    )


def _msrvtt_scst() -> ExperimentConfig:
    base = _msrvtt_xe_attention()
    return dataclasses.replace(
        base,
        name="msrvtt_scst",
        rl=RLConfig(
            enabled=True,
            num_rollouts=1,
            baseline="greedy",
            reward_cider_weight=1.0,
            reward_bleu4_weight=0.0,
            lr=2e-5,
        ),
    )


def _msrvtt_cst_consensus() -> ExperimentConfig:
    base = _msrvtt_xe_attention()
    return dataclasses.replace(
        base,
        name="msrvtt_cst_consensus",
        rl=RLConfig(
            enabled=True,
            num_rollouts=5,
            baseline="scb",
            reward_cider_weight=1.0,
            reward_bleu4_weight=0.5,
            lr=2e-5,
        ),
    )


def _msrvtt_eval_beam5() -> ExperimentConfig:
    base = _msrvtt_xe_attention()
    return dataclasses.replace(
        base,
        name="msrvtt_eval_beam5",
        eval=EvalConfig(beam_size=5, max_len=30, split="test"),
    )


PRESETS = {
    "msvd_xe_meanpool": _msvd_xe_meanpool,
    "msrvtt_xe_attention": _msrvtt_xe_attention,
    "msrvtt_scst": _msrvtt_scst,
    "msrvtt_cst_consensus": _msrvtt_cst_consensus,
    "msrvtt_eval_beam5": _msrvtt_eval_beam5,
}


def get_preset(name: str) -> ExperimentConfig:
    try:
        return PRESETS[name]()
    except KeyError:
        raise KeyError(
            f"unknown preset {name!r}; available: {sorted(PRESETS)}"
        ) from None
