"""Typed experiment configuration (replaces the reference's ``opts.py``).

The reference drives everything from a flat argparse namespace of ~50 flags
plus Makefile recipes (SURVEY.md §2 rows 1, 12).  Here the same surface is a
set of frozen dataclasses — one per subsystem — composed into an
:class:`ExperimentConfig`, plus named presets reproducing the five capability
configs pinned by ``BASELINE.json``.
"""

from cst_captioning_tpu.config.config import (
    PAD_ID,
    BOS_ID,
    EOS_ID,
    UNK_ID,
    ModelConfig,
    DataConfig,
    TrainConfig,
    RLConfig,
    EvalConfig,
    MeshConfig,
    ExperimentConfig,
)
from cst_captioning_tpu.config.presets import PRESETS, get_preset

__all__ = [
    "PAD_ID",
    "BOS_ID",
    "EOS_ID",
    "UNK_ID",
    "ModelConfig",
    "DataConfig",
    "TrainConfig",
    "RLConfig",
    "EvalConfig",
    "MeshConfig",
    "ExperimentConfig",
    "PRESETS",
    "get_preset",
]
