"""SCST orchestration: fused decode dispatch -> host reward -> REINFORCE update.

The throughput-critical path (SURVEY.md §3.2, the north-star metric). Design
vs the reference's per-batch host↔device ping-pong:

1. ``make_rl_decode``   — ONE jitted program, ONE scan loop: the greedy
   baseline rides as lane 0 of the (1+K)-lane rollout scan
   (decoding/fused.py), sharing the encoder pass and every per-step
   attention/LSTM dispatch with the K multinomial rollouts (the reference
   runs two separate ``model.sample`` calls; the pre-PR-4 build ran two
   sequential scan loops in one program — kept behind ``fused=False`` as
   the bit-exactness reference).
2. Host: ``RewardComputer`` scores rollouts + greedy against the consensus
   pools (vectorized numpy, precomputed df); advantage = reward − baseline
   (greedy SCST or self-consensus SCB).
3. ``make_rl_update``   — second jitted program teacher-forces the sampled
   tokens to get *differentiable* logprobs and applies the REINFORCE grad
   (psum-DP in the parallel variant).

Two dispatches, not ``io_callback``, exactly per SURVEY.md §7 step 5: the
reward stays debuggable on host, the device work stays fused.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from cst_captioning_tpu import obs
from cst_captioning_tpu.compat import pcast
from cst_captioning_tpu.config.config import PAD_ID, RLConfig
from cst_captioning_tpu.decoding import fused_decode, greedy_decode, sample_decode
from cst_captioning_tpu.decoding.common import _exit_stride, mask_from_tokens
from cst_captioning_tpu.obs import flops as _flops
from cst_captioning_tpu.losses import reinforce_loss, sequence_log_probs
from cst_captioning_tpu.models.captioner import CaptionModel
from cst_captioning_tpu.parallel.comms import reduce_tree
from cst_captioning_tpu.parallel.compile import CompilePlan, compile_fn
from cst_captioning_tpu.resilience import chaos
from cst_captioning_tpu.resilience.health import collective_span
from cst_captioning_tpu.resilience.retry import RetryPolicy, retry_call
from cst_captioning_tpu.rl.rewards import RewardComputer, scb_baseline
from cst_captioning_tpu.train.state import TrainState
from cst_captioning_tpu.train.steps import _apply


def compaction_stats(greedy_np, samples_np, stride: int, budget: int,
                     compact: bool = True) -> dict:
    """Host-side decode ledger from already-decoded tokens (no device reads).

    -> ``{depth, lanes_stepped, lanes_skipped}``: the scan depth the
    early-exit loop ran (next ``stride`` multiple of the longest row,
    capped at the padded budget), and how many (lane, batch-column) steps
    the compacted decode computed vs skipped. A lane stops computing after
    its own (EOS-inclusive) length: compaction packs still-active columns
    into a dense prefix and the stride kernel additionally skips a lane's
    batch block once every row in it is finished, so the row-granular
    ledger here is ``sum(min(len, depth))`` stepped out of ``G*B*depth``
    total (block granularity makes the realized kernel savings slightly
    lower — a block dies only when its last row does). Without ``compact``
    every lane rides to the global early exit. Shared by ``SCSTTrainer``
    (the ``rl.decode.compaction`` counter pair) and ``bench_decode.py``
    (the tokens-stepped-saved column), so the two reports can't drift.
    """
    lanes = []
    if greedy_np is not None and np.asarray(greedy_np).size:
        lanes.append(np.asarray(greedy_np)[None])
    if samples_np is not None and np.asarray(samples_np).size:
        lanes.append(np.asarray(samples_np))
    if not lanes:
        return {"depth": 0, "lanes_stepped": 0, "lanes_skipped": 0}
    toks = np.concatenate(lanes, axis=0)                      # [G, B, T]
    G, B, _ = toks.shape
    stride = max(int(stride), 1)
    padded = -(-int(budget) // stride) * stride
    lens = (toks != PAD_ID).sum(axis=-1)                      # [G, B]
    depth = min(
        padded, stride * -(-max(int(lens.max()), 1) // stride)
    )
    total = G * B * depth
    if compact:
        stepped = int(np.minimum(lens, depth).sum())
    else:
        stepped = total
    return {
        "depth": int(depth),
        "lanes_stepped": stepped,
        "lanes_skipped": int(total - stepped),
    }


def sample_entropy(samples_np) -> float:
    """Empirical token-distribution entropy (nats) of the sampled lanes,
    from the already-on-host tokens — the flight recorder's entropy-collapse
    signal (a policy converging onto a few captions drives this toward 0
    while the reward mean can still look healthy). Pad tokens are excluded
    so short captions don't masquerade as low entropy."""
    toks = np.asarray(samples_np).ravel()
    toks = toks[toks != PAD_ID]
    if toks.size == 0:
        return 0.0
    counts = np.bincount(toks)
    p = counts[counts > 0] / toks.size
    return float(-(p * np.log(p)).sum())


def make_rl_decode(model, num_rollouts: int, temperature: float = 1.0,
                   max_len: int | None = None,
                   with_greedy: bool = True, fused: bool = True) -> Callable:
    """Jitted: (params, feats, masks, rng) -> (greedy [B,T], samples [K,B,T]).

    ``fused=True`` (default): ONE scan produces greedy and samples — the
    greedy baseline is lane 0 of the (1+K)-lane rollout scan
    (decoding/fused.py), eliminating the second loop's encoder pass, its
    per-step fixed overhead, and the duplicate attention/LSTM dispatch.
    ``fused=False`` is the two-loop reference the fused path is pinned
    bit-exact against (tests/test_rl.py) and the baseline ``bench_decode.py``
    measures speedup over.

    ``with_greedy=False`` skips the greedy rollout (``greedy`` is None):
    only the 'greedy' baseline consumes it, so the scb/none baselines save
    one of the K+1 decoded rows per clip plus its host transfer + reward
    (already one loop — ``fused`` changes nothing there)."""

    def decode(params, feats, masks, rng):
        if with_greedy and fused:
            greedy, _, samples, _ = fused_decode(
                model, params, feats, masks, rng,
                num_rollouts=num_rollouts, temperature=temperature,
                max_len=max_len,
            )
            return greedy, samples
        greedy = None
        if with_greedy:
            greedy, _ = greedy_decode(
                model, params, feats, masks, max_len=max_len
            )
        samples, _ = sample_decode(
            model, params, feats, masks, rng,
            num_rollouts=num_rollouts, temperature=temperature, max_len=max_len,
        )
        return greedy, samples

    return compile_fn(decode, CompilePlan())


def make_parallel_rl_decode(model, mesh: Mesh, num_rollouts: int,
                            temperature: float = 1.0,
                            max_len: int | None = None,
                            axis: str = "data",
                            with_greedy: bool = True,
                            fused: bool = True) -> Callable:
    """shard_map decode: batch sharded over the mesh, the dominant RL cost
    scales with chips (SURVEY.md §3.2/§7 step 6) instead of running on one.

    Decode has no cross-example interaction, so each device decodes its own
    batch shard. The greedy path is deterministic — sharded output equals the
    single-device decode of the concatenated batch (pinned by
    tests/test_rl.py). Sampling folds ``axis_index`` into the rollout key so
    shards draw independent streams.
    """

    def device_decode(params, feats, masks, rng):
        local_rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        if with_greedy and fused:
            greedy, _, samples, _ = fused_decode(
                model, params, feats, masks, local_rng,
                num_rollouts=num_rollouts, temperature=temperature,
                max_len=max_len, batch_axes=(axis,),
            )
            return greedy, samples
        greedy = None
        if with_greedy:
            greedy, _ = greedy_decode(
                model, params, feats, masks, max_len=max_len,
                batch_axes=(axis,),
            )
        samples, _ = sample_decode(
            model, params, feats, masks, local_rng,
            num_rollouts=num_rollouts, temperature=temperature, max_len=max_len,
            batch_axes=(axis,),
        )
        return greedy, samples

    # check_vma stays ON (VERDICT r4 weak #3 closed): the decode loops pcast
    # their device-invariant inits (BOS tokens, output buffers) to varying
    # over ``batch_axes`` and psum the early-exit row count over it, so the
    # compiler verifies the per-shard/collective split instead of a comment
    # promising the exactness tests will.
    return compile_fn(device_decode, CompilePlan(
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P()),
        out_specs=(P(axis), P(None, axis)),
    ))


def _tile_enc(enc, K):
    """EncoderOutput [B, ...] -> [K*B, ...] (rollout-major tiling to match
    ``samples.reshape``).

    Tiling the ENCODED memory instead of the raw features lets the update
    run the encoder once per clip instead of once per rollout row — the
    encoder is ~12% of the update FLOPs at the flagship dims, and gradients
    flow through the tile as a sum over the K copies (same math as the
    feature-tiled computation up to float summation order)."""
    t = lambda x: jnp.tile(x, (K,) + (1,) * (x.ndim - 1))
    return jax.tree.map(t, enc)


def _decode_loss_sums(model, params, enc_tiled, tokens_flat, advantage_flat,
                      valid_tiled):
    """(numerator, denominator) REINFORCE sums from tiled encoder output.

    ``valid_tiled`` zeroes wrap-padded duplicate rows from short final
    batches so they carry no gradient weight and don't dilute the
    normalization. Uses the in-scan ``teacher_force_logps`` path: the full
    [rows, T, V] logits stack (~2 GB f32 at the flagship dims) is never
    materialized — each step's logits are reduced to the target-token
    logprob in place."""

    logp = model.apply(
        params, enc_tiled, tokens_flat, method=CaptionModel.teacher_force_logps
    )
    mask = mask_from_tokens(tokens_flat) * valid_tiled[:, None]
    den = jnp.sum(mask)
    num = reinforce_loss(logp, mask, advantage_flat) * jnp.maximum(den, 1.0)
    return num, den


def _chunked_loss_grads(model, params, feats, masks, samples, advantage,
                        valid, chunks: int, vary_axis: str | None = None,
                        comm=None):
    """REINFORCE loss sums + gradients, accumulated over ``chunks`` slices
    of the K rollout axis — with ONE encoder pass shared by every chunk.

    Teacher-forcing all K*B sequences at once is the HBM ceiling on batch
    size (VERDICT r2 weak #1); chunking bounds the live activation footprint
    to K/chunks rollouts. The encoder runs once on the B clip rows
    (``jax.vjp`` keeps its backward); each scanned chunk differentiates the
    decode w.r.t. (params, encoder output), the encoder-output cotangents
    accumulate in f32 across chunks, and one ``enc_vjp`` call at the end
    folds them into the parameter gradients. Same total gradient as the
    feature-tiled computation up to float summation order.

    ``comm`` (parallel/comms.CommConfig) with ``overlap != "off"`` moves the
    cross-device grad allreduce INSIDE the scan (needs ``vary_axis``): each
    chunk's parameter grads are reduced per chunk instead of accumulate-
    then-reduce, so the collective can run while the next chunk's backward
    computes. Two spellings, bit-identical to each other at f32:

    - ``"defer"`` — the production overlap: a double-buffered carry holds
      the PREVIOUS chunk's unreduced grads; iteration *i* issues the psum
      of chunk *i-1*'s grads alongside chunk *i*'s forward+backward, giving
      the scheduler a full chunk of compute to hide each collective behind
      (one flush reduction after the scan drains the buffer).
    - ``"eager"`` — reduce each chunk's grads in its own iteration; no
      buffering, nothing to overlap. Float-order-identical to "defer"
      (defer merely adds a leading ``+ psum(zeros)``, a bitwise no-op), so
      it serves as its bit-exact parity reference in tests/bench.

    When overlap is active the returned gradients are ALREADY reduced over
    ``vary_axis`` (axis-invariant); the caller must not psum them again —
    only the scalar num/den sums still need their reduction. Note the
    per-chunk reductions move (chunks+1)x the payload of the single fused
    reduction (each chunk reduces a full params-shaped tree, plus the
    encoder-cotangent fold at the end) — that is the latency-for-bandwidth
    trade, ledgered honestly by bench_comms.py.
    """

    K, B, T = samples.shape
    if K % chunks:
        raise ValueError(f"update_chunks {chunks} must divide K={K} rollouts")
    kc = K // chunks

    def enc_fn(p):
        e = model.apply(p, feats, masks, method=CaptionModel.encode)
        if vary_axis is not None:
            # inside shard_map, outputs that don't depend on the sharded
            # inputs (e.g. the meanpool encoder's all-ones memory_mask) are
            # device-INVARIANT, and the vjp would then reject the varying
            # per-shard cotangents accumulated below. Adding a varying zero
            # to every leaf makes the whole output uniformly varying; its
            # transpose lands in the (discarded) feats cotangent, so the
            # parameter gradients are untouched.
            zv = jnp.sum(jax.tree.leaves(feats)[0]) * 0.0
            e = jax.tree.map(lambda x: x + zv.astype(x.dtype), e)
        return e

    enc, enc_vjp = jax.vjp(enc_fn, params)
    valid_f = jnp.tile(valid, (kc,))
    sam = samples.reshape(chunks, kc * B, T)
    adv = advantage.reshape(chunks, kc * B)

    def sums_fn(p, e, tokens, a):
        return _decode_loss_sums(
            model, p, _tile_enc(e, kc), tokens, a, valid_f
        )

    overlap = comm is not None and comm.overlap != "off"
    if overlap and vary_axis is None:
        raise ValueError(
            "comm overlap needs vary_axis (the per-chunk reduction runs "
            "inside shard_map); single-device updates have nothing to "
            "overlap"
        )

    def chunk_grads(x):
        return jax.value_and_grad(sums_fn, argnums=(0, 1), has_aux=True)(
            params, enc, *x
        )

    def accum_ge(ge_acc, ge):
        # f32 accumulation: the cotangents arrive in the model dtype
        # (bf16 on the flagship config) and 8 mantissa bits across
        # `chunks` additions is avoidable error
        return jax.tree.map(lambda a_, g: a_ + g.astype(a_.dtype), ge_acc, ge)

    zeros_p = jax.tree.map(jnp.zeros_like, params)
    zeros_e = jax.tree.map(
        lambda x: jnp.zeros(x.shape, jnp.promote_types(x.dtype, jnp.float32)),
        enc,
    )
    if vary_axis is not None:
        # inside shard_map the per-chunk grads/sums vary over the batch
        # axis; the scan carry init must carry the same varying-axis type
        vary = lambda t: jax.tree.map(
            lambda x: pcast(x, vary_axis, to="varying"), t
        )
    else:
        vary = lambda t: t

    if overlap:
        # gp_acc accumulates the REDUCED (axis-invariant) per-chunk grads;
        # gp_pend is the double buffer holding the previous chunk's
        # unreduced (varying) grads, drained one iteration late so its
        # psum can fly while this iteration's backward computes
        def body(acc, x):
            gp_acc, gp_pend, ge_acc, num_acc, den_acc = acc
            if comm.overlap == "defer":
                gp_acc = jax.tree.map(
                    jnp.add, gp_acc, reduce_tree(gp_pend, vary_axis, comm)
                )
            (num, den), (gp, ge) = chunk_grads(x)
            if comm.overlap == "eager":
                gp_acc = jax.tree.map(
                    jnp.add, gp_acc, reduce_tree(gp, vary_axis, comm)
                )
                gp = gp_pend  # buffer unused: stays the zeros it came in as
            return (
                gp_acc, gp, accum_ge(ge_acc, ge),
                num_acc + num, den_acc + den,
            ), None

        init = (
            zeros_p, vary(zeros_p), vary(zeros_e),
            vary(jnp.zeros(())), vary(jnp.zeros(())),
        )
        (gp, gp_pend, ge, num, den), _ = jax.lax.scan(body, init, (sam, adv))
        if comm.overlap == "defer":
            # flush: the last chunk's grads are still in the buffer ("defer"
            # is bit-equal to "eager" — its extra leading `+ psum(zeros)`
            # adds +0.0, a bitwise no-op)
            gp = jax.tree.map(
                jnp.add, gp, reduce_tree(gp_pend, vary_axis, comm)
            )
    else:
        def body(acc, x):
            gp_acc, ge_acc, num_acc, den_acc = acc
            (num, den), (gp, ge) = chunk_grads(x)
            return (
                jax.tree.map(jnp.add, gp_acc, gp), accum_ge(ge_acc, ge),
                num_acc + num, den_acc + den,
            ), None

        init = vary((zeros_p, zeros_e, jnp.zeros(()), jnp.zeros(())))
        (gp, ge, num, den), _ = jax.lax.scan(body, init, (sam, adv))

    # vjp cotangents must match the primal dtype
    ge = jax.tree.map(lambda g, x: g.astype(x.dtype), ge, enc)
    (g_enc,) = enc_vjp(ge)
    if overlap:
        # keep the already-reduced invariant: fold the encoder grads in
        # reduced too, so the caller skips its own grad psum entirely
        g_enc = reduce_tree(g_enc, vary_axis, comm)
    g_sum = jax.tree.map(jnp.add, gp, g_enc)
    return num, den, g_sum


def make_rl_update(model, chunks: int = 1, donate: bool = False,
                   guard: bool = False, comm=None,
                   stats: bool = False) -> Callable:
    """Jitted: (state, feats, masks, samples [K,B,T], adv [K,B]) -> (state, metrics).

    ``chunks > 1`` accumulates gradients over slices of the rollout axis
    (same total gradient, K/chunks of the activation memory — see
    :func:`_chunked_loss_grads`). ``donate=True`` donates the input state's
    buffers (params + Adam moments update in place; the passed-in state is
    consumed — rebind, never reuse); off by default so exactness tests can
    replay one state through several update variants. ``guard=True``
    suppresses non-finite updates on device (resilience/guard.py) and adds
    a ``nonfinite`` metric. ``comm`` (parallel/comms.CommConfig) is accepted
    for factory-signature symmetry and ignored: no collectives here.
    ``stats=True`` adds the flight recorder's per-family update-ratio
    metrics (train/steps._update_ratios) — extra outputs only, params
    bit-identical.
    """
    del comm  # no cross-device reduction on this path

    def update(state: TrainState, feats, masks, samples, advantage, valid):
        if chunks > 1:
            num, den, g_sum = _chunked_loss_grads(
                model, state.params, feats, masks, samples, advantage, valid,
                chunks,
            )
            den = jnp.maximum(den, 1.0)
            loss = num / den
            grads = jax.tree.map(lambda g: g / den, g_sum)
        else:

            K, B, T = samples.shape
            tokens = samples.reshape(K * B, T)
            adv = advantage.reshape(K * B)
            valid_f = jnp.tile(valid, (K,))

            def loss_fn(p):
                # one encoder pass per clip; memory tiled over rollouts
                enc = model.apply(p, feats, masks, method=CaptionModel.encode)
                num, den = _decode_loss_sums(
                    model, p, _tile_enc(enc, K), tokens, adv, valid_f
                )
                return num / jnp.maximum(den, 1.0)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
        gnorm = optax.global_norm(grads)
        return _apply(state, grads, loss, gnorm, guard, key="rl_loss",
                      stats=stats)

    return compile_fn(
        update, CompilePlan(donate_argnums=(0,) if donate else ())
    )


def make_parallel_rl_update(model, mesh: Mesh, axis: str = "data",
                            chunks: int = 1, donate: bool = False,
                            guard: bool = False, comm=None,
                            stats: bool = False) -> Callable:
    """shard_map variant: batch axis sharded, exact global normalization.
    ``chunks`` / ``donate`` / ``guard`` / ``stats`` exactly like
    :func:`make_rl_update`.

    ``comm`` (parallel/comms.CommConfig) selects the grad-allreduce
    spelling: None keeps the original per-leaf psum; otherwise bucketed
    (and optionally bf16) reduction, and with ``comm.overlap != "off"`` the
    per-chunk reduction runs inside the update scan so it can hide behind
    the next chunk's backward (see :func:`_chunked_loss_grads` — the
    chunked path then returns already-reduced grads).
    """
    overlap = comm is not None and comm.overlap != "off"
    if overlap and chunks < 2:
        raise ValueError(
            "comm overlap requires chunks >= 2: the rl.update_chunks "
            "boundary is the overlap seam (config validation enforces the "
            f"same; got chunks={chunks})"
        )

    def device_update(state, feats, masks, samples, advantage, valid):
        if chunks > 1:
            num, den, grads_num = _chunked_loss_grads(
                model, state.params, feats, masks, samples, advantage, valid,
                chunks, vary_axis=axis, comm=comm,
            )
        else:

            K, Bl, T = samples.shape
            tokens = samples.reshape(K * Bl, T)
            adv = advantage.reshape(K * Bl)
            valid_f = jnp.tile(valid, (K,))

            def local_num(p):
                enc = model.apply(p, feats, masks, method=CaptionModel.encode)
                return _decode_loss_sums(
                    model, p, _tile_enc(enc, K), tokens, adv, valid_f
                )

            (num, den), grads_num = jax.value_and_grad(
                local_num, has_aux=True
            )(state.params)
        den_total = jax.lax.psum(den, axis)
        loss = jax.lax.psum(num, axis) / jnp.maximum(den_total, 1.0)
        if not overlap:
            # the chunked-overlap path hands back already-reduced grads;
            # everything else reduces here, after the full local backward
            grads_num = reduce_tree(grads_num, axis, comm)
        grads = jax.tree.map(
            lambda g: g / jnp.maximum(den_total, 1.0), grads_num
        )
        gnorm = optax.global_norm(grads)
        # psum'd grads/loss are device-invariant: the guarded select picks
        # the same branch on every shard, so state stays replicated
        return _apply(state, grads, loss, gnorm, guard, key="rl_loss",
                      stats=stats)

    return compile_fn(device_update, CompilePlan(
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(None, axis), P(None, axis), P(axis)),
        out_specs=(P(), P()),
        donate_argnums=(0,) if donate else (),
    ))


class SCSTTrainer:
    """Per-batch CST step: decode -> consensus reward -> REINFORCE update.

    ``baseline``: 'greedy' (SCST / CST_GT_None), 'scb' (self-consensus across
    the other K-1 rollouts, CST_MS_SCB), or 'none'.

    With a mesh, BOTH dispatches are shard_map-parallel — decode (the dominant
    cost) and update shard the batch over 'data'; host reward stays per-host.

    :meth:`train_step` is the strict sequential step. :meth:`train_epoch`
    is the pipelined loop (SURVEY.md §7 "hard parts"): the host scores batch
    *i* while the device decodes batch *i+1*.
    """

    def __init__(
        self,
        model,
        reward: RewardComputer,
        cfg: RLConfig,
        mesh: Mesh | None = None,
        max_len: int | None = None,
        donate: bool = False,
        guard: bool = False,
        retry: RetryPolicy | None = None,
        on_event: Callable | None = None,
        comm=None,
        stats: bool = False,
    ):
        """``donate=True`` makes the REINFORCE update consume its input state
        (buffer donation — see :func:`make_rl_update`); the production
        Trainer/bench path enables it, tests that replay a state don't.
        ``guard=True`` adds the on-device non-finite update guard.
        ``retry`` is the backoff policy for the (host-side, fallible in
        production) reward scorer; ``on_event(event, **fields)`` receives
        ``reward_retry`` events (an EventLogger.log works as-is).
        ``comm`` (parallel/comms.CommConfig) selects the update's grad
        allreduce spelling (None = original per-leaf psum); the Trainer
        builds it from the ``train.comm_*`` knobs. ``stats=True`` builds
        the update with the flight recorder's per-family update-ratio
        outputs (train/steps._update_ratios)."""
        self.model = model
        self.reward = reward
        self.cfg = cfg
        self.mesh = mesh
        self.comm = comm
        self.retry = retry or RetryPolicy()
        self.on_event = on_event or (lambda event, **fields: None)
        # analytic per-clip FLOPs (obs/flops.py) for the run report's MFU
        # column, plus the early-exit depth accounting (budget + stride) —
        # all host-side constants, nothing here touches a device value
        mc = model.cfg
        dims = dict(
            F=mc.max_frames, d_embed=mc.d_embed, d_hidden=mc.d_hidden,
            d_att=mc.d_att, V=mc.vocab_size,
            feat_dims=tuple(d for _, d in mc.modalities),
            num_layers=mc.num_layers,
        )
        self._depth_budget = max_len or mc.max_len
        # exit-check granularity of the decode actually dispatched: the
        # strided driver checks every decode_stride steps; the stride-1
        # uncompacted loop keeps scan_until_finished's ~5-step divisor
        decode_stride = max(
            1, min(int(getattr(mc, "decode_stride", 1)), self._depth_budget)
        )
        self._compact = bool(getattr(mc, "decode_compact", False))
        self._depth_stride = (
            decode_stride if decode_stride > 1 or self._compact
            else _exit_stride(self._depth_budget)
        )
        self._decode_flops_per_clip = _flops.decode_flops_per_clip(
            K=cfg.num_rollouts, T=self._depth_budget,
            with_greedy=(cfg.baseline == "greedy"),
            stride=self._depth_stride, **dims,
        )
        self._update_flops_per_clip = _flops.update_flops_per_clip(
            K=cfg.num_rollouts, T=self._depth_budget, **dims,
        )
        # compile-time update cost (obs/flops.compiled_cost), resolved
        # lazily at the first dispatch when obs is on: None = not yet
        # probed, False = XLA exposed no cost (analytic fallback), float =
        # whole-update FLOPs from the compiled program
        self._update_cost = None
        obs.gauge("rl.decode.budget").set(float(self._depth_budget))
        # decode FLOPs are always the analytic per-clip model (the early-exit
        # loop's realized cost isn't a fixed compiled number)
        obs.gauge("flops.backend.rl.decode").set(0.0)
        # only the 'greedy' baseline consumes the greedy rollout: scb/none
        # skip its decode, host transfer, and reward scoring entirely (one
        # of the K+1 decoded rows per clip on the flagship config)
        wg = cfg.baseline == "greedy"
        if mesh is not None and "seq" in mesh.axis_names:
            # DP x SP (MeshConfig.seq_devices > 1): frames shard over 'seq'
            # with the collective attention softmax, batch over 'data'
            from cst_captioning_tpu.parallel import (
                make_sp_decode, make_sp_rl_update, sp_model,
            )

            spm = model if model.cfg.seq_axis else sp_model(model.cfg)
            self.decode = make_sp_decode(
                spm, mesh, cfg.num_rollouts, cfg.temperature, max_len,
                data_axis="data", with_greedy=wg,
            )
            self.update = make_sp_rl_update(
                spm, mesh, chunks=cfg.update_chunks, donate=donate,
                guard=guard, comm=comm, stats=stats,
            )
        elif mesh is not None:
            self.decode = make_parallel_rl_decode(
                model, mesh, cfg.num_rollouts, cfg.temperature, max_len,
                with_greedy=wg,
            )
            self.update = make_parallel_rl_update(
                model, mesh, chunks=cfg.update_chunks, donate=donate,
                guard=guard, comm=comm, stats=stats,
            )
        else:
            self.decode = make_rl_decode(
                model, cfg.num_rollouts, cfg.temperature, max_len,
                with_greedy=wg,
            )
            self.update = make_rl_update(
                model, chunks=cfg.update_chunks, donate=donate, guard=guard,
                comm=comm, stats=stats,
            )

    # ---- reward / advantage (host) ------------------------------------------

    def _reward_call(self, video_ids, rows):
        """The reward scorer behind jittered-backoff retries: in-process
        numpy never fails, but the production deployment scores against a
        service — transient failures are retried, not fatal (and the chaos
        ``reward.call`` point lets tests inject both)."""

        def call():
            chaos.visit("reward.call")
            return self.reward(video_ids, rows)

        return retry_call(
            call,
            policy=self.retry,
            on_retry=lambda info: self.on_event("reward_retry", **info),
        )

    def _advantage(self, greedy, samples_np, video_ids, valid_np):
        """-> (advantage [K,B] np, metrics dict). Blocks on decode transfer."""
        K = self.cfg.num_rollouts
        B = samples_np.shape[1]
        r_samples = self._reward_call(video_ids, samples_np.reshape(K * B, -1))
        r_kb = r_samples.reshape(K, B)

        if self.cfg.baseline == "greedy":
            if greedy is None:
                raise ValueError(
                    "baseline='greedy' needs the greedy rollout; the decode "
                    "was built with with_greedy=False"
                )
            r_greedy = self._reward_call(video_ids, np.asarray(greedy))
            baseline = np.broadcast_to(r_greedy[None, :], (K, B))
        elif self.cfg.baseline == "scb":
            baseline = scb_baseline(r_kb)
        elif self.cfg.baseline == "none":
            baseline = np.zeros_like(r_kb)
        else:
            raise ValueError(f"unknown baseline {self.cfg.baseline!r}")

        advantage = (r_kb - baseline) * valid_np[None, :]
        n_valid = max(valid_np.sum(), 1.0)
        v = valid_np[None, :]
        r_valid = r_kb[:, valid_np > 0]
        a_valid = advantage[:, valid_np > 0]
        has_valid = valid_np.sum() > 0
        metrics = {
            "reward_mean": float((r_kb * v).sum() / (K * n_valid)),
            "reward_std": float(r_valid.std()) if has_valid else 0.0,
            # reward tails (flight recorder): collapse shows up as p90
            # pinning to p10 long before the mean moves
            "reward_p10": (
                float(np.percentile(r_valid, 10.0)) if has_valid else 0.0
            ),
            "reward_p90": (
                float(np.percentile(r_valid, 90.0)) if has_valid else 0.0
            ),
            "baseline_mean": float((np.asarray(baseline) * v).sum() / (K * n_valid)),
            "advantage_mean": float(advantage.sum() / (K * n_valid)),
            # advantage spread — the REINFORCE gradient's variance driver
            "advantage_std": float(a_valid.std()) if has_valid else 0.0,
            # rows behind reward_mean: lets epoch/cross-host aggregation weight
            # steps exactly (wrap-padded final batches have fewer valid rows)
            "valid_rows": float(valid_np.sum()),
        }
        return advantage, metrics

    def _score(self, greedy, samples, feats, masks, video_ids, valid_np):
        """Host half of the step: read the decoded tokens back and compute
        the advantage. Returns the argument tuple for :meth:`_apply`.

        Multi-host: ``video_ids``/``valid_np`` are THIS process's rows (the
        host-sharded Batcher), so the decoded tokens come back per-host
        (``to_host_local``), the reward is computed on local rows only, and
        the local advantage is re-assembled into a global sharded array for
        the update — host scoring never crosses DCN (SURVEY.md §5).
        """
        from cst_captioning_tpu.train import multihost

        # the rl.reward span covers the device->host token readback AND the
        # consensus scoring: this is the host half the pipeline must hide,
        # so its p95 against rl.decode/rl.update is THE pipelining health
        # signal in the run report
        with obs.span("rl.reward"):
            samples_np = multihost.to_host_local(          # [K, B_local, T]
                samples, self.mesh, P(None, "data")
            ) if self.mesh is not None else np.asarray(samples)
            greedy_np = None
            if greedy is not None:
                greedy_np = multihost.to_host_local(
                    greedy, self.mesh, P("data")
                ) if self.mesh is not None else np.asarray(greedy)
            entropy = self._observe_decode(greedy_np, samples_np)
            advantage, host_metrics = self._advantage(
                greedy_np, samples_np, video_ids, valid_np
            )
            if entropy is not None:
                host_metrics["sample_entropy"] = entropy
        return (advantage, host_metrics, samples, feats, masks, valid_np)

    # depth buckets sized to caption-length budgets (T <= ~64), not the
    # default latency buckets
    _DEPTH_BUCKETS = (2.0, 4.0, 6.0, 8.0, 10.0, 12.0, 16.0, 20.0, 24.0,
                      28.0, 32.0, 40.0, 48.0, 64.0)

    def _observe_decode(self, greedy_np, samples_np) -> float | None:
        """Decode accounting from the already-on-host tokens: the analytic
        FLOPs counter behind the report's MFU column, the early-exit depth
        histogram (scan steps the while loop actually ran vs the T budget),
        and the ``rl.decode.compaction`` counter pair — (lane, column)
        steps the compacted driver computed vs skipped (what finished-lane
        compaction saves per batch; ``cli.obs_report`` surfaces the pair).
        All derived from this process's local rows; no device reads.
        Returns the sampled-lane entropy (:func:`sample_entropy`) for the
        flight recorder's step record, or None when obs is off."""
        obs.counter("flops.rl.decode").inc(
            samples_np.shape[1] * self._decode_flops_per_clip
        )
        if not obs.enabled():
            return None
        # rows finish at their (EOS-inclusive) length; the loop checks the
        # exit every `stride` steps, so it runs to the next stride multiple
        # of the longest row, capped at the padded budget
        stats = compaction_stats(
            greedy_np, samples_np, self._depth_stride, self._depth_budget,
            compact=self._compact,
        )
        obs.histogram("rl.decode.depth", self._DEPTH_BUCKETS).observe(
            stats["depth"]
        )
        obs.counter("rl.decode.compaction.lanes_stepped").inc(
            stats["lanes_stepped"]
        )
        obs.counter("rl.decode.compaction.lanes_skipped").inc(
            stats["lanes_skipped"]
        )
        return sample_entropy(samples_np)

    def _update_flops_inc(self, n_rows, args) -> float:
        """Per-process FLOPs to count for one update dispatch. Prefers the
        COMPILED program's own cost (obs/flops.compiled_cost — the number
        bench_comms.py ledgers, so ``cli.obs_report`` MFU and the bench
        agree); falls back to the analytic per-clip model when XLA exposes
        no cost or obs is off (probing forces a lower+compile walk — free
        on the hot path only because the jit cache already holds this
        program, so don't pay it when nothing reads the counter). Either
        way the per-process streams sum to the global total: the compiled
        number is the whole (global-batch) program split evenly across
        processes; the analytic one is counted over this host's rows."""
        if self._update_cost is None and obs.enabled():
            cost = _flops.compiled_cost(self.update, *args)
            self._update_cost = cost["flops"] if cost else False
            # probe ledger: the degraded-mesh continuation rebuilds this
            # trainer and must re-probe (tested); the backend gauge labels
            # the report's MFU rows compiled-vs-analytic
            obs.counter("obs.flops.probes").inc()
            obs.gauge("flops.backend.rl.update").set(
                1.0 if self._update_cost else 0.0
            )
        if self._update_cost:
            return self._update_cost / jax.process_count()
        return n_rows * self._update_flops_per_clip

    def _apply(self, state, advantage, host_metrics, samples, feats, masks,
               valid_np):
        """Device half: upload the advantage, dispatch the REINFORCE update."""
        from cst_captioning_tpu.train import multihost

        # host time only: the update is dispatched, never waited on here
        with obs.span("rl.update"):
            # host numpy goes straight to its TARGET sharding (explicit
            # placement): converting to a single-device jnp array first
            # would leave the sharded update to re-scatter it implicitly
            # on every dispatch
            adv = np.asarray(advantage, np.float32)
            valid = np.asarray(valid_np, np.float32)
            if self.mesh is not None:
                adv = multihost.from_host_local(adv, self.mesh, P(None, "data"))
                valid = multihost.from_host_local(valid, self.mesh, P("data"))
            else:
                adv = jnp.asarray(adv, jnp.float32)
                valid = jnp.asarray(valid)
            args = (state, feats, masks, samples, adv, valid)
            obs.counter("flops.rl.update").inc(
                self._update_flops_inc(len(valid_np), args)
            )
            if self.mesh is not None and self.comm is not None:
                # the update carries the grad allreduce: ledger its dispatch
                # under the DCN/ICI collective span (PR 6 machinery) so
                # stalls surface in the same place multihost barriers do
                with collective_span("rl.update.allreduce"):
                    state, metrics = self.update(*args)
            else:
                state, metrics = self.update(*args)
        metrics = dict(metrics)
        metrics.update(host_metrics)
        return state, metrics

    def _finish(self, state, greedy, samples, feats, masks, video_ids, valid_np):
        """Score a decoded batch and apply the REINFORCE update."""
        return self._apply(
            state,
            *self._score(greedy, samples, feats, masks, video_ids, valid_np),
        )

    @staticmethod
    def _valid_np(valid, B):
        return (
            np.ones((B,), np.float32) if valid is None
            else np.asarray(valid, np.float32)
        )

    # ---- strict sequential step ---------------------------------------------

    def train_step(self, state: TrainState, feats, masks, video_ids, rng,
                   valid=None):
        with obs.span("rl.decode"):
            greedy, samples = self.decode(state.params, feats, masks, rng)
        # sized from the LOCAL row count (== global single-host; under
        # multi-host, samples is a global array but the reward rows are ours)
        valid_np = self._valid_np(valid, len(video_ids))
        return self._finish(
            state, greedy, samples, feats, masks, video_ids, valid_np
        )

    # ---- drain-aware seam (pipelined preemption) ---------------------------

    def _seam_capture(self, decoded_pair, video_ids) -> dict:
        """Host copies of a decoded-but-unscored batch's tokens — the
        rollout/update SEAM of the pipelined loop. Gathered globally so any
        surviving process can replay them (single-process: plain asarray)."""
        from cst_captioning_tpu.train import multihost

        greedy, samples = decoded_pair
        all_ids = [
            i for sub in multihost.allgather_pyobj(list(video_ids))
            for i in sub
        ]
        out = {
            "samples": multihost.allgather_to_host(samples),
            "video_ids": all_ids,
        }
        if greedy is not None:
            out["greedy"] = multihost.allgather_to_host(greedy)
        return out

    def _seam_tokens_to_device(self, seam: dict):
        """Persisted seam tokens -> device arrays in the decode's output
        layout (greedy [B,T] over 'data', samples [K,B,T] over (None,'data'))
        so the resumed pipeline is indistinguishable from a live decode."""
        from cst_captioning_tpu.train import multihost

        samples = np.asarray(seam["samples"])
        greedy = seam.get("greedy")
        if self.mesh is not None:
            from jax.sharding import NamedSharding

            samples = multihost.put_full_global(
                NamedSharding(self.mesh, P(None, "data")), samples
            )
            if greedy is not None:
                greedy = multihost.put_full_global(
                    NamedSharding(self.mesh, P("data")), np.asarray(greedy)
                )
        else:
            samples = jnp.asarray(samples)
            if greedy is not None:
                greedy = jnp.asarray(np.asarray(greedy))
        return greedy, samples

    @staticmethod
    def _seam_matches(seam: dict, video_ids) -> bool:
        from cst_captioning_tpu.train import multihost

        ids = [
            i for sub in multihost.allgather_pyobj(list(video_ids))
            for i in sub
        ]
        return list(seam.get("video_ids", [])) == ids

    # ---- pipelined epoch ----------------------------------------------------

    def train_epoch(self, state: TrainState, batches, rng, on_step=None,
                    pipelined: bool = True, should_stop=None,
                    seam: dict | None = None,
                    seam_sink: dict | None = None):
        """SCST over an epoch of batches.

        ``should_stop()`` (optional) is polled once per batch; when it turns
        True the epoch stops consuming batches and the pipeline DRAINS —
        every batch already decoded gets its update applied, so the returned
        state corresponds to exactly ``len(metrics)`` completed steps (the
        preemption-save path depends on this invariant).

        ``seam_sink`` (pipelined only) opts into the DRAIN-AWARE stop order:
        instead of discarding the batch fetched when ``should_stop`` fired,
        the loop runs that iteration's schedule prefix — update(i-2) ->
        decode(i) — captures the freshly decoded tokens into ``seam_sink``
        (via :meth:`_seam_capture`), then scores+applies the final pending
        batch. The caller persists the sink next to the checkpoint; a resume
        that passes it back as ``seam`` replays those tokens for its first
        batch instead of re-decoding — the decode then used params from the
        exact pipeline schedule position, so a pipelined mid-epoch resume is
        BIT-IDENTICAL to the uninterrupted run (previously the seam batch
        was re-decoded against params one update fresher).

        ``seam`` (pipelined only): tokens for the first batch, from a prior
        ``seam_sink``. Ignored (with a live decode fallback) when the batch
        identity check fails — a changed data order must never silently
        marry old tokens to new features.

        ``batches`` yields ``(feats, masks, video_ids, valid)`` with arrays
        already on device.

        ``pipelined=True`` (default): two-stage software pipeline. Per
        iteration the dispatch order is **update(i-2) -> decode(i) ->
        host-score(i-1)** — the update that became ready from the previous
        iteration's scoring is dispatched *before* the host starts scoring
        the next batch, so the device always has ~a full step of queued work
        (one update + one decode) while the host computes the consensus
        reward, and never idles on it (VERDICT r3: the 1-deep
        score-then-update order left the device idle for the reward tail).
        The decoded policy is ONE update stale — identical to the plain
        decode-then-score-then-update pipelining (update *i-1* cannot be
        ready before decode *i* is dispatched without serializing on the
        host), and the parameter/rng/metric sequence is bit-identical to
        it; with the RL learning rate (~2e-5) the one-step policy drift is
        negligible (measured vs strict in BASELINE.md), and the REINFORCE
        logprobs are recomputed from the *current* params in the update, so
        the gradient estimator itself stays well-formed. HBM note: three
        batches' features are live at once (scored, decoded-awaiting-score,
        current) vs two in the strict loop.

        ``pipelined=False``: strict on-policy SCST — :meth:`train_step` per
        batch with the same rng stream (the reference's loop, SURVEY.md
        §3.2).

        Returns ``(state, metrics_list)``; ``on_step(metrics)`` fires per batch.
        """
        if self.mesh is not None:
            # replicate the epoch key onto the mesh ONCE: the sharded decode
            # takes its rng replicated (in_specs P()), and a single-device
            # key would otherwise be implicitly re-replicated device-to-
            # device on EVERY batch's dispatch (the sanitizer gate's
            # transfer_guard vetoes that); every split below inherits the
            # replicated placement. Bit-identical — placement only.
            from jax.sharding import NamedSharding

            rng = jax.device_put(rng, NamedSharding(self.mesh, P()))
        out = []

        def emit(m):
            out.append(m)
            if on_step is not None:
                on_step(m)

        if not pipelined:
            for feats, masks, video_ids, valid in batches:
                if should_stop is not None and should_stop():
                    break
                rng, srng = jax.random.split(rng)
                state, m = self.train_step(
                    state, feats, masks, video_ids, srng, valid
                )
                emit(m)
            return state, out

        scored = None     # _apply args: advantage ready, update not dispatched
        decoded = None    # _score args: decode dispatched, not yet scored
        first = True
        for feats, masks, video_ids, valid in batches:
            if should_stop is not None and should_stop():
                if seam_sink is not None:
                    # drain-aware stop: run THIS iteration's schedule prefix
                    # (update(i-2) then decode(i)) so the seam batch is
                    # decoded against the params the uninterrupted pipeline
                    # would have used, and capture its tokens for the
                    # checkpoint instead of scoring it
                    if scored is not None:
                        state, m = self._apply(state, *scored)
                        scored = None
                        emit(m)
                    rng, srng = jax.random.split(rng)
                    with obs.span("rl.decode"):
                        d = self.decode(state.params, feats, masks, srng)
                    seam_sink.update(self._seam_capture(d, video_ids))
                    if decoded is not None:
                        state, m = self._apply(state, *self._score(*decoded))
                        emit(m)
                    decoded = None
                break
            if scored is not None:
                state, m = self._apply(state, *scored)
                scored = None
                emit(m)
            rng, srng = jax.random.split(rng)
            if first and seam is not None and self._seam_matches(
                seam, video_ids
            ):
                # resumed seam batch: replay the persisted tokens (decoded
                # pre-preemption at this exact schedule position); the rng
                # split above is still consumed so later batches' streams
                # stay aligned with the uninterrupted run
                d = self._seam_tokens_to_device(seam)
            else:
                with obs.span("rl.decode"):
                    d = self.decode(state.params, feats, masks, srng)
                    for arr in d:
                        # start the device->host token transfer NOW, so it
                        # overlaps this decode — by the time _score reads the
                        # tokens they are already on host. greedy is None for
                        # the scb/none baselines (no greedy rollout);
                        # multi-host global arrays are not fully addressable
                        # here and their reads go through to_host_local.
                        if arr is not None and arr.is_fully_addressable:
                            arr.copy_to_host_async()
            first = False
            if decoded is not None:
                # host scores batch i-1 while the device runs update(i-2) +
                # decode(i) queued above
                scored = self._score(*decoded)
            greedy, samples = d
            valid_np = self._valid_np(valid, len(video_ids))
            decoded = (greedy, samples, feats, masks, video_ids, valid_np)
        # drain in order: update(n-2), then score+update(n-1)
        if scored is not None:
            state, m = self._apply(state, *scored)
            emit(m)
        if decoded is not None:
            state, m = self._apply(state, *self._score(*decoded))
            emit(m)
        return state, out
