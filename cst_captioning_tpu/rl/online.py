"""Online SCST from served traffic: the serving-as-actor feedback loop.

The decoupled topology (rl/async_scst.py) made the actor a separate
submesh; this module makes it the SERVING ENGINE. A live
:class:`~cst_captioning_tpu.serving.engine.CaptionService` already decodes
the exact fused (1+K)-lane programs SCST trains on — lane 0 greedy, K
sampled lanes on the request's own RNG stream — and until now threw the
sampled lanes away after NPAD best-lane selection. The feedback capture
turns each completed request into an actor rollout at ZERO extra dispatch
(tokens and logprobs are already host arrays at completion), scored by the
consensus :class:`~cst_captioning_tpu.rl.rewards.RewardComputer` against a
reference pool and consumed through the PR 15 :class:`RolloutRing` and the
existing ``rl_update`` factories. After each learner update the new params
publish back into the service through the drain-free hot swap
(:meth:`CaptionService.publish_params`), closing the loop: the service
improves while it serves (the RLAX serving+training shape, PAPERS.md
arXiv 2512.06392).

**Staleness: drop-and-COUNT, not drop-and-recount.** The decoupled trainer
re-decodes an over-stale rollout under fresh params (its RNG key is stored;
a rollout is just a sample, so recounting is free and deterministic). A
TRAFFIC entry is different: its tokens were SERVED — they are ground truth
about a live interaction under the version that served it, and re-decoding
would fabricate traffic that never happened. So an entry whose admission
version lags the learner by more than ``rl.staleness_bound`` updates is
dropped and *counted* (``rl.online.dropped_stale`` + the staleness
histogram), never recounted. The drop sequence is a deterministic function
of (trace, swap schedule), which is what makes two seeded online runs
produce bit-identical learner params (tests/test_rl_online.py).

**Version arithmetic.** The learner's update counter IS the version
namespace: every applied update bumps ``self.version``; a publish stamps
the service with the learner version at publish time, and a request's
admission pins that stamp. Staleness of a capture is therefore measured in
learner updates, exactly like the decoupled trainer's — one counter, no
translation. A mixed-version batch (captures straddling a swap) takes the
OLDEST member's version: conservative, and deterministic.

Single-process by construction (``mesh=None``): the learner shares the
serving host, which is the CPU/single-chip shape benches and tests run.
The learner-submesh split composes later through the same
``SCSTTrainer(mesh=...)`` machinery the async trainer uses (ROADMAP
residual).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from cst_captioning_tpu import obs
from cst_captioning_tpu.config.config import RLConfig
from cst_captioning_tpu.rl.async_scst import AsyncSCSTTrainer, RolloutRing
from cst_captioning_tpu.rl.rewards import RewardComputer
from cst_captioning_tpu.rl.scst import SCSTTrainer
from cst_captioning_tpu.train.state import TrainState


class OnlineSCSTTrainer(SCSTTrainer):
    """SCSTTrainer fed by served traffic instead of a dataset epoch.

    Wire-up (the closed loop)::

        trainer = OnlineSCSTTrainer(model, reward, cfg, state)
        svc = CaptionService(model, state.params, ...)
        trainer.attach(svc)          # feedback capture + publish target
        svc.serve(requests)          # captures -> ring -> updates -> swaps
        trainer.flush()              # consume what the ring still holds
        state = trainer.state

    :meth:`on_result` is the service's feedback hook: it buffers completed
    requests into learner batches of ``cfg.online_batch_size``, pushes full
    batches into a depth-``cfg.rollout_depth`` :class:`RolloutRing`, and
    consumes ring entries once the ring is full — score (consensus reward
    vs the reference pool, greedy lane as the SCST baseline), staleness
    gate (drop-and-count, module docstring), REINFORCE update, then a
    version-stamped param publish into the attached service every
    ``cfg.swap_every`` updates. Everything runs on the serving thread in
    deterministic order.

    ``ref_id`` maps a :class:`ClipRequest` to the reward pool's video id
    (default: the request id verbatim — the bench/test convention where
    requests are named after their source clips).
    """

    _STALE_BUCKETS = AsyncSCSTTrainer._STALE_BUCKETS

    def __init__(self, model, reward: RewardComputer, cfg: RLConfig,
                 state: TrainState, *, max_len: int | None = None,
                 ref_id: Callable | None = None, donate: bool = False,
                 guard: bool = False, retry=None, on_event=None, comm=None,
                 stats: bool = False):
        super().__init__(
            model, reward, cfg, mesh=None, max_len=max_len, donate=donate,
            guard=guard, retry=retry, on_event=on_event, comm=comm,
            stats=stats,
        )
        self.state = state
        self._donate = bool(donate)
        self._bound = max(0, int(getattr(cfg, "staleness_bound", 1)))
        self._batch_size = max(1, int(getattr(cfg, "online_batch_size", 4)))
        self._swap_every = max(1, int(getattr(cfg, "swap_every", 1)))
        self._ref_id = ref_id or (lambda req: req.req_id)
        self._ring = RolloutRing(
            max(1, int(getattr(cfg, "rollout_depth", 2)))
        )
        self._buffer: list[dict] = []
        self._service = None
        # the learner's update counter IS the param-version namespace
        self.version = 0
        # run ledgers the bench/tests read back
        self.last_dropped = 0
        self.last_applied = 0
        self.last_staleness: dict[int, int] = {}
        self.history: list[dict] = []   # per-update metrics (reward trend)

    # ---- wiring -------------------------------------------------------------

    def attach(self, service, swap_every: int | None = None) -> None:
        """Bind a live :class:`CaptionService`: its completions feed
        :meth:`on_result`, and every ``swap_every``-th learner update
        publishes params back for the drain-free hot swap.

        Requires a version-aligned service (a fresh one, or one whose
        active version equals the learner's) so admission stamps and the
        learner counter share one namespace, and a non-donating update
        (``donate=False``): published param trees stay live inside the
        service across later updates — a donating update would invalidate
        the buffers the service still decodes from."""
        if self._donate:
            raise ValueError(
                "OnlineSCSTTrainer.attach needs donate=False — the service "
                "keeps decoding from published param buffers after later "
                "updates run"
            )
        if service.param_version != self.version:
            raise ValueError(
                f"service param_version {service.param_version} != learner "
                f"version {self.version} — attach a fresh (or version-"
                "aligned) service so staleness arithmetic shares one counter"
            )
        if swap_every is not None:
            self._swap_every = max(1, int(swap_every))
        self._service = service
        service._feedback = self.on_result

    # ---- the feedback capture (CaptionService hook) -------------------------

    def on_result(self, req, result, param_version: int) -> None:
        """Feedback hook: one completed served request becomes one rollout
        row. Zero extra dispatch — ``result.tokens``/``logprobs`` are the
        host arrays the service already read back at the stride seam."""
        K = self.cfg.num_rollouts
        if result.tokens.shape[0] != 1 + K:
            raise ValueError(
                f"served request {req.req_id!r} has "
                f"{result.tokens.shape[0]} lanes; the online learner is "
                f"configured for 1+K={1 + K}"
            )
        self._buffer.append({
            "req_id": req.req_id,
            "seed": int(req.seed),
            "version": int(param_version),
            "video_id": self._ref_id(req),
            "greedy": np.asarray(result.tokens[0], np.int32),
            "samples": np.asarray(result.tokens[1:], np.int32),
            "lps": np.asarray(result.logprobs[1:], np.float32),
            "feats": req.feats,
            "masks": req.masks,
        })
        obs.counter("rl.online.captured").inc()
        if len(self._buffer) >= self._batch_size:
            self._push_batch()
        while len(self._ring) >= self._ring.depth:
            self._consume_one()

    @property
    def pending_captures(self) -> int:
        """Captures buffered toward the next (not yet full) batch."""
        return len(self._buffer)

    def flush(self) -> int:
        """Consume every COMPLETE batch still in the ring (end-of-trace /
        pre-drain). A trailing partial capture buffer stays put — batch
        shapes through the ring are constant, and more traffic may land;
        ``pending_captures`` exposes what waits."""
        n = 0
        while len(self._ring):
            self._consume_one()
            n += 1
        return n

    # ---- batch forming ------------------------------------------------------

    def _push_batch(self) -> None:
        batch, self._buffer = (
            self._buffer[:self._batch_size],
            self._buffer[self._batch_size:],
        )
        F = self.model.cfg.max_frames
        feats: dict[str, np.ndarray] = {}
        masks: dict[str, np.ndarray] = {}
        for name, _ in self.model.cfg.modalities:
            rows, mrows = [], []
            for cap in batch:
                x = np.asarray(cap["feats"][name], np.float32)
                mk = np.asarray(cap["masks"][name], np.float32)
                pad = F - x.shape[0]
                rows.append(np.pad(x, ((0, pad), (0, 0))))
                mrows.append(np.pad(mk, ((0, pad),)))
            feats[name] = np.stack(rows)
            masks[name] = np.stack(mrows)
        greedy = np.stack([cap["greedy"] for cap in batch])        # [B, T]
        samples = np.stack(
            [cap["samples"] for cap in batch], axis=1
        )                                                          # [K, B, T]
        lps = np.stack([cap["lps"] for cap in batch], axis=1)
        self._ring.push(
            greedy, samples, lps,
            # a mixed-version batch is as stale as its OLDEST capture
            version=min(cap["version"] for cap in batch),
            feats=feats, masks=masks,
            video_ids=[cap["video_id"] for cap in batch],
            valid_np=np.ones((len(batch),), np.float32),
            req_ids=[cap["req_id"] for cap in batch],
            seeds=[cap["seed"] for cap in batch],
        )
        obs.counter("rl.online.batches").inc()
        obs.gauge("rl.online.ring_occupancy").set(float(len(self._ring)))

    # ---- consumption --------------------------------------------------------

    def _consume_one(self) -> None:
        meta, greedy, samples, lps = self._ring.pop()
        stale = self.version - meta["version"]
        self.last_staleness[stale] = self.last_staleness.get(stale, 0) + 1
        obs.histogram("rl.online.staleness", self._STALE_BUCKETS).observe(
            float(stale)
        )
        if stale > self._bound:
            # drop-and-COUNT: served tokens are ground truth from a live
            # interaction under an old version — unlike an actor rollout
            # there is nothing to recount (module docstring). Dropped,
            # counted, never re-decoded; deterministic run-to-run.
            self.last_dropped += 1
            obs.counter("rl.online.dropped_stale").inc()
            self.on_event(
                "rl_online_dropped", staleness=stale,
                version=meta["version"], req_ids=meta["req_ids"],
            )
            return
        with obs.span("rl.online.step"):
            scored = self._score(
                greedy, samples, meta["feats"], meta["masks"],
                meta["video_ids"], meta["valid_np"],
            )
            self.state, m = self._apply(self.state, *scored)
        self.version += 1
        self.last_applied += 1
        obs.counter("rl.online.steps").inc()
        m = dict(m, staleness=stale, param_version=self.version)
        self.history.append(m)
        self.on_event("rl_online_step", **{
            k: m[k] for k in ("reward_mean", "staleness", "param_version")
            if k in m
        })
        if (self._service is not None
                and self.version % self._swap_every == 0):
            # version-stamped publish into the live service; the swap
            # applies at the service's next stride boundary — drain-free
            self._service.publish_params(
                self.state.params, version=self.version
            )
