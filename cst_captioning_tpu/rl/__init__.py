"""RL layer: consensus rewards + self-critical sequence training (CST).

Rebuilds the reference's RL phase (SURVEY.md §3.2, BASELINE configs 3-4) as
the two-dispatch TPU design of §7 step 5: one jitted program decodes the
greedy baseline AND the K Monte-Carlo rollouts in a single launch; the host
computes CIDEr-D(+BLEU4) consensus rewards with a precomputed train-split df;
a second jitted program re-scores the sampled tokens differentiably and
applies the REINFORCE update (with psum-DP over the mesh).
"""

from cst_captioning_tpu.rl.async_scst import (
    AsyncSCSTTrainer,
    RolloutRing,
    make_actor_decode,
    request_actor_preempt,
)
from cst_captioning_tpu.rl.online import OnlineSCSTTrainer
from cst_captioning_tpu.rl.rewards import RewardComputer, scb_baseline
from cst_captioning_tpu.rl.scst import (
    SCSTTrainer,
    make_rl_decode,
    make_parallel_rl_decode,
    make_rl_update,
    make_parallel_rl_update,
)

__all__ = [
    "AsyncSCSTTrainer",
    "OnlineSCSTTrainer",
    "RewardComputer",
    "RolloutRing",
    "scb_baseline",
    "SCSTTrainer",
    "make_actor_decode",
    "make_rl_decode",
    "make_parallel_rl_decode",
    "make_rl_update",
    "make_parallel_rl_update",
    "request_actor_preempt",
]
