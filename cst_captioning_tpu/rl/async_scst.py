"""Decoupled actor/learner SCST (Podracer/Sebulba-style, arXiv 2104.06272).

``train.rl_topology="decoupled"``: the data mesh splits into an ACTOR
submesh and a LEARNER submesh (parallel/submesh.py). Actor devices run the
fused rollout decode continuously into a device-resident double-buffered
rollout ring (:class:`RolloutRing` — tokens + sample logprobs + the
per-batch RNG stream, ``rl.rollout_depth`` batches deep); learner devices
consume completed batches with the existing in-scan-logp ``rl_update``
factories (the comms config rides along unchanged); params broadcast
actor-ward after every learner update. A rollout decoded under params more
than ``rl.staleness_bound`` learner updates old at consumption time is
DROPPED and recounted: re-decoded under the actor's refreshed params with
the entry's stored RNG key, so the drop/recount sequence is deterministic
run-to-run.

The single-controller dispatch loop is the async machinery: every decode
and update is dispatched without waiting, so with disjoint submeshes the
actor's decode of batch *i* genuinely overlaps the learner's update of
batch *i-depth+1* on different devices — the host only blocks when it
reads rollout tokens back for the consensus reward.

STRICT mode (``strict=True``, or ``rollout_depth=1`` + ``staleness_bound=0``)
pins bit-identity: both roles run on the FULL mesh (so the decode's
``axis_index`` RNG folds match the sync loop's), the ring depth replays the
sync schedule exactly — depth 2 IS the sync loop's default 1-deep pipeline
(decode(i) one update stale, update(i-1) dispatched after decode(i)), depth
1 the ``pipelined=False`` sequential loop — and the per-batch
``rng, srng = jax.random.split(rng)`` chain is the sync loop's — tokens,
logprobs, params, and opt_state reproduce ``SCSTTrainer.train_epoch``
bit-for-bit (tests/test_async_scst.py). Genuinely decoupled runs are NOT
token-identical to sync: the per-shard RNG fold runs over a different
submesh size — documented, and why strict exists.

Chaos story: the ``rl.actor.step`` injection point takes the
``actor_preempt`` fault kind (resilience/chaos.py). Preemption of an actor
device sheds it from the submesh plan, recounts the in-flight ring entries
under the survivors, and re-broadcasts; when no actor survives (or the
roles share one device), the epoch falls back to the sync schedule on the
learner submesh. The same point takes ``host_rejoin`` in the grow-back
direction: a previously-shed device re-admits via
:func:`~cst_captioning_tpu.parallel.submesh.grow_actors` (membership and
order restored from the pristine initial plan), the ring re-binds to the
grown submesh, and in-flight rollouts from the degraded period are drained
and deterministically recounted in order — the same drop-and-recount
spelling as the shrink. Drain: ``should_stop`` persists the in-flight ring as a
``seam.npz``-style blob (the trainer's ``_seam_bytes`` ring format) and a
resume replays those exact tokens — strict-mode drains hold bit-identity
(the depth-1 ring is empty between steps), decoupled drains are
replay-consistent.
"""

from __future__ import annotations

import functools
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cst_captioning_tpu import obs
from cst_captioning_tpu.compat import shard_map
from cst_captioning_tpu.config.config import RLConfig
from cst_captioning_tpu.decoding import fused_decode, sample_decode
from cst_captioning_tpu.parallel.submesh import (
    SubmeshPlan,
    grow_actors,
    plan_submesh,
    shared_plan,
    shrink_actors,
)
from cst_captioning_tpu.resilience import chaos
from cst_captioning_tpu.rl.rewards import RewardComputer
from cst_captioning_tpu.rl.scst import SCSTTrainer
from cst_captioning_tpu.train.state import TrainState

# pending actor-slice preemptions (chaos `actor_preempt` faults land here;
# the epoch loop services them at the next rl.actor.step)
_PREEMPT_REQUESTS: list[int] = []

# pending actor-slice rejoins (chaos `host_rejoin` at rl.actor.step lands
# here; the epoch loop services them at its next batch boundary)
_REJOIN_REQUESTS: list[int] = []


def request_actor_preempt(slice_index=None) -> None:
    """Mark one actor device (by index into the current actor submesh) as
    preempted. Called by the chaos harness's ``actor_preempt`` kind; the
    running :class:`AsyncSCSTTrainer` epoch services the request at its
    next ``rl.actor.step`` visit."""
    _PREEMPT_REQUESTS.append(0 if slice_index is None else int(slice_index))


def request_actor_rejoin(slice_index=None) -> None:
    """Inverse of :func:`request_actor_preempt`: re-admit one previously
    shed actor device (by index into the INITIAL actor submesh — the
    pristine plan, so a preempt/rejoin pair addressing the same index
    round-trips the same device). Called by the chaos harness's
    ``host_rejoin`` kind when fired at ``rl.actor.step``."""
    _REJOIN_REQUESTS.append(0 if slice_index is None else int(slice_index))


def make_actor_decode(model, mesh: Mesh | None, num_rollouts: int,
                      temperature: float = 1.0, max_len: int | None = None,
                      axis: str = "data", with_greedy: bool = True):
    """Jitted actor decode: (params, feats, masks, rng) ->
    (greedy [B,T] | None, samples [K,B,T], sample_lps [K,B,T]).

    Token streams are bit-identical to ``make_rl_decode`` /
    ``make_parallel_rl_decode`` on the same mesh — it is the same fused
    program (the per-lane logprobs already exist inside the scan; this
    factory just stops discarding the sampled lanes') — which is what lets
    strict mode pin against the sync loop's decode."""

    def device_decode(params, feats, masks, rng, batch_axes=()):
        if with_greedy:
            greedy, _, samples, lps = fused_decode(
                model, params, feats, masks, rng,
                num_rollouts=num_rollouts, temperature=temperature,
                max_len=max_len, batch_axes=batch_axes,
            )
            return greedy, samples, lps
        samples, lps = sample_decode(
            model, params, feats, masks, rng,
            num_rollouts=num_rollouts, temperature=temperature,
            max_len=max_len, batch_axes=batch_axes,
        )
        return samples, lps

    if mesh is None:
        fn = jax.jit(device_decode)
    else:
        def sharded(params, feats, masks, rng):
            local_rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
            return device_decode(
                params, feats, masks, local_rng, batch_axes=(axis,)
            )

        out_specs = (
            (P(axis), P(None, axis), P(None, axis)) if with_greedy
            else (P(None, axis), P(None, axis))
        )
        fn = jax.jit(shard_map(
            sharded, mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P()),
            out_specs=out_specs,
        ))
    if with_greedy:
        return fn

    def no_greedy(params, feats, masks, rng):
        samples, lps = fn(params, feats, masks, rng)
        return None, samples, lps

    return no_greedy


class RolloutRing:
    """Device-resident ring of decoded rollout batches (the actor->learner
    handoff buffer; depth 2 is the double buffer).

    Storage is three preallocated stacked device buffers — sampled tokens
    [D,K,B,T], their logprobs [D,K,B,T], and (greedy baseline) [D,B,T] —
    written in place by a DONATING jitted slot update: each push consumes
    the previous buffer and rebinds the attribute, so the ring's HBM
    footprint is exactly ``depth`` batches for the epoch regardless of how
    many batches stream through (graftlint GL017 tracks this donate-through-
    ``self._write``/rebind-``self._tokens`` shape — the attribute-rooted
    donation case). Per-entry host metadata (RNG key, params version, batch
    refs, video ids) rides in a deque; the device arrays never leave the
    ring until :meth:`pop` reads a slot out for consumption.
    """

    def __init__(self, depth: int, mesh: Mesh | None = None,
                 axis: str = "data"):
        self.depth = max(1, int(depth))
        self.mesh = mesh
        self.axis = axis
        self._tokens = None      # [D, K, B, T] sampled tokens
        self._lps = None         # [D, K, B, T] sample logprobs
        self._greedy = None      # [D, B, T] greedy baseline (optional)
        self._meta: deque = deque()
        self._slot = 0

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0,))
    def _write(buf, update, slot):
        return jax.lax.dynamic_update_index_in_dim(buf, update, slot, 0)

    @staticmethod
    @jax.jit
    def _read(buf, slot):
        return jax.lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)

    def __len__(self) -> int:
        return len(self._meta)

    def _alloc(self, like, spec):
        buf = jnp.zeros((self.depth,) + like.shape, like.dtype)
        if self.mesh is not None:
            buf = jax.device_put(buf, NamedSharding(self.mesh, spec))
        return buf

    def push(self, greedy, samples, lps, **meta) -> None:
        """Write one decoded batch into the next slot (donating the ring
        buffers) and queue its metadata. Batch shapes must be constant
        across the epoch (the video-mode batcher wrap-pads, so they are)."""
        slot = self._slot
        self._slot = (slot + 1) % self.depth
        if self._tokens is None:
            self._tokens = self._alloc(samples, P(None, None, self.axis))
            self._lps = self._alloc(lps, P(None, None, self.axis))
            if greedy is not None:
                self._greedy = self._alloc(greedy, P(None, self.axis))
        self._tokens = self._write(self._tokens, samples, slot)
        self._lps = self._write(self._lps, lps, slot)
        if greedy is not None:
            self._greedy = self._write(self._greedy, greedy, slot)
        self._meta.append(dict(slot=slot, **meta))

    def pop(self):
        """Oldest entry -> (meta, greedy, samples, lps) device arrays."""
        meta = self._meta.popleft()
        slot = meta["slot"]
        greedy = (
            None if self._greedy is None else self._read(self._greedy, slot)
        )
        return meta, greedy, self._read(self._tokens, slot), \
            self._read(self._lps, slot)

    def entries(self):
        """Every in-flight entry, oldest first, WITHOUT consuming (the
        seam-capture read)."""
        for meta in list(self._meta):
            slot = meta["slot"]
            greedy = (
                None if self._greedy is None
                else self._read(self._greedy, slot)
            )
            yield meta, greedy, self._read(self._tokens, slot), \
                self._read(self._lps, slot)

    def drain_meta(self) -> list[dict]:
        """Drop the device buffers (an actor submesh died under them) and
        return the orphaned metadata so the caller can recount each entry
        from its stored RNG key."""
        metas = list(self._meta)
        self._meta.clear()
        self._tokens = self._lps = self._greedy = None
        self._slot = 0
        return metas


class AsyncSCSTTrainer(SCSTTrainer):
    """SCSTTrainer with the actor/learner split epoch schedule.

    The parent's reward/advantage/update halves are reused verbatim —
    ``self.mesh`` (and therefore ``_score``/``_apply``'s host transfers and
    the update factory) is the LEARNER submesh; the actor side gets its own
    decode closure on the actor submesh and a :class:`RolloutRing`. With
    ``mesh=None`` or in strict mode both roles share one mesh and the
    schedule degenerates to the sequential sync loop (the bit-identity pin).

    Multihost actor slices and async broadcast over DCN are explicitly out
    of scope here (ROADMAP carry-overs): the split is within one process's
    devices.
    """

    # staleness-in-updates buckets: small integers, not latencies
    _STALE_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)

    def __init__(self, model, reward: RewardComputer, cfg: RLConfig,
                 mesh: Mesh | None = None, max_len: int | None = None,
                 donate: bool = False, guard: bool = False, retry=None,
                 on_event=None, comm=None, stats: bool = False,
                 strict: bool = False, batch_size: int = 0,
                 axis: str = "data"):
        depth = max(1, int(getattr(cfg, "rollout_depth", 2)))
        bound = max(0, int(getattr(cfg, "staleness_bound", 1)))
        # depth 1 + bound 0 IS the strict sequential schedule — honor it
        # implicitly so config-driven strict runs need no extra flag
        implicit = depth == 1 and bound == 0
        self._strict = bool(strict) or implicit
        if strict:
            # replay whichever schedule the sync loop runs: its default
            # 1-deep pipeline is exactly a depth-2 ring (decode(i) lands one
            # update stale, update(i-1) dispatches after decode(i));
            # pipelined=False is the depth-1 sequential ring
            depth = 2 if getattr(cfg, "pipelined", True) else 1
            bound = depth - 1
        elif implicit:
            depth, bound = 1, 0
        self._axis = axis
        self._full_mesh = mesh
        self._batch_size = int(batch_size)
        if mesh is None:
            plan = None
        elif self._strict:
            plan = shared_plan(mesh, axis=axis)
        else:
            plan = plan_submesh(
                mesh, getattr(cfg, "actor_fraction", 0.5), axis=axis,
                batch_size=batch_size,
            )
        self._plan = plan
        # the pristine plan: grow-back restores membership/order from it,
        # minus whatever the dead-actor ledger still names as lost
        self._initial_plan = plan
        self._dead_actors: set = set()
        lmesh = mesh if plan is None or plan.shared else plan.learner
        super().__init__(
            model, reward, cfg, mesh=lmesh, max_len=max_len, donate=donate,
            guard=guard, retry=retry, on_event=on_event, comm=comm,
            stats=stats,
        )
        self._max_len = max_len
        self._wg = cfg.baseline == "greedy"
        self._depth = depth
        self._bound = bound
        self._actor_mesh = None if plan is None else plan.actor
        self._actor_decode = make_actor_decode(
            model, self._actor_mesh, cfg.num_rollouts, cfg.temperature,
            max_len, axis=axis, with_greedy=self._wg,
        )
        self._fallback_sync = False
        self._actor_params = None
        self._actor_version = -1
        self._learner_version = 0
        # per-epoch ledgers the bench and the recovery tests read back
        self.last_staleness: dict[int, int] = {}
        self.last_dropped = 0
        self.last_rejoined = 0
        self.last_occupancy: dict[str, float] = {}

    # ---- submesh plumbing ---------------------------------------------------

    def _shared_roles(self) -> bool:
        return (
            self._fallback_sync or self._plan is None or self._plan.shared
        )

    def _to_actor(self, tree, spec):
        # an unconditional reshard: a same-sharding device_put is a no-op,
        # and the sync FALLBACK still needs full-mesh inputs pulled down
        # onto the learner submesh even though the roles then "share" it
        if self._actor_mesh is None:
            return tree
        return jax.device_put(tree, NamedSharding(self._actor_mesh, spec))

    def _to_learner(self, tree, spec):
        if self.mesh is None:
            return tree
        return jax.device_put(tree, NamedSharding(self.mesh, spec))

    def _refresh_actor(self, state: TrainState) -> None:
        """Broadcast the learner's current params actor-ward. Shared-role
        layouts just rebind (the strict path: the decode must see the SAME
        arrays the sync loop would); split layouts reshard a copy onto the
        actor submesh so the learner's buffer donation can't invalidate
        in-flight actor reads."""
        if self._actor_version == self._learner_version:
            return
        with obs.span("rl.actor.broadcast"):
            p = state.params
            if not self._shared_roles():
                p = jax.device_put(p, NamedSharding(self._actor_mesh, P()))
            self._actor_params = p
        self._actor_version = self._learner_version

    def _dispatch_decode(self, feats, masks, srng):
        """One actor decode dispatch -> (greedy, samples, lps) on the actor
        submesh (no host sync — the transfer out happens at consumption)."""
        feats_a = self._to_actor(feats, P(self._axis))
        masks_a = self._to_actor(masks, P(self._axis))
        if self._actor_mesh is not None:
            srng = jax.device_put(
                srng, NamedSharding(self._actor_mesh, P())
            )
        with obs.span("rl.actor.decode"):
            out = self._actor_decode(self._actor_params, feats_a, masks_a,
                                     srng)
        obs.counter("rl.actor.batches").inc()
        return out

    # ---- chaos: actor preemption -------------------------------------------

    def _service_preemptions(self) -> list[dict]:
        """Apply pending ``actor_preempt`` requests: shrink the actor
        submesh (or fall back to sync when nothing survives), rebuild the
        actor decode, and return the orphaned ring metadata for recount."""
        lost: list[dict] = []
        while _PREEMPT_REQUESTS:
            idx = _PREEMPT_REQUESTS.pop(0)
            obs.counter("rl.actor.preempted").inc()
            if self._fallback_sync:
                continue
            lost.extend(self._ring.drain_meta())
            new_plan = None
            if self._plan is not None and not self._plan.shared:
                devs = self._plan.actor_devices
                self._dead_actors.add(devs[idx % len(devs)])
                new_plan = shrink_actors(
                    self._plan, idx, axis=self._axis,
                    batch_size=self._batch_size,
                )
            if new_plan is None:
                self._fallback_sync = True
                self._actor_mesh = self.mesh
                self.on_event(
                    "rl_actor_fallback_sync", recount=len(lost),
                )
            else:
                self._plan = new_plan
                self._actor_mesh = new_plan.actor
                self.on_event(
                    "rl_actor_degraded", survivors=new_plan.n_actors,
                    recount=len(lost),
                )
            self._actor_decode = make_actor_decode(
                self.model, self._actor_mesh, self.cfg.num_rollouts,
                self.cfg.temperature, self._max_len, axis=self._axis,
                with_greedy=self._wg,
            )
            # drained ring reallocates on the survivors' mesh at next push
            self._ring.mesh = self._actor_mesh
            self._actor_version = -1    # survivors need a fresh broadcast
        return lost

    def _service_rejoins(self) -> list[dict]:
        """Apply pending ``host_rejoin`` requests: grow the actor submesh
        back toward its initial plan (climbing out of the sync fallback if
        that is where the shrinks left us), re-bind the ring to the grown
        submesh, rebuild the actor decode, and return the orphaned ring
        metadata so in-flight rollouts from the degraded period are
        deterministically recounted in order — the shrink's drop-and-recount
        spelling, run in the grow direction."""
        lost: list[dict] = []
        while _REJOIN_REQUESTS:
            idx = _REJOIN_REQUESTS.pop(0)
            obs.counter("rl.actor.rejoined").inc()
            init = self._initial_plan
            if init is None or init.shared:
                continue    # nothing was ever split; nothing to grow
            device = init.actor_devices[idx % len(init.actor_devices)]
            self._dead_actors.discard(device)
            new_plan = grow_actors(
                None if self._fallback_sync else self._plan, device, init,
                axis=self._axis, batch_size=self._batch_size,
                dead=self._dead_actors,
            )
            if new_plan is None:
                continue    # already present — a duplicate rejoin is a no-op
            lost.extend(self._ring.drain_meta())
            self._fallback_sync = False
            self._plan = new_plan
            self._actor_mesh = new_plan.actor
            self.last_rejoined += 1
            self.on_event(
                "rl_actor_regrown", actors=new_plan.n_actors,
                recount=len(lost),
            )
            self._actor_decode = make_actor_decode(
                self.model, self._actor_mesh, self.cfg.num_rollouts,
                self.cfg.temperature, self._max_len, axis=self._axis,
                with_greedy=self._wg,
            )
            # drained ring reallocates on the grown mesh at next push
            self._ring.mesh = self._actor_mesh
            self._actor_version = -1    # the rejoiner needs the broadcast
        return lost

    # ---- drain-aware ring seam ---------------------------------------------

    def _seam_capture_ring(self) -> dict:
        """Host copies of every in-flight ring entry (tokens, logprobs, RNG
        key data, params version) — the decoupled loop's drain payload."""
        ring = []
        for meta, greedy, samples, lps in self._ring.entries():
            # one explicit batched readback per entry; this runs once per
            # drain (not per step), depth entries at most
            toks, logps, key = jax.device_get(  # graftlint: disable=GL001 (drain path: at most rollout_depth entries, once per preemption save)
                (samples, lps, jax.random.key_data(meta["rng"]))
            )
            e = {
                "samples": toks,
                "lps": logps,
                "video_ids": [str(v) for v in meta["video_ids"]],
                "valid": meta["valid_np"],    # host float32 (_valid_np)
                "rng": key,
                "batch_index": int(meta["batch_index"]),
            }
            if greedy is not None:
                e["greedy"] = jax.device_get(greedy)
            ring.append(e)
        return {"ring": ring}

    def _replay_entry(self, entry: dict, feats, masks, video_ids, valid_np,
                      batch_index: int) -> None:
        """Push one persisted seam entry back into the ring as if it had
        just been decoded: tokens/logprobs come from the blob (decoded
        pre-drain — replay-consistent), the stored RNG key keeps a later
        drop/recount deterministic, and the version is the CURRENT actor
        version so the replayed work isn't immediately dropped."""
        spec_kbt = P(None, self._axis)
        samples = entry["samples"]
        lps = entry["lps"]
        greedy = entry.get("greedy")
        if self._actor_mesh is not None:
            sh = NamedSharding(self._actor_mesh, spec_kbt)
            samples = jax.device_put(samples, sh)
            lps = jax.device_put(lps, sh)
            if greedy is not None:
                greedy = jax.device_put(
                    greedy, NamedSharding(self._actor_mesh, P(self._axis))
                )
        else:
            samples = jnp.asarray(samples)
            lps = jnp.asarray(lps)
            if greedy is not None:
                greedy = jnp.asarray(greedy)
        rng = jax.random.wrap_key_data(jnp.asarray(entry["rng"]))
        self._ring.push(
            greedy, samples, lps, rng=rng, version=self._actor_version,
            feats=feats, masks=masks, video_ids=video_ids,
            valid_np=valid_np, batch_index=batch_index,
            t_disp=time.perf_counter(),
        )

    # ---- the decoupled epoch ------------------------------------------------

    def train_epoch(self, state: TrainState, batches, rng, on_step=None,
                    pipelined: bool = True, should_stop=None,
                    seam: dict | None = None,
                    seam_sink: dict | None = None):
        """Actor/learner epoch. The two-stage ``pipelined`` flag is
        subsumed by the ring schedule and ignored. Contract matches the
        parent: every batch not persisted into ``seam_sink`` gets exactly
        one applied update, so the returned state corresponds to
        ``len(metrics)`` completed steps."""
        del pipelined
        if self.mesh is not None:
            rng = jax.device_put(rng, NamedSharding(self.mesh, P()))
        # a split layout's update runs on the learner submesh: pull the
        # (replicated) state down onto it; it is pushed back to the full
        # mesh on return so checkpoints/eval see the caller's layout
        state = self._to_learner(state, P())
        out: list[dict] = []

        def emit(m):
            out.append(m)
            if on_step is not None:
                on_step(m)

        _PREEMPT_REQUESTS.clear()
        _REJOIN_REQUESTS.clear()
        self._ring = RolloutRing(
            self._depth, mesh=self._actor_mesh, axis=self._axis
        )
        self._actor_params = None
        self._actor_version = -1
        self._learner_version = 0
        self.last_staleness = {}
        self.last_dropped = 0
        self.last_rejoined = 0
        replay: deque = deque(
            seam.get("ring", []) if seam else []
        )
        t0 = time.perf_counter()
        busy = {"actor": 0.0, "learner": 0.0}
        last_done = {"actor": t0, "learner": t0}
        pending_update = None       # (dispatch_time, metrics ref)

        def flush_update():
            nonlocal pending_update
            if pending_update is None:
                return
            t_disp, ref = pending_update
            pending_update = None
            jax.block_until_ready(ref)
            now = time.perf_counter()
            busy["learner"] += now - max(t_disp, last_done["learner"])
            last_done["learner"] = now

        def consume(state, meta, greedy, samples, lps):
            """Score + update one ring entry on the learner submesh,
            dropping and recounting it first if its params are stale."""
            nonlocal pending_update
            with obs.span("rl.learner.step"):
                stale = self._learner_version - meta["version"]
                if stale > self._bound:
                    obs.counter("rl.staleness.dropped").inc()
                    self.last_dropped += 1
                    # recount: refresh the actor to the learner's version
                    # and re-decode with the entry's OWN rng key — the
                    # token stream depends only on (params, rng), so two
                    # runs drop and recount identically
                    self._refresh_actor(state)
                    greedy, samples, lps = self._dispatch_decode(
                        meta["feats"], meta["masks"], meta["rng"]
                    )
                    meta = dict(meta, version=self._actor_version,
                                t_disp=time.perf_counter())
                    stale = self._learner_version - meta["version"]
                self.last_staleness[stale] = (
                    self.last_staleness.get(stale, 0) + 1
                )
                obs.histogram("rl.staleness", self._STALE_BUCKETS).observe(
                    float(stale)
                )
                # host-observed actor busy window: dispatch -> tokens ready
                # (clipped against the previous window so queued decodes
                # don't double-count)
                t_wait = time.perf_counter()
                jax.block_until_ready(samples)
                now = time.perf_counter()
                busy["actor"] += now - max(
                    min(meta["t_disp"], t_wait), last_done["actor"]
                )
                last_done["actor"] = now
                greedy_l = self._to_learner(greedy, P(self._axis))
                samples_l = self._to_learner(samples, P(None, self._axis))
                feats_l = self._to_learner(meta["feats"], P(self._axis))
                masks_l = self._to_learner(meta["masks"], P(self._axis))
                scored = self._score(
                    greedy_l, samples_l, feats_l, masks_l,
                    meta["video_ids"], meta["valid_np"],
                )
                flush_update()
                t_disp = time.perf_counter()
                state, m = self._apply(state, *scored)
            obs.counter("rl.learner.steps").inc()
            self._learner_version += 1
            emit(m)
            pending_update = (t_disp, m.get("rl_loss"))
            return state

        stopped = False
        batch_index = -1
        for feats, masks, video_ids, valid in batches:
            batch_index += 1
            if should_stop is not None and should_stop():
                stopped = True
                break
            if not self._fallback_sync:
                chaos.visit("rl.actor.step")
            # rejoins first: a rejoin+preempt landing on the same boundary
            # grows then shrinks, in that deterministic order
            lost = self._service_rejoins() + self._service_preemptions()
            if lost:
                # recount the orphaned in-flight rollouts under whatever
                # decodes now (survivor actors, or the learner submesh in
                # the sync fallback), in original order
                for meta in lost:
                    self._refresh_actor(state)
                    g, s, l = self._dispatch_decode(
                        meta["feats"], meta["masks"], meta["rng"]
                    )
                    meta = dict(meta, version=self._actor_version,
                                t_disp=time.perf_counter())
                    state = consume(state, meta, g, s, l)
            if self._fallback_sync:
                # sync schedule on the learner submesh: the parent's strict
                # sequential step, same per-batch rng chain
                rng, srng = jax.random.split(rng)
                state, m = self.train_step(
                    state, self._to_learner(feats, P(self._axis)),
                    self._to_learner(masks, P(self._axis)),
                    video_ids, srng, valid,
                )
                self._learner_version += 1
                emit(m)
                continue
            self._refresh_actor(state)
            rng, srng = jax.random.split(rng)
            valid_np = self._valid_np(valid, len(video_ids))
            if replay and list(replay[0]["video_ids"]) == [
                str(v) for v in video_ids
            ]:
                self._replay_entry(
                    replay.popleft(), feats, masks, video_ids, valid_np,
                    batch_index,
                )
            else:
                if replay:
                    # changed data order: never marry old tokens to new
                    # features — fall through to a live decode
                    self.on_event("seam_ring_discarded", entries=len(replay))
                    replay.clear()
                greedy, samples, lps = self._dispatch_decode(
                    feats, masks, srng
                )
                self._ring.push(
                    greedy, samples, lps, rng=srng,
                    version=self._actor_version, feats=feats, masks=masks,
                    video_ids=video_ids, valid_np=valid_np,
                    batch_index=batch_index, t_disp=time.perf_counter(),
                )
            while len(self._ring) >= self._depth:
                state = consume(state, *self._ring.pop())
        if stopped and seam_sink is not None and len(self._ring):
            # drain-aware stop: the in-flight buffer persists instead of
            # being consumed — the resume replays these exact tokens
            seam_sink.update(self._seam_capture_ring())
        else:
            while len(self._ring):
                state = consume(state, *self._ring.pop())
        flush_update()
        wall = max(time.perf_counter() - t0, 1e-9)
        occ = {
            "actor": min(1.0, busy["actor"] / wall),
            "learner": min(1.0, busy["learner"] / wall),
        }
        self.last_occupancy = dict(occ, wall_s=wall)
        obs.gauge("rl.actor.occupancy").set(occ["actor"])
        obs.gauge("rl.learner.occupancy").set(occ["learner"])
        if not self._shared_roles() and self._full_mesh is not None:
            state = jax.device_put(
                state, NamedSharding(self._full_mesh, P())
            )
        return state, out
