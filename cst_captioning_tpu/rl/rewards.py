"""Consensus reward computation (host side, cached + vectorized).

The reward of a sampled caption is scored against the video's FULL pool of
ground-truth captions (the "consensus" of CST, paper §3.3): CIDEr-D with a
precomputed train-split document frequency — exactly the reference's
``CiderD(df=...)`` reward path — optionally mixed with sentence BLEU-4
(BASELINE config 4: ``w_c·CIDErD + w_b·BLEU4``).

This is the host hot path of the RL phase (SURVEY.md §3.2): profiling showed
naive per-call scoring (re-precooking every reference each step) at ~850ms
for a 64-clip × 5-rollout batch — 80% of the whole SCST step. Here all
reference-side work is done ONCE at construction:

- per video, per reference: tf-idf n-gram vectors, norms, lengths (CIDEr-D),
- per video: max-clipped reference n-gram counts + ref lengths (BLEU-4),

so each step only precooks the B×K hypotheses and takes sparse dot products.
Numbers are bit-identical to the ``metrics.cider.CiderD`` /
``metrics.bleu.Bleu`` oracles (pinned by tests/test_rl.py parity tests).
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Mapping, Sequence

import numpy as np

from cst_captioning_tpu.data.vocab import Vocab
from cst_captioning_tpu.metrics.cider import CorpusDF
from cst_captioning_tpu.metrics.ngram import precook

_MAX_N = 4
_SIGMA = 6.0


class _RefStats:
    """Cached per-video reference statistics for CIDEr-D and BLEU-4."""

    __slots__ = ("cider_vecs", "bleu_max_counts", "ref_lens")

    def __init__(self, refs: list[list[str]], df: dict, log_ndoc: float):
        # CIDEr-D: per ref, (vec per n, norm per n, unigram length)
        self.cider_vecs = []
        for ref in refs:
            counts = precook(ref, _MAX_N)
            vec = [dict() for _ in range(_MAX_N)]
            norm = np.zeros(_MAX_N)
            length = 0
            for gram, tf in counts.items():
                n_idx = len(gram) - 1
                idf = log_ndoc - math.log(max(1.0, df.get(gram, 0.0)))
                w = float(tf) * idf
                vec[n_idx][gram] = w
                norm[n_idx] += w * w
                if n_idx == 0:
                    length += tf
            self.cider_vecs.append((vec, np.sqrt(norm), length))
        # BLEU: per n, elementwise-max reference counts; plus ref lengths
        self.bleu_max_counts = [Counter() for _ in range(_MAX_N)]
        self.ref_lens = [len(r) for r in refs]
        for ref in refs:
            counts = precook(ref, _MAX_N)
            for gram, tf in counts.items():
                n_idx = len(gram) - 1
                if tf > self.bleu_max_counts[n_idx][gram]:
                    self.bleu_max_counts[n_idx][gram] = tf


def _cider_d_score(hyp_counts: Counter, stats: _RefStats, df: dict,
                   log_ndoc: float) -> float:
    """CIDEr-D of one hypothesis vs a cached reference pool (×10 scale)."""
    hvec = [dict() for _ in range(_MAX_N)]
    hnorm = np.zeros(_MAX_N)
    hlen = 0
    for gram, tf in hyp_counts.items():
        n_idx = len(gram) - 1
        idf = log_ndoc - math.log(max(1.0, df.get(gram, 0.0)))
        w = float(tf) * idf
        hvec[n_idx][gram] = w
        hnorm[n_idx] += w * w
        if n_idx == 0:
            hlen += tf
    hnorm = np.sqrt(hnorm)

    per_ref = np.zeros(_MAX_N)
    for rvec, rnorm, rlen in stats.cider_vecs:
        val = np.zeros(_MAX_N)
        for n_idx in range(_MAX_N):
            rv = rvec[n_idx]
            dot = 0.0
            for gram, hw in hvec[n_idx].items():
                rw = rv.get(gram)
                if rw is not None:
                    dot += min(hw, rw) * rw
            denom = hnorm[n_idx] * rnorm[n_idx]
            if denom > 0:
                val[n_idx] = dot / denom
        delta = float(hlen - rlen)
        per_ref += val * math.exp(-(delta**2) / (2.0 * _SIGMA**2))
    per_ref /= max(1, len(stats.cider_vecs))
    return float(np.mean(per_ref)) * 10.0


def _closest_ref_len(hyp_len: int, ref_lens: Sequence[int]) -> int:
    return min(ref_lens, key=lambda r: (abs(r - hyp_len), r))


def _bleu4_score(hyp: list[str], hyp_counts: Counter, stats: _RefStats) -> float:
    """Smoothed sentence BLEU-4 vs cached max-clipped ref counts.

    Mirrors metrics.bleu.Bleu.sentence_bleu: +1 smoothing above unigrams,
    brevity penalty against the closest reference length.
    """
    if not hyp:
        return 0.0
    hyp_len = len(hyp)
    r = _closest_ref_len(hyp_len, stats.ref_lens)
    bp = 1.0 if hyp_len >= r else math.exp(1.0 - r / hyp_len)
    log_p = 0.0
    score = 0.0
    for n in range(1, _MAX_N + 1):
        matched, total = 0, 0
        maxc = stats.bleu_max_counts[n - 1]
        for gram, tf in hyp_counts.items():
            if len(gram) == n:
                total += tf
                m = maxc.get(gram)
                if m:
                    matched += min(tf, m)
        if n == 1:
            p = matched / total if total else 0.0
        else:
            p = (matched + 1.0) / (total + 1.0) if total else 0.0
        if p == 0.0:
            # only reachable at n=1 (higher orders are +1-smoothed): a
            # hypothesis with zero unigram matches scores 0
            return 0.0
        log_p += math.log(p)
        score = bp * math.exp(log_p / n)
    return score


class RewardComputer:
    def __init__(
        self,
        vocab: Vocab,
        gts_pool: Mapping[str, Sequence[str]],   # video_id -> tokenized GT strings
        df: CorpusDF | None = None,
        cider_weight: float = 1.0,
        bleu_weight: float = 0.0,
        bleu_scale: float = 10.0,
        num_threads: int = 0,
        use_native: bool = True,
    ):
        self.vocab = vocab
        refs = {vid: [c.split() for c in caps] for vid, caps in gts_pool.items()}
        if df is None:
            df = CorpusDF.from_refs(list(refs.values()))
        self.df = df.df
        # same tiny-corpus clamp as metrics.cider (idf stays >= 0)
        self.log_ndoc = math.log(max(float(df.num_docs), math.e))
        self.cider_weight = cider_weight
        self.bleu_weight = bleu_weight
        # BLEU4 is in [0,1] vs CIDEr's x10 scale; bleu_scale (config
        # rl.reward_bleu4_scale) maps it onto the mixing scale. UNVERIFIED
        # interpretation of the reference's convention — see BASELINE.md
        # "Mixed-reward BLEU4 scale"
        self.bleu_scale = bleu_scale
        # 0 = all cores: the reward is the host hot path of the RL phase and
        # the pipelined epoch hides exactly as much of it as the threads cover
        import os

        self.num_threads = num_threads if num_threads > 0 else (os.cpu_count() or 1)
        self._native = None
        if use_native:
            self._init_native(refs)
        if self._native is None:
            # pure-Python fallback path (also the parity oracle's twin)
            self.stats = {
                vid: _RefStats(r, self.df, self.log_ndoc) for vid, r in refs.items()
            }

    # ---- native path --------------------------------------------------------

    def _init_native(self, refs: Mapping[str, list[list[str]]]) -> None:
        """Intern words, preload df + reference pools into the C++ kernel.

        Scoring stays in *string space*: the intern table covers reference
        words (incl. OOV words absent from the model vocab) plus all vocab
        words, so id-space grams are bijective with word-tuple grams.
        """
        from cst_captioning_tpu.config.config import (
            BOS_ID,
            EOS_ID,
            NUM_SPECIAL_TOKENS,
            PAD_ID,
        )
        from cst_captioning_tpu.native import load_creward

        lib = load_creward()
        if lib is None:
            return
        import ctypes

        intern: dict[str, int] = {}

        def iid(word: str) -> int:
            i = intern.get(word)
            if i is None:
                i = len(intern) + NUM_SPECIAL_TOKENS
                intern[word] = i
            return i

        handle = lib.crw_create(
            ctypes.c_double(self.log_ndoc), ctypes.c_double(_SIGMA),
            PAD_ID, BOS_ID, EOS_ID,
        )

        # df table -> flat arrays of interned grams
        gram_tokens: list[int] = []
        gram_lens: list[int] = []
        gram_counts: list[float] = []
        for gram, count in self.df.items():
            gram_tokens.extend(iid(w) for w in gram)
            gram_lens.append(len(gram))
            gram_counts.append(float(count))
        if gram_lens:
            gt = np.asarray(gram_tokens, np.int32)
            gl = np.asarray(gram_lens, np.int32)
            gc = np.asarray(gram_counts, np.float64)
            lib.crw_set_df(
                handle,
                gt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                gl.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                gc.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                ctypes.c_int64(len(gram_lens)),
            )

        # reference pools
        self._video_index: dict[str, int] = {}
        for vid, pool in refs.items():
            toks = np.asarray(
                [iid(w) for ref in pool for w in ref], np.int32
            )
            lens = np.asarray([len(ref) for ref in pool], np.int32)
            idx = lib.crw_add_video(
                handle,
                toks.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ctypes.c_int32(len(pool)),
            )
            self._video_index[vid] = int(idx)

        # vocab-id -> intern-id lookup (specials map to themselves, so the
        # kernel's EOS/PAD/BOS handling sees the standard ids)
        lut = np.arange(len(self.vocab), dtype=np.int32)
        for i, word in enumerate(self.vocab.words):
            if i >= NUM_SPECIAL_TOKENS:
                lut[i] = iid(word)
        # UNK decodes to the literal "<unk>" word string in the Python path
        lut[3] = iid("<unk>")
        self._lut = lut
        self._lib = lib
        self._handle = handle
        self._native = True

    def __del__(self):
        if getattr(self, "_native", None) and getattr(self, "_handle", None):
            try:
                self._lib.crw_free(self._handle)
            except Exception:
                pass

    # ---- scoring ------------------------------------------------------------

    def __call__(
        self, video_ids: Sequence[str], token_rows: np.ndarray
    ) -> np.ndarray:
        """Score decoded rows against their videos' consensus pools.

        ``token_rows``: [N, T] int array (N = any multiple of len(video_ids);
        rollout-major layouts flatten to rows with ``video_ids`` cycling).
        Returns rewards [N] in CIDEr units (×10 scale, like the reference).
        """
        token_rows = np.ascontiguousarray(token_rows, dtype=np.int32)
        n = len(token_rows)
        nv = len(video_ids)
        if self._native:
            return self._score_native(video_ids, token_rows, n, nv)
        rewards = np.zeros(n, np.float32)
        for i in range(n):
            stats = self.stats[video_ids[i % nv]]
            hyp = self.vocab.decode(token_rows[i]).split()
            counts = precook(hyp, _MAX_N)
            r = self.cider_weight * _cider_d_score(
                counts, stats, self.df, self.log_ndoc
            )
            if self.bleu_weight != 0.0:
                r += (
                    self.bleu_weight * _bleu4_score(hyp, counts, stats)
                    * self.bleu_scale
                )
            rewards[i] = r
        return rewards

    def _score_native(self, video_ids, token_rows, n, nv) -> np.ndarray:
        import ctypes

        from cst_captioning_tpu.config.config import UNK_ID

        # ids outside the vocab (model vocab_size > len(vocab)) intern as
        # '<unk>', matching Vocab.decode on the Python path
        in_range = (token_rows >= 0) & (token_rows < len(self._lut))
        interned = np.ascontiguousarray(
            self._lut[np.where(in_range, token_rows, UNK_ID)]
        )
        vidx = np.asarray(
            [self._video_index[video_ids[i % nv]] for i in range(n)], np.int32
        )
        out = np.zeros(n, np.float32)
        self._lib.crw_score(
            self._handle,
            vidx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            interned.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(n),
            ctypes.c_int32(token_rows.shape[1]),
            ctypes.c_double(self.cider_weight),
            # the kernel mixes bw*BLEU4*10 (its fixed x10 convention); fold
            # the configurable scale into the weight so bw_eff*10 == w_b*scale
            ctypes.c_double(self.bleu_weight * self.bleu_scale / 10.0),
            ctypes.c_int32(self.num_threads),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return out


def scb_baseline(rewards_kb: np.ndarray) -> np.ndarray:
    """Self-consensus baseline (CST_MS_SCB, paper §3.4).

    ``rewards_kb``: [K, B] rollout rewards. Baseline for rollout k is the mean
    reward of the OTHER K-1 rollouts of the same video; K=1 degrades to 0.
    """
    K = rewards_kb.shape[0]
    if K < 2:
        return np.zeros_like(rewards_kb)
    total = rewards_kb.sum(axis=0, keepdims=True)
    return (total - rewards_kb) / (K - 1)
