"""Consensus reward computation (host side, vectorized numpy).

The reward of a sampled caption is scored against the video's FULL pool of
ground-truth captions (the "consensus" of CST, paper §3.3): CIDEr-D with a
precomputed train-split document frequency — exactly the reference's
``CiderD(df=...)`` reward path — optionally mixed with sentence BLEU-4
(BASELINE config 4: ``w_c·CIDErD + w_b·BLEU4``).

Reference pools are pre-tokenized once at construction; per-step work is one
pass over the decoded hypotheses.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from cst_captioning_tpu.data.vocab import Vocab
from cst_captioning_tpu.metrics.bleu import Bleu
from cst_captioning_tpu.metrics.cider import CiderD, CorpusDF


class RewardComputer:
    def __init__(
        self,
        vocab: Vocab,
        gts_pool: Mapping[str, Sequence[str]],   # video_id -> tokenized GT strings
        df: CorpusDF | None = None,
        cider_weight: float = 1.0,
        bleu_weight: float = 0.0,
    ):
        self.vocab = vocab
        self.refs = {vid: [c.split() for c in caps] for vid, caps in gts_pool.items()}
        if df is None:
            df = CorpusDF.from_refs(list(self.refs.values()))
        self.cider = CiderD(df=df)
        self.bleu = Bleu(4) if bleu_weight != 0.0 else None
        self.cider_weight = cider_weight
        self.bleu_weight = bleu_weight

    def __call__(
        self, video_ids: Sequence[str], token_rows: np.ndarray
    ) -> np.ndarray:
        """Score decoded rows against their videos' consensus pools.

        ``token_rows``: [N, T] int array (N = any multiple of len(video_ids);
        rollout-major layouts flatten to rows with ``video_ids`` cycling).
        Returns rewards [N] in CIDEr units (×10 scale, like the reference).
        """
        n = len(token_rows)
        vids = [video_ids[i % len(video_ids)] for i in range(n)]
        hyps = [self.vocab.decode(row).split() for row in token_rows]
        gts = {str(i): self.refs[v] for i, v in enumerate(vids)}
        res = {str(i): [hyps[i]] for i in range(n)}
        _, cider_scores = self.cider.compute_score(gts, res)
        rewards = self.cider_weight * np.asarray(cider_scores)
        if self.bleu is not None:
            bleu4 = np.array(
                [self.bleu.sentence_bleu(hyps[i], gts[str(i)])[3] for i in range(n)]
            )
            # BLEU in [0,1] vs CIDEr's ×10 scale: match the reference's mixed
            # reward by scaling BLEU4 ×10 so the weights act on like scales
            rewards = rewards + self.bleu_weight * bleu4 * 10.0
        return rewards.astype(np.float32)


def scb_baseline(rewards_kb: np.ndarray) -> np.ndarray:
    """Self-consensus baseline (CST_MS_SCB, paper §3.4).

    ``rewards_kb``: [K, B] rollout rewards. Baseline for rollout k is the mean
    reward of the OTHER K-1 rollouts of the same video; K=1 degrades to 0.
    """
    K = rewards_kb.shape[0]
    if K < 2:
        return np.zeros_like(rewards_kb)
    total = rewards_kb.sum(axis=0, keepdims=True)
    return (total - rewards_kb) / (K - 1)
