"""Evaluation harness (reference ``test.py``, SURVEY.md §3.3)."""

from cst_captioning_tpu.eval.evaluator import Evaluator, evaluate_split

__all__ = ["Evaluator", "evaluate_split"]
