"""Beam-search evaluation + COCO-style metric report (BASELINE config 5).

Reference flow (SURVEY.md §3.3): load checkpoint -> beam=5 decode the split ->
ids->words -> PTB tokenize -> BLEU/METEOR/ROUGE-L/CIDEr -> results json. Here
the decode is one jitted fixed-shape program per batch and the metrics are the
pure-Python scorers; results keep a schema in the reference's spirit:
``{"captions": {vid: text}, "metrics": {...}}``.

Eval fast path (README): round-5 profiling put host metric scoring at 71.5%
of eval wall-clock with the device idle the whole time, so ``evaluate`` runs
a TWO-STAGE pipeline by default (``EvalConfig.pipelined``): the device
decodes batch i+1 while a worker pool PTB-tokenizes batch i's captions (the
per-caption half of scoring — the corpus scorers need the full split and run
at the drain). Per-batch tokenization is independent and the drain assembles
the tokenized dicts in the serial path's exact key order, so the metric
table is BIT-IDENTICAL to the serial evaluator (pinned in
tests/test_eval_pipeline.py) — eval wall-clock approaches
max(decode, tokenize) + corpus instead of their sum (the Podracer
actor/learner decoupling, arXiv 2104.06272, in miniature). The overlap
ledger (eval.decode_seconds / eval.score_seconds histograms,
eval.overlap_* gauges, fill/drain spans) feeds cli.obs_report's eval
section. Decoding itself picks beam-on-lanes (``EvalConfig.beam_impl``) or
the NPAD anytime mode (``EvalConfig.npad_lanes``, arXiv 1605.03835).
"""

from __future__ import annotations

import json
import logging
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cst_captioning_tpu import obs
from cst_captioning_tpu.config.config import EvalConfig
from cst_captioning_tpu.data.batcher import Batcher
from cst_captioning_tpu.data.dataset import CaptionDataset
from cst_captioning_tpu.decoding import beam_search, greedy_decode, npad_decode
from cst_captioning_tpu.metrics.scorer import CaptionScorer
from cst_captioning_tpu.metrics.tokenizer import ptb_tokenize
from cst_captioning_tpu.parallel import (
    CompilePlan,
    compile_fn,
    sp_batch_specs,
    sp_model,
)
from cst_captioning_tpu.train import multihost
from cst_captioning_tpu.train.mesh import batch_sharding
from cst_captioning_tpu.train.steps import batch_arrays


class Evaluator:
    """With a ``mesh``, the decode is shard_map-parallel: every device
    beam-decodes its batch shard, and the generated token ids are gathered
    back to the host when the global output array is read (the SURVEY.md §5
    dist-comm row's eval-time gather). ``valid``-row filtering is unchanged,
    so multi-device eval produces the exact single-device captions (pinned
    by tests/test_ckpt_eval.py)."""

    def __init__(
        self,
        model,
        dataset: CaptionDataset,
        cfg: EvalConfig | None = None,
        batch_size: int = 32,
        mesh: Mesh | None = None,
    ):
        self.model = model
        self.ds = dataset
        self.cfg = cfg or EvalConfig()
        self.mesh = mesh
        # 2-D ('data','seq') mesh: frames shard over 'seq' with the SP
        # collective attention (MeshConfig.seq_devices > 1)
        self.sp = mesh is not None and "seq" in mesh.axis_names
        if mesh is not None:
            # every batch size shards: round up to the next data-axis multiple
            # — the Batcher wrap-pads to the (static) batch size and marks the
            # extra rows invalid, so generate() drops them and the captions
            # stay exactly the single-device ones (VERDICT r2 next #5)
            n = mesh.shape["data"]
            if batch_size % n:
                padded = -(-batch_size // n) * n
                # warning level: visible under the default root-logger config
                logging.getLogger(__name__).warning(
                    "eval batch_size %d -> %d (next multiple of the %d-device "
                    "'data' axis; wrap-padded rows are masked out)",
                    batch_size, padded, n,
                )
                batch_size = padded
            if self.sp and dataset.max_frames % mesh.shape["seq"]:
                raise ValueError(
                    f"dataset max_frames {dataset.max_frames} must be "
                    f"divisible by the mesh's 'seq' axis {mesh.shape['seq']}"
                )
        # multi-host: each process collates/decodes only its own rows and
        # the caption dicts are merged once per split (SURVEY.md §5
        # dist-comm row) — host h5/collate/score work divides by process
        # count instead of being replicated everywhere
        self.multiproc = mesh is not None and multihost.is_multiprocess()
        # construct (and thereby validate) the scorers up front, on EVERY
        # process: a bad metric selector failing only on process 0 after the
        # full decode would leave the other processes hung in the metric
        # broadcast collective. The pre-tokenized twin scores the pipelined
        # drain (its inputs already went through ptb_tokenize in the worker
        # pool); persistent so the native CIDEr-D reference pool caches
        # across evaluate calls, like the serial scorer's.
        self._scorer = CaptionScorer(metrics=self.cfg.metrics)
        self._scorer_pre = CaptionScorer(
            metrics=self.cfg.metrics, pre_tokenized=True
        )
        self.batcher = Batcher(
            dataset, batch_size=batch_size, max_len=self.cfg.max_len,
            mode="video",
            host_shard=multihost.host_shard() if self.multiproc else (0, 1),
        )
        W, T, lp = self.cfg.beam_size, self.cfg.max_len, self.cfg.length_penalty
        ml = self.cfg.min_len

        dec_model = model
        if self.sp and not model.cfg.seq_axis:
            dec_model = sp_model(model.cfg)  # params are layout-identical
        # inside shard_map the batch is sharded over 'data': the decode loops
        # pcast their invariant inits over it + psum their early-exit count,
        # keeping check_vma ON (VERDICT r4 weak #3 closed)
        bx = ("data",) if mesh is not None else ()
        # every decode takes (params, feats, masks, rng); only the NPAD mode
        # consumes the key (per-batch fold_in of npad_seed) — one uniform
        # signature keeps the shard_map specs and the dispatch loop mode-free
        self._decode_key = jax.random.key(self.cfg.npad_seed)
        if self.cfg.npad_lanes > 0:
            M, tmp = self.cfg.npad_lanes, self.cfg.npad_temperature
            decode = lambda p, f, m, r: npad_decode(
                dec_model, p, f, m, r, num_lanes=M, temperature=tmp,
                max_len=T, min_len=ml, batch_axes=bx,
            )[0]
        elif W > 1:
            decode = lambda p, f, m, r: beam_search(
                dec_model, p, f, m, beam_size=W, max_len=T, min_len=ml,
                length_penalty=lp, batch_axes=bx,
                beam_impl=self.cfg.beam_impl,
            )[0]
        else:
            decode = lambda p, f, m, r: greedy_decode(
                dec_model, p, f, m, max_len=T, min_len=ml, batch_axes=bx
            )[0]
        self._fm_shardings = None
        plan = CompilePlan()
        if mesh is not None:
            if self.sp:
                f_spec, m_spec = sp_batch_specs(model.cfg, "data")
                in_specs = (P(), f_spec, m_spec, P())
                self._fm_shardings = (
                    {k: NamedSharding(mesh, s) for k, s in f_spec.items()},
                    {k: NamedSharding(mesh, s) for k, s in m_spec.items()},
                )
            else:
                in_specs = (P(), P("data"), P("data"), P())
                s = batch_sharding(mesh)
                self._fm_shardings = (s, s)
            plan = CompilePlan(
                mesh=mesh, in_specs=in_specs, out_specs=P("data")
            )
        self._decode = compile_fn(decode, plan)

    def _dispatch(self, params, batch, bi: int):
        """Collate-upload batch ``bi`` and launch its decode (async)."""
        if self._fm_shardings is not None:
            # numpy straight into the target sharding (single transfer)
            put = (
                multihost.put_global if self.multiproc
                else multihost.put_full_global
            )
            feats, masks = put(
                self._fm_shardings, (batch.feats, batch.feat_masks)
            )
        else:
            feats, masks, *_ = batch_arrays(batch)
        tokens = self._decode(
            params, feats, masks, jax.random.fold_in(self._decode_key, bi)
        )
        if tokens.is_fully_addressable:
            # start the device->host transfer now so it overlaps the next
            # decode; by readback time the tokens are already on host
            tokens.copy_to_host_async()
        return tokens

    def generate(self, params) -> dict[str, str]:
        """Decode every video of the split -> {video_id: caption string}.

        One-deep software pipeline (the SCST epoch pattern, rl/scst.py):
        batch *i+1*'s collate + feature upload + decode dispatch all happen
        BEFORE batch *i*'s tokens are read back and converted to words, so
        the host half (h5 collate, device->host transfer, id->word decode)
        overlaps the device decode instead of serializing after it. The
        decoded captions are identical — only the dispatch order changes.

        Multi-host: each process collates only its contiguous slice of every
        global batch (the Batcher ``host_shard`` path the Trainer uses),
        reads back only its own decoded rows, and the per-host caption dicts
        are merged with ONE gather at the end — so the host-side h5 reads
        and collates divide by process count while every process still
        returns the full dict (train/multihost.py)."""
        out: dict[str, str] = {}

        def collect(tokens, batch):
            if self.multiproc:
                # this host's decoded rows only — batch.video_ids/valid are
                # already the matching local slice
                tok = multihost.to_host_local(tokens, self.mesh, P("data"))
            else:
                tok = jax.device_get(tokens)
            for i, ok in enumerate(batch.valid):
                if ok:
                    out[batch.video_ids[i]] = self.ds.vocab.decode(tok[i])

        pending = None  # (device tokens, source batch) awaiting readback
        for bi, batch in enumerate(self.batcher.epoch(shuffle=False)):
            tokens = self._dispatch(params, batch, bi)
            if pending is not None:
                collect(*pending)
            pending = (tokens, batch)
        if pending is not None:
            collect(*pending)
        if self.multiproc:
            merged: dict[str, str] = {}
            for part in multihost.allgather_pyobj(out):
                merged.update(part)
            out = merged
        return out

    def _tok_res_shard(self, items):
        """[(vid, token row)] -> ([(vid, text, ptb tokens)], worker seconds).

        The per-caption half of scoring — runs on the worker pool WHILE the
        device decodes later batches. ``vocab.decode`` and ``ptb_tokenize``
        are pure functions of their inputs, so sharding them changes nothing
        but when they run.
        """
        t0 = time.perf_counter()
        out = []
        for vid, row in items:
            text = self.ds.vocab.decode(row)
            out.append((vid, text, ptb_tokenize(text)))
        return out, time.perf_counter() - t0

    def _tok_gts_shard(self, items):
        """[(vid, [ref strings])] -> ([(vid, [ptb tokens])], worker seconds)."""
        t0 = time.perf_counter()
        out = [
            (vid, [ptb_tokenize(c) for c in caps]) for vid, caps in items
        ]
        return out, time.perf_counter() - t0

    def _evaluate_pipelined(self, params):
        """Two-stage decode/score pipeline -> (captions, metrics).

        Stage 1 (device): the one-deep decode pipeline of ``generate``.
        Stage 2 (host pool): per-batch caption tokenization, plus the
        reference-pool tokenization fanned out BEFORE the first decode (the
        references don't depend on the model). The drain gathers the shards
        in submission order — batch order for hypotheses, ``gts_pool``
        order for references, the serial path's exact dict orders — and
        runs the corpus scorers on the pre-tokenized tables, so the metric
        table is bit-identical to the serial evaluator's.
        """
        wall0 = time.perf_counter()
        decode_total = 0.0
        score_total = 0.0
        dec_hist = obs.histogram("eval.decode_seconds")
        sc_hist = obs.histogram("eval.score_seconds")
        res_futs: list = []
        with ThreadPoolExecutor(max_workers=self.cfg.score_workers) as pool:
            gts_items = [
                (vid, list(caps)) for vid, caps in self.ds.gts_pool().items()
            ]
            shard = max(1, -(-len(gts_items) // self.cfg.score_workers))
            gts_futs = [
                pool.submit(self._tok_gts_shard, gts_items[i:i + shard])
                for i in range(0, len(gts_items), shard)
            ]

            def collect(tokens, batch):
                nonlocal decode_total
                t0 = time.perf_counter()
                tok = jax.device_get(tokens)
                dt = time.perf_counter() - t0
                decode_total += dt
                dec_hist.observe(dt)
                obs.counter("eval.batches").inc()
                items = [
                    (batch.video_ids[i], tok[i])
                    for i, ok in enumerate(batch.valid) if ok
                ]
                obs.counter("eval.captions").inc(len(items))
                res_futs.append(pool.submit(self._tok_res_shard, items))

            # fill: batch 0's collate + upload + decode dispatch — the
            # pipeline's lead-in, before any decode/score overlap can exist
            batches = enumerate(self.batcher.epoch(shuffle=False))
            with obs.span("eval.pipeline.fill"):
                t_f0 = time.perf_counter()
                bi, batch = next(batches, (None, None))
                pending = (
                    (self._dispatch(params, batch, bi), batch)
                    if batch is not None else None
                )
                fill_s = time.perf_counter() - t_f0
            for bi, batch in batches:
                tokens = self._dispatch(params, batch, bi)
                collect(*pending)
                pending = (tokens, batch)
            if pending is not None:
                collect(*pending)

            # drain: decode is done — gather the tokenizer shards (mostly
            # already resolved if the overlap worked) and run the corpus
            # scorers, which need the full split
            with obs.span("eval.pipeline.drain"):
                t_d0 = time.perf_counter()
                res_items: list = []
                for fut in res_futs:
                    out, dt = fut.result()
                    score_total += dt
                    sc_hist.observe(dt)
                    res_items.extend(out)
                gts_t: dict[str, list] = {}
                for fut in gts_futs:
                    out, dt = fut.result()
                    score_total += dt
                    sc_hist.observe(dt)
                    for vid, toks in out:
                        gts_t[vid] = toks
                gather_wait = time.perf_counter() - t_d0
                captions = {vid: text for vid, text, _ in res_items}
                res_t = {vid: [toks] for vid, _, toks in res_items}
                with obs.span("eval.score"):
                    metrics = self._scorer_pre.score(gts_t, res_t)
                drain_s = time.perf_counter() - t_d0

        # the overlap ledger: scoring seconds that did NOT stall the drain
        # were hidden under device decode. efficiency normalizes by the
        # shorter stage — the most overlap the pipeline could possibly hide.
        overlap_s = max(0.0, score_total - gather_wait)
        hideable = min(decode_total, score_total)
        obs.gauge("eval.overlap_fraction").set(
            overlap_s / score_total if score_total > 0 else 0.0
        )
        obs.gauge("eval.overlap_efficiency").set(
            min(1.0, overlap_s / hideable) if hideable > 0 else 0.0
        )
        obs.gauge("eval.pipeline.fill_s").set(fill_s)
        obs.gauge("eval.pipeline.drain_s").set(drain_s)
        obs.gauge("eval.decode_total_s").set(decode_total)
        obs.gauge("eval.score_total_s").set(score_total)
        obs.gauge("eval.wall_s").set(time.perf_counter() - wall0)
        return captions, metrics

    def evaluate(self, params, results_json: str = "") -> dict[str, Any]:
        """generate + score; optionally write the results json.

        Single-process with ``cfg.pipelined`` (default): the two-stage
        decode/score pipeline (``_evaluate_pipelined`` — bit-identical
        metric table, overlapped wall-clock). Multi-host keeps the serial
        split: the tokenized shards live only on the process that decoded
        them, and only process 0 runs the metric scorers (pure host compute
        on inputs every process already holds); the metrics dict is
        broadcast so the return value is identical everywhere."""
        with obs.span("eval", split=self.ds.split):
            if self.cfg.pipelined and not self.multiproc:
                captions, metrics = self._evaluate_pipelined(params)
                obs.snapshot_metrics(split=self.ds.split)
            else:
                captions = self.generate(params)
                metrics = None
                if not self.multiproc or jax.process_index() == 0:
                    gts = {
                        vid: list(caps)
                        for vid, caps in self.ds.gts_pool().items()
                    }
                    res = {vid: [captions[vid]] for vid in captions}
                    with obs.span("eval.score"):
                        metrics = self._scorer.score(gts, res)
                if self.multiproc:
                    metrics = multihost.broadcast_pyobj(metrics)
        result = {"split": self.ds.split, "metrics": metrics, "captions": captions}
        if results_json and self.multiproc and jax.process_index() != 0:
            # shared-filesystem contract (same as checkpointing): N identical
            # concurrent writers can corrupt the file — process 0 writes
            results_json = ""
        if results_json:
            os.makedirs(os.path.dirname(results_json) or ".", exist_ok=True)
            with open(results_json, "w") as f:
                json.dump(result, f, indent=2, default=float)
        return result


def evaluate_split(model, params, dataset, cfg: EvalConfig | None = None,
                   batch_size: int = 32, results_json: str = "",
                   mesh: Mesh | None = None) -> dict[str, Any]:
    return Evaluator(model, dataset, cfg, batch_size, mesh=mesh).evaluate(
        params, results_json
    )
