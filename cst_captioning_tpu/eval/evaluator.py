"""Beam-search evaluation + COCO-style metric report (BASELINE config 5).

Reference flow (SURVEY.md §3.3): load checkpoint -> beam=5 decode the split ->
ids->words -> PTB tokenize -> BLEU/METEOR/ROUGE-L/CIDEr -> results json. Here
the decode is one jitted fixed-shape program per batch and the metrics are the
pure-Python scorers; results keep a schema in the reference's spirit:
``{"captions": {vid: text}, "metrics": {...}}``.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cst_captioning_tpu import obs
from cst_captioning_tpu.compat import shard_map
from cst_captioning_tpu.config.config import EvalConfig
from cst_captioning_tpu.data.batcher import Batcher
from cst_captioning_tpu.data.dataset import CaptionDataset
from cst_captioning_tpu.decoding import beam_search, greedy_decode
from cst_captioning_tpu.metrics.scorer import CaptionScorer
from cst_captioning_tpu.parallel import sp_batch_specs, sp_model
from cst_captioning_tpu.train import multihost
from cst_captioning_tpu.train.mesh import batch_sharding
from cst_captioning_tpu.train.steps import batch_arrays


class Evaluator:
    """With a ``mesh``, the decode is shard_map-parallel: every device
    beam-decodes its batch shard, and the generated token ids are gathered
    back to the host when the global output array is read (the SURVEY.md §5
    dist-comm row's eval-time gather). ``valid``-row filtering is unchanged,
    so multi-device eval produces the exact single-device captions (pinned
    by tests/test_ckpt_eval.py)."""

    def __init__(
        self,
        model,
        dataset: CaptionDataset,
        cfg: EvalConfig | None = None,
        batch_size: int = 32,
        mesh: Mesh | None = None,
    ):
        self.model = model
        self.ds = dataset
        self.cfg = cfg or EvalConfig()
        self.mesh = mesh
        # 2-D ('data','seq') mesh: frames shard over 'seq' with the SP
        # collective attention (MeshConfig.seq_devices > 1)
        self.sp = mesh is not None and "seq" in mesh.axis_names
        if mesh is not None:
            # every batch size shards: round up to the next data-axis multiple
            # — the Batcher wrap-pads to the (static) batch size and marks the
            # extra rows invalid, so generate() drops them and the captions
            # stay exactly the single-device ones (VERDICT r2 next #5)
            n = mesh.shape["data"]
            if batch_size % n:
                padded = -(-batch_size // n) * n
                # warning level: visible under the default root-logger config
                logging.getLogger(__name__).warning(
                    "eval batch_size %d -> %d (next multiple of the %d-device "
                    "'data' axis; wrap-padded rows are masked out)",
                    batch_size, padded, n,
                )
                batch_size = padded
            if self.sp and dataset.max_frames % mesh.shape["seq"]:
                raise ValueError(
                    f"dataset max_frames {dataset.max_frames} must be "
                    f"divisible by the mesh's 'seq' axis {mesh.shape['seq']}"
                )
        # multi-host: each process collates/decodes only its own rows and
        # the caption dicts are merged once per split (SURVEY.md §5
        # dist-comm row) — host h5/collate/score work divides by process
        # count instead of being replicated everywhere
        self.multiproc = mesh is not None and multihost.is_multiprocess()
        # construct (and thereby validate) the scorer up front, on EVERY
        # process: a bad metric selector failing only on process 0 after the
        # full decode would leave the other processes hung in the metric
        # broadcast collective
        self._scorer = CaptionScorer(metrics=self.cfg.metrics)
        self.batcher = Batcher(
            dataset, batch_size=batch_size, max_len=self.cfg.max_len,
            mode="video",
            host_shard=multihost.host_shard() if self.multiproc else (0, 1),
        )
        W, T, lp = self.cfg.beam_size, self.cfg.max_len, self.cfg.length_penalty
        ml = self.cfg.min_len

        dec_model = model
        if self.sp and not model.cfg.seq_axis:
            dec_model = sp_model(model.cfg)  # params are layout-identical
        # inside shard_map the batch is sharded over 'data': the decode loops
        # pcast their invariant inits over it + psum their early-exit count,
        # keeping check_vma ON (VERDICT r4 weak #3 closed)
        bx = ("data",) if mesh is not None else ()
        if W > 1:
            decode = lambda p, f, m: beam_search(
                dec_model, p, f, m, beam_size=W, max_len=T, min_len=ml,
                length_penalty=lp, batch_axes=bx,
            )[0]
        else:
            decode = lambda p, f, m: greedy_decode(
                dec_model, p, f, m, max_len=T, min_len=ml, batch_axes=bx
            )[0]
        self._fm_shardings = None
        if mesh is not None:
            if self.sp:
                f_spec, m_spec = sp_batch_specs(model.cfg, "data")
                in_specs = (P(), f_spec, m_spec)
                self._fm_shardings = (
                    {k: NamedSharding(mesh, s) for k, s in f_spec.items()},
                    {k: NamedSharding(mesh, s) for k, s in m_spec.items()},
                )
            else:
                in_specs = (P(), P("data"), P("data"))
                s = batch_sharding(mesh)
                self._fm_shardings = (s, s)
            decode = shard_map(
                decode,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=P("data"),
            )
        self._decode = jax.jit(decode)

    def generate(self, params) -> dict[str, str]:
        """Decode every video of the split -> {video_id: caption string}.

        One-deep software pipeline (the SCST epoch pattern, rl/scst.py):
        batch *i+1*'s collate + feature upload + decode dispatch all happen
        BEFORE batch *i*'s tokens are read back and converted to words, so
        the host half (h5 collate, device->host transfer, id->word decode)
        overlaps the device decode instead of serializing after it. The
        decoded captions are identical — only the dispatch order changes.

        Multi-host: each process collates only its contiguous slice of every
        global batch (the Batcher ``host_shard`` path the Trainer uses),
        reads back only its own decoded rows, and the per-host caption dicts
        are merged with ONE gather at the end — so the host-side h5 reads
        and collates divide by process count while every process still
        returns the full dict (train/multihost.py)."""
        out: dict[str, str] = {}

        def collect(tokens, batch):
            if self.multiproc:
                # this host's decoded rows only — batch.video_ids/valid are
                # already the matching local slice
                tok = multihost.to_host_local(tokens, self.mesh, P("data"))
            else:
                tok = np.asarray(tokens)
            for i, ok in enumerate(batch.valid):
                if ok:
                    out[batch.video_ids[i]] = self.ds.vocab.decode(tok[i])

        pending = None  # (device tokens, source batch) awaiting readback
        for batch in self.batcher.epoch(shuffle=False):
            if self._fm_shardings is not None:
                # numpy straight into the target sharding (single transfer)
                put = (
                    multihost.put_global if self.multiproc
                    else multihost.put_full_global
                )
                feats, masks = put(
                    self._fm_shardings, (batch.feats, batch.feat_masks)
                )
            else:
                feats, masks, *_ = batch_arrays(batch)
            tokens = self._decode(params, feats, masks)
            if tokens.is_fully_addressable:
                # start the device->host transfer now so it overlaps this
                # decode; by collect() time the tokens are already on host
                tokens.copy_to_host_async()
            if pending is not None:
                collect(*pending)
            pending = (tokens, batch)
        if pending is not None:
            collect(*pending)
        if self.multiproc:
            merged: dict[str, str] = {}
            for part in multihost.allgather_pyobj(out):
                merged.update(part)
            out = merged
        return out

    def evaluate(self, params, results_json: str = "") -> dict[str, Any]:
        """generate + score; optionally write the results json.

        Multi-host: only process 0 runs the metric scorers (pure host
        compute on inputs every process already holds); the metrics dict is
        broadcast so the return value is identical everywhere."""
        with obs.span("eval", split=self.ds.split):
            captions = self.generate(params)
            metrics = None
            if not self.multiproc or jax.process_index() == 0:
                gts = {
                    vid: list(caps) for vid, caps in self.ds.gts_pool().items()
                }
                res = {vid: [captions[vid]] for vid in captions}
                with obs.span("eval.score"):
                    metrics = self._scorer.score(gts, res)
            if self.multiproc:
                metrics = multihost.broadcast_pyobj(metrics)
        result = {"split": self.ds.split, "metrics": metrics, "captions": captions}
        if results_json and self.multiproc and jax.process_index() != 0:
            # shared-filesystem contract (same as checkpointing): N identical
            # concurrent writers can corrupt the file — process 0 writes
            results_json = ""
        if results_json:
            os.makedirs(os.path.dirname(results_json) or ".", exist_ok=True)
            with open(results_json, "w") as f:
                json.dump(result, f, indent=2, default=float)
        return result


def evaluate_split(model, params, dataset, cfg: EvalConfig | None = None,
                   batch_size: int = 32, results_json: str = "",
                   mesh: Mesh | None = None) -> dict[str, Any]:
    return Evaluator(model, dataset, cfg, batch_size, mesh=mesh).evaluate(
        params, results_json
    )
