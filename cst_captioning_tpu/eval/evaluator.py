"""Beam-search evaluation + COCO-style metric report (BASELINE config 5).

Reference flow (SURVEY.md §3.3): load checkpoint -> beam=5 decode the split ->
ids->words -> PTB tokenize -> BLEU/METEOR/ROUGE-L/CIDEr -> results json. Here
the decode is one jitted fixed-shape program per batch and the metrics are the
pure-Python scorers; results keep a schema in the reference's spirit:
``{"captions": {vid: text}, "metrics": {...}}``.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from cst_captioning_tpu.config.config import EvalConfig
from cst_captioning_tpu.data.batcher import Batcher
from cst_captioning_tpu.data.dataset import CaptionDataset
from cst_captioning_tpu.decoding import beam_search, greedy_decode
from cst_captioning_tpu.metrics.scorer import CaptionScorer
from cst_captioning_tpu.train.mesh import batch_sharding
from cst_captioning_tpu.train.steps import batch_arrays


class Evaluator:
    """With a ``mesh``, the decode is shard_map-parallel: every device
    beam-decodes its batch shard, and the generated token ids are gathered
    back to the host when the global output array is read (the SURVEY.md §5
    dist-comm row's eval-time gather). ``valid``-row filtering is unchanged,
    so multi-device eval produces the exact single-device captions (pinned
    by tests/test_ckpt_eval.py)."""

    def __init__(
        self,
        model,
        dataset: CaptionDataset,
        cfg: EvalConfig | None = None,
        batch_size: int = 32,
        mesh: Mesh | None = None,
    ):
        self.model = model
        self.ds = dataset
        self.cfg = cfg or EvalConfig()
        self.mesh = mesh
        if mesh is not None:
            # every batch size shards: round up to the next device multiple —
            # the Batcher wrap-pads to the (static) batch size and marks the
            # extra rows invalid, so generate() drops them and the captions
            # stay exactly the single-device ones (VERDICT r2 next #5)
            n = mesh.devices.size
            if batch_size % n:
                padded = -(-batch_size // n) * n
                # warning level: visible under the default root-logger config
                logging.getLogger(__name__).warning(
                    "eval batch_size %d -> %d (next multiple of %d devices; "
                    "wrap-padded rows are masked out)", batch_size, padded, n,
                )
                batch_size = padded
        self.batcher = Batcher(
            dataset, batch_size=batch_size, max_len=self.cfg.max_len, mode="video"
        )
        W, T, lp = self.cfg.beam_size, self.cfg.max_len, self.cfg.length_penalty
        ml = self.cfg.min_len

        if W > 1:
            decode = lambda p, f, m: beam_search(
                model, p, f, m, beam_size=W, max_len=T, min_len=ml,
                length_penalty=lp,
            )[0]
        else:
            decode = lambda p, f, m: greedy_decode(
                model, p, f, m, max_len=T, min_len=ml
            )[0]
        if mesh is not None:
            decode = jax.shard_map(
                decode,
                mesh=mesh,
                in_specs=(P(), P("data"), P("data")),
                out_specs=P("data"),
                # decode is collective-free; see make_parallel_rl_decode
                check_vma=False,
            )
        self._decode = jax.jit(decode)

    def generate(self, params) -> dict[str, str]:
        """Decode every video of the split -> {video_id: caption string}."""
        out: dict[str, str] = {}
        sharding = batch_sharding(self.mesh) if self.mesh is not None else None
        for batch in self.batcher.epoch(shuffle=False):
            feats, masks, *_ = batch_arrays(batch)
            if sharding is not None:
                feats, masks = jax.device_put((feats, masks), sharding)
            tokens = np.asarray(self._decode(params, feats, masks))
            for i, ok in enumerate(batch.valid):
                if ok:
                    out[batch.video_ids[i]] = self.ds.vocab.decode(tokens[i])
        return out

    def evaluate(self, params, results_json: str = "") -> dict[str, Any]:
        """generate + score; optionally write the results json."""
        captions = self.generate(params)
        gts = {vid: list(caps) for vid, caps in self.ds.gts_pool().items()}
        res = {vid: [captions[vid]] for vid in captions}
        scorer = CaptionScorer(metrics=self.cfg.metrics)
        metrics = scorer.score(gts, res)
        result = {"split": self.ds.split, "metrics": metrics, "captions": captions}
        if results_json:
            os.makedirs(os.path.dirname(results_json) or ".", exist_ok=True)
            with open(results_json, "w") as f:
                json.dump(result, f, indent=2, default=float)
        return result


def evaluate_split(model, params, dataset, cfg: EvalConfig | None = None,
                   batch_size: int = 32, results_json: str = "",
                   mesh: Mesh | None = None) -> dict[str, Any]:
    return Evaluator(model, dataset, cfg, batch_size, mesh=mesh).evaluate(
        params, results_json
    )
