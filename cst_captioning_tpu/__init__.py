"""cst_captioning_tpu — a TPU-native video-captioning training framework.

A from-scratch JAX/XLA/Flax rebuild of the capabilities of
``AislingGui/cst_captioning`` (consensus-based sequence training for video
captioning, Phan et al. 2017, arXiv:1712.09532):

- pre-extracted feature loading (ResNet-152 / C3D / arbitrary modalities) for
  MSVD and MSR-VTT style datasets,
- mean-pool and temporal-attention encoders + an LSTM caption decoder as
  jit-compiled Flax modules,
- masked / consensus-weighted cross-entropy (XE / WXE) training,
- a self-critical RL phase (greedy baseline, K Monte-Carlo rollouts, CIDEr-D /
  BLEU4 consensus rewards, REINFORCE gradients) with the device work fused into
  single XLA-traced programs,
- beam-search evaluation with COCO-style metrics (pure Python — no JVM),
- data-parallel training over ICI via ``jax.sharding.Mesh`` + ``shard_map``.

The reference mount was unreadable during the survey (see SURVEY.md §0); parity
claims are therefore cited against the CST paper and BASELINE.json rather than
reference file:line.
"""

__version__ = "0.1.0"
