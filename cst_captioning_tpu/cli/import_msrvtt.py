"""MSR-VTT import CLI: standard distribution -> framework dataset files.

    python -m cst_captioning_tpu.cli.import_msrvtt \\
        --videodatainfo videodatainfo.json --out-dir data/msrvtt \\
        --feature resnet=/path/to/resnet_feats.h5 \\
        --feature c3d=/path/to/c3d_npy_dir

Feature sources are either an h5 keyed by video id or a directory of
``<video_id>.npy`` arrays. The output is consumable directly:

    python -m cst_captioning_tpu.cli.train --preset msrvtt_xe_attention \\
        --info-json data/msrvtt/info.json \\
        --feature resnet=data/msrvtt/resnet.h5 --feature c3d=data/msrvtt/c3d.h5 \\
        --set "data__cider_df='data/msrvtt/cider_df.pkl'"
"""

from __future__ import annotations

import argparse
import json

from cst_captioning_tpu.data.importers import import_msrvtt


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--videodatainfo", required=True,
                   help="MSR-VTT videodatainfo.json")
    p.add_argument("--out-dir", required=True)
    p.add_argument(
        "--feature",
        action="append",
        default=[],
        metavar="NAME=SOURCE",
        help="modality source (h5 keyed by video id, or dir of <vid>.npy)",
    )
    p.add_argument("--min-word-count", type=int, default=2)
    p.add_argument("--no-weights", action="store_true",
                   help="skip consensus (WXE) weight computation")
    p.add_argument("--no-df", action="store_true",
                   help="skip CIDEr df computation")
    args = p.parse_args(argv)

    features = {}
    for pair in args.feature:
        name, sep, src = pair.partition("=")
        if not sep:
            raise SystemExit(f"--feature expects NAME=SOURCE, got {pair!r}")
        features[name] = src

    paths = import_msrvtt(
        args.videodatainfo,
        args.out_dir,
        features=features,
        min_word_count=args.min_word_count,
        write_consensus_weights=not args.no_weights,
        write_cider_df=not args.no_df,
    )
    print(json.dumps(paths, indent=2))


if __name__ == "__main__":
    main()
