"""Shared CLI plumbing: preset loading + typed overrides + dataset wiring."""

from __future__ import annotations

import argparse
import ast

from cst_captioning_tpu.config import ExperimentConfig, get_preset
from cst_captioning_tpu.data.dataset import CaptionDataset


def add_common_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--preset", required=True, help="named experiment preset")
    p.add_argument("--info-json", default="", help="dataset info.json path")
    p.add_argument(
        "--feature",
        action="append",
        default=[],
        metavar="NAME=PATH",
        help="modality h5 file, repeatable (e.g. resnet=feats/resnet.h5)",
    )
    p.add_argument(
        "--set",
        action="append",
        default=[],
        metavar="SECTION__FIELD=VALUE",
        help="config override, repeatable (e.g. train__epochs=10)",
    )
    p.add_argument("--log-jsonl", default="", help="structured event log path")
    p.add_argument(
        "--profile",
        default="",
        metavar="DIR",
        help="write a jax.profiler trace of training steps to DIR",
    )
    p.add_argument(
        "--obs",
        default="",
        metavar="DIR",
        help="enable the observability subsystem and write the run's "
             "events.jsonl / trace.json / metrics.prom to DIR "
             "(sets train.obs + train.obs_dir; report with "
             "`python -m cst_captioning_tpu.cli.obs_report DIR`)",
    )
    p.add_argument(
        "--debug-nans",
        action="store_true",
        help="enable the jax_debug_nans sanitizer (raises at the first NaN)",
    )


def parse_overrides(pairs: list[str]) -> dict:
    out = {}
    for pair in pairs:
        key, sep, raw = pair.partition("=")
        if not sep:
            raise SystemExit(f"--set expects SECTION__FIELD=VALUE, got {pair!r}")
        try:
            out[key] = ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            out[key] = raw  # plain string
    return out


def load_config(args: argparse.Namespace) -> ExperimentConfig:
    cfg = get_preset(args.preset)
    overrides = parse_overrides(args.set)
    if getattr(args, "profile", ""):
        overrides["train__profile_dir"] = args.profile
    if getattr(args, "obs", ""):
        overrides["train__obs"] = True
        overrides["train__obs_dir"] = args.obs
    if getattr(args, "debug_nans", False):
        overrides["train__debug_nans"] = True
    if overrides:
        cfg = cfg.override(**overrides)
    return cfg


def feature_map(args: argparse.Namespace) -> dict[str, str]:
    out = {}
    for pair in args.feature:
        name, sep, path = pair.partition("=")
        if not sep:
            raise SystemExit(f"--feature expects NAME=PATH, got {pair!r}")
        out[name] = path
    return out


def open_dataset(args: argparse.Namespace, cfg: ExperimentConfig,
                 split: str) -> CaptionDataset:
    if not args.info_json:
        raise SystemExit("--info-json is required for real data runs")
    feats = feature_map(args)
    missing = [n for n in cfg.model.modality_names if n not in feats]
    if missing:
        raise SystemExit(
            f"preset {cfg.name!r} needs --feature for modalities: {missing}"
        )
    return CaptionDataset(
        args.info_json,
        {n: feats[n] for n in cfg.model.modality_names},
        split=split,
        max_frames=cfg.model.max_frames,
        consensus_weights=cfg.data.consensus_weights,
        cache_features=cfg.data.cache_features,
    )
