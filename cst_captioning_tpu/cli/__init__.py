"""CLI entry points (the reference's ``train.py`` / ``test.py`` / Makefile).

Usage:
    python -m cst_captioning_tpu.cli.train --preset msrvtt_xe_attention \\
        --info-json data/info.json --feature resnet=data/resnet.h5 \\
        --feature c3d=data/c3d.h5 --set train__epochs=50
    python -m cst_captioning_tpu.cli.eval --preset msrvtt_eval_beam5 ...
    python -m cst_captioning_tpu.cli.preprocess --captions raw.json --out-dir data/
"""
