"""Preprocessing CLI: captions json -> info.json + consensus weights + df.

Reference equivalent: the standalone vocab/tokenize/consensus/df scripts
(SURVEY.md §2 row 3). Input format:

    {"videos": [{"id": "video0", "split": "train",
                 "captions": ["a man is cooking", ...]}, ...]}
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

from cst_captioning_tpu.data.preprocess import (
    build_info_json,
    compute_cider_df,
    compute_consensus_weights,
    tokenize_captions,
)


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--captions", required=True, help="raw captions json")
    p.add_argument("--out-dir", required=True)
    p.add_argument("--min-count", type=int, default=1, help="vocab threshold")
    args = p.parse_args(argv)

    with open(args.captions) as f:
        raw = json.load(f)
    caps = {v["id"]: v["captions"] for v in raw["videos"]}
    splits = {v["id"]: v.get("split", "train") for v in raw["videos"]}
    os.makedirs(args.out_dir, exist_ok=True)

    info_path = os.path.join(args.out_dir, "info.json")
    build_info_json(info_path, caps, splits, min_count=args.min_count)

    train_caps = {vid: c for vid, c in caps.items() if splits[vid] == "train"}
    tokenized = tokenize_captions(train_caps)
    df = compute_cider_df(tokenized)
    df.save(os.path.join(args.out_dir, "cider_df.pkl"))

    weights = compute_consensus_weights(tokenized, df=df)
    np.savez(os.path.join(args.out_dir, "consensus_weights.npz"), **weights)
    print(f"wrote info.json, cider_df.pkl, consensus_weights.npz to {args.out_dir}")


if __name__ == "__main__":
    main()
