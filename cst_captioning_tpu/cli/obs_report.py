"""Run report CLI: phase breakdown + resilience summary from an obs run dir.

    python -m cst_captioning_tpu.cli.obs_report <run_dir> [--json]
    python -m cst_captioning_tpu.cli.obs_report --postmortem <bundle> [--json]

``<run_dir>`` is the directory ``train.obs_dir`` (or ``--obs``) pointed a
run at — it must contain the run's ``events.jsonl``. Prints the phase table
(per-phase totals, self-time %-of-wall-clock, mfu with its FLOPs-source tag,
p50/p95/max), the decode early-exit summary (scan depth vs the T budget),
the serving funnel + SLO burn rates, and the resilience summary (nan-skips,
rollbacks, retries, chaos faults).

``--postmortem`` renders a flight-recorder bundle
(``postmortem_*/`` under the run dir, obs/recorder.py) instead: manifest
verification, the trip context, and the ring as a step timeline with
anomaly verdicts inline. Pure stdlib — no jax import, safe anywhere
(scripts/lint.sh runs both modes as smoke checks against committed
fixtures).
"""

from __future__ import annotations

import argparse
import json
import sys

from cst_captioning_tpu.obs.report import (
    load_postmortem,
    render_postmortem,
    render_report,
    report_run,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="obs_report",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("run_dir", nargs="?", default=None,
                   help="obs run directory (holds events.jsonl)")
    p.add_argument("--postmortem", metavar="BUNDLE", default=None,
                   help="render a flight-recorder postmortem bundle dir "
                        "instead of a run dir")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the machine-readable report on stdout")
    args = p.parse_args(argv)
    if args.postmortem is None and args.run_dir is None:
        p.error("a run_dir (or --postmortem BUNDLE) is required")
    try:
        if args.postmortem is not None:
            pm = load_postmortem(args.postmortem)
            if args.as_json:
                print(json.dumps(pm, indent=2, default=float))
            else:
                print(render_postmortem(pm))
            return 0
        report = report_run(args.run_dir)
    except FileNotFoundError as e:
        print(f"obs_report: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(report, indent=2, default=float))
    else:
        print(render_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
