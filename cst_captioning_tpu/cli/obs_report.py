"""Run report CLI: phase breakdown + resilience summary from an obs run dir.

    python -m cst_captioning_tpu.cli.obs_report <run_dir> [--json]
    python -m cst_captioning_tpu.cli.obs_report --postmortem <bundle> [--json]
    python -m cst_captioning_tpu.cli.obs_report --postmortem <run_dir> [--json]
    python -m cst_captioning_tpu.cli.obs_report --postmortem <run_dir> --list

``<run_dir>`` is the directory ``train.obs_dir`` (or ``--obs``) pointed a
run at — it must contain the run's ``events.jsonl``. Prints the phase table
(per-phase totals, self-time %-of-wall-clock, mfu with its FLOPs-source tag,
p50/p95/max), the decode early-exit summary (scan depth vs the T budget),
the serving funnel + SLO burn rates, and the resilience summary (nan-skips,
rollbacks, retries, chaos faults).

``--postmortem`` renders flight-recorder evidence (obs/recorder.py)
instead. Pointed at a single bundle dir (it has a ``meta.json``) it renders
that bundle: manifest verification, the trip context, and the ring as a
step timeline with anomaly verdicts inline. Pointed at a RUN dir it merges
the latest bundle of every process (``postmortem_*`` plus
``proc<k>/postmortem_*``) into one skew-corrected fleet timeline — one
column per host, trip marker, straggler/victim attribution, DCN stalls
interleaved (obs/fleet.py). ``--list`` enumerates every bundle under the
run dir with its trip kind + step. Pure stdlib — no jax import, safe
anywhere (scripts/lint.sh runs these modes as smoke checks against
committed fixtures).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from cst_captioning_tpu.obs.fleet import (
    list_bundles,
    merge_bundles,
    render_fleet,
)
from cst_captioning_tpu.obs.report import (
    load_postmortem,
    render_postmortem,
    render_report,
    report_run,
)


def _render_listing(rows: list[dict]) -> str:
    lines = []
    hdr = (f"{'proc':>5} {'reason':<28} {'phase':<6} {'step':>8} "
           f"{'ring':>5} {'ok':<3} bundle")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for r in rows:
        step = r["step"] if r["step"] is not None else ""
        lines.append(
            f"{r['proc']:>5} {r['reason']:<28} {r['phase'] or '':<6} "
            f"{step:>8} {r['ring_steps']:>5} "
            f"{'yes' if r['verified'] else 'NO':<3} {r['bundle']}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="obs_report",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("run_dir", nargs="?", default=None,
                   help="obs run directory (holds events.jsonl)")
    p.add_argument("--postmortem", metavar="DIR", default=None,
                   help="render a flight-recorder postmortem bundle dir, or "
                        "merge every proc's latest bundle when DIR is a run "
                        "dir (fleet timeline)")
    p.add_argument("--list", action="store_true", dest="list_bundles",
                   help="with --postmortem RUN_DIR: enumerate all bundles "
                        "(proc, trip kind, step, integrity) instead of "
                        "merging")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the machine-readable report on stdout")
    args = p.parse_args(argv)
    if args.postmortem is None and args.run_dir is None:
        p.error("a run_dir (or --postmortem DIR) is required")
    if args.list_bundles and args.postmortem is None:
        p.error("--list requires --postmortem RUN_DIR")
    try:
        if args.postmortem is not None:
            if args.list_bundles:
                rows = list_bundles(args.postmortem)
                if not rows:
                    print(f"obs_report: no postmortem bundles under "
                          f"{args.postmortem!r}", file=sys.stderr)
                    return 2
                if args.as_json:
                    print(json.dumps(rows, indent=2, default=float))
                else:
                    print(_render_listing(rows))
                return 0
            if os.path.exists(os.path.join(args.postmortem, "meta.json")):
                # a single bundle dir: the per-process render (back-compat)
                pm = load_postmortem(args.postmortem)
                if args.as_json:
                    print(json.dumps(pm, indent=2, default=float))
                else:
                    print(render_postmortem(pm))
                return 0
            # a run dir: merge every proc's latest bundle (obs/fleet.py)
            fleet = merge_bundles(args.postmortem)
            if args.as_json:
                print(json.dumps(fleet, indent=2, default=float))
            else:
                print(render_fleet(fleet))
            return 0
        report = report_run(args.run_dir)
    except FileNotFoundError as e:
        print(f"obs_report: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(report, indent=2, default=float))
    else:
        print(render_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
