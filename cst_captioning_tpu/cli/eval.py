"""Evaluation CLI: beam-search decode a split + COCO-style metric table.

Reference equivalent: ``python test.py --beam_size 5 --checkpoint ...``
(SURVEY.md §3.3, BASELINE config 5).
"""

from __future__ import annotations

import argparse
import json

import jax

from cst_captioning_tpu.cli.common import add_common_args, load_config, open_dataset
from cst_captioning_tpu.ckpt import load_params
from cst_captioning_tpu.eval.evaluator import evaluate_split
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.train.mesh import make_mesh, replicate
from cst_captioning_tpu.train.steps import batch_arrays
from cst_captioning_tpu.data.batcher import Batcher


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    add_common_args(p)
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--ckpt-name", default="best")
    p.add_argument("--split", default="")
    p.add_argument("--results-json", default="results.json")
    args = p.parse_args(argv)

    from cst_captioning_tpu import obs
    from cst_captioning_tpu.train import multihost

    multihost.initialize()  # no-op unless the JAX_* cluster env vars are set
    cfg = load_config(args)
    split = args.split or cfg.eval.split
    if cfg.train.obs:
        # standalone eval runs get their own obs stream (the Evaluator's
        # "eval" spans + prefetchless decode metrics land here); report it
        # with cli.obs_report like a training run
        obs_dir = cfg.train.obs_dir or "obs_eval"
        if jax.process_index() != 0:
            obs_dir = f"{obs_dir}/proc{jax.process_index()}"
        obs.configure(obs_dir, run=f"{cfg.name}-eval-{split}")
    ds = open_dataset(args, cfg, split)

    model = CaptionModel(cfg.model)
    # template params from a throwaway init on one batch
    sample = next(iter(
        Batcher(ds, batch_size=2, max_len=cfg.model.max_len, mode="video").epoch(False)
    ))
    feats, masks, labels, *_ = batch_arrays(sample)
    template = model.init(jax.random.key(0), feats, masks, labels)
    params = load_params(args.ckpt_dir, args.ckpt_name, template)

    # shard the decode over all visible devices; the Evaluator wrap-pads any
    # indivisible batch size up to a device multiple, so no silent fallback.
    # seq_devices>1 carries the training layout into eval: frames shard over
    # 'seq' (the long-context case where one device can't hold the frame axis)
    n_dev = cfg.mesh.num_devices or len(jax.devices())
    mesh = None
    if n_dev > 1 or cfg.mesh.seq_devices > 1:
        mesh = make_mesh(cfg.mesh.num_devices,
                         seq_devices=cfg.mesh.seq_devices,
                         mp_devices=cfg.mesh.mp_devices)
        params = replicate(mesh, params)

    # multi-host: every process computes the full result (the caption gather
    # is collective), but only process 0 writes the shared results file
    results_json = args.results_json if jax.process_index() == 0 else ""
    try:
        result = evaluate_split(
            model, params, ds, cfg.eval,
            batch_size=cfg.data.batch_size, results_json=results_json,
            mesh=mesh,
        )
    finally:
        obs.shutdown()
    if jax.process_index() == 0:
        print(json.dumps(result["metrics"], indent=2, default=float))


if __name__ == "__main__":
    main()
