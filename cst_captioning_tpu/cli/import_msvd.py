"""MSVD import CLI: standard distribution -> framework dataset files.

    python -m cst_captioning_tpu.cli.import_msvd \\
        --corpus video_corpus.csv --mapping youtube_mapping.txt \\
        --out-dir data/msvd \\
        --feature resnet=/path/to/resnet_feats.h5

``--corpus`` is the MSR Video Description Corpus csv (``VideoID, Start, End,
..., Language, Description``; only English rows are used) or a plain-text
``<clip_id> <caption>``-per-line file. ``--mapping`` (optional) is the
conventional ``youtube_mapping.txt`` fixing the canonical 1970-clip order; the
split is then the standard 1200 train / 100 val / 670 test (override with
``--n-train`` / ``--n-val``). This is BASELINE config 1's ingestion path
(SURVEY.md §2 row 3, §3.4); the output is consumable directly:

    python -m cst_captioning_tpu.cli.train --preset msvd_xe_meanpool \\
        --info-json data/msvd/info.json \\
        --feature resnet=data/msvd/resnet.h5
"""

from __future__ import annotations

import argparse
import json

from cst_captioning_tpu.data.importers import (
    MSVD_NUM_TRAIN,
    MSVD_NUM_VAL,
    import_msvd,
)


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--corpus", required=True,
                   help="MSVD caption csv or '<clip_id> <caption>' text file")
    p.add_argument("--mapping", default=None,
                   help="youtube_mapping.txt ('<clip_id> vid<N>' per line)")
    p.add_argument("--out-dir", required=True)
    p.add_argument(
        "--feature",
        action="append",
        default=[],
        metavar="NAME=SOURCE",
        help="modality source (h5 keyed by clip id, or dir of <clip_id>.npy)",
    )
    p.add_argument("--n-train", type=int, default=MSVD_NUM_TRAIN)
    p.add_argument("--n-val", type=int, default=MSVD_NUM_VAL)
    p.add_argument("--min-word-count", type=int, default=2)
    p.add_argument("--no-weights", action="store_true",
                   help="skip consensus (WXE) weight computation")
    p.add_argument("--no-df", action="store_true",
                   help="skip CIDEr df computation")
    args = p.parse_args(argv)

    features = {}
    for pair in args.feature:
        name, sep, src = pair.partition("=")
        if not sep:
            raise SystemExit(f"--feature expects NAME=SOURCE, got {pair!r}")
        features[name] = src

    paths = import_msvd(
        args.corpus,
        args.out_dir,
        mapping=args.mapping,
        features=features,
        n_train=args.n_train,
        n_val=args.n_val,
        min_word_count=args.min_word_count,
        write_consensus_weights=not args.no_weights,
        write_cider_df=not args.no_df,
    )
    print(json.dumps(paths, indent=2))


if __name__ == "__main__":
    main()
