"""Training CLI: XE phase and/or CST-RL phase per the preset.

Reference equivalent: ``python train.py --feats resnet c3d --loss xe ...``
driven by Makefile recipes (SURVEY.md §3.1). The two-stage paper recipe is

    # stage 1: cross-entropy
    python -m cst_captioning_tpu.cli.train --preset msrvtt_xe_attention ...
    # stage 2: CST fine-tune from the best XE checkpoint
    python -m cst_captioning_tpu.cli.train --preset msrvtt_cst_consensus \\
        --set rl__init_from=checkpoints/msrvtt_xe_attention ...
"""

from __future__ import annotations

import argparse

from cst_captioning_tpu.cli.common import add_common_args, load_config, open_dataset
from cst_captioning_tpu.train import multihost
from cst_captioning_tpu.train.trainer import Trainer


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    add_common_args(p)
    p.add_argument("--skip-xe", action="store_true", help="run only the RL phase")
    args = p.parse_args(argv)
    # multi-host: no-op unless JAX_COORDINATOR_ADDRESS etc. are set
    multihost.initialize()
    if args.log_jsonl and multihost.is_multiprocess():
        import jax

        if jax.process_index() != 0:
            # one JSONL per process: append-interleaving on a shared path
            # would corrupt per-epoch analysis
            args.log_jsonl = f"{args.log_jsonl}.proc{jax.process_index()}"

    cfg = load_config(args)
    train_ds = open_dataset(args, cfg, "train")
    try:
        val_ds = open_dataset(args, cfg, "val")
    except ValueError as e:
        # only a genuinely absent val split is optional; every other dataset
        # error (dim mismatch, missing h5 keys, ...) must surface
        if "no videos for split" not in str(e):
            raise
        val_ds = None

    # the Trainer configures the obs recorder from cfg.train.obs; the CLI
    # owns finalization so a crashed/finished run still gets its trace.json
    # + final metrics snapshot (obs.shutdown is a no-op when obs is off)
    from cst_captioning_tpu import obs

    try:
        trainer = Trainer(cfg, train_ds, val_ds, log_path=args.log_jsonl)
        if not args.skip_xe:
            trainer.train_xe()
        if cfg.rl.enabled:
            if cfg.rl.init_from:
                trainer.load_params_from(cfg.rl.init_from, "best")
            trainer.train_rl()
    finally:
        obs.shutdown()


if __name__ == "__main__":
    main()
