"""Background host->device prefetch.

The reference moves each batch with ``.cuda()`` inline in the hot loop
(SURVEY.md §3.1); here a daemon thread stages upcoming batches into HBM with
``jax.device_put`` while the current step runs, hiding PCIe/host latency —
the flax ``prefetch_to_device`` pattern, generalized to our Batch pytrees and
to explicit shardings (so prefetch lands per-device shards directly when a
Mesh is in play).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator

import jax


def prefetch_to_device(
    it: Iterable[Any],
    size: int = 2,
    sharding: Any | None = None,
    transform: Callable[[Any], Any] | None = None,
    place: bool = True,
    stop_event: threading.Event | None = None,
) -> Iterator[Any]:
    """Iterate ``it``, staging ``size`` elements ahead onto device.

    ``transform`` runs on the host thread before the transfer (e.g. Batch ->
    device-ready pytree); ``sharding`` is forwarded to ``jax.device_put`` so
    multi-device layouts are materialized without a separate reshard.

    ``place=False`` skips the internal ``device_put`` — for items that mix
    device arrays with host-only leaves (e.g. video-id strings for the RL
    reward), ``transform`` does its own placement of the array part.

    ``stop_event`` (optional) makes the staging thread quit before its next
    collate/transfer once set — the preemption path: when SIGTERM lands, the
    grace window should go to the checkpoint fsync, not to prefetching
    batches that will never run. Items already staged are still yielded.
    """
    if not place:
        _place = lambda x: x
    elif sharding is not None:
        _place = lambda x: jax.device_put(x, sharding)
    else:
        _place = jax.device_put

    if size < 1:
        for x in it:
            x = transform(x) if transform is not None else x
            yield _place(x)
        return

    q: queue.Queue = queue.Queue(maxsize=size)
    _END = object()
    err: list[BaseException] = []
    stop = threading.Event()

    def _put(x) -> bool:
        """put that gives up when the consumer abandoned the generator."""
        while not stop.is_set():
            try:
                q.put(x, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for x in it:
                if stop_event is not None and stop_event.is_set():
                    return  # preempting: yield only what's already staged
                x = transform(x) if transform is not None else x
                x = _place(x)
                if not _put(x):
                    return  # consumer gone: drop staged work, free buffers
        except BaseException as e:  # propagate into the consumer
            err.append(e)
        finally:
            _put(_END)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            x = q.get()
            if x is _END:
                if err:
                    raise err[0]
                return
            yield x
    finally:
        # consumer broke out early (or errored): unblock and retire the worker
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=2.0)
