"""Background host->device prefetch.

The reference moves each batch with ``.cuda()`` inline in the hot loop
(SURVEY.md §3.1); here a daemon thread stages upcoming batches into HBM with
``jax.device_put`` while the current step runs, hiding PCIe/host latency —
the flax ``prefetch_to_device`` pattern, generalized to our Batch pytrees and
to explicit shardings (so prefetch lands per-device shards directly when a
Mesh is in play).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator

import jax

from cst_captioning_tpu import obs
from cst_captioning_tpu.resilience import chaos
from cst_captioning_tpu.resilience.chaos import TransientIOError
from cst_captioning_tpu.resilience.retry import RetryPolicy, retry_call

# transient H2D transfer failures (a torn DMA / chaos partial_h2d) are
# redone in place under a tight budget: the staged numpy batch is still on
# host, so re-placing it is always safe. Anything non-transient propagates.
_H2D_RETRY = RetryPolicy(
    max_attempts=3, base_delay=0.01, max_delay=0.1, budget=1.0,
    retry_on=(TransientIOError,),
)


def prefetch_to_device(
    it: Iterable[Any],
    size: int = 2,
    sharding: Any | None = None,
    transform: Callable[[Any], Any] | None = None,
    place: bool = True,
    stop_event: threading.Event | None = None,
    stall_warn_s: float = 5.0,
) -> Iterator[Any]:
    """Iterate ``it``, staging ``size`` elements ahead onto device.

    ``transform`` runs on the host thread before the transfer (e.g. Batch ->
    device-ready pytree); ``sharding`` is forwarded to ``jax.device_put`` so
    multi-device layouts are materialized without a separate reshard.

    ``place=False`` skips the internal ``device_put`` — for items that mix
    device arrays with host-only leaves (e.g. video-id strings for the RL
    reward), ``transform`` does its own placement of the array part.

    ``stop_event`` (optional) makes the staging thread quit before its next
    collate/transfer once set — the preemption path: when SIGTERM lands, the
    grace window should go to the checkpoint fsync, not to prefetching
    batches that will never run. Items already staged are still yielded.

    ``stall_warn_s``: when the consumer waits longer than this on an empty
    queue while the worker is still alive (a wedged prefetch thread, a
    stalled filesystem read), a structured ``prefetch_stall`` event and the
    ``resilience.prefetch_stall`` counter fire once per stall episode —
    starvation becomes diagnosable instead of looking like slow compute.
    The consumer keeps waiting (the worker may unwedge); 0 disables.
    """
    if not place:
        _place = lambda x: x
    elif sharding is not None:
        _place = lambda x: jax.device_put(x, sharding)
    else:
        _place = jax.device_put

    # per-batch staging metrics: stage latency (collate+transfer, on the
    # worker thread's own trace track), batches staged, and the queue depth
    # the consumer sees — depth pinned at 0 is the "input-bound" smoking gun
    # next to a fat xe.epoch/rl.epoch self-time in the run report
    stage_hist = obs.histogram("prefetch.stage_seconds")
    staged = obs.counter("prefetch.batches")
    depth = obs.gauge("prefetch.queue_depth")

    def _h2d(x):
        def put():
            chaos.visit("prefetch.h2d")
            return _place(x)

        return retry_call(
            put,
            policy=_H2D_RETRY,
            on_retry=lambda info: (
                obs.counter("resilience.h2d_retry").inc(),
                obs.event("h2d_retry", **info),
            ),
        )

    def _stage(x):
        t0 = time.perf_counter()
        with obs.span("prefetch.stage"):
            x = chaos.visit("prefetch.stage", x)
            x = transform(x) if transform is not None else x
            x = _h2d(x)
        stage_hist.observe(time.perf_counter() - t0)
        staged.inc()
        return x

    if size < 1:
        for x in it:
            yield _stage(x)
        return

    q: queue.Queue = queue.Queue(maxsize=size)
    _END = object()
    err: list[BaseException] = []
    stop = threading.Event()

    def _put(x) -> bool:
        """put that gives up when the consumer abandoned the generator."""
        while not stop.is_set():
            try:
                q.put(x, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for x in it:
                if stop_event is not None and stop_event.is_set():
                    return  # preempting: yield only what's already staged
                x = _stage(x)
                if not _put(x):
                    return  # consumer gone: drop staged work, free buffers
                depth.set(q.qsize())
        except BaseException as e:  # propagate into the consumer
            err.append(e)
        finally:
            _put(_END)

    def _get_with_stall_watchdog():
        """q.get that reports (once per episode) when the worker starves the
        step loop past ``stall_warn_s`` — the wedged-prefetch signature."""
        if stall_warn_s <= 0:
            return q.get()
        reported = False
        waited = 0.0
        while True:
            try:
                return q.get(timeout=stall_warn_s)
            except queue.Empty:
                waited += stall_warn_s
                if not reported:
                    reported = True
                    obs.counter("resilience.prefetch_stall").inc()
                    obs.event(
                        "prefetch_stall",
                        waited_s=round(waited, 3),
                        queue_depth=q.qsize(),
                        worker_alive=t.is_alive(),
                    )

    t = threading.Thread(target=worker, daemon=True, name="prefetch")
    t.start()
    try:
        while True:
            x = _get_with_stall_watchdog()
            # depth as the CONSUMER sees it post-get: 0 here while the
            # worker is mid-stage means the step loop is input-bound
            depth.set(q.qsize())
            if x is _END:
                if err:
                    raise err[0]
                return
            yield x
    finally:
        # consumer broke out early (or errored): unblock and retire the worker
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=2.0)
