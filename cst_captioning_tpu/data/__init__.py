"""Data layer: vocab, feature stores, batching, prefetch, preprocessing.

Replaces the reference's ``dataloader.py`` + preprocessing scripts
(SURVEY.md §2 rows 2-3) with a TPU-first pipeline: h5 multi-modality feature
reading on the host, fixed-shape padded batches (static shapes for XLA), and a
background prefetcher that lands per-device shards in HBM ahead of the step.
"""

from cst_captioning_tpu.data.vocab import Vocab
from cst_captioning_tpu.data.dataset import CaptionDataset, VideoRecord
from cst_captioning_tpu.data.batcher import Batch, Batcher
from cst_captioning_tpu.data.synthetic import make_synthetic_dataset
from cst_captioning_tpu.data.prefetch import prefetch_to_device
from cst_captioning_tpu.data.preprocess import (
    build_vocab,
    tokenize_captions,
    compute_consensus_weights,
    compute_cider_df,
)
from cst_captioning_tpu.data.importers import import_msrvtt, import_msvd

__all__ = [
    "import_msrvtt",
    "import_msvd",
    "Vocab",
    "CaptionDataset",
    "VideoRecord",
    "Batch",
    "Batcher",
    "make_synthetic_dataset",
    "prefetch_to_device",
    "build_vocab",
    "tokenize_captions",
    "compute_consensus_weights",
    "compute_cider_df",
]
