"""Caption dataset: h5 multi-modality features + json metadata.

Mirrors the reference's on-disk contract (SURVEY.md §3.4) with a cleaner
schema we own (the reference's exact h5 key names were unverifiable, §0):

- one h5 file per modality; dataset key = video id; value = [n_frames, dim]
  float array (mean-pooled modalities may have n_frames == 1),
- one ``info.json``: vocab table, per-video split + tokenized captions
  (both as id lists and raw strings, the latter feeding reward/eval pools),
- optional ``consensus_weights`` npz (WXE) and CIDEr df pickle-free npz (RL),
  produced by :mod:`cst_captioning_tpu.data.preprocess`.

All feature arrays are padded/truncated to ``max_frames`` on read and carry a
frame-validity mask, so every batch has static shapes for XLA.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

import numpy as np

from cst_captioning_tpu.data.vocab import Vocab

try:
    import h5py
except ImportError:  # pragma: no cover - h5py is baked into the image
    h5py = None


@dataclass
class VideoRecord:
    video_id: str
    split: str
    # tokenized captions as id lists (no BOS/EOS; added at batch time)
    caption_ids: list[list[int]] = field(default_factory=list)
    # raw tokenized caption strings (reward/eval reference pools)
    captions: list[str] = field(default_factory=list)
    # per-caption consensus weights (WXE), parallel to caption_ids
    weights: list[float] = field(default_factory=list)


class FeatureStore:
    """Lazy h5-backed frame features for one modality, padded to max_frames."""

    def __init__(self, path: str, max_frames: int, dim: int | None = None):
        if h5py is None:
            raise RuntimeError("h5py unavailable")
        self.path = path
        self.max_frames = max_frames
        self._h5 = h5py.File(path, "r")
        first = next(iter(self._h5))
        arr = self._h5[first]
        self.dim = int(dim if dim is not None else arr.shape[-1])

    def keys(self):
        return list(self._h5.keys())

    def get(self, video_id: str) -> tuple[np.ndarray, np.ndarray]:
        """-> (feats [max_frames, dim] f32, mask [max_frames] f32)."""
        raw = np.asarray(self._h5[video_id], dtype=np.float32)
        if raw.ndim == 1:
            raw = raw[None, :]
        n = min(raw.shape[0], self.max_frames)
        if raw.shape[0] > self.max_frames:
            # uniform temporal subsample instead of truncation: keeps coverage
            # of the whole clip when frame counts exceed the budget.
            idx = np.linspace(0, raw.shape[0] - 1, self.max_frames).round().astype(int)
            raw = raw[idx]
            n = self.max_frames
        feats = np.zeros((self.max_frames, self.dim), dtype=np.float32)
        feats[:n] = raw[:n]
        mask = np.zeros((self.max_frames,), dtype=np.float32)
        mask[:n] = 1.0
        return feats, mask

    def close(self):
        self._h5.close()


class CaptionDataset:
    """Videos of one split with their features, captions, and reward pools."""

    def __init__(
        self,
        info_json: str,
        feature_files: dict[str, str],
        split: str,
        max_frames: int = 60,
        consensus_weights: str = "",
        cache_features: bool = False,
    ):
        with open(info_json) as f:
            info = json.load(f)
        self.vocab = Vocab(info["vocab"])
        self.split = split
        self.records: list[VideoRecord] = []
        for v in info["videos"]:
            if v["split"] != split:
                continue
            if not v["caption_ids"]:
                raise ValueError(
                    f"video {v['id']!r} has no captions; every record needs at "
                    "least one (empty rows would produce all-PAD label rows)"
                )
            self.records.append(
                VideoRecord(
                    video_id=v["id"],
                    split=v["split"],
                    caption_ids=[list(map(int, c)) for c in v["caption_ids"]],
                    captions=[str(c) for c in v["captions"]],
                )
            )
        if not self.records:
            raise ValueError(f"no videos for split {split!r} in {info_json}")
        self.stores = {
            name: FeatureStore(path, max_frames=max_frames)
            for name, path in feature_files.items()
        }
        self.max_frames = max_frames
        self._gts_pool: dict[str, list[str]] | None = None
        # opt-in host-RAM feature cache (DataConfig.cache_features): h5 reads
        # are the host hot path on repeat epochs — with the cache, each
        # video's padded features are read once and every later epoch is a
        # dict lookup. Memory = n_videos * max_frames * sum(dims) * 4 bytes
        self._feat_cache: dict[str, dict] | None = {} if cache_features else None
        if consensus_weights:
            if not os.path.exists(consensus_weights):
                raise FileNotFoundError(
                    f"consensus_weights file not found: {consensus_weights}"
                )
            self._load_weights(consensus_weights)
        else:
            for r in self.records:
                r.weights = [1.0] * len(r.caption_ids)

    def _load_weights(self, path: str):
        """npz: one array per video id, parallel to its caption list."""
        data = np.load(path)
        for r in self.records:
            if r.video_id in data:
                w = np.asarray(data[r.video_id], dtype=np.float32)
                if len(w) != len(r.caption_ids):
                    raise ValueError(
                        f"weights/captions length mismatch for {r.video_id}"
                    )
                r.weights = [float(x) for x in w]
            else:
                r.weights = [1.0] * len(r.caption_ids)

    def __len__(self) -> int:
        return len(self.records)

    def features_for(self, video_id: str) -> dict[str, tuple[np.ndarray, np.ndarray]]:
        if self._feat_cache is not None:
            hit = self._feat_cache.get(video_id)
            if hit is None:
                hit = {
                    name: store.get(video_id)
                    for name, store in self.stores.items()
                }
                for f, m in hit.values():
                    # the same arrays are handed out on every hit: an
                    # in-place consumer would silently poison later epochs —
                    # make that an immediate ValueError instead
                    f.flags.writeable = False
                    m.flags.writeable = False
                self._feat_cache[video_id] = hit
            return hit
        return {name: store.get(video_id) for name, store in self.stores.items()}

    def gts_pool(self) -> dict[str, list[str]]:
        """video_id -> list of tokenized GT caption strings (reward/eval refs).

        Cached after the first call (records are immutable post-init); callers
        treat the returned pool as read-only.
        """
        if self._gts_pool is None:
            self._gts_pool = {r.video_id: list(r.captions) for r in self.records}
        return self._gts_pool

    def close(self):
        for s in self.stores.values():
            s.close()
