"""Vocabulary: word <-> id mapping with fixed special tokens.

The reference keeps an ``ix_to_word`` dict inside its info json and reserves
index 0 for the pad/end token (SURVEY.md §3.4). Here the special ids are fixed
framework-wide (PAD=0, BOS=1, EOS=2, UNK=3) so device-side code can hardcode
them as static constants inside jitted programs.
"""

from __future__ import annotations

import json
from typing import Iterable, Sequence

from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID, UNK_ID

SPECIAL_TOKENS = ("<pad>", "<bos>", "<eos>", "<unk>")


class Vocab:
    def __init__(self, words: Sequence[str]):
        """``words`` is the full id->word table INCLUDING the 4 special tokens."""
        if tuple(words[:4]) != SPECIAL_TOKENS:
            raise ValueError(
                f"vocab must start with {SPECIAL_TOKENS}, got {tuple(words[:4])}"
            )
        self._words = list(words)
        self._ids = {w: i for i, w in enumerate(self._words)}
        if len(self._ids) != len(self._words):
            raise ValueError("duplicate words in vocab")

    @classmethod
    def from_corpus_words(cls, words: Iterable[str]) -> "Vocab":
        return cls(list(SPECIAL_TOKENS) + list(words))

    def __len__(self) -> int:
        return len(self._words)

    @property
    def words(self) -> list[str]:
        return list(self._words)

    def encode(self, tokens: Sequence[str]) -> list[int]:
        return [self._ids.get(t, UNK_ID) for t in tokens]

    def decode(self, ids: Sequence[int], stop_at_eos: bool = True) -> str:
        """ids -> sentence, dropping PAD/BOS and stopping at EOS."""
        out = []
        for i in ids:
            i = int(i)
            if i == EOS_ID and stop_at_eos:
                break
            if i in (PAD_ID, BOS_ID, EOS_ID):
                continue
            out.append(self._words[i] if 0 <= i < len(self._words) else "<unk>")
        return " ".join(out)

    def decode_batch(self, id_rows, stop_at_eos: bool = True) -> list[str]:
        return [self.decode(row, stop_at_eos=stop_at_eos) for row in id_rows]

    # ---- persistence ------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps(self._words)

    @classmethod
    def from_json(cls, s: str) -> "Vocab":
        return cls(json.loads(s))
