"""Deterministic synthetic dataset generator (test/bench fixtures).

The real MSVD/MSR-VTT h5s are not shippable (SURVEY.md §4 item 5), so tests
and benchmarks run on seeded synthetic data with the exact on-disk schema of
:mod:`cst_captioning_tpu.data.dataset`: per-modality h5 feature files plus an
``info.json``.

Captions are topic-conditioned: each video draws a latent topic, its captions
are built from that topic's word pool, and its features embed the topic
pattern plus gaussian noise — so features genuinely predict captions and
overfit/learning tests (SURVEY.md §4 item 3) are meaningful, not vacuous.

Two caption styles:

- ``"pool"`` (default, the original): every caption is an i.i.d. random word
  sequence from the topic pool. Good for overfit/mechanics tests, but the GT
  pool has NO consensus structure — there is nothing for consensus-reward
  (CST) training to sharpen that transfers across videos, so XE-vs-CST
  comparisons on this style measure memorization, not the algorithm.
- ``"template"``: each topic owns a few canonical phrases; every caption is
  a noisy realization of one of them (word-level replacement noise). This
  mirrors real caption pools — many roughly-agreeing captions around a few
  central phrasings — so the consensus reward points at structure that
  GENERALIZES to held-out videos of the same topic. Use for any XE-vs-CST
  quality comparison (bench_recipe.py).
"""

from __future__ import annotations

import json
import os

import numpy as np

from cst_captioning_tpu.data.vocab import Vocab

try:
    import h5py
except ImportError:  # pragma: no cover
    h5py = None


def make_synthetic_dataset(
    out_dir: str,
    num_videos: int = 24,
    num_topics: int = 4,
    vocab_words: int = 40,
    captions_per_video: int = 5,
    caption_len: tuple[int, int] = (4, 9),
    modalities: dict[str, int] | None = None,
    max_frames: int = 8,
    splits: tuple[float, float] = (0.75, 0.125),   # train, val (rest = test)
    seed: int = 0,
    caption_style: str = "pool",     # "pool" | "template" (see module doc)
    templates_per_topic: int = 4,
    template_noise: float = 0.25,    # per-word replacement probability
    feature_noise: float = 0.3,      # per-frame gaussian amplitude on top of
                                     # the topic signature. NOTE: this is a
                                     # stable per-video fingerprint (frame
                                     # means identify the video), so models
                                     # CAN memorize per-video targets through
                                     # it; pass ~0.05 for generalization
                                     # studies where that channel must be
                                     # closed (bench_recipe.py)
) -> dict[str, str]:
    """Writes h5 + info.json under ``out_dir``; returns the path map.

    Returns ``{"info_json": ..., "<modality>": <h5 path>, ...}``.
    """
    if h5py is None:
        raise RuntimeError("h5py unavailable")
    modalities = modalities or {"resnet": 64}
    rng = np.random.default_rng(seed)
    os.makedirs(out_dir, exist_ok=True)

    if caption_style not in ("pool", "template"):
        raise ValueError(f"unknown caption_style {caption_style!r}")
    words = [f"w{i:03d}" for i in range(vocab_words)]
    vocab = Vocab.from_corpus_words(words)
    # topic -> disjoint word pool
    pools = np.array_split(np.arange(vocab_words), num_topics)
    # "template" style: per-topic canonical phrases shared by ALL videos of
    # the topic (train and held-out alike) — the consensus target
    topic_templates: list[list[np.ndarray]] = []
    if caption_style == "template":
        for t in range(num_topics):
            topic_templates.append([
                rng.choice(pools[t],
                           size=int(rng.integers(caption_len[0], caption_len[1])),
                           replace=True)
                for _ in range(templates_per_topic)
            ])

    # topic signature per modality: a fixed random pattern features orbit
    sigs = {
        name: rng.normal(size=(num_topics, dim)).astype(np.float32)
        for name, dim in modalities.items()
    }

    videos = []
    feat_arrays: dict[str, dict[str, np.ndarray]] = {m: {} for m in modalities}
    n_train = int(num_videos * splits[0])
    n_val = int(num_videos * splits[1])
    for vi in range(num_videos):
        vid = f"video{vi}"
        split = "train" if vi < n_train else ("val" if vi < n_train + n_val else "test")
        topic = int(rng.integers(num_topics))
        caps_ids, caps_raw = [], []
        for _ in range(captions_per_video):
            pool = pools[topic]
            if caption_style == "template":
                base = topic_templates[topic][
                    int(rng.integers(templates_per_topic))
                ]
                noise = rng.random(base.size) < template_noise
                word_ids = np.where(
                    noise, rng.choice(pool, size=base.size, replace=True), base
                )
            else:
                L = int(rng.integers(caption_len[0], caption_len[1]))
                word_ids = rng.choice(pool, size=L, replace=True)
            toks = [words[w] for w in word_ids]
            caps_raw.append(" ".join(toks))
            caps_ids.append(vocab.encode(toks))
        videos.append(
            {
                "id": vid,
                "split": split,
                "topic": topic,
                "captions": caps_raw,
                "caption_ids": caps_ids,
            }
        )
        n_frames = int(rng.integers(max(2, max_frames // 2), max_frames + 1))
        for name, dim in modalities.items():
            noise = feature_noise * rng.normal(size=(n_frames, dim)).astype(
                np.float32
            )
            feat_arrays[name][vid] = sigs[name][topic][None, :] + noise

    paths: dict[str, str] = {}
    for name in modalities:
        p = os.path.join(out_dir, f"{name}.h5")
        with h5py.File(p, "w") as f:
            for vid, arr in feat_arrays[name].items():
                f.create_dataset(vid, data=arr)
        paths[name] = p

    info = {"vocab": vocab.words, "videos": videos}
    info_path = os.path.join(out_dir, "info.json")
    with open(info_path, "w") as f:
        json.dump(info, f)
    paths["info_json"] = info_path
    return paths
