"""Importers: standard public dataset layouts -> the framework's on-disk schema.

The reference consumed MSR-VTT/MSVD through ad-hoc preprocessing scripts
(SURVEY.md §2 row 3, §3.4); our schema (``info.json`` + one h5 per modality,
:mod:`cst_captioning_tpu.data.dataset`) is self-chosen, so this module is the
documented bridge from the standard distributions to it — the first real-data
run should be a converter call, not a surprise (VERDICT r1 missing #8).

MSR-VTT ``videodatainfo.json`` layout (the 2016 challenge distribution):

    {"videos":    [{"video_id": "video0", "split": "train", ...}, ...],
     "sentences": [{"video_id": "video0", "caption": "a man is ...", ...}, ...]}

splits are named ``train`` / ``validate`` / ``test``; we map ``validate`` ->
``val``.

MSVD (Microsoft Video Description corpus / YouTubeClips) has no split field at
all; its standard distribution is

  - a caption CSV (``video_corpus.csv`` / "MSR Video Description Corpus"):
    columns ``VideoID, Start, End, ..., Language, Description``, one row per
    (clip, annotation); the clip id is ``{VideoID}_{Start}_{End}`` and only
    ``Language == English`` rows are captions. A plain-text variant
    (``<clip_id> <caption>`` per line, e.g. AllVideoDescriptions.txt) is also
    accepted.
  - optionally ``youtube_mapping.txt`` (``<clip_id> vid<N>`` per line) fixing
    the canonical clip order; the conventional captioning split is then the
    first 1200 clips train / next 100 val / remaining 670 test (the boundaries
    used by the CST paper's MSVD experiments — BASELINE config 1).

Features are accepted either as an existing h5 keyed by video id
(copied/filtered) or as a directory of ``<video_id>.npy`` arrays (packed).
"""

from __future__ import annotations

import csv
import json
import os
import re
from typing import Mapping

import numpy as np

from cst_captioning_tpu.data.preprocess import (
    compute_cider_df,
    compute_consensus_weights,
    tokenize_captions,
    build_vocab,
)

try:
    import h5py
except ImportError:  # pragma: no cover - h5py is baked into the image
    h5py = None

_SPLIT_MAP = {"train": "train", "validate": "val", "val": "val", "test": "test"}

# conventional MSVD captioning split boundaries (1200/100/670 of 1970 clips)
MSVD_NUM_TRAIN = 1200
MSVD_NUM_VAL = 100


def parse_msrvtt_info(videodatainfo: str | Mapping) -> tuple[dict, dict]:
    """-> (raw_captions {vid: [sentence, ...]}, splits {vid: split}).

    Accepts a path to ``videodatainfo.json`` or the already-loaded dict.
    """
    if isinstance(videodatainfo, str):
        with open(videodatainfo) as f:
            videodatainfo = json.load(f)
    splits: dict[str, str] = {}
    for v in videodatainfo["videos"]:
        vid = str(v["video_id"])
        split = _SPLIT_MAP.get(str(v.get("split", "train")).lower())
        if split is None:
            raise ValueError(f"unknown MSR-VTT split {v['split']!r} for {vid}")
        splits[vid] = split
    raw: dict[str, list[str]] = {vid: [] for vid in splits}
    for s in videodatainfo["sentences"]:
        vid = str(s["video_id"])
        if vid not in raw:
            raise ValueError(f"sentence references unknown video {vid!r}")
        raw[vid].append(str(s["caption"]))
    empty = [vid for vid, caps in raw.items() if not caps]
    if empty:
        raise ValueError(f"videos without captions: {empty[:5]}...")
    return raw, splits


def _parse_msvd_csv(path: str) -> dict[str, list[str]]:
    """MSR Video Description Corpus csv -> {clip_id: [sentence, ...]}.

    Column names are matched case-insensitively; non-English rows and rows
    with an empty description are skipped.
    """
    raw: dict[str, list[str]] = {}
    with open(path, newline="", encoding="utf-8", errors="replace") as f:
        reader = csv.DictReader(f)
        if reader.fieldnames is None:
            raise ValueError(f"{path}: empty csv")
        cols = {c.strip().lower(): c for c in reader.fieldnames}
        missing = [c for c in ("videoid", "start", "end", "description")
                   if c not in cols]
        if missing:
            raise ValueError(
                f"{path}: not an MSVD corpus csv (missing columns {missing}; "
                f"found {reader.fieldnames})"
            )
        lang_col = cols.get("language")
        for row in reader:
            if lang_col and row[lang_col].strip().lower() != "english":
                continue
            sent = (row[cols["description"]] or "").strip()
            if not sent:
                continue
            clip = (
                f"{row[cols['videoid']].strip()}_"
                f"{row[cols['start']].strip()}_{row[cols['end']].strip()}"
            )
            raw.setdefault(clip, []).append(sent)
    return raw


def _parse_msvd_txt(path: str) -> dict[str, list[str]]:
    """``<clip_id> <caption>`` per line -> {clip_id: [sentence, ...]}."""
    raw: dict[str, list[str]] = {}
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            clip, _, sent = line.partition(" ")
            if sent.strip():
                raw.setdefault(clip, []).append(sent.strip())
    return raw


def parse_msvd_mapping(path: str) -> list[str]:
    """``youtube_mapping.txt`` (``<clip_id> vid<N>`` per line) -> clip ids in
    canonical order (sorted by N)."""
    indexed: list[tuple[int, str]] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            clip, _, tag = line.rpartition(" ")
            m = re.fullmatch(r"vid(\d+)", tag.strip())
            if not clip or m is None:
                raise ValueError(
                    f"{path}: expected '<clip_id> vid<N>' lines, got {line!r}"
                )
            indexed.append((int(m.group(1)), clip.strip()))
    indexed.sort()
    return [clip for _, clip in indexed]


def parse_msvd_corpus(
    corpus: str | Mapping,
    mapping: str | None = None,
    n_train: int = MSVD_NUM_TRAIN,
    n_val: int = MSVD_NUM_VAL,
) -> tuple[dict, dict]:
    """-> (raw_captions {clip_id: [sentence, ...]}, splits {clip_id: split}).

    ``corpus`` is the caption file (csv or ``<clip_id> <caption>`` text) or an
    already-loaded ``{clip_id: [sentence, ...]}`` mapping. ``mapping`` is the
    optional ``youtube_mapping.txt`` fixing the canonical clip order (clips
    absent from it are dropped, mirroring the conventional 1970-clip subset);
    without it clips are ordered by sorted id. The first ``n_train`` clips are
    the train split, the next ``n_val`` the val split, the remainder test.
    """
    if isinstance(corpus, Mapping):
        raw = {str(k): [str(s) for s in v] for k, v in corpus.items()}
    elif corpus.endswith(".csv"):
        raw = _parse_msvd_csv(corpus)
    else:
        raw = _parse_msvd_txt(corpus)
    if not raw:
        raise ValueError("MSVD corpus contains no captions")

    if mapping is not None:
        order = parse_msvd_mapping(mapping)
        missing = [c for c in order if c not in raw or not raw[c]]
        if missing:
            raise ValueError(
                f"mapped clips without captions: {missing[:5]}..."
            )
        raw = {clip: raw[clip] for clip in order}
    else:
        order = sorted(raw)
        raw = {clip: raw[clip] for clip in order}

    if len(order) <= n_train:
        raise ValueError(
            f"only {len(order)} clips for n_train={n_train}, n_val={n_val}; "
            "pass split sizes matching the corpus"
        )
    splits = {
        clip: ("train" if i < n_train else "val" if i < n_train + n_val
               else "test")
        for i, clip in enumerate(order)
    }
    return raw, splits


def pack_features(source: str, out_h5: str, video_ids: list[str]) -> str:
    """Features -> one h5 keyed by video id ([n_frames, dim] float32 each).

    ``source``: an h5 (rows copied for ``video_ids``) or a directory of
    ``<video_id>.npy`` arrays.
    """
    if h5py is None:
        raise RuntimeError("h5py unavailable")
    def as_frames(vid: str, arr: np.ndarray) -> np.ndarray:
        """-> [n_frames, dim]; 1-D rows become a single frame; reject others."""
        if arr.ndim == 2:
            return arr
        if arr.ndim == 1:
            return arr[None, :]
        raise ValueError(
            f"feature for {vid!r} has shape {arr.shape}; expected "
            "[n_frames, dim] or [dim] (strip any leading batch dimension)"
        )

    os.makedirs(os.path.dirname(out_h5) or ".", exist_ok=True)
    with h5py.File(out_h5, "w") as out:
        if os.path.isdir(source):
            for vid in video_ids:
                path = os.path.join(source, f"{vid}.npy")
                if not os.path.exists(path):
                    raise FileNotFoundError(f"missing feature file {path}")
                out[vid] = as_frames(vid, np.asarray(np.load(path), np.float32))
        else:
            with h5py.File(source, "r") as src:
                for vid in video_ids:
                    if vid not in src:
                        raise KeyError(f"{source} has no key {vid!r}")
                    out[vid] = as_frames(vid, np.asarray(src[vid], np.float32))
    return out_h5


def _write_dataset(
    out_dir: str,
    raw: Mapping[str, list[str]],
    splits: Mapping[str, str],
    features: Mapping[str, str] | None,
    min_word_count: int,
    write_consensus_weights: bool,
    write_cider_df: bool,
) -> dict[str, str]:
    """Tokenized corpus + splits -> info.json / h5 / weights / df on disk.

    Shared tail of every importer. The vocab is built from the TRAIN split
    only (standard preprocessing: val/test-only words encode to <unk>), the
    same restriction already applied to the CIDEr df and consensus weights.
    """
    os.makedirs(out_dir, exist_ok=True)
    tokenized = tokenize_captions(raw)
    train_tok = {v: t for v, t in tokenized.items() if splits[v] == "train"}
    if not train_tok:
        raise ValueError("no train-split videos — cannot build a vocab")
    vocab = build_vocab(train_tok, min_count=min_word_count)

    videos = []
    for vid, caps in tokenized.items():
        videos.append(
            {
                "id": vid,
                "split": splits[vid],
                "captions": [" ".join(t) for t in caps],
                "caption_ids": [vocab.encode(t) for t in caps],
            }
        )
    info_path = os.path.join(out_dir, "info.json")
    with open(info_path, "w") as f:
        json.dump({"vocab": vocab.words, "videos": videos}, f)
    out = {"info_json": info_path}

    if write_cider_df:
        df = compute_cider_df(train_tok)
        df_path = os.path.join(out_dir, "cider_df.pkl")
        df.save(df_path)
        out["cider_df"] = df_path
    if write_consensus_weights:
        weights = compute_consensus_weights(train_tok)
        w_path = os.path.join(out_dir, "consensus_weights.npz")
        np.savez(w_path, **weights)
        out["consensus_weights"] = w_path

    vids = [v["id"] for v in videos]
    for name, source in (features or {}).items():
        out[name] = pack_features(
            source, os.path.join(out_dir, f"{name}.h5"), vids
        )
    return out


def import_msrvtt(
    videodatainfo: str | Mapping,
    out_dir: str,
    features: Mapping[str, str] | None = None,
    min_word_count: int = 2,
    write_consensus_weights: bool = True,
    write_cider_df: bool = True,
) -> dict[str, str]:
    """Convert an MSR-VTT distribution into the framework's dataset files.

    Writes under ``out_dir``:
      - ``info.json``                 (vocab + splits + tokenized captions)
      - ``<modality>.h5``             per entry in ``features``
      - ``consensus_weights.npz``     per-caption WXE weights (train tokenizer)
      - ``cider_df.pkl``              train-split document frequencies

    Returns a path map usable directly as ``DataConfig`` inputs.
    """
    raw, splits = parse_msrvtt_info(videodatainfo)
    return _write_dataset(
        out_dir, raw, splits, features, min_word_count,
        write_consensus_weights, write_cider_df,
    )


def import_msvd(
    corpus: str | Mapping,
    out_dir: str,
    mapping: str | None = None,
    features: Mapping[str, str] | None = None,
    n_train: int = MSVD_NUM_TRAIN,
    n_val: int = MSVD_NUM_VAL,
    min_word_count: int = 2,
    write_consensus_weights: bool = True,
    write_cider_df: bool = True,
) -> dict[str, str]:
    """Convert an MSVD distribution into the framework's dataset files.

    Same outputs as :func:`import_msrvtt` (BASELINE config 1's ingestion
    path). ``corpus``/``mapping``/``n_train``/``n_val`` are documented at
    :func:`parse_msvd_corpus`; the defaults are the conventional
    1200/100/670 captioning split.
    """
    raw, splits = parse_msvd_corpus(
        corpus, mapping=mapping, n_train=n_train, n_val=n_val
    )
    return _write_dataset(
        out_dir, raw, splits, features, min_word_count,
        write_consensus_weights, write_cider_df,
    )
