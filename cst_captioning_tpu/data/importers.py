"""Importers: standard public dataset layouts -> the framework's on-disk schema.

The reference consumed MSR-VTT/MSVD through ad-hoc preprocessing scripts
(SURVEY.md §2 row 3, §3.4); our schema (``info.json`` + one h5 per modality,
:mod:`cst_captioning_tpu.data.dataset`) is self-chosen, so this module is the
documented bridge from the standard distributions to it — the first real-data
run should be a converter call, not a surprise (VERDICT r1 missing #8).

MSR-VTT ``videodatainfo.json`` layout (the 2016 challenge distribution):

    {"videos":    [{"video_id": "video0", "split": "train", ...}, ...],
     "sentences": [{"video_id": "video0", "caption": "a man is ...", ...}, ...]}

splits are named ``train`` / ``validate`` / ``test``; we map ``validate`` ->
``val``. Features are accepted either as an existing h5 keyed by video id
(copied/filtered) or as a directory of ``<video_id>.npy`` arrays (packed).
"""

from __future__ import annotations

import json
import os
from typing import Mapping

import numpy as np

from cst_captioning_tpu.data.preprocess import (
    compute_cider_df,
    compute_consensus_weights,
    tokenize_captions,
    build_vocab,
)

try:
    import h5py
except ImportError:  # pragma: no cover - h5py is baked into the image
    h5py = None

_SPLIT_MAP = {"train": "train", "validate": "val", "val": "val", "test": "test"}


def parse_msrvtt_info(videodatainfo: str | Mapping) -> tuple[dict, dict]:
    """-> (raw_captions {vid: [sentence, ...]}, splits {vid: split}).

    Accepts a path to ``videodatainfo.json`` or the already-loaded dict.
    """
    if isinstance(videodatainfo, str):
        with open(videodatainfo) as f:
            videodatainfo = json.load(f)
    splits: dict[str, str] = {}
    for v in videodatainfo["videos"]:
        vid = str(v["video_id"])
        split = _SPLIT_MAP.get(str(v.get("split", "train")).lower())
        if split is None:
            raise ValueError(f"unknown MSR-VTT split {v['split']!r} for {vid}")
        splits[vid] = split
    raw: dict[str, list[str]] = {vid: [] for vid in splits}
    for s in videodatainfo["sentences"]:
        vid = str(s["video_id"])
        if vid not in raw:
            raise ValueError(f"sentence references unknown video {vid!r}")
        raw[vid].append(str(s["caption"]))
    empty = [vid for vid, caps in raw.items() if not caps]
    if empty:
        raise ValueError(f"videos without captions: {empty[:5]}...")
    return raw, splits


def pack_features(source: str, out_h5: str, video_ids: list[str]) -> str:
    """Features -> one h5 keyed by video id ([n_frames, dim] float32 each).

    ``source``: an h5 (rows copied for ``video_ids``) or a directory of
    ``<video_id>.npy`` arrays.
    """
    if h5py is None:
        raise RuntimeError("h5py unavailable")
    def as_frames(vid: str, arr: np.ndarray) -> np.ndarray:
        """-> [n_frames, dim]; 1-D rows become a single frame; reject others."""
        if arr.ndim == 2:
            return arr
        if arr.ndim == 1:
            return arr[None, :]
        raise ValueError(
            f"feature for {vid!r} has shape {arr.shape}; expected "
            "[n_frames, dim] or [dim] (strip any leading batch dimension)"
        )

    os.makedirs(os.path.dirname(out_h5) or ".", exist_ok=True)
    with h5py.File(out_h5, "w") as out:
        if os.path.isdir(source):
            for vid in video_ids:
                path = os.path.join(source, f"{vid}.npy")
                if not os.path.exists(path):
                    raise FileNotFoundError(f"missing feature file {path}")
                out[vid] = as_frames(vid, np.asarray(np.load(path), np.float32))
        else:
            with h5py.File(source, "r") as src:
                for vid in video_ids:
                    if vid not in src:
                        raise KeyError(f"{source} has no key {vid!r}")
                    out[vid] = as_frames(vid, np.asarray(src[vid], np.float32))
    return out_h5


def import_msrvtt(
    videodatainfo: str | Mapping,
    out_dir: str,
    features: Mapping[str, str] | None = None,
    min_word_count: int = 2,
    write_consensus_weights: bool = True,
    write_cider_df: bool = True,
) -> dict[str, str]:
    """Convert an MSR-VTT distribution into the framework's dataset files.

    Writes under ``out_dir``:
      - ``info.json``                 (vocab + splits + tokenized captions)
      - ``<modality>.h5``             per entry in ``features``
      - ``consensus_weights.npz``     per-caption WXE weights (train tokenizer)
      - ``cider_df.pkl``              train-split document frequencies

    Returns a path map usable directly as ``DataConfig`` inputs.
    """
    os.makedirs(out_dir, exist_ok=True)
    raw, splits = parse_msrvtt_info(videodatainfo)
    tokenized = tokenize_captions(raw)
    vocab = build_vocab(tokenized, min_count=min_word_count)

    videos = []
    for vid, caps in tokenized.items():
        videos.append(
            {
                "id": vid,
                "split": splits[vid],
                "captions": [" ".join(t) for t in caps],
                "caption_ids": [vocab.encode(t) for t in caps],
            }
        )
    info_path = os.path.join(out_dir, "info.json")
    with open(info_path, "w") as f:
        json.dump({"vocab": vocab.words, "videos": videos}, f)
    out = {"info_json": info_path}

    train_tok = {v: t for v, t in tokenized.items() if splits[v] == "train"}
    if write_cider_df:
        df = compute_cider_df(train_tok)
        df_path = os.path.join(out_dir, "cider_df.pkl")
        df.save(df_path)
        out["cider_df"] = df_path
    if write_consensus_weights:
        weights = compute_consensus_weights(train_tok)
        w_path = os.path.join(out_dir, "consensus_weights.npz")
        np.savez(w_path, **weights)
        out["consensus_weights"] = w_path

    vids = [v["id"] for v in videos]
    for name, source in (features or {}).items():
        out[name] = pack_features(
            source, os.path.join(out_dir, f"{name}.h5"), vids
        )
    return out
