"""Preprocessing: vocab build, tokenization, consensus weights, CIDEr df.

Rebuilds the reference's standalone preprocessing scripts (SURVEY.md §2 row 3):

- :func:`build_vocab` — frequency-thresholded word table (rare words -> <unk>),
- :func:`tokenize_captions` — PTB-style tokenization via our metrics tokenizer,
- :func:`compute_consensus_weights` — per-caption consensus score: CIDEr-D of
  each GT caption against the OTHER GTs of the same video; these become the
  WXE loss weights (CST paper §3.2),
- :func:`compute_cider_df` — train-split document frequencies for the RL
  reward's CiderD (precomputed once, like the reference's df pickle),
- :func:`build_info_json` — assembles the dataset's ``info.json``.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Mapping, Sequence

import numpy as np

from cst_captioning_tpu.data.vocab import Vocab
from cst_captioning_tpu.metrics.cider import CiderD, CorpusDF
from cst_captioning_tpu.metrics.tokenizer import ptb_tokenize_corpus


def tokenize_captions(raw: Mapping[str, Sequence[str]]) -> dict[str, list[list[str]]]:
    """{video_id: [raw sentence, ...]} -> {video_id: [[token, ...], ...]}.

    Delegates to the metrics tokenizer so preprocessing (vocab, df, consensus
    weights) and reward/eval scoring can never diverge on tokenization.
    """
    return ptb_tokenize_corpus(dict(raw))


def build_vocab(
    tokenized: Mapping[str, Sequence[Sequence[str]]],
    min_count: int = 1,
) -> Vocab:
    """Frequency-thresholded vocab over all captions (rare words become <unk>)."""
    counts: Counter = Counter()
    for caps in tokenized.values():
        for toks in caps:
            counts.update(toks)
    words = sorted(w for w, c in counts.items() if c >= min_count)
    return Vocab.from_corpus_words(words)


def compute_consensus_weights(
    tokenized: Mapping[str, Sequence[Sequence[str]]],
    df: CorpusDF | None = None,
    normalize: str = "mean1",
) -> dict[str, np.ndarray]:
    """Per-caption consensus = CIDEr-D of the caption vs its sibling GTs.

    ``normalize="mean1"`` rescales each video's weights to mean 1 so WXE keeps
    the same overall loss scale as XE; ``"none"`` keeps raw CIDEr-D/10 scores.

    When ``df`` is None a corpus df (one document per video) is built over all
    of ``tokenized`` — scoring leave-one-out pools with df computed from the
    pools themselves would drive the idf of every shared n-gram to zero.
    """
    if df is None:
        df = compute_cider_df(tokenized)
    scorer = CiderD(df=df)
    out: dict[str, np.ndarray] = {}
    for vid, caps in tokenized.items():
        caps = [list(c) for c in caps]
        if len(caps) < 2:
            out[vid] = np.ones((len(caps),), dtype=np.float32)
            continue
        gts, res = {}, {}
        for i, cap in enumerate(caps):
            key = f"{vid}#{i}"
            res[key] = [cap]
            gts[key] = [c for j, c in enumerate(caps) if j != i]
        _, per_cap = scorer.compute_score(gts, res)
        w = np.asarray(per_cap, dtype=np.float32) / 10.0
        if normalize == "mean1":
            mean = float(w.mean())
            w = w / mean if mean > 1e-8 else np.ones_like(w)
        out[vid] = w
    return out


def compute_cider_df(
    tokenized: Mapping[str, Sequence[Sequence[str]]], max_n: int = 4
) -> CorpusDF:
    """Train-split document frequencies (one document = one video's GT pool)."""
    return CorpusDF.from_refs(list(tokenized.values()), max_n=max_n)


def build_info_json(
    out_path: str,
    raw_captions: Mapping[str, Sequence[str]],
    splits: Mapping[str, str],
    min_count: int = 1,
) -> Vocab:
    """Tokenize + build vocab + write the dataset info.json; returns the vocab."""
    tokenized = tokenize_captions(raw_captions)
    vocab = build_vocab(tokenized, min_count=min_count)
    videos = []
    for vid, caps in tokenized.items():
        videos.append(
            {
                "id": vid,
                "split": splits.get(vid, "train"),
                "captions": [" ".join(t) for t in caps],
                "caption_ids": [vocab.encode(t) for t in caps],
            }
        )
    with open(out_path, "w") as f:
        json.dump({"vocab": vocab.words, "videos": videos}, f)
    return vocab
