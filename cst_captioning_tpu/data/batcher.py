"""Fixed-shape batch construction.

The reference collates variable-length captions by padding to the batch max
(SURVEY.md §3.4). On TPU that would retrace/recompile per batch shape, so here
EVERY batch is padded to the static ``(batch_size, max_len)`` /
``(batch_size, max_frames, dim)`` envelope — XLA compiles each program once.

Two iteration modes:

- ``mode="caption"`` (XE phase): one row per (video, caption) pair,
  ``seq_per_vid`` captions sampled per video per epoch.
- ``mode="video"`` (RL decode / eval): one row per video; caption slots carry
  an arbitrary GT row (unused by decoding).

Short final batches are wrapped (circular) with a ``valid`` row mask so shapes
stay static while eval stays exact.

Shuffling is keyed by ``(seed, epoch_index)`` — not a running RNG stream — so
a resumed run that sets :attr:`Batcher.epoch_index` from the checkpoint epoch
reproduces the exact batch order of an uninterrupted run (SURVEY.md §3.5
resume semantics, hardened with determinism the reference never had).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from cst_captioning_tpu.config.config import EOS_ID, PAD_ID
from cst_captioning_tpu.data.dataset import CaptionDataset


@dataclass
class Batch:
    feats: dict[str, np.ndarray]       # name -> [B, F, D] float32
    feat_masks: dict[str, np.ndarray]  # name -> [B, F]    float32
    labels: np.ndarray                 # [B, T] int32: word ids + EOS, then PAD
    mask: np.ndarray                   # [B, T] float32: 1 on real tokens incl. EOS
    weights: np.ndarray                # [B]    float32: WXE consensus weights
    valid: np.ndarray                  # [B]    bool: False on wrap-padding rows
    video_ids: list[str]

    @property
    def size(self) -> int:
        return int(self.valid.sum())


def encode_label_row(caption_ids: list[int], max_len: int) -> tuple[np.ndarray, np.ndarray]:
    """ids (no specials) -> (labels [T], mask [T]) with EOS and PAD=0 padding."""
    row = np.full((max_len,), PAD_ID, dtype=np.int32)
    m = np.zeros((max_len,), dtype=np.float32)
    toks = caption_ids[: max_len - 1]          # reserve one slot for EOS
    row[: len(toks)] = toks
    row[len(toks)] = EOS_ID
    m[: len(toks) + 1] = 1.0
    return row, m


class Batcher:
    def __init__(
        self,
        dataset: CaptionDataset,
        batch_size: int,
        max_len: int,
        mode: str = "caption",
        seq_per_vid: int = 1,
        seed: int = 0,
        drop_last: bool = False,
        host_shard: tuple[int, int] = (0, 1),
    ):
        if mode not in ("caption", "video"):
            raise ValueError(f"unknown mode {mode!r}")
        self.ds = dataset
        self.batch_size = batch_size
        self.max_len = max_len
        self.mode = mode
        self.seq_per_vid = seq_per_vid
        self.seed = seed
        self.epoch_index = 0  # set from the checkpoint epoch on resume
        # divergence-rollback salt (resilience/sentinel.py): 0 keeps the
        # historical (seed, epoch) keying bit-for-bit; a rollback bumps it so
        # the replayed epochs draw a fresh — still deterministic — order
        self.salt = 0
        self.drop_last = drop_last
        # multi-host data feeding (train/multihost.py): every process forms
        # the SAME global batch order — the shuffle is keyed by (seed,
        # epoch_index), no communication needed — and collates only its own
        # contiguous slice of each batch. batch_size stays GLOBAL; collated
        # arrays are [batch_size // count] rows.
        idx, count = host_shard
        if batch_size % count:
            raise ValueError(
                f"global batch_size {batch_size} must be divisible by "
                f"host_shard count {count}"
            )
        if not 0 <= idx < count:
            raise ValueError(f"host_shard index {idx} not in [0, {count})")
        self.host_shard = (idx, count)
        self.local_batch_size = batch_size // count

    def _items(self, rng: np.random.Generator | None) -> list[tuple[int, int]]:
        """List of (record_idx, caption_idx) rows for one epoch."""
        items: list[tuple[int, int]] = []
        for ri, rec in enumerate(self.ds.records):
            ncap = max(len(rec.caption_ids), 1)
            if self.mode == "video":
                items.append((ri, 0))
            else:
                k = min(self.seq_per_vid, ncap)
                caps = rng.choice(ncap, size=k, replace=False) if rng is not None else range(k)
                items.extend((ri, int(ci)) for ci in caps)
        if rng is not None:
            rng.shuffle(items)
        return items

    def __iter__(self):
        return self.epoch(shuffle=self.mode == "caption")

    def epoch(self, shuffle: bool = True):
        # per-epoch derived RNG: order depends only on (seed, epoch_index);
        # unshuffled epochs (eval, template peeks) consume no epoch index
        rng = None
        if shuffle:
            key = (
                (self.seed, self.epoch_index) if not self.salt
                else (self.seed, self.salt, self.epoch_index)
            )
            rng = np.random.default_rng(key)
            self.epoch_index += 1
        items = self._items(rng)
        bs = self.batch_size
        idx, count = self.host_shard
        lb = self.local_batch_size
        n = len(items)
        for start in range(0, n, bs):
            chunk = items[start : start + bs]
            if len(chunk) < bs:
                if self.drop_last:
                    return
                pad = [chunk[i % len(chunk)] for i in range(bs - len(chunk))]
                valid = np.array([True] * len(chunk) + [False] * len(pad))
                chunk = chunk + pad
            else:
                valid = np.ones((bs,), dtype=bool)
            if count > 1:
                # this process's contiguous slice of the global batch
                chunk = chunk[idx * lb : (idx + 1) * lb]
                valid = valid[idx * lb : (idx + 1) * lb]
            yield self._collate(chunk, valid)

    def _collate(self, items: list[tuple[int, int]], valid: np.ndarray) -> Batch:
        bs, T = self.local_batch_size, self.max_len
        names = list(self.ds.stores)
        feats = {
            n: np.zeros((bs, self.ds.max_frames, self.ds.stores[n].dim), np.float32)
            for n in names
        }
        fmasks = {n: np.zeros((bs, self.ds.max_frames), np.float32) for n in names}
        labels = np.full((bs, T), PAD_ID, dtype=np.int32)
        mask = np.zeros((bs, T), dtype=np.float32)
        weights = np.ones((bs,), dtype=np.float32)
        video_ids = []
        # memoize per-video features within the batch: seq_per_vid>1 and
        # wrap-padding repeat videos, and h5 reads are the host hot path
        feat_cache: dict[str, dict] = {}
        for b, (ri, ci) in enumerate(items):
            rec = self.ds.records[ri]
            video_ids.append(rec.video_id)
            if rec.video_id not in feat_cache:
                feat_cache[rec.video_id] = self.ds.features_for(rec.video_id)
            for n, (f, fm) in feat_cache[rec.video_id].items():
                feats[n][b] = f
                fmasks[n][b] = fm
            if rec.caption_ids:
                ci = min(ci, len(rec.caption_ids) - 1)
                labels[b], mask[b] = encode_label_row(rec.caption_ids[ci], T)
                if rec.weights:
                    weights[b] = rec.weights[ci]
        return Batch(
            feats=feats,
            feat_masks=fmasks,
            labels=labels,
            mask=mask,
            weights=weights,
            valid=valid,
            video_ids=video_ids,
        )

    def num_batches(self) -> int:
        n = len(self._items(None))
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)
