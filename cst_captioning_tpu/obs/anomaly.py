"""Online anomaly detection over training-dynamics scalar streams.

The flight recorder (:mod:`obs.recorder`) feeds every flushed step's scalars
through one :class:`AnomalyDetector`; the divergence sentinel
(:mod:`resilience.sentinel`) reports its verdicts through the same
:func:`record_anomaly` spelling — so rollback decisions, the postmortem
timeline, and the ``obs.anomaly.<kind>`` counters all agree on what an
anomaly is called and how it is counted.

Detection model (pure stdlib, O(1) per observation):

- per-stream **EWMA z-score**: exponentially-weighted mean/variance
  (``alpha`` — the effective memory is ~``2/alpha`` steps) updated online;
  once ``warmup`` observations are in, a value more than ``z_threshold``
  EW-standard-deviations from the EW-mean is flagged. Flagged values still
  update the moments (a level shift re-converges instead of alarming
  forever).
- **nonfinite**: NaN/inf observations short-circuit to their own kind —
  they would poison the moments and are categorically worse than a spike.
- **stall**: the recorder timestamps each step on the host; a gap exceeding
  ``stall_factor`` x the p95 of the recent-gap window means no step
  completed within the budget (a wedged prefetch thread, a hung collective,
  a dead reward service).

Anomaly kinds currently emitted: ``nonfinite``, ``spike`` (the sentinel's
median-based loss-spike policy), ``stall``, ``slo_burn`` (serving), and
``<stream>_z`` for each z-score stream (``step_time_z``, ``grad_norm_z``,
``reward_z``, ``loss_z``).
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any

from cst_captioning_tpu.obs import metrics as _metrics
# name import, not `obs import span`: the obs package re-exports the span()
# context-manager FUNCTION under that name, shadowing the submodule
from cst_captioning_tpu.obs.span import event as _span_event


def record_anomaly(kind: str, **fields: Any) -> None:
    """THE anomaly spelling: one structured ``anomaly`` event on the obs
    stream plus the ``obs.anomaly.<kind>`` counter. Every producer — the
    recorder's online detectors, the divergence sentinel, the serving SLO
    burn-rate monitor — reports through here so reports and dashboards
    aggregate one vocabulary."""
    _metrics.counter(f"obs.anomaly.{kind}").inc()
    _span_event("anomaly", kind=kind, **fields)


class Ewma:
    """Exponentially-weighted mean/variance with a warmup gate.

    :meth:`update` returns the observation's z-score against the moments
    *before* it was folded in (``None`` until ``warmup`` observations are
    seen — early z-scores against a 1-sample variance are noise)."""

    __slots__ = ("alpha", "warmup", "n", "mean", "var")

    def __init__(self, alpha: float = 0.1, warmup: int = 8):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha {alpha} must be in (0, 1]")
        self.alpha = alpha
        self.warmup = warmup
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, x: float) -> float | None:
        z = None
        if self.n >= self.warmup and self.var > 0.0:
            z = (x - self.mean) / math.sqrt(self.var)
        if self.n == 0:
            self.mean = x
        else:
            a = self.alpha
            d = x - self.mean
            self.mean += a * d
            # West's EW variance update: unbiased enough for thresholding
            self.var = (1.0 - a) * (self.var + a * d * d)
        self.n += 1
        return z


class AnomalyDetector:
    """z-score detectors over named scalar streams + the step-gap stall
    detector. :meth:`observe` returns the list of anomaly kinds the value
    tripped (empty when healthy) and reports each via
    :func:`record_anomaly`."""

    # streams the recorder routes through the z-score detectors; everything
    # else in a step record is carried but not judged
    STREAMS = ("step_time", "grad_norm", "reward", "loss")

    def __init__(self, z_threshold: float = 4.0, alpha: float = 0.1,
                 warmup: int = 8, stall_factor: float = 10.0,
                 gap_window: int = 64):
        self.z_threshold = z_threshold
        self.stall_factor = stall_factor
        self._ewma = {s: Ewma(alpha=alpha, warmup=warmup)
                      for s in self.STREAMS}
        self._gaps: deque[float] = deque(maxlen=gap_window)

    def ewma(self, stream: str) -> Ewma:
        """The live :class:`Ewma` behind ``stream`` — shared with
        :class:`resilience.adaptive.AdaptiveThresholds` so the sentinel's
        adaptive spike bound and the detector's z-scores read the *same*
        moments instead of maintaining drifting copies."""
        return self._ewma[stream]

    def observe(self, stream: str, value: float, *, step: int = -1,
                phase: str = "") -> list[str]:
        """Judge one observation of ``stream``. Unknown streams are carried
        without judgment (the recorder records more than it detects on)."""
        ew = self._ewma.get(stream)
        if ew is None:
            return []
        if not math.isfinite(value):
            record_anomaly("nonfinite", stream=stream, step=step, phase=phase,
                           value=value)
            return ["nonfinite"]
        z = ew.update(value)
        if z is not None and abs(z) > self.z_threshold:
            kind = f"{stream}_z"
            record_anomaly(kind, stream=stream, step=step, phase=phase,
                           value=value, z=z)
            return [kind]
        return []

    def observe_gap(self, gap_s: float, *, step: int = -1,
                    phase: str = "") -> list[str]:
        """Feed one host-side step-completion gap; flags a stall when the
        gap exceeds ``stall_factor`` x the p95 of the recent-gap window."""
        out: list[str] = []
        if len(self._gaps) >= 8:
            ordered = sorted(self._gaps)
            p95 = ordered[min(int(0.95 * len(ordered)), len(ordered) - 1)]
            if p95 > 0.0 and gap_s > self.stall_factor * p95:
                record_anomaly("stall", step=step, phase=phase, gap_s=gap_s,
                               p95_s=p95)
                out.append("stall")
        self._gaps.append(gap_s)
        return out
