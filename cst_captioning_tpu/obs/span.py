"""Nested wall-clock tracing spans + the run-level obs recorder.

One process-global :class:`ObsRecorder` (installed by :func:`configure`,
normally from the Trainer or a CLI) owns the run's observability outputs:

- ``<dir>/events.jsonl`` — every finished span and every metrics snapshot as
  one JSON line (the input to ``cli.obs_report``), line-buffered so a killed
  run still has its stream;
- ``<dir>/trace.json``   — the same spans in Chrome/Perfetto trace-event
  format (load in https://ui.perfetto.dev), one track per thread plus named
  virtual tracks (the profiler window);
- ``<dir>/metrics.prom`` — the registry in Prometheus textfile format,
  rewritten on every snapshot (point a node_exporter textfile collector at
  the run dir).

``with span("rl.decode"):`` costs two ``perf_counter`` calls plus one dict +
one JSONL line when enabled; when no recorder is installed it returns a
shared no-op object — one global load and an identity check, so hot paths
keep their instrumentation unconditionally. Spans never read device values
(wall clock only): instrumentation adds zero host syncs by construction.

A thread-local context carries run-position fields (``phase``/``epoch``/
``step`` via :func:`set_context`) onto every event emitted by that thread;
a thread-local span stack provides nesting depth, parent names, and exact
self-time (parent duration minus time spent in child spans), which is what
lets the report's per-phase totals partition wall clock without double
counting.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any

from cst_captioning_tpu.obs import metrics as _metrics

_TLS = threading.local()


def wall_time() -> float:
    """Epoch-seconds "now" — the obs spelling for wall-clock timestamps.

    Event streams, the flight recorder, and the JSONL event log all stamp
    through here, so graftlint's GL010 ban on ad-hoc ``time.time()`` call
    sites has exactly one sanctioned home."""
    return time.time()  # graftlint: disable=GL010 (the single sanctioned wall-clock read)


def _ctx() -> dict:
    d = getattr(_TLS, "ctx", None)
    if d is None:
        d = _TLS.ctx = {}
    return d


def _stack() -> list:
    s = getattr(_TLS, "stack", None)
    if s is None:
        s = _TLS.stack = []
    return s


def set_context(**fields: Any) -> None:
    """Attach run-position fields (phase/epoch/step/...) to every event this
    thread emits; a value of ``None`` removes the field. No-op cheapness is
    the caller's concern — guard with :func:`enabled` in per-step loops."""
    d = _ctx()
    for k, v in fields.items():
        if v is None:
            d.pop(k, None)
        else:
            d[k] = v


class _NoopSpan:
    """Shared do-nothing span: the disabled-mode fast path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass

    begin = __enter__

    def end(self) -> None:
        pass


_NOOP = _NoopSpan()


class Span:
    """One timed window. Use as a context manager, or via ``begin()`` /
    ``end()`` for windows that don't nest lexically (the profiler trace
    window). ``track`` puts the span on a named virtual timeline track and
    keeps it out of the thread's nesting stack — for exactly those
    improperly-nested windows."""

    __slots__ = ("rec", "name", "track", "attrs", "_t0", "_child")

    def __init__(self, rec: "ObsRecorder", name: str, track: str | None,
                 attrs: dict):
        self.rec = rec
        self.name = name
        self.track = track
        self.attrs = attrs
        self._t0 = 0.0
        self._child = 0.0  # seconds spent in child spans

    def begin(self) -> "Span":
        if self.track is None:
            _stack().append(self)
        self._t0 = time.perf_counter()
        return self

    __enter__ = begin

    def end(self) -> None:
        t1 = time.perf_counter()
        dur = t1 - self._t0
        parent = None
        if self.track is None:
            stack = _stack()
            # tolerate a foreign stack state (a begin() without end() above
            # us): pop down to self so accounting degrades, never corrupts
            while stack:
                top = stack.pop()
                if top is self:
                    break
            if stack:
                parent = stack[-1]
                parent._child += dur
        self.rec.record_span(
            name=self.name,
            t0=self._t0,
            dur=dur,
            self_dur=max(dur - self._child, 0.0),
            depth=len(_stack()) if self.track is None else 0,
            parent=parent.name if parent is not None else None,
            track=self.track,
            attrs=self.attrs,
        )

    def __exit__(self, *exc) -> None:
        self.end()


class ObsRecorder:
    """Owns the run's event stream, trace buffer, and metric snapshots."""

    def __init__(self, out_dir: str, run: str = "run",
                 snapshot_every: int = 0):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.run = run
        self.snapshot_every = snapshot_every
        self._t_origin = time.perf_counter()  # trace timestamp origin
        self._lock = threading.Lock()
        self._trace: list[dict] = []
        self._closed = False
        self._fh = open(os.path.join(out_dir, "events.jsonl"), "a",
                        buffering=1)
        self._atexit = self.close
        atexit.register(self._atexit)
        _metrics.install_compile_listener()
        # the configuring thread is the run's foreground timeline: the
        # report partitions wall clock over ITS spans only (background
        # threads overlap it and are listed separately)
        self.main_thread = threading.current_thread().name
        self.emit("run_start", run=run, pid=os.getpid(),
                  thread=self.main_thread)

    # ---- event stream -------------------------------------------------------

    def emit(self, event: str, **fields: Any) -> None:
        rec = {"ts": wall_time(), "event": event, **_ctx(), **fields}
        with self._lock:
            if self._closed:
                return
            self._fh.write(json.dumps(rec, default=float) + "\n")

    def record_span(self, name: str, t0: float, dur: float, self_dur: float,
                    depth: int, parent: str | None, track: str | None,
                    attrs: dict) -> None:
        thread = threading.current_thread().name
        fields = {
            "name": name,
            "dur": round(dur, 6),
            "self_dur": round(self_dur, 6),
            "depth": depth,
            "thread": thread,
        }
        if parent:
            fields["parent"] = parent
        if track:
            fields["track"] = track
        for k, v in attrs.items():
            # span attrs must not shadow the span schema (a span attribute
            # literally named "name"/"dur"/... gets an attr_ prefix)
            fields[("attr_" + k) if k in fields else k] = v
        self.emit("span", **fields)
        tid = track or thread
        ev = {
            "name": name,
            "ph": "X",
            "ts": round((t0 - self._t_origin) * 1e6, 1),
            "dur": round(dur * 1e6, 1),
            "pid": os.getpid(),
            "tid": tid,
        }
        if attrs:
            ev["args"] = attrs
        with self._lock:
            if not self._closed:
                self._trace.append(ev)

    # ---- metrics ------------------------------------------------------------

    def snapshot(self, **fields: Any) -> None:
        """Snapshot the process-wide registry into the event stream (plus the
        Prometheus textfile), refreshing the device-memory gauges first."""
        _metrics.observe_device_memory()
        snap = _metrics.snapshot()
        self.emit("metrics", **fields, **snap)
        self.write_prometheus()

    def maybe_snapshot(self, step: int) -> None:
        """Cadenced snapshot: fires when ``step`` hits ``snapshot_every``."""
        if self.snapshot_every and step % self.snapshot_every == 0:
            self.snapshot(step=step)

    def write_prometheus(self) -> None:
        text = _metrics.REGISTRY.to_prometheus()
        tmp = os.path.join(self.out_dir, ".metrics.prom.tmp")
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, os.path.join(self.out_dir, "metrics.prom"))

    def write_trace(self) -> None:
        with self._lock:
            events = list(self._trace)
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        tmp = os.path.join(self.out_dir, ".trace.json.tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(self.out_dir, "trace.json"))

    # ---- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        if self._atexit is not None:
            atexit.unregister(self._atexit)
            self._atexit = None
        self.snapshot(final=True)
        self.emit("run_end", run=self.run)
        self.write_trace()
        with self._lock:
            self._closed = True
            self._fh.flush()
            self._fh.close()


_RECORDER: ObsRecorder | None = None


def configure(out_dir: str, run: str = "run", enabled: bool = True,
              snapshot_every: int = 0) -> ObsRecorder | None:
    """Install the process-global recorder (closing any previous one).

    ``enabled=False`` is a no-op returning None — callers thread their
    config flag straight through — it deliberately does NOT tear down a
    recorder another owner installed."""
    global _RECORDER
    if not enabled:
        return None
    if _RECORDER is not None:
        _RECORDER.close()
    _RECORDER = ObsRecorder(out_dir, run=run, snapshot_every=snapshot_every)
    return _RECORDER


def shutdown() -> None:
    """Finalize and uninstall the recorder (final snapshot, trace.json)."""
    global _RECORDER
    if _RECORDER is not None:
        _RECORDER.close()
        _RECORDER = None


def active() -> ObsRecorder | None:
    return _RECORDER


def enabled() -> bool:
    return _RECORDER is not None


def span(name: str, /, track: str | None = None, **attrs: Any):
    """A timed span: ``with span("rl.decode"): ...``. No-op when disabled.

    ``name`` is positional-only so an attribute called ``name`` stays a
    legal attr (it lands in the event as ``attr_name``)."""
    rec = _RECORDER
    if rec is None:
        return _NOOP
    return Span(rec, name, track, attrs)


def event(name: str, **fields: Any) -> None:
    """Emit one structured event into the obs stream (no-op when disabled)."""
    rec = _RECORDER
    if rec is not None:
        rec.emit(name, **fields)


def snapshot_metrics(**fields: Any) -> None:
    """Force a metrics snapshot into the stream (no-op when disabled)."""
    rec = _RECORDER
    if rec is not None:
        rec.snapshot(**fields)


def maybe_snapshot(step: int) -> None:
    """Cadenced snapshot per the recorder's configured interval."""
    rec = _RECORDER
    if rec is not None:
        rec.maybe_snapshot(step)
