"""Turn a run's obs event stream into a phase-breakdown + resilience report.

Pure stdlib over ``<run_dir>/events.jsonl`` (the :mod:`obs.span` stream) —
no jax import, so ``python -m cst_captioning_tpu.cli.obs_report`` runs
anywhere in milliseconds (scripts/lint.sh uses it as a smoke check).

Accounting model: every finished span carries its full duration AND its
*self* time (duration minus time spent in child spans on the same thread).
Grouping self-time by span name partitions the instrumented wall clock
exactly — nested spans never double count — so the phase table's totals sum
to the span-covered fraction of the run, and ``coverage`` says how much of
the measured wall clock the instrumentation explains. p50/p95/max are over
full per-span durations (the latency view); totals/percentages are over
self time (the where-did-the-time-go view). Only spans from the run's
foreground thread (the one that configured the recorder) enter the phase
table: background threads (the prefetch worker) and virtual-track windows
(the profiler trace) run CONCURRENTLY with it — they're reported in a
separate overlap section, never summed against wall clock.

The resilience summary reads the LAST metrics snapshot in the stream —
counters are cumulative, so the newest snapshot is the run total even if
the run died between cadenced snapshots. The same snapshot feeds two more
sections (PR 4):

- the phase table's **mfu** column: ``flops.<phase>`` counters (analytic
  matmul FLOPs the trainer/SCST loop accumulate per step, obs/flops.py)
  over the RUN's wall clock and the chip's assumed peak
  (``device.peak_flops`` gauge) — each row is that phase's contribution to
  run MFU, so the rows SUM to the run's overall analytic MFU. Wall clock,
  not span self-time, because device programs are dispatched async: a
  span's wall time measures the host's dispatch window, not the device
  occupancy, and dividing by it would fabricate impossible MFUs.
- the **decode early-exit** section: the ``rl.decode.depth`` histogram
  (scan steps the EOS early-exit loop actually ran per batch, observed
  host-side from the decoded tokens) against the ``rl.decode.budget``
  gauge (the T step budget) — what ``scan_until_finished`` saves per
  epoch.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Any, Iterable

EVENTS_FILE = "events.jsonl"

# per-process sub-streams of a multi-host run: process 0 writes the run dir
# itself, processes k>0 write proc<k>/ underneath it (Trainer obs wiring)
_PROC_DIR_RE = re.compile(r"^proc(\d+)$")

# canonical phase order for the table; unknown names sort after, by total
_PHASE_ORDER = (
    "setup", "xe.epoch", "xe.step", "rl.epoch", "rl.decode", "rl.reward",
    "rl.update", "rl.actor.decode", "rl.actor.broadcast", "rl.learner.step",
    "eval", "eval.pipeline.fill", "eval.pipeline.drain",
    "eval.score", "serving.admit", "serving.encode",
    "serving.stride", "serving.detok", "ckpt", "ckpt.save", "ckpt.restore",
    "dcn.collective", "degraded_rendezvous", "prefetch.stage",
    "profile.window",
)

# per-request serving phases surfaced as their own report section (the
# engine records one histogram observation per request per phase)
_SERVING_PHASES = ("queue_wait", "encode", "decode", "detok")


def load_events(run_dir: str) -> list[dict]:
    path = os.path.join(run_dir, EVENTS_FILE)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no {EVENTS_FILE} under {run_dir!r} — was the run started with "
            "train.obs enabled (or --obs)?"
        )
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn final line of a killed run
    return out


def _hist_quantile(snap: dict, q: float) -> float:
    """Bucket-interpolated quantile over a Histogram SNAPSHOT dict
    (mirrors obs.metrics.Histogram.quantile, which the report cannot call —
    it only sees the serialized {buckets, counts, sum, count, max})."""
    bounds, counts = snap.get("buckets", []), snap.get("counts", [])
    total, vmax = snap.get("count", 0), snap.get("max", 0.0)
    if not total:
        return 0.0
    rank = q * total
    seen = 0
    for i, c in enumerate(counts):
        if seen + c >= rank and c > 0:
            if i >= len(bounds):
                return vmax
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - seen) / c
            return min(lo + (hi - lo) * frac, vmax if vmax else hi)
        seen += c
    return vmax


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Exact nearest-rank-interpolated percentile over raw durations."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def build_report(events: Iterable[dict]) -> dict[str, Any]:
    """Aggregate an event stream into the report structure (JSON-ready)."""
    events = list(events)
    spans: dict[str, dict] = {}
    overlap: dict[str, dict] = {}
    t_first = t_last = None
    t_start = t_end = None
    run = ""
    main_thread: str | None = None
    last_metrics: dict | None = None
    profiler_windows = 0

    for ev in events:
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            t_first = ts if t_first is None else min(t_first, ts)
            t_last = ts if t_last is None else max(t_last, ts)
        kind = ev.get("event")
        if kind == "run_start":
            t_start = ts
            run = ev.get("run", run)
            main_thread = ev.get("thread", main_thread)
        elif kind == "run_end":
            t_end = ts
        elif kind == "metrics":
            last_metrics = ev
        elif kind == "profiler_trace_written":
            profiler_windows += 1
        elif kind == "span":
            name = str(ev.get("name", "?"))
            foreground = not ev.get("track") and (
                main_thread is None or ev.get("thread", main_thread) == main_thread
            )
            agg = (spans if foreground else overlap).setdefault(
                name, {"count": 0, "total": 0.0, "self_total": 0.0,
                       "durs": []},
            )
            dur = float(ev.get("dur", 0.0))
            agg["count"] += 1
            agg["total"] += dur
            agg["self_total"] += float(ev.get("self_dur", dur))
            agg["durs"].append(dur)

    wall = 0.0
    if t_start is not None and t_end is not None:
        wall = max(t_end - t_start, 0.0)
    elif t_first is not None and t_last is not None:
        wall = max(t_last - t_first, 0.0)

    order = {name: i for i, name in enumerate(_PHASE_ORDER)}
    counters = (last_metrics or {}).get("counters", {})
    gauges = (last_metrics or {}).get("gauges", {})
    histograms = (last_metrics or {}).get("histograms", {})
    peak = float(gauges.get("device.peak_flops", 0.0))

    def rows(groups: dict[str, dict]) -> list[dict]:
        out = []
        for name, agg in groups.items():
            durs = sorted(agg["durs"])
            flops = float(counters.get(f"flops.{name}", 0.0))
            # which FLOPs source the mfu cell reflects: the trainer/SCST/
            # serving loops publish flops.backend.<phase> = 1.0 when the
            # counter accumulates the COMPILED program's XLA cost, 0.0 for
            # the analytic matmul model (obs/flops.py); absent = the phase
            # predates the probe or never counted FLOPs
            backend = gauges.get(f"flops.backend.{name}")
            out.append({
                "phase": name,
                "count": agg["count"],
                "total_s": agg["total"],
                "self_s": agg["self_total"],
                "pct_wall": (
                    100.0 * agg["self_total"] / wall if wall > 0 else 0.0
                ),
                # this phase's contribution to run MFU: analytic FLOPs over
                # run wall x chip peak (module docstring — span wall would
                # measure the async dispatch window, not device occupancy)
                "mfu": (
                    flops / wall / peak if flops and wall > 0 and peak > 0
                    else None
                ),
                "flops_backend": (
                    None if backend is None
                    else ("compiled" if backend else "analytic")
                ),
                "p50_s": _percentile(durs, 0.50),
                "p95_s": _percentile(durs, 0.95),
                "max_s": durs[-1] if durs else 0.0,
            })
        out.sort(key=lambda p: (order.get(p["phase"], len(order)),
                                -p["self_s"]))
        return out

    phases = rows(spans)
    overlap_rows = rows(overlap)
    covered = sum(p["self_s"] for p in phases)

    depth = histograms.get("rl.decode.depth")
    decode = None
    if depth and depth.get("count"):
        budget = float(gauges.get("rl.decode.budget", 0.0))
        mean = depth["sum"] / depth["count"]
        stepped = float(counters.get("rl.decode.compaction.lanes_stepped", 0))
        skipped = float(counters.get("rl.decode.compaction.lanes_skipped", 0))
        decode = {
            "batches": depth["count"],
            "depth_mean": mean,
            "depth_p50": _hist_quantile(depth, 0.50),
            "depth_p95": _hist_quantile(depth, 0.95),
            "depth_max": depth["max"],
            "budget": budget,
            # share of the T-step budget the early exit skipped
            "saved_frac": (1.0 - mean / budget) if budget > 0 else 0.0,
            # finished-lane compaction ledger (rl.decode.compaction.*
            # counter pair, SCSTTrainer._observe_decode): lane-column steps
            # the driving loop computed vs compacted away
            "lanes_stepped": stepped,
            "lanes_skipped": skipped,
            "compaction_saved_frac": (
                skipped / (stepped + skipped) if stepped + skipped > 0
                else 0.0
            ),
        }

    # serving section (serving/engine.py): request funnel counters + the
    # per-request phase histograms (queue-wait / encode / decode / detok)
    # and the paged-bank gauges. None when the run never served.
    serving = None
    lat = histograms.get("serving.latency_seconds")
    if counters.get("serving.requests_submitted") or (
        lat and lat.get("count")
    ):
        phases_out = {}
        for name in _SERVING_PHASES:
            h = histograms.get(f"serving.{name}_seconds")
            if h and h.get("count"):
                phases_out[name] = {
                    "count": h["count"],
                    "p50_s": _hist_quantile(h, 0.50),
                    "p95_s": _hist_quantile(h, 0.95),
                    "max_s": h.get("max", 0.0),
                }
        serving = {
            "submitted": counters.get("serving.requests_submitted", 0),
            "admitted": counters.get("serving.requests_admitted", 0),
            "completed": counters.get("serving.requests_completed", 0),
            "strides": counters.get("serving.strides", 0),
            "drains": counters.get("serving.drains", 0),
            "admission_blocked_pages": counters.get(
                "serving.admission_blocked_pages", 0
            ),
            "latency_p50_s": _hist_quantile(lat, 0.50) if lat else 0.0,
            "latency_p95_s": _hist_quantile(lat, 0.95) if lat else 0.0,
            "latency_max_s": (lat or {}).get("max", 0.0),
            "phases": phases_out,
            "pages_in_use": gauges.get("serving.pages_in_use"),
            "slots_in_use": gauges.get("serving.slots_in_use"),
            "queue_depth": gauges.get("serving.queue_depth"),
            # paged in-kernel attention (ops/decode_pallas
            # .fused_decode_stride_paged): device-resident page-table
            # occupancy + encode-ahead staging depth + the HBM bytes the
            # killed dense-bank gather would have moved
            "pages": {
                "in_use": gauges.get("serving.pages.in_use"),
                "free": gauges.get("serving.pages.free"),
                "table_rows": gauges.get("serving.pages.table_rows"),
            },
            "staged": counters.get("serving.requests_staged", 0),
            "gather_bytes_avoided": counters.get(
                "serving.gather_bytes_avoided", 0
            ),
            # drain-free hot param swap (serving/engine.publish_params):
            # the active learner-param version plus applied/refused swaps
            "param_version": gauges.get("serving.param_version"),
            "param_swaps": counters.get("serving.param_swaps", 0),
            "param_swaps_refused": counters.get(
                "serving.param_swaps_refused", 0
            ),
        }
        # SLO burn-rate monitor (serving/engine.SloMonitor): rolling-window
        # attainment/burn gauges + breach/alert counters, keyed by window
        slo_windows = sorted(
            int(m.group(1)) for m in (
                re.match(r"serving\.slo\.attainment\.(\d+)s$", k)
                for k in gauges
            ) if m
        )
        if slo_windows:
            serving["slo"] = {
                "target_s": gauges.get("serving.slo.target_s"),
                "windows": {
                    w: {
                        "attainment": gauges.get(
                            f"serving.slo.attainment.{w}s"
                        ),
                        "burn_rate": gauges.get(
                            f"serving.slo.burn_rate.{w}s"
                        ),
                    }
                    for w in slo_windows
                },
                "breaches": counters.get("serving.slo.breaches", 0),
                "alerts": counters.get("serving.slo.alerts", 0),
            }

    # eval overlap ledger (eval/evaluator.py _evaluate_pipelined): per-batch
    # decode-stage and per-shard score-stage histograms plus the stage-total
    # gauges from the two-stage decode/score pipeline. None when the run
    # never ran a pipelined eval (serial evaluator, multi-host, or no eval).
    eval_sec = None
    edec = histograms.get("eval.decode_seconds")
    esc = histograms.get("eval.score_seconds")
    if (edec and edec.get("count")) or (esc and esc.get("count")):
        eval_sec = {
            "batches": counters.get("eval.batches", 0),
            "captions": counters.get("eval.captions", 0),
            "decode_total_s": gauges.get("eval.decode_total_s", 0.0),
            "score_total_s": gauges.get("eval.score_total_s", 0.0),
            "wall_s": gauges.get("eval.wall_s", 0.0),
            "decode_p50_s": _hist_quantile(edec, 0.50) if edec else 0.0,
            "decode_p95_s": _hist_quantile(edec, 0.95) if edec else 0.0,
            "score_p50_s": _hist_quantile(esc, 0.50) if esc else 0.0,
            "score_p95_s": _hist_quantile(esc, 0.95) if esc else 0.0,
            "overlap_fraction": gauges.get("eval.overlap_fraction", 0.0),
            "overlap_efficiency": gauges.get("eval.overlap_efficiency", 0.0),
            "fill_s": gauges.get("eval.pipeline.fill_s", 0.0),
            "drain_s": gauges.get("eval.pipeline.drain_s", 0.0),
        }

    # decoupled actor/learner RL (rl/async_scst.py): throughput counters,
    # host-observed occupancy gauges, and the staleness-in-updates
    # histogram. None when the run never used train.rl_topology="decoupled".
    rl_async = None
    stale = histograms.get("rl.staleness")
    if counters.get("rl.actor.batches") or counters.get("rl.learner.steps") \
            or (stale and stale.get("count")):
        rl_async = {
            "actor_batches": counters.get("rl.actor.batches", 0),
            "learner_steps": counters.get("rl.learner.steps", 0),
            "dropped_stale": counters.get("rl.staleness.dropped", 0),
            "actor_preemptions": counters.get("rl.actor.preempted", 0),
            "actor_occupancy": gauges.get("rl.actor.occupancy"),
            "learner_occupancy": gauges.get("rl.learner.occupancy"),
            "staleness_mean": (
                stale["sum"] / stale["count"]
                if stale and stale.get("count") else 0.0
            ),
            "staleness_p95": (
                _hist_quantile(stale, 0.95)
                if stale and stale.get("count") else 0.0
            ),
            "staleness_max": (stale or {}).get("max", 0.0),
        }

    resilience = {
        "nan_skips": counters.get("resilience.nan_skip", 0),
        "divergences": sum(
            v for k, v in counters.items()
            if k.startswith("resilience.divergence.")
        ),
        "rollbacks": counters.get("resilience.rollback", 0),
        "retry_attempts": counters.get("resilience.retry.attempt", 0),
        "retry_give_ups": counters.get("resilience.retry.give_up", 0),
        "ckpt_corrupt_fallbacks": counters.get("resilience.ckpt_corrupt", 0),
        "ckpt_enospc": counters.get("resilience.ckpt_enospc", 0),
        "prefetch_stalls": counters.get("resilience.prefetch_stall", 0),
        "h2d_retries": counters.get("resilience.h2d_retry", 0),
        "peer_loss_drains": counters.get("resilience.peer_loss_drain", 0),
        "degraded_continuations": counters.get(
            "resilience.degraded_continuation", 0
        ),
        "chaos_faults": counters.get("resilience.chaos_fault", 0),
        "chaos_faults_by_kind": {
            k.rsplit(".", 1)[1]: v
            for k, v in counters.items()
            if k.startswith("resilience.chaos_fault.")
        },
    }

    # elastic-health summary (resilience/health.py): heartbeat gauges + the
    # DCN-stall probe around cross-host collectives. None when the run never
    # produced a health signal (monitor off, single-host, no collectives).
    dcn = histograms.get("dcn.collective_seconds")
    health = None
    if any((
        counters.get("health.heartbeats"), counters.get("health.dcn_stall"),
        counters.get("health.peer_lost"), dcn and dcn.get("count"),
        "health.peers_alive" in gauges,
    )):
        health = {
            "heartbeats": counters.get("health.heartbeats", 0),
            "peers_alive": gauges.get("health.peers_alive"),
            "peer_age_max_s": gauges.get("health.peer_age_max_s"),
            "peer_losses": counters.get("health.peer_lost", 0),
            "dcn_stalls": counters.get("health.dcn_stall", 0),
            "collectives": dcn.get("count", 0) if dcn else 0,
            "collective_p95_s": (
                _hist_quantile(dcn, 0.95) if dcn and dcn.get("count") else 0.0
            ),
        }

    return {
        "run": run,
        "wall_s": wall,
        "covered_s": covered,
        "coverage": covered / wall if wall > 0 else 0.0,
        "complete": t_end is not None,
        "phases": phases,
        "overlap": overlap_rows,
        "decode": decode,
        "serving": serving,
        "eval": eval_sec,
        "rl_async": rl_async,
        "resilience": resilience,
        "health": health,
        "compile": {
            "count": counters.get("jit.compiles", 0),
            "seconds": counters.get("jit.compile_seconds", 0.0),
        },
        "profiler_windows": profiler_windows,
        # absolute run window (wall-clock): feeds the cross-process skew
        # attribution when per-proc streams are merged
        "t_start": t_start if t_start is not None else t_first,
        "t_end": t_end if t_end is not None else t_last,
        "events": len(events),
    }


def _fmt_s(v: float) -> str:
    return f"{v:8.3f}"


def render_report(report: dict[str, Any]) -> str:
    """Fixed-width human rendering of :func:`build_report`'s output."""
    lines: list[str] = []
    run = report["run"] or "(unnamed)"
    tail = "" if report["complete"] else "  [run did not close cleanly]"
    lines.append(f"run: {run}   wall clock: {report['wall_s']:.3f}s   "
                 f"events: {report['events']}{tail}")
    comp = report["compile"]
    if comp["count"] or comp["seconds"]:
        lines.append(
            f"jit: {int(comp['count'])} backend compile(s), "
            f"{comp['seconds']:.3f}s total compile time"
        )
    if report["profiler_windows"]:
        lines.append(f"profiler: {report['profiler_windows']} trace "
                     "window(s) captured")
    lines.append("")
    hdr = (f"{'phase':<16} {'count':>6} {'total_s':>8} {'self_s':>8} "
           f"{'%wall':>6} {'mfu':>7} {'p50_s':>8} {'p95_s':>8} {'max_s':>8}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    mfu_total = 0.0
    backends_seen = set()
    for p in report["phases"]:
        mfu = p.get("mfu")
        mfu_total += mfu or 0.0
        backend = p.get("flops_backend")
        if mfu is not None:
            # single-char FLOPs-source tag on the mfu cell: c = compiled
            # XLA cost, a = analytic model (legend below the table)
            mark = {"compiled": "c", "analytic": "a"}.get(backend, " ")
            backends_seen.add(mark.strip() or None)
            mfu_col = f"{mfu:6.4f}{mark}"
        else:
            mfu_col = " " * 7
        lines.append(
            f"{p['phase']:<16} {p['count']:>6} {_fmt_s(p['total_s'])} "
            f"{_fmt_s(p['self_s'])} {p['pct_wall']:>6.1f} {mfu_col} "
            f"{_fmt_s(p['p50_s'])} {_fmt_s(p['p95_s'])} {_fmt_s(p['max_s'])}"
        )
    lines.append("-" * len(hdr))
    lines.append(
        f"{'covered':<16} {'':>6} {'':>8} {_fmt_s(report['covered_s'])} "
        f"{100.0 * report['coverage']:>6.1f}"
        + (f" {mfu_total:7.4f}" if mfu_total else "")
    )
    if backends_seen - {None}:
        lines.append(
            "mfu flops source: c = compiled program (XLA cost analysis), "
            "a = analytic matmul model"
        )
    if report["overlap"]:
        lines.append("")
        lines.append("overlapped work (background threads / virtual tracks,"
                     " not part of the wall-clock sum):")
        for p in report["overlap"]:
            lines.append(
                f"{p['phase']:<16} {p['count']:>6} {_fmt_s(p['total_s'])} "
                f"{_fmt_s(p['self_s'])} {'':>6} "
                f"{_fmt_s(p['p50_s'])} {_fmt_s(p['p95_s'])} "
                f"{_fmt_s(p['max_s'])}"
            )
    d = report.get("decode")
    if d:
        lines.append("")
        lines.append(
            f"decode early-exit: {int(d['batches'])} batch(es), depth "
            f"p50/p95/max {d['depth_p50']:.1f}/{d['depth_p95']:.1f}/"
            f"{d['depth_max']:.0f} of budget {d['budget']:.0f} steps "
            f"(mean {d['depth_mean']:.1f} — early exit skips "
            f"{100.0 * d['saved_frac']:.1f}% of the scan budget)"
        )
        if d["lanes_stepped"] or d["lanes_skipped"]:
            lines.append(
                f"decode compaction: {int(d['lanes_stepped'])} lane-steps "
                f"computed, {int(d['lanes_skipped'])} skipped "
                f"({100.0 * d['compaction_saved_frac']:.1f}% of lane-steps "
                "compacted away)"
            )
    sv = report.get("serving")
    if sv:
        lines.append("")
        lines.append(
            f"serving: {int(sv['submitted'])} submitted, "
            f"{int(sv['admitted'])} admitted, {int(sv['completed'])} "
            f"completed over {int(sv['strides'])} stride(s); latency "
            f"p50/p95/max {sv['latency_p50_s']:.3f}/"
            f"{sv['latency_p95_s']:.3f}/{sv['latency_max_s']:.3f}s"
        )
        for name in _SERVING_PHASES:
            p = sv["phases"].get(name)
            if p:
                lines.append(
                    f"  {name:<12} {int(p['count']):>6} req(s)  p50 "
                    f"{p['p50_s']:.4f}s  p95 {p['p95_s']:.4f}s  max "
                    f"{p['max_s']:.4f}s"
                )
        slo = sv.get("slo")
        if slo:
            target = slo.get("target_s")
            win_bits = "   ".join(
                f"{w}s: {100.0 * (v['attainment'] or 0.0):.1f}% "
                f"(burn {v['burn_rate'] or 0.0:.1f}x)"
                for w, v in sorted(slo["windows"].items())
            )
            lines.append(
                "  slo"
                + (f" (target {target:.3f}s):" if target else ":")
                + f" {win_bits}   breaches: {int(slo['breaches'])}   "
                f"alerts: {int(slo['alerts'])}"
            )
        bits = []
        if sv["drains"]:
            bits.append(f"drains: {int(sv['drains'])}")
        if sv["admission_blocked_pages"]:
            bits.append(
                "page backpressure: "
                f"{int(sv['admission_blocked_pages'])} blocked admission(s)"
            )
        if sv.get("pages_in_use") is not None:
            bits.append(f"pages in use: {int(sv['pages_in_use'])}")
        pg = sv.get("pages") or {}
        if pg.get("in_use") is not None or pg.get("free") is not None:
            bits.append(
                f"page table: {int(pg.get('in_use') or 0)} in use / "
                f"{int(pg.get('free') or 0)} free over "
                f"{int(pg.get('table_rows') or 0)} row(s)"
            )
        if sv.get("staged"):
            bits.append(f"staged admissions: {int(sv['staged'])}")
        if sv.get("gather_bytes_avoided"):
            bits.append(
                "gather bytes avoided: "
                f"{sv['gather_bytes_avoided'] / 2**20:.1f} MiB"
            )
        if sv.get("param_swaps") or sv.get("param_swaps_refused"):
            bits.append(
                f"param swaps: {int(sv['param_swaps'])} applied"
                + (
                    f" / {int(sv['param_swaps_refused'])} refused"
                    if sv.get("param_swaps_refused") else ""
                )
                + (
                    f" (active v{int(sv['param_version'])})"
                    if sv.get("param_version") is not None else ""
                )
            )
        if bits:
            lines.append("  " + "   ".join(bits))
    ev = report.get("eval")
    if ev:
        lines.append("")
        lines.append(
            f"eval pipeline: {int(ev['batches'])} batch(es), "
            f"{int(ev['captions'])} caption(s); stage totals decode "
            f"{ev['decode_total_s']:.3f}s / score {ev['score_total_s']:.3f}s "
            f"over {ev['wall_s']:.3f}s wall"
        )
        lines.append(
            f"  decode p50/p95 {ev['decode_p50_s']:.4f}/"
            f"{ev['decode_p95_s']:.4f}s   score p50/p95 "
            f"{ev['score_p50_s']:.4f}/{ev['score_p95_s']:.4f}s"
        )
        lines.append(
            f"  overlap: {100.0 * ev['overlap_fraction']:.1f}% of scoring "
            f"hidden under decode (efficiency "
            f"{100.0 * ev['overlap_efficiency']:.1f}% of the hideable "
            f"stage)   fill {ev['fill_s']:.3f}s   drain {ev['drain_s']:.3f}s"
        )
    ra = report.get("rl_async")
    if ra:
        lines.append("")
        occ_bits = "   ".join(
            f"{role} occupancy {100.0 * v:.1f}%"
            for role, v in (
                ("actor", ra.get("actor_occupancy")),
                ("learner", ra.get("learner_occupancy")),
            )
            if v is not None
        )
        lines.append(
            f"actor/learner: {int(ra['actor_batches'])} rollout batch(es) "
            f"decoded, {int(ra['learner_steps'])} learner step(s)"
            + (f"   {occ_bits}" if occ_bits else "")
        )
        lines.append(
            f"  staleness (updates): mean {ra['staleness_mean']:.2f}   "
            f"p95 {ra['staleness_p95']:.2f}   max "
            f"{ra['staleness_max']:.0f}   dropped+recounted: "
            f"{int(ra['dropped_stale'])}   actor preemptions: "
            f"{int(ra['actor_preemptions'])}"
        )
    r = report["resilience"]
    lines.append("")
    lines.append("resilience:")
    lines.append(
        f"  nan-skips: {int(r['nan_skips'])}   divergences: "
        f"{int(r['divergences'])}   rollbacks: {int(r['rollbacks'])}"
    )
    lines.append(
        f"  retries: {int(r['retry_attempts'])} attempt(s), "
        f"{int(r['retry_give_ups'])} give-up(s)   ckpt-corrupt fallbacks: "
        f"{int(r['ckpt_corrupt_fallbacks'])}"
    )
    elastic_bits = []
    for key, label in (
        ("peer_loss_drains", "peer-loss drains"),
        ("degraded_continuations", "degraded continuations"),
        ("ckpt_enospc", "ckpt ENOSPC reclaims"),
        ("prefetch_stalls", "prefetch stalls"),
        ("h2d_retries", "h2d retries"),
    ):
        if r.get(key):
            elastic_bits.append(f"{label}: {int(r[key])}")
    if elastic_bits:
        lines.append("  " + "   ".join(elastic_bits))
    by_kind = r["chaos_faults_by_kind"]
    kinds = (
        " (" + ", ".join(f"{k}={int(v)}" for k, v in sorted(by_kind.items()))
        + ")" if by_kind else ""
    )
    lines.append(f"  chaos faults injected: {int(r['chaos_faults'])}{kinds}")
    h = report.get("health")
    if h:
        lines.append("")
        alive = h.get("peers_alive")
        lines.append(
            "health: "
            f"{int(h['heartbeats'])} heartbeat(s)"
            + (f", {int(alive)} peer(s) alive" if alive is not None else "")
            + f", {int(h['peer_losses'])} peer loss(es); "
            f"dcn: {int(h['collectives'])} collective(s), "
            f"p95 {h['collective_p95_s']:.3f}s, "
            f"{int(h['dcn_stalls'])} stall(s)"
        )
    if report.get("hosts"):
        c = report["cluster"]
        lines.append("")
        lines.append(
            f"cluster: {c['processes']} process streams merged — max end "
            f"skew {c['max_end_skew_s']:.3f}s (straggler: proc"
            f"{c['straggler_proc']}); totals: {int(c['chaos_faults'])} chaos "
            f"fault(s), {int(c['dcn_stalls'])} dcn stall(s), "
            f"{int(c['peer_losses'])} peer loss(es)"
        )
        hdr2 = (f"{'proc':>5} {'events':>7} {'wall_s':>8} {'start+':>8} "
                f"{'end+':>8} {'top phase':<16} {'self_s':>8}")
        lines.append(hdr2)
        lines.append("-" * len(hdr2))
        for host in report["hosts"]:
            lines.append(
                f"{host['proc']:>5} {host['events']:>7} "
                f"{_fmt_s(host['wall_s'])} {_fmt_s(host['start_skew_s'])} "
                f"{_fmt_s(host['end_skew_s'])} {host['top_phase']:<16} "
                f"{_fmt_s(host['top_phase_self_s'])}"
            )
    return "\n".join(lines)


def _merge_proc_reports(report: dict[str, Any],
                        procs: list[tuple[int, dict[str, Any]]]) -> None:
    """Fold per-process sub-reports into the primary report: a ``hosts``
    table with per-host skew attribution (who started late, who finished
    last, where that host's time went) and cluster-total resilience/health
    counts. ``procs`` includes process 0 (the primary stream)."""
    ends = [r["t_end"] for _, r in procs if r["t_end"] is not None]
    starts = [r["t_start"] for _, r in procs if r["t_start"] is not None]
    t0 = min(starts) if starts else None
    t_end_min = min(ends) if ends else None
    hosts = []
    for proc, rep in procs:
        top_phase, top_self = "", 0.0
        for p in rep["phases"]:
            if p["self_s"] > top_self:
                top_phase, top_self = p["phase"], p["self_s"]
        hosts.append({
            "proc": proc,
            "events": rep["events"],
            "wall_s": rep["wall_s"],
            "complete": rep["complete"],
            # skew attribution: how late this host started, and how long
            # the earliest-finishing host would have waited on it at the
            # final barrier — the per-host "who is the straggler" answer
            "start_skew_s": (
                rep["t_start"] - t0
                if t0 is not None and rep["t_start"] is not None else 0.0
            ),
            "end_skew_s": (
                rep["t_end"] - t_end_min
                if t_end_min is not None and rep["t_end"] is not None
                else 0.0
            ),
            "top_phase": top_phase,
            "top_phase_self_s": top_self,
            "chaos_faults": rep["resilience"]["chaos_faults"],
            "dcn_stalls": (rep.get("health") or {}).get("dcn_stalls", 0),
        })
    straggler = max(hosts, key=lambda h: h["end_skew_s"])
    report["hosts"] = hosts
    report["cluster"] = {
        "processes": len(hosts),
        "max_end_skew_s": straggler["end_skew_s"],
        "straggler_proc": straggler["proc"],
        # cluster totals: per-process counters are per-host streams, so the
        # cluster view is their SUM (the primary table stays process 0's)
        "chaos_faults": sum(h["chaos_faults"] for h in hosts),
        "dcn_stalls": sum(h["dcn_stalls"] for h in hosts),
        "peer_losses": sum(
            (r.get("health") or {}).get("peer_losses", 0) for _, r in procs
        ),
        "heartbeats": sum(
            (r.get("health") or {}).get("heartbeats", 0) for _, r in procs
        ),
    }


def report_run(run_dir: str) -> dict[str, Any]:
    """Load + aggregate one run dir (the CLI's single entry point).

    Multi-host runs leave one stream per process (process 0 in ``run_dir``
    itself, process k in ``run_dir/proc<k>/``); every stream is merged into
    the ``hosts``/``cluster`` sections with per-host skew attribution."""
    report = build_report(load_events(run_dir))
    procs: list[tuple[int, dict[str, Any]]] = [(0, report)]
    for entry in sorted(os.listdir(run_dir)):
        m = _PROC_DIR_RE.match(entry)
        if m and os.path.exists(os.path.join(run_dir, entry, EVENTS_FILE)):
            procs.append((
                int(m.group(1)),
                build_report(load_events(os.path.join(run_dir, entry))),
            ))
    if len(procs) > 1:
        procs.sort()
        _merge_proc_reports(report, procs)
    return report


# ---- postmortem bundles (obs/recorder.py) -----------------------------------

# the ring-record bookkeeping keys; everything else in a record is a metric
_RING_META_KEYS = ("step", "phase", "ts", "probe", "anomalies")


def _verify_bundle(bundle_dir: str) -> tuple[bool, list[str]]:
    """Inline sha256/size check against the bundle's ``manifest.json``.

    Reimplements ``resilience.durable.verify_manifest`` on purpose: this
    module must stay importable without jax, and ``resilience.__init__``
    pulls jax in through the sentinel. Returns ``(verified, problems)`` —
    no manifest is reported as unverified, not as an error (the bundle may
    predate the manifest machinery or be mid-write)."""
    mpath = os.path.join(bundle_dir, "manifest.json")
    if not os.path.exists(mpath):
        return False, ["no manifest.json (bundle unverifiable)"]
    try:
        with open(mpath, encoding="utf-8") as f:
            files = json.load(f)["files"]
    except (ValueError, KeyError, OSError) as e:
        return False, [f"unreadable manifest: {e}"]
    problems: list[str] = []
    for name, meta in files.items():
        fpath = os.path.join(bundle_dir, name)
        if not os.path.exists(fpath):
            problems.append(f"{name}: missing")
            continue
        size = os.path.getsize(fpath)
        if size != int(meta["size"]):
            problems.append(f"{name}: size {size} != {meta['size']}")
            continue
        h = hashlib.sha256()
        with open(fpath, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != meta["sha256"]:
            problems.append(f"{name}: sha256 mismatch")
    return not problems, problems


def load_postmortem(bundle_dir: str) -> dict[str, Any]:
    """Load a flight-recorder postmortem bundle into a render-ready dict."""
    meta_path = os.path.join(bundle_dir, "meta.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(
            f"no meta.json under {bundle_dir!r} — is this a "
            "flight-recorder postmortem bundle (obs/recorder.py)?"
        )
    verified, problems = _verify_bundle(bundle_dir)
    with open(meta_path, encoding="utf-8") as f:
        meta = json.load(f)
    ring: list[dict] = []
    ring_path = os.path.join(bundle_dir, "ring.jsonl")
    if os.path.exists(ring_path):
        with open(ring_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        ring.append(json.loads(line))
                    except ValueError:
                        continue  # torn line of a crash-time dump
    registry: dict = {}
    reg_path = os.path.join(bundle_dir, "registry.json")
    if os.path.exists(reg_path):
        try:
            with open(reg_path, encoding="utf-8") as f:
                registry = json.load(f)
        except ValueError:
            pass
    events_tail = 0
    tail_path = os.path.join(bundle_dir, "events_tail.jsonl")
    if os.path.exists(tail_path):
        with open(tail_path, "rb") as f:
            events_tail = sum(1 for line in f if line.strip())
    return {
        "bundle": bundle_dir,
        "meta": meta,
        "ring": ring,
        "registry": registry,
        "events_tail_lines": events_tail,
        "verified": verified,
        "problems": problems,
    }


def render_postmortem(pm: dict[str, Any]) -> str:
    """Human rendering of :func:`load_postmortem`: the trip header, then the
    ring as a step timeline with anomaly verdicts inline."""
    meta = pm["meta"]
    ring = pm["ring"]
    lines: list[str] = []
    # schema-2 bundles (obs/recorder.py) carry host identity; schema-1 ones
    # predate it and render without the proc/host tag
    ident = ""
    if "proc" in meta:
        ident = (
            f"   proc: {meta['proc']}/{meta.get('world', '?')}"
            f" ({meta.get('host', '?')})"
        )
    lines.append(
        f"postmortem: {meta.get('reason', '?')}   run: "
        f"{meta.get('run', '?')}{ident}   bundle: {pm['bundle']}"
    )
    trip = {
        k: v for k, v in meta.items()
        if k not in ("schema", "reason", "run", "capacity", "steps",
                     "dumped_ts", "proc", "world", "host", "anchors",
                     "flush_error")
    }
    if trip:
        lines.append(
            "trip: " + "   ".join(f"{k}={v}" for k, v in sorted(trip.items()))
        )
    if meta.get("flush_error"):
        # the dump-time flush failing IS evidence (the ring predates the
        # trip by one flush) — front and center, not buried in raw meta
        lines.append(
            f"FLUSH FAILED at dump time: {meta['flush_error']} — ring below "
            "is stale by up to one flush interval"
        )
    if ring:
        lines.append(
            f"ring: {len(ring)} step(s) of {meta.get('capacity', '?')} "
            f"(steps {ring[0]['step']}..{ring[-1]['step']})"
        )
    else:
        lines.append("ring: empty (tripped before any recorded step)")
    lines.append(
        "integrity: "
        + ("manifest verified (sha256)" if pm["verified"] else
           "NOT verified — " + "; ".join(pm["problems"]))
    )
    counters = (pm.get("registry") or {}).get("counters", {})
    anomaly_counts = {
        k.rsplit(".", 1)[1]: v for k, v in counters.items()
        if k.startswith("obs.anomaly.")
    }
    if anomaly_counts:
        lines.append(
            "anomalies (run totals): " + ", ".join(
                f"{k}={int(v)}" for k, v in sorted(anomaly_counts.items())
            )
        )
    if pm["events_tail_lines"]:
        lines.append(f"events tail: {pm['events_tail_lines']} line(s)")
    if not ring:
        return "\n".join(lines)

    # timeline: one row per ring record, the trip-relevant scalars first,
    # anomaly verdicts flagged inline
    lines.append("")
    t0 = ring[0].get("ts")
    hdr = (f"{'step':>6} {'phase':<4} {'t+s':>8} {'loss':>10} "
           f"{'grad_norm':>10} {'reward':>8}  anomalies / extras")
    lines.append(hdr)
    lines.append("-" * len(hdr))

    def num(rec, *keys):
        for k in keys:
            v = rec.get(k)
            if isinstance(v, (int, float)):
                return v
        return None

    def cell(v, width, prec=4):
        return f"{v:>{width}.{prec}g}" if v is not None else " " * width

    for rec in ring:
        dt = (rec["ts"] - t0) if (t0 is not None and "ts" in rec) else None
        anomalies = rec.get("anomalies") or []
        extras = []
        ent = num(rec, "sample_entropy")
        if ent is not None:
            extras.append(f"entropy={ent:.2f}")
        upd = num(rec, "upd_ratio/global")
        if upd is not None:
            extras.append(f"upd={upd:.2e}")
        flag = (" <-- " + ",".join(anomalies)) if anomalies else ""
        tail = "  ".join(extras)
        lines.append(
            f"{rec.get('step', '?'):>6} {rec.get('phase', ''):<4} "
            f"{cell(dt, 8, 3)} {cell(num(rec, 'loss', 'rl_loss'), 10)} "
            f"{cell(num(rec, 'grad_norm'), 10)} "
            f"{cell(num(rec, 'reward_mean'), 8)}  {tail}{flag}"
        )
    probe = ring[-1].get("probe")
    if probe:
        lines.append("")
        lines.append(
            "last probe: " + "   ".join(
                f"{k}={v:g}" for k, v in sorted(probe.items())
            )
        )
    return "\n".join(lines)
