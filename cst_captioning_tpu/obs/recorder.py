"""Black-box flight recorder: a ring of per-step records + postmortem bundles.

The obs event stream answers *where the time went*; the flight recorder
answers *what training looked like right before it died*. It keeps the last
``capacity`` steps' structured records (loss, grad-norm, per-family update
ratios, reward/advantage stats, sampled-lane entropy, comms/compaction/health
probes, anomaly verdicts) in a fixed-size ring, and on any trip — sentinel
divergence, rollback, chaos fault, SIGTERM/peer-loss drain, atexit crash —
dumps the ring plus its context as a durable **postmortem bundle** that
``cli.obs_report --postmortem`` renders as a step-by-step timeline.

Hot-path contract (graftlint GL001/GL013):

- :meth:`FlightRecorder.record` buffers the step's *device* scalars as-is —
  no ``float()``, no ``device_get``, one host ``perf_counter`` read; the
  step loops stay zero-sync.
- :meth:`FlightRecorder.flush` performs ONE ``jax.device_get`` over
  everything buffered (the sentinel's batched-readback pattern) on the
  existing ``log_every_steps`` / sentinel-flush cadence, finalizes the ring
  records, and feeds the anomaly detector (:mod:`obs.anomaly`).
- Everything is a no-op when no recorder is configured (``train.obs`` off or
  ``train.recorder_steps == 0``).

Bundle layout (all files manifest-checksummed via :mod:`resilience.durable`,
written tmp-dir-then-rename like a checkpoint)::

    postmortem_<n>_<reason>/
      ring.jsonl         one JSON line per ring record, oldest first
      registry.json      full metrics-registry snapshot at dump time
      events_tail.jsonl  last lines of the live obs event stream
      config.json        the run's resolved config (as configured)
      meta.json          reason, trip fields, ring coverage, schema version
      manifest.json      sha256 + size per file (durable.write_manifest)

jax and :mod:`resilience.durable` are imported lazily (flush/dump time): the
module itself stays importable from jax-free contexts (the chaos harness
hooks in from the prefetch thread; ``cli.obs_report`` never pulls it in).
"""

from __future__ import annotations

import atexit
import json
import os
import re
import socket
import sys
import threading
import time
from collections import deque
from typing import Any, Callable

from cst_captioning_tpu.obs import metrics as _metrics
# name imports, not `obs import span`: the obs package re-exports the span()
# context-manager FUNCTION under that name, shadowing the submodule
from cst_captioning_tpu.obs.span import (
    active as _span_active,
    event as _span_event,
    wall_time as _wall_time,
)

# registry metrics attached to every flush batch as the records' ``probe``
# field: the host-side run state a postmortem wants next to the step scalars
_PROBE_GAUGES = (
    "comm.bytes_on_wire", "comm.buckets", "health.peers_alive",
    "health.peer_age_max_s", "serving.slo.burn_rate.60s",
    "serving.param_version",
    "serving.pages.in_use", "serving.pages.free",
    "serving.pages.table_rows",
    "rl.actor.occupancy", "rl.learner.occupancy",
)
_PROBE_COUNTERS = (
    "rl.decode.compaction.lanes_stepped",
    "rl.decode.compaction.lanes_skipped",
    "rl.staleness.dropped", "rl.actor.preempted",
    "resilience.nan_skip", "resilience.rollback", "resilience.chaos_fault",
    "health.peer_lost",
    "resilience.regrow.attempts", "resilience.regrow.admitted",
    "resilience.regrow.refused",
    "serving.gather_bytes_avoided",
)

_EVENTS_TAIL_LINES = 200

# monotonic<->wall anchor pairs kept per recorder: one at start plus one per
# flush, capped so a long run's meta.json stays small (the last slot keeps
# sliding forward, so the newest anchor always brackets the newest records)
_MAX_ANCHORS = 256

# bundle meta schema history:
#   1 — PR 12: reason/run/capacity/steps/dumped_ts
#   2 — this PR: + proc/world/host identity and ``anchors`` (the fleet merge
#       in obs/fleet.py uses them to put N rings on one corrected timeline;
#       schema-1 bundles still merge, with ``skew="unknown"``)
_META_SCHEMA = 2


def _sanitize(reason: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", reason)[:64] or "unknown"


class FlightRecorder:
    """Ring buffer of per-step records + the postmortem dump machinery."""

    def __init__(self, capacity: int, out_dir: str, run: str = "run",
                 detector=None, config: dict | None = None,
                 max_dumps: int = 4,
                 probe: Callable[[], dict] | None = None,
                 proc: int = 0, world: int = 1, host: str = ""):
        if capacity < 1:
            raise ValueError(f"recorder capacity {capacity} must be >= 1")
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.run = run
        self.detector = detector
        self.config = config or {}
        self.max_dumps = max_dumps
        self.probe = probe
        # host identity: which process/host this ring belongs to, so the
        # fleet merge can name hosts instead of bundle paths
        self.proc = int(proc)
        self.world = max(int(world), 1)
        self.host = host or socket.gethostname()
        self.ring: deque[dict] = deque(maxlen=capacity)
        self._buf: list[tuple[int, str, Any, float]] = []
        self._lock = threading.Lock()
        self._dumps = 0
        self._last_t: float | None = None
        self._closed = False
        # perf_counter -> wall-clock mapping fixed at configure time: records
        # get absolute timestamps without a wall-clock read per step
        self._pc0 = time.perf_counter()
        self._wall0 = _wall_time()
        # anchors pair the monotonic clock with the wall clock at start and
        # at each flush; the fleet merge maps ring ``ts`` (derived from the
        # start anchor alone) through the freshest bracket, so NTP steps or
        # wall-clock drift during the run don't corrupt cross-host alignment
        self._anchors: list[tuple[float, float]] = [(self._pc0, self._wall0)]
        self._atexit = self._crash_dump
        atexit.register(self._atexit)

    # ---- hot path -----------------------------------------------------------

    def record(self, step: int, phase: str, scalars: dict) -> None:
        """Buffer one step's scalars (device arrays and/or host floats) —
        zero-sync: values are not read here, only held until :meth:`flush`."""
        t = time.perf_counter()
        with self._lock:
            self._buf.append((step, phase, scalars, t))

    def flush(self) -> None:
        """ONE host readback for every buffered step, then ring + anomaly
        finalization. Safe from any thread; no-op when nothing is buffered."""
        with self._lock:
            buf, self._buf = self._buf, []
        if not buf:
            return
        import jax  # lazy: keep the module importable jax-free

        values = jax.device_get([scalars for _, _, scalars, _ in buf])
        probe = self._probe()
        anchor = (time.perf_counter(), _wall_time())
        with self._lock:
            if len(self._anchors) < _MAX_ANCHORS:
                self._anchors.append(anchor)
            else:
                self._anchors[-1] = anchor
            for (step, phase, _, t), vals in zip(buf, values):
                rec: dict[str, Any] = {
                    "step": int(step),
                    "phase": phase,
                    "ts": self._wall0 + (t - self._pc0),
                }
                for k, v in vals.items():
                    rec[k] = float(v)
                if probe:
                    rec["probe"] = probe
                rec["anomalies"] = self._judge(rec, t)
                self.ring.append(rec)

    def _judge(self, rec: dict, t: float) -> list[str]:
        last_t, self._last_t = self._last_t, t
        det = self.detector
        if det is None:
            return []
        step, phase = rec["step"], rec["phase"]
        out: list[str] = []
        loss = rec.get("loss", rec.get("rl_loss"))
        if loss is not None:
            out += det.observe("loss", loss, step=step, phase=phase)
        if "grad_norm" in rec:
            out += det.observe("grad_norm", rec["grad_norm"], step=step,
                               phase=phase)
        if "reward_mean" in rec:
            out += det.observe("reward", rec["reward_mean"], step=step,
                               phase=phase)
        if last_t is not None:
            gap = t - last_t
            out += det.observe("step_time", gap, step=step, phase=phase)
            out += det.observe_gap(gap, step=step, phase=phase)
        # dedupe, order-preserving: loss AND grad_norm going non-finite on
        # the same step is one verdict, not two
        return list(dict.fromkeys(out))

    def _probe(self) -> dict:
        if self.probe is not None:
            try:
                return dict(self.probe())
            except Exception:
                return {}
        out: dict[str, float] = {}
        snap = _metrics.snapshot()
        for name in _PROBE_GAUGES:
            v = snap["gauges"].get(name)
            if v is not None:
                out[name] = float(v)
        for name in _PROBE_COUNTERS:
            v = snap["counters"].get(name)
            if v is not None:
                out[name] = float(v)
        return out

    # ---- postmortem bundles -------------------------------------------------

    def postmortem(self, reason: str, *,
                   registry_extra: dict | None = None,
                   **fields: Any) -> str | None:
        """Flush, then dump the ring + context as a durable bundle.

        ``registry_extra`` merges extra top-level blocks into the bundle's
        ``registry.json`` (the serving drain rides its SLO snapshot along
        this way). Returns the bundle directory, or ``None`` when the
        per-process dump budget (``max_dumps``) is spent — a run stuck in a
        divergence loop must not fill the disk with identical bundles."""
        flush_error = ""
        try:
            self.flush()
        except Exception as e:
            # a dying process still gets the already-flushed ring; the
            # failure itself is evidence and rides along in meta.json
            flush_error = f"{type(e).__name__}: {e}"
        with self._lock:
            if self._dumps >= self.max_dumps:
                self._budget_gauge()
                return None
            self._dumps += 1
            n = self._dumps
            ring = list(self.ring)
            anchors = list(self._anchors)
        self._budget_gauge()
        # lazy: resilience.__init__ pulls jax via the sentinel; only dump
        # paths (never import time) pay that
        from cst_captioning_tpu.resilience import durable

        name = f"postmortem_{n:02d}_{_sanitize(reason)}"
        final = os.path.join(self.out_dir, name)
        tmp = final + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        meta = {
            "schema": _META_SCHEMA,
            "reason": reason,
            "run": self.run,
            "proc": self.proc,
            "world": self.world,
            "host": self.host,
            "capacity": self.ring.maxlen,
            "steps": [r["step"] for r in ring],
            "anchors": [[pc, wall] for pc, wall in anchors],
            "dumped_ts": _wall_time(),
            **fields,
        }
        if flush_error:
            meta["flush_error"] = flush_error
        registry = _metrics.snapshot()
        if registry_extra:
            registry = {**registry, **registry_extra}
        blobs = {
            "ring.jsonl": "".join(
                json.dumps(r, default=float) + "\n" for r in ring
            ).encode(),
            "registry.json": json.dumps(
                registry, default=float, indent=2
            ).encode(),
            "events_tail.jsonl": self._events_tail(),
            "config.json": json.dumps(
                self.config, default=str, indent=2
            ).encode(),
            "meta.json": json.dumps(meta, default=float, indent=2).encode(),
        }
        for fname, blob in blobs.items():
            durable.write_bytes_durable(os.path.join(tmp, fname), blob)
        durable.write_manifest(tmp, blobs)
        durable.fsync_dir(tmp)
        if os.path.exists(final):  # stale bundle from a prior run: keep ours
            final = final + "_" + str(int(meta["dumped_ts"]))
        os.replace(tmp, final)
        durable.fsync_dir(self.out_dir)
        _span_event("postmortem", reason=reason, bundle=final,
                    steps=len(ring))
        return final

    def _budget_gauge(self) -> None:
        """Export the remaining dump budget — an exhausted budget means later
        trips leave no bundle, which a dashboard should show *before* the
        postmortem someone goes looking for turns out not to exist. Only the
        process-global recorder owns the gauge; ephemeral recorders (serving
        drains without obs configured) must not clobber it."""
        if _FLIGHT is self:
            left = max(self.max_dumps - self._dumps, 0)
            _metrics.gauge("obs.recorder.dump_budget").set(float(left))

    def _events_tail(self) -> bytes:
        """Last lines of the live obs event stream (line-buffered on disk, so
        this is current up to the latest emit)."""
        rec = _span_active()
        if rec is None:
            return b""
        path = os.path.join(rec.out_dir, "events.jsonl")
        try:
            with open(path, "rb") as f:
                lines = f.readlines()
        except OSError:
            return b""
        return b"".join(lines[-_EVENTS_TAIL_LINES:])

    # ---- lifecycle ----------------------------------------------------------

    def _crash_dump(self) -> None:
        """atexit hook: a process that never reached :meth:`close` died with
        work in flight — dump what the ring holds."""
        try:
            self.postmortem("atexit_crash")
        except Exception as e:
            # interpreter teardown: the event stream may already be closed,
            # stderr is the only sink left standing
            sys.stderr.write(f"flight-recorder: atexit dump failed: {e}\n")

    def close(self) -> None:
        """Clean shutdown: final flush, no dump, atexit hook disarmed."""
        if self._closed:
            return
        self._closed = True
        if self._atexit is not None:
            atexit.unregister(self._atexit)
            self._atexit = None
        try:
            self.flush()
        except Exception as e:
            sys.stderr.write(f"flight-recorder: final flush failed: {e}\n")


# ---- process-global wiring (mirrors obs.span's configure/active) ------------

_FLIGHT: FlightRecorder | None = None


def configure(capacity: int, out_dir: str, run: str = "run", detector=None,
              config: dict | None = None, max_dumps: int = 4,
              probe: Callable[[], dict] | None = None,
              proc: int = 0, world: int = 1,
              host: str = "") -> FlightRecorder:
    """Install the process-global flight recorder (closing any previous)."""
    global _FLIGHT
    if _FLIGHT is not None:
        _FLIGHT.close()
    _FLIGHT = FlightRecorder(capacity, out_dir, run=run, detector=detector,
                             config=config, max_dumps=max_dumps, probe=probe,
                             proc=proc, world=world, host=host)
    _FLIGHT._budget_gauge()
    return _FLIGHT


def shutdown() -> None:
    """Cleanly close and uninstall the recorder (no crash dump)."""
    global _FLIGHT
    if _FLIGHT is not None:
        _FLIGHT.close()
        _FLIGHT = None


def active() -> FlightRecorder | None:
    return _FLIGHT


def record(step: int, phase: str, scalars: dict) -> None:
    """Buffer one step's scalars on the global recorder (no-op when off)."""
    fr = _FLIGHT
    if fr is not None:
        fr.record(step, phase, scalars)


def flush() -> None:
    fr = _FLIGHT
    if fr is not None:
        fr.flush()


def postmortem(reason: str, *, registry_extra: dict | None = None,
               **fields: Any) -> str | None:
    fr = _FLIGHT
    if fr is not None:
        return fr.postmortem(reason, registry_extra=registry_extra, **fields)
    return None


def note_fault(point: str, kind: str, visit: int, **fields: Any) -> None:
    """Chaos-harness hook (lazy-imported from resilience/chaos.py): an
    injected fault is a trip — capture the ring as it was when the fault
    fired, before its consequences land. Extra ``fields`` (e.g. the victim
    ``host`` of a ``partial_preempt``) ride into the bundle's meta so the
    fleet merge can name the victim."""
    fr = _FLIGHT
    if fr is not None:
        fr.postmortem(f"chaos_{kind}", point=point, visit=visit, **fields)
