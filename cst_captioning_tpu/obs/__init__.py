"""Unified observability: tracing spans, metrics registry, run reports.

The subsystem threaded through trainer/SCST/evaluator/prefetch/ckpt/
resilience (README "Observability"):

- :mod:`obs.span`    — nested wall-clock spans + the run recorder
  (``events.jsonl``, Perfetto ``trace.json``); ``obs.span("rl.decode")`` is
  a no-op identity check when no recorder is configured.
- :mod:`obs.metrics` — process-wide counters/gauges/histograms, snapshotted
  into the event stream on the ``train.log_every_steps`` cadence and
  exported as a Prometheus textfile.
- :mod:`obs.report`  — aggregates a run dir into the phase-breakdown +
  resilience report behind ``python -m cst_captioning_tpu.cli.obs_report``.
- :mod:`obs.recorder` — the training-dynamics flight recorder: a ring of
  per-step records flushed with one batched readback, dumped as a durable
  postmortem bundle when a run trips (README "Observability").
- :mod:`obs.anomaly` — online EWMA z-score + stall anomaly detection over
  the recorder's streams; every producer (recorder, divergence sentinel,
  serving SLO monitor) reports through ``anomaly.record_anomaly`` so the
  ``anomaly`` events and ``obs.anomaly.<kind>`` counters share one spelling.

Stdlib-only at import time (jax is touched lazily, for the optional
device-memory gauges and the jax.monitoring compile listener), and
zero-sync by construction: nothing in here reads a device value.
"""

from cst_captioning_tpu.obs.metrics import (
    REGISTRY,
    StepMeter,
    counter,
    gauge,
    histogram,
    snapshot,
)
from cst_captioning_tpu.obs.span import (
    ObsRecorder,
    Span,
    active,
    configure,
    enabled,
    event,
    maybe_snapshot,
    set_context,
    shutdown,
    snapshot_metrics,
    span,
    wall_time,
)

__all__ = [
    "REGISTRY",
    "ObsRecorder",
    "Span",
    "StepMeter",
    "active",
    "configure",
    "counter",
    "enabled",
    "event",
    "gauge",
    "histogram",
    "maybe_snapshot",
    "set_context",
    "shutdown",
    "snapshot",
    "snapshot_metrics",
    "span",
    "wall_time",
]
