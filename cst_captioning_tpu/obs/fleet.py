"""Fleet-wide postmortem forensics: N per-process flight-recorder bundles
merged onto ONE skew-corrected timeline.

A pod-scale run fails as a *fleet*: the host that tripped first, the
straggler whose lag wedged the collective, and the DCN stall that preceded
the drain live in N different postmortem bundles (process 0's in the obs
run dir, process k's under ``proc<k>/`` — the same layout as the span
streams). :func:`merge_bundles` turns them into one verified forensic:

- **verify** — every bundle's ``manifest.json`` is checked (sha256 + size,
  via :func:`obs.report._verify_bundle`); a tampered/truncated bundle is
  *excluded and reported*, never silently merged.
- **anchor** — schema-2 bundles (obs/recorder.py) carry monotonic↔wall
  anchor pairs stamped at recorder start and each flush; ring timestamps
  (derived from the start anchor alone) are re-mapped through the full
  anchor table, so wall-clock steps (NTP) during the run don't corrupt
  alignment. Schema-1 bundles merge with ``skew="unknown"``.
- **align** — rings join on the global ``(phase, step)`` key; each proc's
  clock offset against the reference proc is the *median* of per-key
  timestamp deltas (robust to the odd late row), mirroring the ``proc<k>``
  skew model ``obs/report.py`` applies to span streams.
- **attribute** — the trip is the first record (in corrected time) carrying
  a nonfinite/anomaly verdict; per-step cross-host lag names the straggler;
  ``dcn_stall`` / ``anomaly`` / drain events from each bundle's
  ``events_tail`` interleave at corrected times; ``lost`` /
  ``victim_host`` meta from peer-loss and chaos bundles name the victim;
  ``mesh_shrink`` / ``mesh_regrow`` events pair into an **elastic**
  section of shrink→regrow arcs, each naming its victim/rejoiner host
  and generation span (``regrow_refused`` marks failed attempts).
- **degrade** — a proc with no bundle at all (it died before its first
  dump, or its filesystem went with it) yields an explicit
  ``missing_procs`` entry; the survivors still merge.

Pure stdlib on top of :mod:`obs.report` — no jax import, so
``cli.obs_report --postmortem <run_dir>`` renders a fleet forensic from
any machine (scripts/lint.sh pins this).
"""

from __future__ import annotations

import json
import os
import re
import statistics
from typing import Any, Callable

from cst_captioning_tpu.obs.report import (
    _PROC_DIR_RE,
    _verify_bundle,
    load_postmortem,
)

_BUNDLE_RE = re.compile(r"^postmortem_\d+_.+")

# events_tail kinds worth a fleet-timeline row (everything else in the tail
# is span traffic the run report already aggregates)
_FLEET_EVENTS = (
    "dcn_stall", "anomaly", "divergence", "preempt", "peer_loss_drain",
    "serving_drain", "postmortem",
    "mesh_shrink", "mesh_regrow", "regrow_refused",
    "serving_param_swap", "serving_param_swap_refused",
)
_MAX_FLEET_EVENTS = 200


# ---- discovery ---------------------------------------------------------------

def discover_bundles(run_dir: str) -> dict[int, list[str]]:
    """Map proc index -> its postmortem bundle dirs (dump order). Process 0
    dumps into ``run_dir`` itself, process k into ``run_dir/proc<k>/`` —
    the trainer's obs layout."""
    out: dict[int, list[str]] = {}

    def scan(d: str, proc: int) -> None:
        try:
            names = os.listdir(d)
        except OSError:
            return
        found = sorted(
            os.path.join(d, n) for n in names
            if _BUNDLE_RE.match(n) and os.path.isdir(os.path.join(d, n))
        )
        if found:
            out[proc] = found

    scan(run_dir, 0)
    try:
        entries = sorted(os.listdir(run_dir))
    except OSError:
        entries = []
    for entry in entries:
        m = _PROC_DIR_RE.match(entry)
        if m:
            scan(os.path.join(run_dir, entry), int(m.group(1)))
    return out


def select_latest(found: dict[int, list[str]]) -> dict[int, str]:
    """Latest bundle per proc — bundle names carry the per-process dump
    ordinal (``postmortem_<n>_<reason>``), so lexicographic order within a
    proc dir IS dump order."""
    return {proc: dirs[-1] for proc, dirs in found.items()}


def list_bundles(run_dir: str) -> list[dict[str, Any]]:
    """Enumerate every bundle under a run dir with its trip kind + step —
    the ``obs_report --postmortem <dir> --list`` view."""
    rows: list[dict[str, Any]] = []
    for proc, dirs in sorted(discover_bundles(run_dir).items()):
        for d in dirs:
            meta: dict = {}
            try:
                with open(os.path.join(d, "meta.json"),
                          encoding="utf-8") as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                pass
            verified, _ = _verify_bundle(d)
            rows.append({
                "proc": proc,
                "bundle": d,
                "reason": meta.get("reason", "?"),
                "step": meta.get("step"),
                "phase": meta.get("phase"),
                "host": meta.get("host"),
                "ring_steps": len(meta.get("steps", [])),
                "dumped_ts": meta.get("dumped_ts"),
                "verified": verified,
            })
    return rows


# ---- skew model --------------------------------------------------------------

def _anchor_fn(meta: dict) -> Callable[[float], float] | None:
    """Piecewise-linear monotonic↔wall map from a schema-2 bundle's anchor
    table, applied to ring ``ts`` values (which the recorder derived from
    the START anchor alone). ``None`` for anchor-free legacy bundles."""
    anchors = meta.get("anchors")
    if not anchors:
        return None
    try:
        pts = sorted((float(p), float(w)) for p, w in anchors)
    except (TypeError, ValueError):
        return None
    if not pts:
        return None
    pc0, wall0 = pts[0]

    def fn(ts: float) -> float:
        # invert the recorder's ts = wall0 + (pc - pc0), then re-map pc
        # through the freshest bracketing anchor pair
        pc = pc0 + (ts - wall0)
        if pc <= pts[0][0]:
            return pts[0][1] + (pc - pts[0][0])
        for (p1, w1), (p2, w2) in zip(pts, pts[1:]):
            if pc <= p2:
                if p2 <= p1:
                    return w2
                f = (pc - p1) / (p2 - p1)
                return w1 + f * (w2 - w1)
        pl, wl = pts[-1]
        return wl + (pc - pl)

    return fn


def _ring_keyed(pm: dict) -> dict[tuple[str, int], dict]:
    """Ring records keyed by the global (phase, step) join key; the LAST
    record wins when a step re-ran (rollback replay)."""
    out: dict[tuple[str, int], dict] = {}
    for rec in pm["ring"]:
        step = rec.get("step")
        if isinstance(step, int):
            out[(str(rec.get("phase", "")), step)] = rec
    return out


def _is_nonfinite(v: Any) -> bool:
    if not isinstance(v, (int, float)):
        return False
    return v != v or v in (float("inf"), float("-inf"))


# ---- the merge ---------------------------------------------------------------

def merge_bundles(run_dir: str) -> dict[str, Any]:
    """Verify + merge the latest bundle of every proc under ``run_dir``
    into the fleet forensic structure (JSON-ready; ``render_fleet`` is the
    human view). Raises ``FileNotFoundError`` when no bundles exist."""
    found = discover_bundles(run_dir)
    if not found:
        raise FileNotFoundError(
            f"no postmortem bundles under {run_dir!r} — expected "
            "postmortem_* dirs (proc 0) and/or proc<k>/postmortem_* "
            "(obs/recorder.py layout)"
        )
    latest = select_latest(found)

    procs: dict[int, dict] = {}
    excluded: list[dict] = []
    for proc, bdir in sorted(latest.items()):
        pm = load_postmortem(bdir)
        if not pm["verified"]:
            # tampered/truncated evidence is worse than missing evidence:
            # report it, never merge it
            excluded.append({
                "proc": proc,
                "bundle": bdir,
                "problems": pm["problems"],
            })
            continue
        procs[proc] = pm

    # expected world size: the largest claim any bundle makes, or the
    # largest proc index actually seen — whichever is bigger
    world = max(
        [p + 1 for p in found]
        + [int(pm["meta"].get("world", 1)) for pm in procs.values()]
    )
    present = set(procs) | {e["proc"] for e in excluded}
    missing_procs = sorted(set(range(world)) - present)

    fleet: dict[str, Any] = {
        "run_dir": run_dir,
        "run": "?",
        "world": world,
        "merged_procs": sorted(procs),
        "missing_procs": missing_procs,
        "excluded": excluded,
        "degraded": bool(missing_procs or excluded),
    }
    if not procs:
        # every bundle failed verification: still a (maximally degraded)
        # answer, not a crash
        fleet.update(procs_info=[], trip=None, straggler=None, steps=[],
                     events=[], victim_hosts=[])
        return fleet

    ref = min(procs)
    fleet["run"] = procs[ref]["meta"].get("run", "?")

    # per-proc anchored timestamps + cross-proc offsets (proc<k> skew model:
    # median delta over shared join keys against the reference proc)
    keyed = {p: _ring_keyed(pm) for p, pm in procs.items()}
    anchored: dict[int, dict[tuple[str, int], float]] = {}
    skew_kind: dict[int, str] = {}
    for p, pm in procs.items():
        fn = _anchor_fn(pm["meta"])
        skew_kind[p] = "anchored" if fn is not None else "unknown"
        anchored[p] = {
            key: (fn(rec["ts"]) if fn is not None else float(rec["ts"]))
            for key, rec in keyed[p].items()
            if isinstance(rec.get("ts"), (int, float))
        }
    offsets: dict[int, float] = {ref: 0.0}
    for p in procs:
        if p == ref:
            continue
        if skew_kind[p] == "unknown" or skew_kind[ref] == "unknown":
            # a clock we can't trust gets no offset model — its rows still
            # join by step, but lag attribution is withheld
            offsets[p] = 0.0
            skew_kind[p] = "unknown"
            continue
        shared = sorted(set(anchored[p]) & set(anchored[ref]))
        if not shared:
            offsets[p] = 0.0
            skew_kind[p] = "unknown"
            continue
        offsets[p] = statistics.median(
            anchored[p][k] - anchored[ref][k] for k in shared
        )

    corrected: dict[int, dict[tuple[str, int], float]] = {
        p: {k: ts - offsets[p] for k, ts in anchored[p].items()}
        for p in procs
    }

    # fleet t0: earliest corrected ring timestamp anywhere
    all_ts = [ts for per in corrected.values() for ts in per.values()]
    t0 = min(all_ts) if all_ts else 0.0
    fleet["t0"] = t0

    # join: one row per (phase, step), ordered by earliest corrected time
    keys = sorted(
        {k for per in keyed.values() for k in per},
        key=lambda k: (
            min((corrected[p][k] for p in procs if k in corrected[p]),
                default=float("inf")),
            k,
        ),
    )
    lags: dict[int, list[float]] = {p: [] for p in procs}
    steps: list[dict] = []
    for key in keys:
        phase, step = key
        cells: dict[str, dict] = {}
        row_ts = [
            corrected[p][key] for p in procs
            if key in corrected[p] and skew_kind[p] == "anchored"
        ]
        row_min = min(row_ts) if row_ts else None
        for p, per in keyed.items():
            rec = per.get(key)
            if rec is None:
                continue
            loss = rec.get("loss", rec.get("rl_loss"))
            lag = None
            if (row_min is not None and len(row_ts) >= 2
                    and skew_kind[p] == "anchored" and key in corrected[p]):
                lag = corrected[p][key] - row_min
                lags[p].append(lag)
            cells[str(p)] = {
                "t_s": (
                    corrected[p][key] - t0 if key in corrected[p] else None
                ),
                "loss": loss,
                "grad_norm": rec.get("grad_norm"),
                "reward_mean": rec.get("reward_mean"),
                "anomalies": list(rec.get("anomalies") or []),
                "lag_s": lag,
            }
        steps.append({"phase": phase, "step": step, "cells": cells})
    fleet["steps"] = steps

    # straggler: the proc whose corrected row times trail the fleet most
    straggler = None
    scored = [
        (sum(v) / len(v), max(v), p) for p, v in lags.items() if v
    ]
    if scored:
        mean_lag, max_lag, p = max(scored)
        # sub-millisecond "lag" is clock-resolution noise, not a straggler
        if mean_lag > 1e-3:
            straggler = {
                "proc": p,
                "host": procs[p]["meta"].get("host", "?"),
                "mean_lag_s": mean_lag,
                "max_lag_s": max_lag,
            }
    fleet["straggler"] = straggler

    # trip attribution: first verdict-carrying ring record in corrected
    # time; bundles whose rings never judged (detector off) fall back to
    # their meta reason at dump time
    trip = None
    for p, pm in sorted(procs.items()):
        for rec in pm["ring"]:
            key = (str(rec.get("phase", "")), rec.get("step"))
            kinds = list(rec.get("anomalies") or [])
            if not kinds and _is_nonfinite(
                rec.get("loss", rec.get("rl_loss"))
            ):
                kinds = ["nonfinite"]
            if not kinds:
                continue
            ts = corrected[p].get(key)
            if ts is None:
                ts = float(rec.get("ts", 0.0)) - offsets[p]
            cand = {
                "proc": p,
                "host": pm["meta"].get("host", "?"),
                "phase": key[0],
                "step": rec.get("step"),
                "t_s": ts - t0,
                "kinds": kinds,
                "reason": pm["meta"].get("reason", "?"),
                "source": "ring",
            }
            if trip is None or ts - t0 < trip["t_s"]:
                trip = cand
            break  # first verdict per proc is that proc's candidate
    if trip is None:
        # no ring verdicts anywhere: earliest dump wins, meta is the story
        dumped = [
            (float(pm["meta"].get("dumped_ts", 0.0)) - offsets[p], p)
            for p, pm in procs.items()
        ]
        _, p = min(dumped)
        meta = procs[p]["meta"]
        trip = {
            "proc": p,
            "host": meta.get("host", "?"),
            "phase": meta.get("phase"),
            "step": meta.get("step"),
            "t_s": None,
            "kinds": [meta.get("reason", "?")],
            "reason": meta.get("reason", "?"),
            "source": "meta",
        }
    fleet["trip"] = trip

    # victims: peer-loss bundles name lost hosts, chaos partial_preempt
    # bundles name the injected victim
    victims: set = set()
    for pm in procs.values():
        meta = pm["meta"]
        lost = meta.get("lost")
        if isinstance(lost, list):
            victims.update(lost)
        if "victim_host" in meta:
            victims.add(meta["victim_host"])
    fleet["victim_hosts"] = sorted(victims, key=str)

    # per-proc summary lines (render + --json)
    fleet["procs_info"] = [
        {
            "proc": p,
            "host": pm["meta"].get("host", "?"),
            "bundle": pm["bundle"],
            "reason": pm["meta"].get("reason", "?"),
            "step": pm["meta"].get("step"),
            "ring_steps": len(pm["ring"]),
            "offset_s": offsets[p],
            "skew": skew_kind[p],
            "flush_error": pm["meta"].get("flush_error", ""),
        }
        for p, pm in sorted(procs.items())
    ]

    # serving param-version attribution: procs that served carry a
    # registry "serving" block (engine._drain_postmortem registry_extra)
    # naming the ACTIVE param version at dump plus the recent swap
    # history — a reward/SLO regression in the timeline joins to the
    # version that served it
    serving_att: dict[int, dict] = {}
    for p, pm in sorted(procs.items()):
        sv = (pm.get("registry") or {}).get("serving")
        if isinstance(sv, dict) and "param_version" in sv:
            serving_att[p] = {
                "host": pm["meta"].get("host", "?"),
                "param_version": sv.get("param_version"),
                "param_swaps": sv.get("param_swaps", 0),
                "swap_history": list(sv.get("swap_history") or []),
            }
    fleet["serving"] = serving_att or None

    # events_tail interleave: per-proc obs events (dcn stalls, anomaly
    # verdicts, drains) at offset-corrected times. Tail timestamps are
    # already wall-clock (span stream), so only the cross-host offset
    # applies — no anchor inversion
    events: list[dict] = []
    for p, pm in sorted(procs.items()):
        path = os.path.join(pm["bundle"], "events_tail.jsonl")
        try:
            with open(path, encoding="utf-8") as f:
                tail_lines = f.readlines()
        except OSError:
            continue
        for line in tail_lines:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if ev.get("event") not in _FLEET_EVENTS:
                continue
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            out = {
                "t_s": ts - offsets[p] - t0,
                "proc": p,
                "event": ev["event"],
            }
            for k in ("kind", "op", "dur_s", "gap_s", "reason", "step",
                      "phase", "value", "victim", "rejoiner", "generation",
                      "devices", "version", "prev", "active",
                      "inflight_pinned"):
                if k in ev:
                    out[k] = ev[k]
            events.append(out)
    events.sort(key=lambda e: e["t_s"])

    # elastic timeline: pair every mesh_shrink (arc opens, names ONE
    # victim) with the next regrow event naming the same host —
    # mesh_regrow closes the arc (re-admitted), regrow_refused marks a
    # failed attempt and the arc stays open. Computed over the FULL event
    # stream before the tail cap so old arcs survive long runs.
    elastic: list[dict] = []
    open_arcs: dict[Any, dict] = {}
    for ev in events:
        kind = ev["event"]
        if kind == "mesh_shrink":
            arc = {
                "host": ev.get("victim"),
                "shrink_t_s": ev["t_s"],
                "shrink_gen": ev.get("generation"),
                "regrow_t_s": None,
                "regrow_gen": None,
                "outcome": "open",
                "refused": 0,
            }
            elastic.append(arc)
            if arc["host"] is not None:
                open_arcs[arc["host"]] = arc
        elif kind == "regrow_refused":
            arc = open_arcs.get(ev.get("rejoiner"))
            if arc is not None:
                arc["refused"] += 1
        elif kind == "mesh_regrow":
            arc = open_arcs.pop(ev.get("rejoiner"), None)
            if arc is not None:
                arc["regrow_t_s"] = ev["t_s"]
                arc["regrow_gen"] = ev.get("generation")
                arc["outcome"] = "readmitted"
    fleet["elastic"] = elastic
    fleet["events"] = events[-_MAX_FLEET_EVENTS:]
    return fleet


# ---- rendering ---------------------------------------------------------------

def _num(v: Any, width: int = 9, prec: int = 4) -> str:
    if isinstance(v, (int, float)):
        return f"{v:>{width}.{prec}g}"
    return " " * width


def render_fleet(fleet: dict[str, Any]) -> str:
    """Human rendering of :func:`merge_bundles`: per-proc summary, trip /
    straggler / victim attribution, then the per-step timeline with one
    column per host (anomaly verdicts inline, trip marker on the trip
    cell), events interleaved at corrected times."""
    lines: list[str] = []
    n_merged = len(fleet.get("merged_procs", []))
    tag = "  [DEGRADED MERGE]" if fleet.get("degraded") else ""
    lines.append(
        f"fleet postmortem: {fleet.get('run', '?')}   procs merged: "
        f"{n_merged}/{fleet.get('world', n_merged)}   run dir: "
        f"{fleet.get('run_dir', '?')}{tag}"
    )
    for info in fleet.get("procs_info", []):
        off = info["offset_s"]
        lines.append(
            f"  proc{info['proc']} ({info['host']})  "
            f"reason={info['reason']}  ring={info['ring_steps']} step(s)  "
            f"offset={off:+.3f}s ({info['skew']})"
        )
        if info.get("flush_error"):
            lines.append(
                f"    FLUSH FAILED at dump time: {info['flush_error']}"
            )
    if fleet.get("missing_procs"):
        lines.append(
            f"  MISSING PROCS: {fleet['missing_procs']} — no bundle found "
            "(died before first dump, or its disk is gone); merged from "
            "survivors"
        )
    for ex in fleet.get("excluded", []):
        lines.append(
            f"  EXCLUDED proc{ex['proc']}: manifest verification failed "
            f"({'; '.join(ex['problems'])}) — {ex['bundle']}"
        )
    trip = fleet.get("trip")
    if trip:
        at = (
            f" at t+{trip['t_s']:.3f}s" if trip.get("t_s") is not None else ""
        )
        lines.append(
            f"trip: proc{trip['proc']} ({trip['host']}) "
            f"{trip.get('phase') or '?'} step {trip.get('step')}{at} — "
            f"{','.join(trip['kinds'])} [{trip['source']}: {trip['reason']}]"
        )
    if fleet.get("victim_hosts"):
        lines.append(f"victim host(s): {fleet['victim_hosts']}")
    st = fleet.get("straggler")
    if st:
        lines.append(
            f"straggler: proc{st['proc']} ({st['host']})  mean lag "
            f"{st['mean_lag_s']:.3f}s  max {st['max_lag_s']:.3f}s"
        )
    for arc in fleet.get("elastic", []):
        refused = (
            f", {arc['refused']} refused attempt(s)" if arc["refused"] else ""
        )
        if arc["outcome"] == "readmitted":
            span = arc["regrow_t_s"] - arc["shrink_t_s"]
            lines.append(
                f"elastic: host {arc['host']} shrink t+"
                f"{arc['shrink_t_s']:.3f}s --> regrow t+"
                f"{arc['regrow_t_s']:.3f}s (degraded {span:.3f}s, gen "
                f"{arc['shrink_gen']}->{arc['regrow_gen']}{refused})"
            )
        else:
            lines.append(
                f"elastic: host {arc['host']} shrink t+"
                f"{arc['shrink_t_s']:.3f}s --> (never rejoined{refused})"
            )
    for p, sv in sorted((fleet.get("serving") or {}).items()):
        hist = sv.get("swap_history") or []
        arrows = "->".join(
            str(h.get("from")) for h in hist[:1]
        ) + "".join(f"->{h.get('version')}" for h in hist)
        lines.append(
            f"serving: proc{p} ({sv['host']}) active param v"
            f"{sv['param_version']} after {int(sv['param_swaps'])} swap(s)"
            + (f"  [{arrows}]" if hist else "")
        )

    steps = fleet.get("steps", [])
    if not steps:
        lines.append("timeline: no ring records in any merged bundle")
        return "\n".join(lines)

    procs = [info["proc"] for info in fleet.get("procs_info", [])]
    trip_key = (
        (trip.get("phase"), trip.get("step"), trip.get("proc"))
        if trip and trip.get("source") == "ring" else None
    )

    def cell_text(row: dict, p: int) -> str:
        c = row["cells"].get(str(p))
        if c is None:
            return "-"
        bits = [_num(c.get("loss")).strip() or "."]
        if c.get("lag_s") is not None and c["lag_s"] > 1e-3:
            bits.append(f"lag+{c['lag_s']:.3f}")
        if c.get("anomalies"):
            bits.append("<-- " + ",".join(c["anomalies"]))
        if trip_key == (row["phase"], row["step"], p):
            bits.append("[TRIP]")
        return " ".join(bits)

    widths = {
        p: max(
            [len(f"proc{p} loss")]
            + [len(cell_text(row, p)) for row in steps]
        )
        for p in procs
    }
    hdr = f"{'phase':>6} {'step':>6} {'t+s':>9}"
    for p in procs:
        hdr += f" | {f'proc{p} loss':<{widths[p]}}"
    lines.append("")
    lines.append(hdr)
    lines.append("-" * len(hdr))

    # interleave events between step rows by corrected time
    events = list(fleet.get("events", []))
    ev_i = 0

    def row_t(row: dict) -> float | None:
        ts = [
            c["t_s"] for c in row["cells"].values()
            if c.get("t_s") is not None
        ]
        return min(ts) if ts else None

    for row in steps:
        rt = row_t(row)
        while ev_i < len(events) and rt is not None and (
            events[ev_i]["t_s"] <= rt
        ):
            ev = events[ev_i]
            detail = "  ".join(
                f"{k}={ev[k]}"
                for k in ("kind", "op", "dur_s", "reason", "version", "prev")
                if k in ev
            )
            lines.append(
                f"  ~ t+{ev['t_s']:.3f}s proc{ev['proc']} "
                f"{ev['event']} {detail}".rstrip()
            )
            ev_i += 1
        line = (
            f"{row['phase']:>6} {row['step']:>6} "
            f"{_num(rt, 9, 5) if rt is not None else ' ' * 9}"
        )
        for p in procs:
            line += f" | {cell_text(row, p):<{widths[p]}}"
        lines.append(line.rstrip())
    for ev in events[ev_i:]:
        detail = "  ".join(
            f"{k}={ev[k]}" for k in ("kind", "op", "dur_s", "reason")
            if k in ev
        )
        lines.append(
            f"  ~ t+{ev['t_s']:.3f}s proc{ev['proc']} "
            f"{ev['event']} {detail}".rstrip()
        )
    return "\n".join(lines)
