"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Dependency-free (stdlib only — jax is imported lazily and only for the
optional device-memory / compile-time feeds), thread-safe (the prefetch
worker and the main step loop both write), and cheap: every metric is a
couple of Python float ops behind one registry-wide lock, with no device
readback anywhere — the hot-path zero-sync contract (graftlint GL001) holds
by construction because nothing here ever touches a jax array.

Three primitives, Prometheus-shaped so the textfile export is mechanical:

- :class:`Counter`   — monotonically increasing float (``inc``).
- :class:`Gauge`     — last-write-wins float (``set``).
- :class:`Histogram` — fixed upper-bound buckets chosen at creation
  (defaults tuned for step latencies); ``observe`` is two bisects and three
  adds, quantiles are interpolated from the buckets at read time.

The module-level :func:`counter`/:func:`gauge`/:func:`histogram` accessors
hit the default process-wide :class:`Registry` (``REGISTRY``) — the trainer,
SCST loop, evaluator, prefetch thread, and resilience layer all write to the
same registry, and the obs recorder snapshots it into the event stream.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Any, Iterable

# step/IO latency buckets (seconds): 1ms .. 2min, roughly x2 per bucket
DEFAULT_TIME_BUCKETS = (
    0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5,
    1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0,
)

# queue depths / small integer counts
DEFAULT_COUNT_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)


class Counter:
    """Monotonic counter (float increments allowed: accumulated seconds)."""

    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        self.value += amount

    def snapshot(self) -> float:
        return self.value


class Gauge:
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def snapshot(self) -> float:
        return self.value


class Histogram:
    """Fixed-bucket histogram over float observations.

    ``buckets`` are inclusive upper bounds in ascending order; observations
    above the last bound land in the implicit ``+Inf`` bucket. ``counts`` is
    cumulative-free (per-bucket); the Prometheus export cumulates. The exact
    ``max`` is tracked (p~100 from buckets alone is useless for tail spikes).
    """

    kind = "histogram"

    def __init__(self, name: str, buckets: Iterable[float] = DEFAULT_TIME_BUCKETS):
        bounds = tuple(float(b) for b in buckets)
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise ValueError(
                f"histogram {name}: buckets must be distinct ascending bounds"
            )
        self.name = name
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self.max = 0.0

    def observe(self, value: float) -> None:
        value = float(value)
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1
        if value > self.max:
            self.max = value

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile in [0, 1] (Prometheus-style).

        Within the located bucket the mass is assumed uniform; the overflow
        bucket reports the exact observed ``max``.
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= rank and c > 0:
                if i >= len(self.bounds):  # +Inf bucket
                    return self.max
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - seen) / c
                return min(lo + (hi - lo) * frac, self.max if self.max else hi)
            seen += c
        return self.max

    def snapshot(self) -> dict[str, Any]:
        return {
            "buckets": list(self.bounds),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
            "max": self.max,
        }


class Registry:
    """Name -> metric map with get-or-create accessors (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, Any] = {}

    def _get(self, name: str, factory, kind: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif m.kind != kind:
                raise TypeError(
                    f"metric {name!r} already registered as a {m.kind}, "
                    f"requested as a {kind}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, lambda: Counter(name), "counter")

    def gauge(self, name: str) -> Gauge:
        return self._get(name, lambda: Gauge(name), "gauge")

    def histogram(self, name: str,
                  buckets: Iterable[float] = DEFAULT_TIME_BUCKETS) -> Histogram:
        return self._get(name, lambda: Histogram(name, buckets), "histogram")

    def snapshot(self) -> dict[str, Any]:
        """One JSON-ready dict of every metric, grouped by kind."""
        with self._lock:
            out: dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
            for name, m in sorted(self._metrics.items()):
                out[m.kind + "s"][name] = m.snapshot()
            return out

    def reset(self) -> None:
        """Drop every metric (tests; a long-lived process never resets)."""
        with self._lock:
            self._metrics.clear()

    # ---- Prometheus textfile export ----------------------------------------

    def to_prometheus(self) -> str:
        """Render the registry in Prometheus text exposition format
        (node_exporter textfile-collector compatible)."""
        lines: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            pname = _prom_name(name)
            lines.append(f"# TYPE {pname} {m.kind}")
            if m.kind in ("counter", "gauge"):
                lines.append(f"{pname} {_prom_num(m.value)}")
                continue
            cum = 0
            for bound, c in zip(m.bounds, m.counts):
                cum += c
                lines.append(
                    f'{pname}_bucket{{le="{_prom_num(bound)}"}} {cum}'
                )
            lines.append(f'{pname}_bucket{{le="+Inf"}} {m.count}')
            lines.append(f"{pname}_sum {_prom_num(m.sum)}")
            lines.append(f"{pname}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return out if not out[:1].isdigit() else "_" + out


def _prom_num(v: float) -> str:
    # Prometheus spells specials "NaN"/"+Inf"/"-Inf" (repr would emit "nan",
    # and int(inf) raises); integers render bare so counters read naturally
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f.is_integer() else repr(f)


REGISTRY = Registry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str,
              buckets: Iterable[float] = DEFAULT_TIME_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, buckets)


def snapshot() -> dict[str, Any]:
    return REGISTRY.snapshot()


# ---- optional device feeds (lazy jax, graceful everywhere) ------------------

def observe_device_memory(registry: Registry | None = None) -> bool:
    """Update memory watermark gauges for EVERY local device.

    Per device ``k``: ``device<k>.bytes_in_use`` /
    ``device<k>.peak_bytes_in_use`` (+ ``bytes_limit``). The aggregate
    ``device.*`` gauges carry the max across local devices — on a balanced
    data-parallel mesh all devices track together, so the max is the HBM
    headroom signal, and a skewed device (a sharding bug, an uneven last
    batch) shows up as ``device<k>`` diverging from the aggregate.

    Returns False (and writes nothing) when the backend has no memory stats
    (CPU) or jax is unavailable — callers never need to guard. Reading
    allocator stats is a host-side query, not a device sync.
    """
    reg = registry or REGISTRY
    try:
        import jax

        per_dev = [
            (d.id, d.memory_stats() or {}) for d in jax.local_devices()
        ]
    except Exception:
        return False
    keys = (
        ("bytes_in_use", "bytes_in_use"),
        ("peak_bytes_in_use", "peak_bytes_in_use"),
        ("bytes_limit", "bytes_limit"),
    )
    wrote = False
    for key, gname in keys:
        vals = [s[key] for _, s in per_dev if key in s]
        if not vals:
            continue
        wrote = True
        reg.gauge(f"device.{gname}").set(float(max(vals)))
        for dev_id, stats in per_dev:
            if key in stats:
                reg.gauge(f"device{dev_id}.{gname}").set(float(stats[key]))
    return wrote


_COMPILE_LISTENER_INSTALLED = False


def install_compile_listener(registry: Registry | None = None) -> bool:
    """Feed ``jit.compiles`` / ``jit.compile_seconds`` from jax.monitoring.

    Registers a duration listener for the ``/jax/core/compile/*`` events jax
    records around tracing/lowering/backend-compile. Idempotent; returns
    False when the monitoring API is missing (older/stripped jax) — the
    metrics then simply stay absent, nothing breaks.
    """
    global _COMPILE_LISTENER_INSTALLED
    if _COMPILE_LISTENER_INSTALLED:
        return True
    reg = registry or REGISTRY
    try:
        from jax import monitoring
    except Exception:
        return False
    if not hasattr(monitoring, "register_event_duration_secs_listener"):
        return False

    def _on_duration(event: str, duration: float, **_kw) -> None:
        if "/compile/" not in event and not event.endswith("compile_time_sec"):
            return
        reg.counter("jit.compile_seconds").inc(max(float(duration), 0.0))
        if event.endswith("backend_compile_duration"):
            reg.counter("jit.compiles").inc()

    monitoring.register_event_duration_secs_listener(_on_duration)
    _COMPILE_LISTENER_INSTALLED = True
    return True


# ---- step meter (shared XE/RL epoch timing) ---------------------------------

class StepMeter:
    """Per-phase step latency + throughput on the process-wide registry.

    Replaces the trainer's per-loop ``StepTimer`` + first-step bookkeeping:
    both XE and RL epochs meter through this one class, so their latency
    accounting is identical by construction. ``tick(clips, first=True)``
    routes the jit-compile step into ``<phase>.compile_seconds`` instead of
    the latency histogram, keeping the throughput meter honest.

    Epoch summaries are windowed deltas over the cumulative metrics
    (:meth:`begin_epoch` marks, :meth:`epoch_summary` diffs), so the
    registry keeps whole-run totals while each epoch reports its own rate.
    """

    def __init__(self, phase: str, registry: Registry | None = None):
        reg = registry or REGISTRY
        self.phase = phase
        self.hist = reg.histogram(f"{phase}.step_seconds")
        self.compile_secs = reg.counter(f"{phase}.compile_seconds")
        self.clips = reg.counter(f"{phase}.clips")
        self.steps = reg.counter(f"{phase}.steps")
        self._t_last: float | None = None
        self._mark = (0.0, 0.0, 0)

    def begin_epoch(self) -> None:
        self._t_last = time.perf_counter()
        self._mark = (self.clips.value, self.hist.sum, self.hist.count)

    def tick(self, clips: int, first: bool = False) -> None:
        now = time.perf_counter()
        if self._t_last is None:  # begin_epoch not called: self-heal
            self._t_last = now
            return
        dur = now - self._t_last
        self._t_last = now
        if first:
            self.compile_secs.inc(dur)
            return
        self.hist.observe(dur)
        self.steps.inc()
        self.clips.inc(clips)

    def epoch_summary(self) -> dict[str, float]:
        clips0, sum0, count0 = self._mark
        d_clips = self.clips.value - clips0
        d_sum = self.hist.sum - sum0
        d_count = self.hist.count - count0
        return {
            "steps": float(d_count),
            "clips_per_sec": d_clips / d_sum if d_sum > 0 else 0.0,
            "step_seconds_p50": self.hist.quantile(0.5),
            "step_seconds_p95": self.hist.quantile(0.95),
        }
