"""Analytic matmul-FLOP cost models + chip peak tables (pure stdlib).

One source of truth for the numbers three consumers previously duplicated
or could not share:

- ``bench.py`` / ``bench_decode.py`` — roofline MFU / bw_util columns;
- the trainer / SCST loop — per-step ``flops.<phase>`` counters feeding the
  run report's MFU column (``obs/report.py``);
- ``cli.obs_report`` — which must aggregate WITHOUT importing jax, hence
  everything here is plain arithmetic over ints.

Conventions (unchanged from bench.py's original model): FLOPs count matmuls
only as ``2*m*n*k`` — elementwise/softmax work is ignored (the model is
matmul-dominated); the backward pass is taken as 2x the forward (3x
overall). ``E`` below is the encoder output dim (== ``d_embed``: every
modality is embedded to ``d_embed`` and concatenated on the frame axis, so
``M = n_modalities * F``).
"""

from __future__ import annotations

# peak dense bf16 FLOP/s and HBM bandwidth per chip by device kind (public
# TPU specs); the match is substring-based and callers carry the assumed
# values in their JSON so they cannot be misread as measured
PEAK_BF16_FLOPS = (
    ("v6e", 918e12), ("v6 lite", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12), ("v5 lite", 197e12), ("v5litepod", 197e12),
    ("v4", 275e12),
)
DEFAULT_PEAK = 197e12
PEAK_HBM_BYTES = (
    ("v6e", 1640e9), ("v6 lite", 1640e9),
    ("v5p", 2765e9),
    ("v5e", 819e9), ("v5 lite", 819e9), ("v5litepod", 819e9),
    ("v4", 1228e9),
)
DEFAULT_PEAK_HBM = 819e9


def peak_flops(device_kind: str) -> float:
    """Assumed peak dense bf16 FLOP/s for a ``device_kind`` string."""
    kind = device_kind.lower()
    for frag, peak in PEAK_BF16_FLOPS:
        if frag in kind:
            return peak
    return DEFAULT_PEAK


def peak_hbm(device_kind: str) -> float:
    """Assumed peak HBM bytes/s for a ``device_kind`` string."""
    kind = device_kind.lower()
    for frag, peak in PEAK_HBM_BYTES:
        if frag in kind:
            return peak
    return DEFAULT_PEAK_HBM


def enc_and_per_tok_flops(
    F: int, d_embed: int, d_hidden: int, d_att: int, V: int,
    feat_dims: tuple[int, ...], num_layers: int = 1,
) -> tuple[float, float]:
    """(encoder-pass, per-decoded-token) matmul FLOPs of the caption model.

    Encoder: per-modality frame embeddings + the attention memory-key
    projection. Per token: additive attention (query proj, scores, context
    sum over the M-slot concat memory), the input-feed LSTM stack (layer 0
    input is ``[word_emb, ctx]`` = ``2*d_embed``), and the output
    projection.
    """
    M = len(feat_dims) * F
    E, H, A = d_embed, d_hidden, d_att
    enc = 2 * F * sum(feat_dims) * E + 2 * M * E * A
    lstm = 2 * (E + E) * (4 * H) + 2 * H * (4 * H)        # layer 0
    lstm += (num_layers - 1) * (2 * H * (4 * H) + 2 * H * (4 * H))
    per_tok = (
        2 * H * A          # attention query projection
        + 2 * M * A        # scores
        + 2 * M * E        # context weighted sum
        + lstm
        + 2 * H * V        # output projection
    )
    return float(enc), float(per_tok)


def stride_steps(T: int, stride: int = 1) -> int:
    """Scan-step budget of a strided decode loop: T rounded up to the next
    stride multiple (the driving loop advances whole strides, so the final
    partial chunk still steps ``stride`` times)."""
    s = max(int(stride), 1)
    return -(-int(T) // s) * s


def decode_flops_per_clip(
    K: int, T: int, F: int, d_embed: int, d_hidden: int, d_att: int, V: int,
    feat_dims: tuple[int, ...], num_layers: int = 1,
    with_greedy: bool = True, fused: bool = True,
    stride: int = 1, active_frac: float = 1.0,
) -> float:
    """Matmul FLOPs of one RL decode per clip.

    ``fused=True`` (the one-loop default, PR 4): ONE encoder pass feeds both
    the greedy lane and the K sampled lanes. ``fused=False`` is the two-loop
    reference: greedy and sampling each run their own encoder pass.

    ``stride`` rounds the step budget up to whole driving-loop chunks
    (``decode_stride``); ``active_frac`` scales the per-token work by the
    fraction of lane-steps actually computed — 1.0 assumes every lane steps
    the full budget (the uncompacted worst case), while a measured value
    from the ``rl.decode.compaction`` counters (lanes_stepped /
    (lanes_stepped + lanes_skipped)) gives the compaction-aware cost.
    """
    enc, per_tok = enc_and_per_tok_flops(
        F, d_embed, d_hidden, d_att, V, feat_dims, num_layers
    )
    lanes = (1 if with_greedy else 0) + K
    enc_passes = 1 if (fused or not with_greedy) else 2
    steps = stride_steps(T, stride)
    return float(enc_passes * enc + lanes * steps * per_tok * active_frac)


def serving_bank_bytes_per_stride(
    rows: int, width_slots: int, d_embed: int, d_att: int,
    dtype_bytes: int = 4, paged: bool = False,
) -> float:
    """Encoder-bank HBM bytes one serving stride moves, per decode path.

    The bank is ``rows`` lanes x ``width_slots`` memory slots of
    ``(E mem + A proj + 1 mask)`` elements. The dense-gather path pays it
    THREE times per stride: the gather reads the pool, writes the dense
    [B, W, *] bank, and the stride kernel reads the bank back. The paged
    in-kernel path DMAs each batch block's pages from the pool into VMEM
    exactly once — one read, no dense bank — so its cost is the bank bytes
    themselves. ``serving.gather_bytes_avoided`` counts the difference
    (2x the bank) per paged stride dispatch. One ``dtype_bytes`` covers
    all three pools (the mask pool is f32 even under a bf16 model — at
    bf16 this overstates mask traffic by 2 of ~E+A+1 elements; the model
    stays deliberately simple)."""
    bank = float(rows) * width_slots * (d_embed + d_att + 1) * dtype_bytes
    return bank if paged else 3.0 * bank


def update_flops_per_clip(
    K: int, T: int, F: int, d_embed: int, d_hidden: int, d_att: int, V: int,
    feat_dims: tuple[int, ...], num_layers: int = 1,
) -> float:
    """Matmul FLOPs of one REINFORCE update per clip: one encoder pass, K
    teacher-forced rollout rows, forward+backward as 3x forward."""
    enc, per_tok = enc_and_per_tok_flops(
        F, d_embed, d_hidden, d_att, V, feat_dims, num_layers
    )
    return float(3 * (enc + K * T * per_tok))


def xe_flops_per_row(
    T: int, F: int, d_embed: int, d_hidden: int, d_att: int, V: int,
    feat_dims: tuple[int, ...], num_layers: int = 1,
) -> float:
    """Matmul FLOPs of one teacher-forced XE row (forward+backward)."""
    enc, per_tok = enc_and_per_tok_flops(
        F, d_embed, d_hidden, d_att, V, feat_dims, num_layers
    )
    return float(3 * (enc + T * per_tok))


# ---- XLA HLO cost-analysis backend ------------------------------------------
#
# The analytic counters above are matmul-only estimates; XLA's own HLO cost
# analysis counts the COMPILED program (every fused op, the real
# elementwise/softmax work, rematerialization). When a jitted callable and
# its example arguments are at hand — benches, the serving engine — prefer
# compiled-program FLOPs for the MFU ledger and fall back to the analytic
# model when the backend can't report them (interpret-mode Pallas calls,
# older runtimes, lowerings without cost data). jax imports stay INSIDE the
# function: this module must keep importing on jax-free boxes
# (cli.obs_report's contract).


def compiled_cost(fn, *args, **kwargs) -> dict | None:
    """``{"flops": float, "bytes_accessed": float}`` of ``jit(fn)(*args)``
    per XLA's HLO cost analysis, or None when unavailable (no jax, no
    backend cost model, analysis raises). ``fn`` may already be jitted
    (anything with ``.lower``)."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax-free box
        return None
    try:
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        analysis = jitted.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if not analysis:
            return None
        flops = float(analysis.get("flops", 0.0) or 0.0)
        if flops <= 0.0:
            return None
        return {
            "flops": flops,
            "bytes_accessed": float(
                analysis.get("bytes accessed", 0.0) or 0.0
            ),
        }
    except Exception:
        # cost analysis is best-effort by contract: any backend refusal
        # degrades to the analytic model, never to a crash
        return None
