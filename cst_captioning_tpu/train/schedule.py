"""LR schedules + optimizer assembly (reference ``opts.py`` LR flags).

The reference decays LR by a factor every N epochs and clips grads by global
norm (SURVEY.md §3.1); expressed here as an optax chain so it lives inside
the jitted step.
"""

from __future__ import annotations

import optax

from cst_captioning_tpu.config.config import TrainConfig


def make_lr_schedule(cfg: TrainConfig, steps_per_epoch: int) -> optax.Schedule:
    """Step-wise exponential decay: lr * decay^(epoch // decay_every)."""
    if cfg.lr_decay_every <= 0 or cfg.lr_decay >= 1.0:
        return optax.constant_schedule(cfg.lr)
    return optax.exponential_decay(
        init_value=cfg.lr,
        transition_steps=cfg.lr_decay_every * max(steps_per_epoch, 1),
        decay_rate=cfg.lr_decay,
        staircase=True,
    )


def make_optimizer(
    cfg: TrainConfig, steps_per_epoch: int, lr_override: float | None = None
) -> optax.GradientTransformation:
    lr = (
        optax.constant_schedule(lr_override)
        if lr_override is not None
        else make_lr_schedule(cfg, steps_per_epoch)
    )
    opt = {
        "adam": optax.adam,
        "adamw": lambda l: optax.adamw(l, weight_decay=cfg.weight_decay),
        "sgd": optax.sgd,
        "rmsprop": optax.rmsprop,
    }
    if cfg.optimizer not in opt:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}; have {sorted(opt)}")
    chain = [optax.clip_by_global_norm(cfg.grad_clip)] if cfg.grad_clip > 0 else []
    chain.append(opt[cfg.optimizer](lr))
    return optax.chain(*chain)
