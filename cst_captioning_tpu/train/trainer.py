"""Experiment driver: XE phase, CST/RL phase, validation, checkpointing.

The orchestration layer of the reference's ``train.py`` (SURVEY.md §3.1-3.2,
§3.5): epoch loop -> jitted steps -> per-epoch greedy validation scored by
CIDEr-D -> best/latest checkpoints -> optional resume -> XE->RL handoff.

Device placement: with a multi-device mesh the step is the shard_map-parallel
variant and batches are placed sharded; single device uses the plain jitted
step. Host batch prep overlaps device compute via the prefetch thread.

Resilience (resilience/ package): both phase loops run under a SIGTERM
preemption handler (mid-epoch save recording the exact batch index, so a
resumed run replays the *remainder* of the epoch — the epoch-keyed shuffle
makes that bit-deterministic; the pipelined RL drain additionally persists
the seam batch's tokens so resume is bit-identical in both pipeline modes),
a divergence sentinel with a configurable policy (``train.on_divergence``),
optional ``train.ckpt_every_steps`` mid-epoch ``step_*`` checkpoints with
keep-last-K rotation, and chaos injection points
(``xe.step``/``xe.batch``/``rl.step``/``rl.batch``) so the fault paths are
testable.

Elastic multi-host resilience (``train.health``, README "Elastic
training"): a heartbeat monitor + peer-loss watchdog
(resilience/health.py) lets the loops detect a lost host, drain + save,
and then either abort for a bit-exact full-mesh restart
(``train.elastic='strict'``) or rendezvous the survivors, rebuild a shrunk
data mesh, reshard optimizer state from the drained checkpoint, and keep
training (``'degraded'``).
"""

from __future__ import annotations

import io
import itertools
import json
import os
import threading

import jax
import numpy as np
from jax.sharding import Mesh

from cst_captioning_tpu import obs
from cst_captioning_tpu.obs import anomaly as _anomaly
from cst_captioning_tpu.obs import flops as _flops
from cst_captioning_tpu.obs import recorder as flight
from cst_captioning_tpu.ckpt import CheckpointManager, load_params
from cst_captioning_tpu.config.config import EvalConfig, ExperimentConfig
from cst_captioning_tpu.data.batcher import Batcher
from cst_captioning_tpu.data.dataset import CaptionDataset
from cst_captioning_tpu.data.prefetch import prefetch_to_device
from cst_captioning_tpu.eval.evaluator import Evaluator
from cst_captioning_tpu.metrics.cider import CorpusDF
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.parallel import (
    CommConfig,
    make_sp_xe_step,
    sp_batch_shardings,
    sp_model,
)
from cst_captioning_tpu.resilience import chaos
from cst_captioning_tpu.resilience import health as health_mod
from cst_captioning_tpu.resilience.adaptive import AdaptiveThresholds
from cst_captioning_tpu.resilience.health import PeerLost
from cst_captioning_tpu.resilience.preempt import Preempted, PreemptionHandler
from cst_captioning_tpu.resilience.sentinel import (
    DivergenceSentinel,
    RollbackRequested,
    TrainingDiverged,
)
from cst_captioning_tpu.rl import AsyncSCSTTrainer, RewardComputer, SCSTTrainer
from cst_captioning_tpu.train import multihost
from cst_captioning_tpu.train.mesh import batch_sharding, make_mesh, replicate
from cst_captioning_tpu.train.schedule import make_optimizer
from cst_captioning_tpu.train.state import (
    TrainState,
    create_train_state,
    device_fold_in,
    device_key,
)
from cst_captioning_tpu.train.steps import batch_arrays, make_parallel_xe_step, make_xe_step
from cst_captioning_tpu.utils.logging import EventLogger
from cst_captioning_tpu.utils.profiling import StepProfiler


# run-plumbing fields expected to differ between the original run and a
# resumed one; excluded from drift detection so the alert stays meaningful
_VOLATILE_CONFIG_FIELDS = frozenset({
    "train.resume", "train.ckpt_dir", "train.profile_dir",
    "train.profile_steps", "train.debug_nans", "train.log_every_steps",
    "train.log_every",  # pre-rename snapshots carry the old field name
    # resilience plumbing: save cadence/rotation/rollback budget change how a
    # run survives faults, not what it computes (on_divergence/spike_factor
    # DO alter numerics under faults, so those two stay drift-tracked;
    # train.elastic also stays tracked — degraded vs strict changes what a
    # faulted run computes)
    "train.ckpt_every_steps", "train.keep_ckpts", "train.max_rollbacks",
    # elastic-health plumbing: where heartbeats go and how fast loss is
    # detected, not what the run computes
    "train.health", "train.health_dir", "train.health_interval_s",
    "train.peer_timeout_s", "train.health_misses", "train.health_sim_hosts",
    "train.dcn_stall_s",
    # observability plumbing: where the spans/metrics go, not what runs
    # (recorder/anomaly add metric OUTPUTS only — params stay bit-identical,
    # see train/steps._apply — so they are resume-volatile like obs itself)
    "train.obs", "train.obs_dir", "train.recorder_steps", "train.anomaly",
    "eval.results_json",
})


def _config_drift(saved: dict, current: dict, prefix: str = "") -> list[str]:
    """Dotted paths whose values differ between two JSON-born snapshots."""
    out: list[str] = []
    for key in sorted(set(saved) | set(current)):
        path = f"{prefix}{key}"
        if path in _VOLATILE_CONFIG_FIELDS:
            continue
        a, b = saved.get(key), current.get(key)
        if isinstance(a, dict) and isinstance(b, dict):
            out.extend(_config_drift(a, b, prefix=path + "."))
        elif a != b:
            out.append(path)
    return out


class Trainer:
    def __init__(
        self,
        cfg: ExperimentConfig,
        train_ds: CaptionDataset,
        val_ds: CaptionDataset | None = None,
        log_path: str = "",
        use_mesh: bool | None = None,
    ):
        self.cfg = cfg
        self.train_ds = train_ds
        self.val_ds = val_ds
        self.model = CaptionModel(cfg.model)
        self.log = EventLogger(log_path)
        # analytic FLOPs per teacher-forced XE row (obs/flops.py) — feeds
        # the run report's MFU column via the flops.xe.step counter
        mc = cfg.model
        self._xe_flops_per_row = _flops.xe_flops_per_row(
            T=mc.max_len, F=mc.max_frames, d_embed=mc.d_embed,
            d_hidden=mc.d_hidden, d_att=mc.d_att, V=mc.vocab_size,
            feat_dims=tuple(d for _, d in mc.modalities),
            num_layers=mc.num_layers,
        )
        if cfg.train.obs:
            obs_dir = cfg.train.obs_dir or os.path.join(
                cfg.train.ckpt_dir, "obs"
            )
            if multihost.is_multiprocess() and jax.process_index() != 0:
                # one stream per process (same contract as the JSONL log)
                obs_dir = os.path.join(obs_dir, f"proc{jax.process_index()}")
            obs.configure(
                obs_dir, run=cfg.name,
                snapshot_every=cfg.train.log_every_steps,
            )
            # the run report's MFU column divides the flops.<phase> counters
            # by this assumed chip peak (obs/flops.py table, keyed on the
            # device kind — same table bench.py carries in its JSON)
            obs.gauge("device.peak_flops").set(
                _flops.peak_flops(jax.devices()[0].device_kind)
            )
        # flight recorder (obs/recorder.py): per-step training-dynamics ring
        # + postmortem bundles. stats=True threads the extra on-device
        # update-ratio outputs through every step factory; the params math is
        # bit-identical either way (train/steps._apply), and recorder_steps=0
        # (default) builds literally the pre-recorder programs
        self._stats = bool(cfg.train.obs and cfg.train.recorder_steps > 0)
        # kept on self: spike_mode="adaptive" shares this detector's loss
        # Ewma with the sentinels built in _make_sentinel
        self._detector = (
            _anomaly.AnomalyDetector()
            if self._stats and cfg.train.anomaly else None
        )
        if self._stats:
            flight.configure(
                cfg.train.recorder_steps,
                obs_dir,
                run=cfg.name,
                detector=self._detector,
                config=cfg.to_dict(),
                # host identity: the fleet merge (obs/fleet.py) uses these to
                # name hosts and detect absent procs in a degraded merge
                proc=jax.process_index(),
                world=jax.process_count(),
            )
        # everything below (state init, resume restore, first collate) is
        # run setup: give it a span so the report's phase totals account for
        # the pre-training wall clock instead of reporting a coverage hole
        setup_span = obs.span("setup").begin()
        if cfg.train.debug_nans:
            # sanitizer mode (SURVEY.md §5 row 2): every jitted step re-runs
            # eagerly on NaN production and raises at the originating op
            jax.config.update("jax_debug_nans", True)

        n_dev = cfg.mesh.num_devices or len(jax.devices())
        sp = cfg.mesh.seq_devices > 1
        self.use_mesh = (n_dev > 1 or sp) if use_mesh is None else use_mesh
        self.mesh = (
            make_mesh(cfg.mesh.num_devices, seq_devices=cfg.mesh.seq_devices,
                      mp_devices=cfg.mesh.mp_devices)
            if self.use_mesh else None
        )
        # 2-D ('data','seq') mesh: batch shards over 'data', the FRAME axis
        # over 'seq' (collective attention softmax — the long-context layout)
        self.sp = self.mesh is not None and "seq" in self.mesh.axis_names
        if self.mesh is not None:
            n_data = self.mesh.shape["data"]
            if cfg.data.batch_size % n_data:
                # unlike eval (which wrap-pads exactly, evaluator.py), padding
                # a TRAINING batch would change how rows group into optimizer
                # steps — fail early with guidance, not a device_put error
                raise ValueError(
                    f"training batch_size {cfg.data.batch_size} must be "
                    f"divisible by the mesh's {n_data}-device 'data' axis; "
                    "pick a multiple or set mesh.num_devices/seq_devices"
                )
            if self.sp and cfg.model.max_frames % self.mesh.shape["seq"]:
                raise ValueError(
                    f"model.max_frames {cfg.model.max_frames} must be "
                    f"divisible by mesh.seq_devices {self.mesh.shape['seq']}"
                )
            if self.sp and multihost.is_multiprocess():
                multihost.assert_seq_axis_within_host(self.mesh.devices)

        # multi-host: each process collates only its slice of every global
        # batch (identical global order — the shuffle is epoch-keyed);
        # put_global below assembles the slices into globally-sharded arrays
        self.batcher = Batcher(
            train_ds,
            batch_size=cfg.data.batch_size,
            max_len=cfg.model.max_len,
            mode="caption",
            seq_per_vid=cfg.data.seq_per_vid,
            seed=cfg.data.shuffle_seed,
            host_shard=multihost.host_shard() if self.use_mesh else (0, 1),
        )
        self.steps_per_epoch = self.batcher.num_batches()
        tx = make_optimizer(cfg.train, self.steps_per_epoch)
        sample = next(iter(self.batcher.epoch(shuffle=False)))
        feats, masks, labels, *_ = batch_arrays(sample)
        self.state = create_train_state(
            self.model, tx, (feats, masks, labels), seed=cfg.train.seed
        )
        # the on-device finite-update guard rides with any active sentinel
        # policy (bit-identical on finite steps; "off" restores the exact
        # unguarded program)
        self.guard = cfg.train.on_divergence != "off"
        if self.mesh is not None:
            self.state = replicate(self.mesh, self.state)
        self._build_xe_step()

        if multihost.is_multiprocess():
            # verifiable evidence the cluster actually formed (a degraded
            # init would silently train N independent copies)
            self.log.log(
                "distributed",
                processes=jax.process_count(),
                process_index=jax.process_index(),
                devices=len(jax.devices()),
            )
        self.ckpt = CheckpointManager(
            cfg.train.ckpt_dir, metric="CIDEr-D", keep=cfg.train.keep_ckpts,
            log=self.log.log,
        )
        self.epoch = 0        # global epoch counter (batch-order key, logging)
        self.xe_epochs = 0    # per-phase progress: epochs-field budgets are
        self.rl_epochs = 0    # TOTALS, so a resumed run finishes the remainder
        # mid-epoch resume/rollback bookkeeping (resilience layer)
        self._resume_batch = 0     # XE batches to skip in the next epoch
        self._resume_rl_batch = 0  # RL batches to skip in the next epoch
        self._rollbacks = 0        # divergence rollbacks consumed this run
        self._rl_batcher: Batcher | None = None
        # drain-aware RL seam (README "Elastic training"): tokens the
        # pipelined loop decoded but never scored before a drain; replayed
        # by the resumed epoch so the seam batch is not re-decoded against
        # fresher params
        self._pending_seam: dict | None = None
        # elastic multi-host resilience (resilience/health.py): a heartbeat
        # monitor + peer-loss watchdog. The step loops poll `peer_lost` — a
        # plain Event read, no host<->device traffic — only when enabled.
        self.health: health_mod.HealthMonitor | None = None
        self._degraded_gen = 0
        self._all_mesh_devices = (
            list(self.mesh.devices.flat) if self.mesh is not None else None
        )
        self._initial_hosts = 1
        # pristine copies for the grow-back direction: _continue_degraded
        # overwrites the two working attributes above at every shrink, but a
        # regrow rebuilds host->device slices from the ORIGINAL layout
        self._original_mesh_devices = (
            None if self._all_mesh_devices is None
            else list(self._all_mesh_devices)
        )
        self._original_hosts = 1
        # a validated rejoiner awaiting admission at the next batch boundary
        self._regrow_host: int | None = None
        # name of the most recent step_* save — the drain seam the elastic
        # continuations restore by name (see restore_latest(prefer=...))
        self._last_step_ckpt: str | None = None
        if cfg.train.health:
            health_mod.set_dcn_stall_threshold(cfg.train.dcn_stall_s)
            num_hosts = cfg.train.health_sim_hosts or jax.process_count()
            self._initial_hosts = num_hosts
            self._original_hosts = num_hosts
            self.health = health_mod.HealthMonitor(
                cfg.train.health_dir
                or os.path.join(cfg.train.ckpt_dir, "health"),
                host_id=jax.process_index(),
                num_hosts=num_hosts,
                interval_s=cfg.train.health_interval_s,
                timeout_s=cfg.train.peer_timeout_s,
                misses=cfg.train.health_misses,
                log=self.log.log,
            ).start()
        if cfg.train.resume:
            self._resume()

        self._build_validator()
        setup_span.end()

    def _build_xe_step(self) -> None:
        """(Re)build the jitted XE step for the CURRENT mesh — called at init
        and again after a degraded-mesh rebuild."""
        cfg = self.cfg
        # the grad-allreduce spelling (parallel/comms.py): bucketing/dtype/
        # overlap from the train.comm_* knobs, shared with the RL update
        comm = CommConfig.from_train(cfg.train)
        # a new jitted step means the compile-time FLOPs probe must re-run
        # (a degraded-mesh rebuild changes the program)
        self._xe_cost = None
        if self.mesh is not None:
            if self.sp:
                # SP params are layout-identical to the plain model's, so the
                # state init above (plain model) feeds the SP step directly
                # donate=True: the step consumes self.state (rebound on every
                # call), so params + Adam moments update in place instead of
                # double-buffering — HBM headroom on the production path
                self.xe_step = make_sp_xe_step(
                    sp_model(cfg.model), self.mesh, cfg.train.label_smoothing,
                    data_axis="data", donate=True, guard=self.guard,
                    comm=comm, stats=self._stats,
                )
            else:
                self.xe_step = make_parallel_xe_step(
                    self.model, self.mesh, cfg.train.label_smoothing,
                    donate=True, guard=self.guard, comm=comm,
                    stats=self._stats,
                )
        else:
            self.xe_step = make_xe_step(
                self.model, cfg.train.label_smoothing, donate=True,
                guard=self.guard, comm=comm, stats=self._stats,
            )

    def _xe_flops_inc(self, rows, args) -> float:
        """Per-process FLOPs to count for one XE step. Prefers the COMPILED
        program's own cost (obs/flops.compiled_cost) so the MFU column and
        bench_comms agree on what a step costs; analytic per-row model when
        XLA exposes no cost or obs is off (the probe forces an AOT compile
        walk — skip it when nothing reads the counter). The compiled number
        is the whole (global-batch) program, split evenly across processes
        so per-process streams still sum to the global total; the analytic
        one counts this host's rows directly."""
        if self._xe_cost is None and obs.enabled():
            cost = _flops.compiled_cost(self.xe_step, *args)
            self._xe_cost = cost["flops"] if cost else False
            # probe bookkeeping: the counter ticks once per (re)compiled
            # program — a degraded-mesh rebuild re-probes and ticks again —
            # and the gauge labels which backend the MFU column reflects
            obs.counter("obs.flops.probes").inc()
            obs.gauge("flops.backend.xe.step").set(
                1.0 if self._xe_cost else 0.0
            )
        if self._xe_cost:
            return self._xe_cost / jax.process_count()
        return rows * self._xe_flops_per_row

    def _build_validator(self) -> None:
        cfg = self.cfg
        self.validator = (
            Evaluator(
                self.model,
                self.val_ds,
                EvalConfig(beam_size=1, max_len=cfg.model.max_len,
                           metrics=("CIDEr-D",)),
                batch_size=cfg.data.batch_size,
                mesh=self.mesh,
            )
            if self.val_ds is not None
            else None
        )

    def close(self) -> None:
        """Stop background machinery (the health watchdog, the flight
        recorder). Safe to call twice; the monitor thread is a daemon
        either way."""
        if self.health is not None:
            self.health.stop()
        if self._stats:
            # orderly close: final flush, NO postmortem dump (crashes that
            # skip close() still dump via the recorder's atexit hook)
            flight.shutdown()

    # ---- resume / handoff --------------------------------------------------

    def _resume(self):
        # resume="auto": newest valid ckpt in this run's ckpt_dir;
        # resume=<dir>: explicit checkpoint directory (latest/best inside it)
        resume = self.cfg.train.resume
        src_dir = self.cfg.train.ckpt_dir if resume == "auto" else resume
        mgr = (
            self.ckpt if resume == "auto"
            else CheckpointManager(src_dir, log=self.log.log)
        )
        restored = mgr.restore_latest(jax.device_get(self.state))
        if restored is None:
            self.log.log("resume_not_found", dir=src_dir)
            return
        state, infos = restored
        batch_index, phase = self._adopt_restored(state, infos, src_dir)
        # surface config drift between the checkpoint and this run
        saved_cfg = infos.get("config")
        if saved_cfg:
            # one json round-trip canonicalizes tuples to lists, matching the
            # JSON-born saved snapshot leaf for leaf
            drift = _config_drift(saved_cfg, json.loads(self.cfg.to_json()))
            if drift:
                self.log.log("resume_config_drift", fields=drift)
        self.log.log(
            "resume", dir=src_dir, step=int(state.step), epoch=self.epoch,
            batch_index=batch_index, phase=phase or "epoch_end",
        )

    def _adopt_restored(self, state, infos: dict, src_dir: str) -> tuple[int, str]:
        """Install a restored state + its resume bookkeeping (shared by
        resume-at-startup and the degraded-mesh continuation). Returns the
        restored ``(batch_index, phase)``."""
        self.state = (
            replicate(self.mesh, state) if self.mesh is not None else state
        )
        self.epoch = int(infos.get("epoch", 0))
        # old checkpoints without phase counters: assume all epochs were XE
        self.xe_epochs = int(infos.get("xe_epochs", self.epoch))
        self.rl_epochs = int(infos.get("rl_epochs", 0))
        # exact data-order resume: epoch-keyed shuffling continues where the
        # uninterrupted run would have been. The caption batcher consumes one
        # epoch index per *shuffled* (XE) epoch only — RL epochs run their own
        # video-mode batcher — so the XE count, not the global one, is the key
        self.batcher.epoch_index = self.xe_epochs
        # mid-epoch checkpoint (preemption or step-interval save): the epoch
        # counters above are COMPLETED epochs; batch_index says how far into
        # the in-progress epoch the save happened, so the next phase call
        # replays exactly the remainder under the same epoch-keyed shuffle
        batch_index = int(infos.get("batch_index", 0))
        phase = infos.get("phase", "")
        self._resume_batch = self._resume_rl_batch = 0
        if batch_index and phase == "xe":
            self._resume_batch = batch_index
        elif batch_index and phase == "rl":
            self._resume_rl_batch = batch_index
        self.batcher.salt = int(infos.get("data_salt", 0))
        self._pending_seam = self._load_seam(src_dir, infos)
        return batch_index, phase

    # ---- drain-aware RL seam ------------------------------------------------

    @staticmethod
    def _seam_bytes(seam: dict, epoch: int, batch_index: int) -> bytes:
        """Serialize a captured seam (scst._seam_capture output, or the
        decoupled loop's in-flight ring) + its position as an npz blob for
        the checkpoint's extra_files."""
        arrays = {
            "epoch": np.asarray(int(epoch)),
            "batch_index": np.asarray(int(batch_index)),
        }
        if "ring" in seam:
            # decoupled drain: every in-flight rollout ring entry persists
            # (tokens + logprobs + RNG key data), flattened as ring{i}_*
            # entries are already host arrays (the capture device_gets);
            # np.savez converts the list/int leaves itself
            arrays["ring_n"] = len(seam["ring"])
            for i, e in enumerate(seam["ring"]):
                arrays[f"ring{i}_samples"] = e["samples"]
                arrays[f"ring{i}_lps"] = e["lps"]
                arrays[f"ring{i}_video_ids"] = [
                    str(v) for v in e["video_ids"]
                ]
                arrays[f"ring{i}_valid"] = e["valid"]
                arrays[f"ring{i}_rng"] = e["rng"]
                arrays[f"ring{i}_batch_index"] = int(e["batch_index"])
                if e.get("greedy") is not None:
                    arrays[f"ring{i}_greedy"] = e["greedy"]
        else:
            arrays["samples"] = np.asarray(seam["samples"])
            arrays["video_ids"] = np.asarray(
                [str(v) for v in seam["video_ids"]]
            )
            if seam.get("greedy") is not None:
                arrays["greedy"] = np.asarray(seam["greedy"])
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        return buf.getvalue()

    def _load_seam(self, src_dir: str, infos: dict) -> dict | None:
        """Load the seam sidecar of the checkpoint that just restored (if
        its save drained a pipelined RL epoch)."""
        name = infos.get("ckpt_name", "")
        if not name or infos.get("phase") != "rl" \
                or not infos.get("batch_index"):
            return None
        path = os.path.join(src_dir, name, "seam.npz")
        if not os.path.exists(path):
            return None
        try:
            with np.load(path, allow_pickle=False) as z:
                if "ring_n" in z.files:
                    # npz members load as host ndarrays already
                    ring = []
                    for i in range(int(z["ring_n"])):
                        e = {
                            "samples": z[f"ring{i}_samples"],
                            "lps": z[f"ring{i}_lps"],
                            "video_ids": [
                                str(v) for v in z[f"ring{i}_video_ids"]
                            ],
                            "valid": z[f"ring{i}_valid"],
                            "rng": z[f"ring{i}_rng"],
                            "batch_index": int(z[f"ring{i}_batch_index"]),
                        }
                        if f"ring{i}_greedy" in z.files:
                            e["greedy"] = z[f"ring{i}_greedy"]
                        ring.append(e)
                    seam = {
                        "ring": ring,
                        "epoch": int(z["epoch"]),
                        "batch_index": int(z["batch_index"]),
                    }
                else:
                    seam = {
                        "samples": np.asarray(z["samples"]),
                        "greedy": (
                            np.asarray(z["greedy"]) if "greedy" in z.files
                            else None
                        ),
                        "video_ids": [str(v) for v in z["video_ids"]],
                        "epoch": int(z["epoch"]),
                        "batch_index": int(z["batch_index"]),
                    }
        except (OSError, ValueError, KeyError) as e:
            # a torn/legacy seam degrades to the old re-decode behavior —
            # never to a crash or to silently wrong tokens
            self.log.log(
                "seam_unreadable", path=path, error=type(e).__name__,
                detail=str(e),
            )
            return None
        self.log.log(
            "seam_loaded", ckpt=name, epoch=seam["epoch"],
            batch_index=seam["batch_index"],
        )
        return seam

    def load_params_from(self, ckpt_dir: str, name: str = "best"):
        """XE -> RL handoff: params only, fresh optimizer (SURVEY.md §5)."""
        params = load_params(ckpt_dir, name, jax.device_get(self.state.params))
        self.state = self.state.replace(params=params)
        if self.mesh is not None:
            self.state = replicate(self.mesh, self.state)
        self.log.log("handoff", source=f"{ckpt_dir}/{name}")

    # ---- phases ------------------------------------------------------------

    def _batch_sharding(self):
        """device_put target for the XE batch tuple: a single axis-0 sharding
        (1-D mesh; a tree prefix for every element), or the per-leaf SP tuple
        (frames over 'seq', batch over 'data')."""
        if self.mesh is None:
            return None
        if self.sp:
            return sp_batch_shardings(self.mesh, self.cfg.model)
        return batch_sharding(self.mesh)

    def _device_batches(self, batcher: Batcher, skip: int = 0,
                        stop_event: threading.Event | None = None):
        shardings = self._batch_sharding()

        def transform(b):
            b = chaos.visit("xe.batch", b)
            if shardings is None:
                # valid rides along so wrap-padding rows get zero weight
                return batch_arrays(b) + (
                    jax.numpy.asarray(np.asarray(b.valid, np.float32)),
                )
            # keep the Batch's numpy arrays as-is: put_global transfers them
            # host->device exactly once, straight into the target sharding
            arrays = (
                b.feats, b.feat_masks, b.labels, b.mask, b.weights,
                np.asarray(b.valid, np.float32),
            )
            return multihost.put_global(shardings, arrays)

        # mid-epoch resume: drop the first ``skip`` batches of this epoch's
        # (already deterministic) order before any transform/transfer
        it = itertools.islice(batcher.epoch(), skip, None)
        yield from prefetch_to_device(
            it,
            size=self.cfg.data.prefetch,
            transform=transform,
            place=shardings is None,
            stop_event=stop_event,
        )

    def _rl_device_batches(self, batcher: Batcher, skip: int = 0,
                           stop_event: threading.Event | None = None):
        """Prefetched RL batches: arrays staged to device (sharded when a mesh
        is in play), video ids + valid mask staying host-side (this process's
        rows) for the reward."""
        sharding = self._batch_sharding()
        if sharding is not None and self.sp:
            sharding = (sharding[0], sharding[1])  # (feats, masks) only

        def transform(b):
            b = chaos.visit("rl.batch", b)
            if sharding is not None:
                # numpy straight into the target sharding (single transfer)
                feats, masks = multihost.put_global(
                    sharding, (b.feats, b.feat_masks)
                )
            else:
                feats, masks = jax.device_put((b.feats, b.feat_masks))
            return (feats, masks, b.video_ids, b.valid)

        it = itertools.islice(batcher.epoch(shuffle=True), skip, None)
        yield from prefetch_to_device(
            it,
            size=self.cfg.data.prefetch,
            transform=transform,
            place=False,
            stop_event=stop_event,
        )

    # ---- resilience helpers ------------------------------------------------

    def _make_sentinel(self, phase: str) -> DivergenceSentinel:
        """Policy/cadence from config: the default ``skip_batch`` policy
        defers every readback to epoch ends / save points (zero extra host
        syncs — the on-device guard already excluded the bad update);
        ``rollback``/``abort`` buy mid-epoch detection for one amortized
        device_get per 32 steps."""
        cfg = self.cfg.train
        adaptive = None
        if cfg.spike_mode == "adaptive" and cfg.spike_factor:
            # the feedback loop (resilience/adaptive.py): the anomaly
            # detector's loss Ewma — updated on the recorder's flush cadence
            # — sets the spike bound; without a detector the thresholds own
            # a private Ewma fed from the sentinel's flushes
            adaptive = AdaptiveThresholds(
                factor_max=cfg.spike_factor,
                factor_min=cfg.spike_factor_min,
                ewma=(
                    self._detector.ewma("loss")
                    if self._detector is not None else None
                ),
            )
        return DivergenceSentinel(
            policy=cfg.on_divergence,
            phase=phase,
            log=self.log.log,
            spike_factor=cfg.spike_factor,
            check_every=32 if cfg.on_divergence in ("rollback", "abort") else None,
            adaptive=adaptive,
        )

    def _ckpt_infos(self, phase: str = "", batch_index: int = 0,
                    step_no: int | None = None) -> dict:
        return {
            "epoch": self.epoch,
            "xe_epochs": self.xe_epochs,
            "rl_epochs": self.rl_epochs,
            "phase": phase,
            "batch_index": batch_index,
            "global_step": step_no,
            "data_salt": self.batcher.salt,
            "config": self.cfg.to_dict(),
        }

    def _save_step_ckpt(self, phase: str, step_no: int, batch_index: int,
                        seam: dict | None = None) -> None:
        """Mid-epoch checkpoint (step-interval or preemption-triggered):
        records the exact batch index so resume replays the epoch remainder.
        ``seam`` (drain-aware RL saves) rides along as ``seam.npz`` in the
        same atomic swap."""
        if jax.process_index() == 0:
            extra = None
            if seam:
                extra = {
                    "seam.npz": self._seam_bytes(
                        seam, self.epoch, batch_index
                    ),
                }
            with obs.span("ckpt", kind="step"):
                self.ckpt.save_step(
                    jax.device_get(self.state), step_no,
                    self._ckpt_infos(phase, batch_index, step_no),
                    extra_files=extra,
                )
        # the elastic continuations restore THIS save by name: its
        # phase-local step ordinal may rank below an older epoch-end ckpt
        self._last_step_ckpt = f"step_{int(step_no):08d}"
        self.log.log(
            "ckpt_step", phase=phase, step=step_no, batch_index=batch_index,
            seam=bool(seam),
        )

    def _preempt_save(self, phase: str, step_no: int, batch_index: int,
                      sentinel: DivergenceSentinel,
                      seam: dict | None = None) -> None:
        """SIGTERM landed: flush pending divergence checks (never checkpoint
        an update the sentinel would have rejected), save mid-epoch, make the
        event log durable, and unwind via :class:`Preempted`."""
        sentinel.flush()
        # postmortem before the unwind: the bundle captures the ring as of
        # the drained step (postmortem self-flushes the recorder)
        flight.postmortem("preempt", phase=phase, step=step_no)
        self._save_step_ckpt(phase, step_no, batch_index, seam=seam)
        self.log.log(
            "preempt", phase=phase, step=step_no, batch_index=batch_index,
        )
        self.log.flush()
        raise Preempted(
            f"preempted at {phase} step {step_no} "
            f"(epoch {self.epoch + 1}, batch {batch_index}); "
            "checkpoint saved — rerun with train.resume='auto'"
        )

    def _peer_loss_save(self, phase: str, step_no: int, batch_index: int,
                        sentinel: DivergenceSentinel,
                        seam: dict | None = None) -> None:
        """A peer host was lost (heartbeat timeout / partial preemption):
        coordinated DRAIN — the in-flight step finished, prefetch is about
        to be flushed by the epoch unwind — then a durable mid-epoch save in
        drain-aware order, then :class:`PeerLost` so the caller picks
        degraded continuation or the strict full-restart fallback."""
        sentinel.flush()
        # lost hosts computed BEFORE the dump so the bundle meta names the
        # victim(s) — the fleet merge reads `lost` for trip attribution
        lost = self.health.lost()
        flight.postmortem("peer_loss", phase=phase, step=step_no, lost=lost)
        self._save_step_ckpt(phase, step_no, batch_index, seam=seam)
        obs.counter("resilience.peer_loss_drain").inc()
        self.log.log(
            "peer_loss_drain", phase=phase, step=step_no,
            batch_index=batch_index, lost=lost,
        )
        self.log.flush()
        raise PeerLost(
            lost,
            f"lost host(s) {lost} at {phase} step {step_no} "
            f"(epoch {self.epoch + 1}, batch {batch_index}); drained and "
            "saved — continuing degraded or restart with train.resume='auto'",
        )

    def _apply_rollback(self, phase: str, err: RollbackRequested,
                        sentinel: DivergenceSentinel) -> None:
        """Divergence rollback: restore the newest verifiable checkpoint and
        re-randomize the data order (salted epoch-keyed shuffle), so the
        replayed epochs don't march straight back into the same poison batch
        sequence. Budgeted by ``train.max_rollbacks``."""
        self._rollbacks += 1
        obs.counter("resilience.rollback").inc()
        # no postmortem here: the sentinel already dumped the ring at the
        # divergence itself (reason=divergence_<kind>, action=rollback) —
        # a second dump would hold the identical ring and burn dump budget
        if self._rollbacks > self.cfg.train.max_rollbacks:
            raise TrainingDiverged(
                f"rollback budget exhausted ({self.cfg.train.max_rollbacks}) "
                f"after {phase} divergence: {err}"
            ) from err
        restored = self.ckpt.restore_latest(jax.device_get(self.state))
        if restored is None:
            raise TrainingDiverged(
                f"{phase} diverged with no checkpoint to roll back to: {err}"
            ) from err
        state, infos = restored
        self.state = (
            replicate(self.mesh, state) if self.mesh is not None else state
        )
        self.epoch = int(infos.get("epoch", 0))
        self.xe_epochs = int(infos.get("xe_epochs", self.epoch))
        self.rl_epochs = int(infos.get("rl_epochs", 0))
        # the in-progress epoch restarts from batch 0 under the new salt (a
        # mid-epoch checkpoint's batch_index indexes the OLD order — it no
        # longer names the same batches, so it must not be replayed; ditto
        # any pending seam tokens, which belong to the old order)
        self._resume_batch = self._resume_rl_batch = 0
        self._pending_seam = None
        self.batcher.salt = self._rollbacks
        if self._rl_batcher is not None:
            self._rl_batcher.salt = self._rollbacks
        sentinel.reset()
        self.log.log(
            "rollback",
            phase=phase,
            step=err.step,
            kind=err.kind,
            restored_step=infos.get("global_step"),
            restored_epoch=self.epoch,
            salt=self._rollbacks,
        )

    # ---- degraded-mesh continuation -----------------------------------------

    def _surviving_devices(self, survivors: list[int], devices=None,
                           hosts: int | None = None) -> list:
        """Devices of the given hosts, in the original mesh order.

        Real multi-process clusters map hosts to ``device.process_index``;
        simulated hosts (train.health_sim_hosts) split the mesh's device
        list evenly — host k owns the k-th contiguous chunk. The default
        base is the CURRENT layout; the regrow path passes the pristine
        ``_original_mesh_devices``/``_original_hosts`` so a re-admitted
        host's slice comes back in its original position."""
        devices = self._all_mesh_devices if devices is None else devices
        hosts = self._initial_hosts if hosts is None else hosts
        if multihost.is_multiprocess():
            alive = set(survivors)
            return [d for d in devices if d.process_index in alive]
        per_host = max(1, len(devices) // hosts)
        out = []
        for h in survivors:
            out.extend(devices[h * per_host:(h + 1) * per_host])
        return out

    def _continue_degraded(self, phase: str, err: PeerLost) -> None:
        """Elastic continuation after a drained peer loss: rendezvous the
        survivors (retry/timeout/backoff), rebuild a SHRUNK 1-D data mesh
        over the surviving devices, reshard params + optimizer state from
        the last durable checkpoint (the drain just wrote one, seam
        included), rescale the per-host batch share, and let the phase loop
        replay the epoch remainder."""
        cfg = self.cfg
        if self.health is None or self._all_mesh_devices is None:
            raise err  # elastic continuation needs the monitor AND a mesh
        if self.sp:
            raise RuntimeError(
                "degraded-mesh continuation does not support the "
                "('data','seq') mesh — a lost host takes part of every seq "
                "row with it; run elastic='strict' with seq_devices > 1"
            ) from err
        self._degraded_gen += 1
        expected = self.health.survivors()
        with obs.span("degraded_rendezvous", generation=self._degraded_gen):
            survivors = health_mod.rendezvous(
                self.health.dir,
                host_id=self.health.host_id,
                hosts=expected,
                generation=self._degraded_gen,
                timeout_s=max(cfg.train.peer_timeout_s * 4.0, 1.0),
            )
        devices = self._surviving_devices(survivors)
        n_data = len(devices)
        if n_data == 0:
            raise RuntimeError(
                f"no devices survive the loss of host(s) {err.hosts}"
            ) from err
        if cfg.data.batch_size % n_data:
            raise RuntimeError(
                f"cannot continue degraded: global batch_size "
                f"{cfg.data.batch_size} is not divisible by the {n_data} "
                "surviving devices — run elastic='strict' or pick a batch "
                "size divisible by every survivable mesh width"
            ) from err
        self.mesh = Mesh(np.asarray(devices), ("data",))
        # per-host batch rescaling: the GLOBAL batch is unchanged, each
        # surviving host's share grows to cover the lost host's rows
        if multihost.is_multiprocess():
            shard = (survivors.index(jax.process_index()), len(survivors))
            self.batcher = self._rebuild_batcher(self.batcher, shard)
        # reshard params + optimizer state from the last durable checkpoint
        # onto the shrunk mesh (the peer-loss drain saved one moments ago,
        # with the exact batch index + pipeline seam — prefer it by NAME:
        # its phase-local step ordinal may rank below an epoch-end save)
        restored = self.ckpt.restore_latest(
            jax.device_get(self.state), prefer=self._last_step_ckpt
        )
        if restored is None:
            raise RuntimeError(
                "degraded continuation found no restorable checkpoint in "
                f"{cfg.train.ckpt_dir} — the peer-loss drain save is missing"
            ) from err
        state, infos = restored
        batch_index, res_phase = self._adopt_restored(
            state, infos, cfg.train.ckpt_dir
        )
        self._build_xe_step()
        self._build_validator()
        self.health.set_membership(survivors)
        self.health.acknowledge()
        # sync the monitor's generation: rejoin markers for the NEXT regrow
        # round are stamped generation+1 (stale ones are refused)
        self.health.generation = self._degraded_gen
        self._all_mesh_devices = devices
        self._initial_hosts = len(survivors)
        obs.counter("resilience.degraded_continuation").inc()
        obs.event(
            "degraded_mesh", phase=phase, lost=err.hosts,
            survivors=survivors, devices=n_data,
        )
        # the elastic-timeline spelling (obs/fleet.py pairs shrink→regrow
        # arcs): one event per victim so every arc names a single host
        for victim in err.hosts:
            obs.event(
                "mesh_shrink", phase=phase, victim=victim, devices=n_data,
                generation=self._degraded_gen,
            )
        self.log.log(
            "degraded_mesh",
            phase=phase,
            lost=err.hosts,
            survivors=survivors,
            devices=n_data,
            global_batch=cfg.data.batch_size,
            resumed_phase=res_phase,
            resumed_batch_index=batch_index,
        )

    # ---- elastic grow-back (host re-admission) ------------------------------

    def _poll_rejoin(self) -> None:
        """Batch-boundary rejoin poll — the grow-back half of README
        "Elastic training". Free unless the run is degraded with regrow
        enabled (a couple of attribute reads); only then does it visit the
        ``health.rejoin`` chaos point and scan for rejoin markers. A
        readable marker triggers liveness validation under the budgeted
        retry policy: success schedules admission at the next batch
        boundary (``_regrow_host``), failure consumes the marker and leaves
        the degraded run untouched."""
        h = self.health
        if (
            h is None
            or self._regrow_host is not None
            or self.cfg.train.elastic != "degraded"
            or not self.cfg.train.elastic_regrow
            or self._original_mesh_devices is None
            or not h.lost_hosts
            or h.peer_lost  # an unacknowledged loss outranks a rejoin
        ):
            return
        chaos.visit("health.rejoin")
        pending = h.pending_rejoins()
        if not pending:
            return
        host = min(pending)  # deterministic order when several announce
        gen = self._degraded_gen + 1
        try:
            health_mod.attempt_rejoin(h, host, gen)
        except health_mod.RejoinRefused as e:
            h.clear_rejoin(host)
            obs.event("rejoin_refused", host=host, generation=gen)
            self.log.log(
                "rejoin_refused", host=host, generation=gen, detail=str(e),
            )
            return
        self._regrow_host = host

    def _regrow_save(self, phase: str, step_no: int, batch_index: int,
                     sentinel: DivergenceSentinel,
                     seam: dict | None = None) -> None:
        """A validated rejoiner is waiting: coordinated DRAIN at the batch
        boundary — mirror of the peer-loss drain, seam included, so the
        admission never tears a pipelined update — then :class:`HostRejoin`
        unwinds to the phase loop, which runs the regrow rendezvous."""
        host = self._regrow_host
        sentinel.flush()
        self._save_step_ckpt(phase, step_no, batch_index, seam=seam)
        obs.counter("resilience.regrow_drain").inc()
        self.log.log(
            "regrow_drain", phase=phase, step=step_no,
            batch_index=batch_index, rejoiner=host,
        )
        self.log.flush()
        raise health_mod.HostRejoin(
            host,
            f"host {host} re-admission scheduled at {phase} step {step_no} "
            f"(epoch {self.epoch + 1}, batch {batch_index}); drained and "
            "saved",
        )

    def _continue_regrown(self, phase: str,
                          err: health_mod.HostRejoin) -> bool:
        """Elastic grow-back: the inverse of :meth:`_continue_degraded`.

        Survivors and the rejoiner rendezvous at the bumped generation,
        the FULL 1-D data mesh is rebuilt from the pristine device layout,
        params + optimizer state reshard onto it via the ``replicate`` /
        ``put_full_global`` path from the drain checkpoint the SURVIVORS
        just wrote (the rejoiner never trusts its own stale checkpoint),
        per-host batch shares rescale back (global batch unchanged), the
        jitted closures rebuild, and the phase loop replays the epoch
        remainder — seam included. Returns True on admission; False when
        the rendezvous timed out or the grown mesh cannot carry the batch,
        in which case the degraded run continues exactly where the drain
        left it, untouched (never a second outage)."""
        cfg = self.cfg
        host = err.host
        self._regrow_host = None
        gen = self._degraded_gen + 1
        members = sorted(set(self.health.survivors()) | {host})
        devices = self._surviving_devices(
            members, devices=self._original_mesh_devices,
            hosts=self._original_hosts,
        )
        n_data = len(devices)
        admitted = False
        refuse_reason = ""
        if cfg.data.batch_size % n_data:
            refuse_reason = (
                f"global batch_size {cfg.data.batch_size} is not divisible "
                f"by the {n_data} regrown devices"
            )
        else:
            try:
                with obs.span("regrow_rendezvous", generation=gen):
                    health_mod.rendezvous(
                        self.health.dir,
                        host_id=self.health.host_id,
                        hosts=members,
                        generation=gen,
                        timeout_s=max(cfg.train.peer_timeout_s * 2.0, 0.5),
                    )
                admitted = True
            except health_mod.RendezvousTimeout as e:
                # the flaky rejoiner: announced, validated, then died
                # before checking in — time out and stay degraded
                refuse_reason = str(e)
        if admitted:
            self.mesh = Mesh(np.asarray(devices), ("data",))
            if multihost.is_multiprocess():
                shard = (members.index(jax.process_index()), len(members))
                self.batcher = self._rebuild_batcher(self.batcher, shard)
            self.health.readmit(host)
            self.health.set_membership(members)
            self._degraded_gen = gen
            self.health.generation = gen
            self._all_mesh_devices = devices
            self._initial_hosts = len(members)
        else:
            obs.counter("resilience.regrow.refused").inc()
            self.health.clear_rejoin(host)
            obs.event(
                "regrow_refused", phase=phase, rejoiner=host, generation=gen,
            )
            self.log.log(
                "regrow_refused", phase=phase, rejoiner=host, generation=gen,
                detail=refuse_reason,
            )
        # state from the SURVIVORS: the regrow drain saved the survivor
        # state moments ago; restoring that checkpoint (by NAME — its
        # phase-local step ordinal may rank below an epoch-end save) and
        # replicating onto self.mesh (full when admitted, unchanged when
        # refused) is the state handoff AND re-arms the mid-epoch resume
        # bookkeeping (batch index + pipeline seam) either way
        restored = self.ckpt.restore_latest(
            jax.device_get(self.state), prefer=self._last_step_ckpt
        )
        if restored is None:
            raise RuntimeError(
                "regrow continuation found no restorable checkpoint in "
                f"{cfg.train.ckpt_dir} — the regrow drain save is missing"
            ) from err
        state, infos = restored
        batch_index, res_phase = self._adopt_restored(
            state, infos, cfg.train.ckpt_dir
        )
        if admitted:
            self._build_xe_step()
            self._build_validator()
            obs.counter("resilience.regrow.admitted").inc()
            obs.event(
                "mesh_regrow", phase=phase, rejoiner=host, devices=n_data,
                generation=gen,
            )
            self.log.log(
                "mesh_regrow",
                phase=phase,
                rejoiner=host,
                hosts=members,
                devices=n_data,
                generation=gen,
                global_batch=cfg.data.batch_size,
                resumed_phase=res_phase,
                resumed_batch_index=batch_index,
            )
        return admitted

    def _rebuild_batcher(self, old: Batcher, host_shard: tuple[int, int]) -> Batcher:
        """Same data order, new host share (degraded multi-process only)."""
        new = Batcher(
            self.train_ds,
            batch_size=old.batch_size,
            max_len=old.max_len,
            mode=old.mode,
            seq_per_vid=old.seq_per_vid,
            seed=old.seed,
            host_shard=host_shard,
        )
        new.epoch_index = old.epoch_index
        new.salt = old.salt
        return new

    # ---- XE phase ----------------------------------------------------------

    def train_xe(self, epochs: int | None = None) -> float | None:
        """Cross-entropy (XE/WXE) phase; returns last validation CIDEr-D.

        ``epochs=None`` treats ``cfg.train.epochs`` as the phase TOTAL: a
        resumed run trains only the remainder (including the remainder of a
        mid-epoch preempted epoch). An explicit ``epochs`` runs exactly that
        many more. Raises :class:`Preempted` after a SIGTERM-triggered save,
        :class:`TrainingDiverged` under the abort policy / exhausted
        rollback budget.
        """
        cfg = self.cfg
        if epochs is None:
            epochs = max(0, cfg.train.epochs - self.xe_epochs)
        if epochs == 0:
            return None
        target = self.xe_epochs + epochs
        meter = obs.StepMeter("xe")
        profiler = StepProfiler(
            os.path.join(cfg.train.profile_dir, "xe") if cfg.train.profile_dir
            else "",
            cfg.train.profile_steps,
            log=self.log.log,
        )
        sentinel = self._make_sentinel("xe")
        last_val = None
        run = {"first_step": True}  # compile-step meter exclusion, phase-wide
        with PreemptionHandler() as pre:
            while self.xe_epochs < target:
                try:
                    last_val = self._xe_epoch(meter, profiler, sentinel, pre, run)
                except RollbackRequested as e:
                    self._apply_rollback("xe", e, sentinel)
                except PeerLost as e:
                    # strict keeps today's abort-and-full-restart (the saved
                    # drain resumes bit-exactly on the full mesh); degraded
                    # shrinks the mesh and keeps training on the survivors
                    if self.cfg.train.elastic != "degraded":
                        raise
                    self._continue_degraded("xe", e)
                    run["first_step"] = True  # recompile on the shrunk mesh
                except health_mod.HostRejoin as e:
                    if self._continue_regrown("xe", e):
                        run["first_step"] = True  # recompile on the full mesh
        return last_val

    def _xe_epoch(self, meter, profiler, sentinel, pre, run) -> float | None:
        """One XE epoch (possibly a resumed remainder): step loop, sentinel,
        mid-epoch saves, epoch-end validation + checkpoint."""
        cfg = self.cfg
        weighted = cfg.train.loss == "wxe"
        log_every = cfg.train.log_every_steps
        ckpt_every = cfg.train.ckpt_every_steps
        # pin the batch-order key: epochs 0..xe_epochs-1 are complete, this
        # epoch replays/starts index xe_epochs (idempotent under rollback)
        self.batcher.epoch_index = self.xe_epochs
        skip = self._resume_batch
        self._resume_batch = 0
        batch_no = skip
        # host-side step counter: reading int(self.state.step) per step in
        # the loop would block on the just-dispatched update every step
        step_no = int(self.state.step)
        if obs.enabled():
            obs.set_context(phase="xe", epoch=self.epoch + 1)
        meter.begin_epoch()
        losses = []
        stop = threading.Event()
        # xe.step spans cover the loop body (dispatch + bookkeeping); the
        # xe.epoch span's SELF time is therefore exactly the host's wait on
        # the input pipeline — the report splits compute-bound from
        # data-bound epochs without any extra probe
        with obs.span("xe.epoch"):
            try:
                for arrays in self._device_batches(self.batcher, skip=skip,
                                                   stop_event=stop):
                    with obs.span("xe.step"):
                        feats, masks, labels, mask, weights, valid = arrays
                        # invalid rows get zero weight -> excluded from loss
                        weights = valid if not weighted else weights * valid
                        self.state, m = self.xe_step(
                            self.state, feats, masks, labels, mask, weights
                        )
                        # keep the device scalar: float() here would sync per
                        # step (graftlint GL001); the epoch summary reads
                        # them all back in one device_get
                        losses.append(m["loss"])
                        # record before push: a sentinel trip's postmortem
                        # self-flushes, so the ring always includes the
                        # diverged step (flight.record keeps device scalars
                        # — zero sync, same contract as sentinel.push)
                        flight.record(step_no + 1, "xe", m)
                        sentinel.push(step_no + 1, m["loss"], m.get("nonfinite"))
                        step_no += 1
                        batch_no += 1
                        if obs.enabled():
                            obs.set_context(step=step_no)
                        if log_every and step_no % log_every == 0:
                            # per-step event: a mid-epoch divergence (NaN,
                            # grad blowup) is locatable from the log alone
                            # (SURVEY.md §5); the float() syncs are gated —
                            # amortized over log_every steps
                            self.log.log(
                                "xe_step",
                                phase="xe",
                                step=step_no,
                                epoch=self.epoch + 1,
                                loss=float(m["loss"]),
                                grad_norm=float(m["grad_norm"]),
                            )
                            # ride the same gate: ONE batched device_get
                            # drains the recorder's pending scalars
                            flight.flush()
                        obs.maybe_snapshot(step_no)
                        profiler.tick()
                        meter.tick(cfg.data.batch_size, first=run["first_step"])
                        run["first_step"] = False
                        # self.state is the step's OUTPUT here (same shapes;
                        # the donated input is already consumed) — safe to
                        # lower against for the one-time cost probe
                        obs.counter("flops.xe.step").inc(
                            self._xe_flops_inc(cfg.data.batch_size, (
                                self.state, feats, masks, labels, mask,
                                weights,
                            ))
                        )
                        chaos.visit("xe.step")
                        if self.health is not None:
                            self.health.note_step(step_no)
                        if pre.requested:
                            self._preempt_save("xe", step_no, batch_no, sentinel)
                        if self.health is not None and self.health.peer_lost:
                            self._peer_loss_save(
                                "xe", step_no, batch_no, sentinel
                            )
                        self._poll_rejoin()
                        if self._regrow_host is not None:
                            self._regrow_save("xe", step_no, batch_no, sentinel)
                        if ckpt_every and step_no % ckpt_every == 0:
                            # never save an update the policy rejects
                            flight.flush()
                            sentinel.flush()
                            self._save_step_ckpt("xe", step_no, batch_no)
            finally:
                stop.set()
            profiler.stop()
            # a SIGTERM that lands between the last step and here must not let
            # the epoch counters advance past the state actually saved
            if pre.requested:
                self._preempt_save("xe", step_no, batch_no, sentinel)
            if self.health is not None and self.health.peer_lost:
                self._peer_loss_save("xe", step_no, batch_no, sentinel)
            self._poll_rejoin()
            if self._regrow_host is not None:
                self._regrow_save("xe", step_no, batch_no, sentinel)
            flight.flush()
            sentinel.flush()
        self.epoch += 1
        self.xe_epochs += 1
        vals = np.asarray(jax.device_get(losses), np.float64)
        vals = vals[np.isfinite(vals)]  # guard-skipped steps carry NaN losses
        self.log.log(
            "xe_epoch",
            epoch=self.epoch,
            # ONE readback for the whole epoch's loss scalars
            loss=float(vals.mean()) if vals.size else float("nan"),
            **meter.epoch_summary(),
        )
        obs.snapshot_metrics(epoch=self.epoch)
        return self._validate_and_checkpoint(step_no)

    def train_rl(self, epochs: int | None = None) -> float | None:
        """CST/RL phase (SCST or consensus-CST per cfg.rl).

        ``epochs=None``: ``cfg.rl.epochs`` is the phase TOTAL (see train_xe).

        Resilience mirrors the XE loop: divergence sentinel on every update,
        SIGTERM (or a detected peer loss) stops the epoch at the next batch
        boundary and the pipeline drains in SCHEDULE ORDER: the saved state
        matches exactly ``batch_index`` completed steps, and the pipelined
        loop additionally decodes the seam batch at its exact pipeline
        position and persists the tokens (``seam.npz``) next to the state.
        A mid-epoch resume replays the remainder of the epoch and the seam
        tokens, so BOTH ``rl.pipelined`` modes resume bit-identically to the
        uninterrupted run (previously the pipelined resume re-decoded the
        seam batch against params one update fresher).
        """
        cfg = self.cfg
        if epochs is None:
            epochs = max(0, cfg.rl.epochs - self.rl_epochs)
        if epochs == 0:
            return None
        rl_setup = obs.span("setup", phase="rl").begin()
        tx = make_optimizer(cfg.train, self.steps_per_epoch, lr_override=cfg.rl.lr)
        if self.rl_epochs == 0:
            # XE -> RL transition: fresh optimizer at RL LR (handoff semantics)
            # device_put, not jnp.zeros: the reset step counter must reach
            # the device via an EXPLICIT transfer, and tx.init runs jitted
            # so its zero-moments materialize on device without staging
            # eager scalar constants (sanitizer gate holds the RL hot loop
            # under jax.transfer_guard("disallow"))
            self.state = self.state.replace(
                step=jax.device_put(np.zeros((), np.int32)),
                opt_state=jax.jit(tx.init)(self.state.params),
                tx=tx,
            )
            if self.mesh is not None:
                self.state = replicate(self.mesh, self.state)
        else:
            # resumed mid-RL: the restored opt_state/step already belong to the
            # RL optimizer (saved during RL) — keep the Adam moments and
            # schedule position, just re-attach the non-serialized tx. The
            # structures must match (make_optimizer differs only in LR value);
            # verify rather than assume, so a future phase-specific optimizer
            # change cannot silently misinterpret the restored moments
            fresh = jax.eval_shape(tx.init, self.state.params)
            if jax.tree.structure(fresh) != jax.tree.structure(self.state.opt_state):
                raise RuntimeError(
                    "mid-RL resume: the checkpoint's opt_state tree does not "
                    "match the RL optimizer built from this config — the "
                    "restored Adam moments would be misinterpreted. Did the "
                    "optimizer definition change between runs?"
                )
            self.state = self.state.replace(tx=tx)

        # df=None lets RewardComputer build the train-pool df itself
        df = CorpusDF.load(cfg.data.cider_df) if cfg.data.cider_df else None
        reward = RewardComputer(
            self.train_ds.vocab,
            self.train_ds.gts_pool(),
            df=df,
            cider_weight=cfg.rl.reward_cider_weight,
            bleu_weight=cfg.rl.reward_bleu4_weight,
            bleu_scale=cfg.rl.reward_bleu4_scale,
            num_threads=cfg.rl.reward_threads,
        )
        def build_scst():
            """SCST step closures + batcher for the CURRENT mesh — rebuilt
            after a degraded-mesh continuation shrinks it."""
            if cfg.train.rl_topology == "decoupled":
                # actor/learner split epoch schedule (rl/async_scst.py);
                # batch_size clamps the submesh split to batch divisors
                scst = AsyncSCSTTrainer(
                    self.model, reward, cfg.rl, mesh=self.mesh,
                    max_len=cfg.model.max_len, donate=True,
                    guard=self.guard, on_event=self.log.log,
                    comm=CommConfig.from_train(cfg.train),
                    stats=self._stats, batch_size=cfg.data.batch_size,
                )
            else:
                scst = SCSTTrainer(
                    self.model, reward, cfg.rl, mesh=self.mesh,
                    max_len=cfg.model.max_len, donate=True, guard=self.guard,
                    on_event=self.log.log,
                    comm=CommConfig.from_train(cfg.train),
                    stats=self._stats,
                )
            rl_batcher = Batcher(
                self.train_ds,
                batch_size=cfg.data.batch_size,
                max_len=cfg.model.max_len,
                mode="video",
                seed=cfg.data.shuffle_seed,
                host_shard=self.batcher.host_shard if self.use_mesh else (0, 1),
            )
            rl_batcher.salt = self.batcher.salt
            return scst, rl_batcher

        scst, rl_batcher = build_scst()
        self._rl_batcher = rl_batcher
        target = self.rl_epochs + epochs
        meter = obs.StepMeter("rl")
        profiler = StepProfiler(
            os.path.join(cfg.train.profile_dir, "rl") if cfg.train.profile_dir
            else "",
            cfg.train.profile_steps,
            log=self.log.log,
        )
        sentinel = self._make_sentinel("rl")
        last_val = None
        run = {"first_step": True}
        rl_setup.end()
        try:
            with PreemptionHandler() as pre:
                while self.rl_epochs < target:
                    try:
                        last_val = self._rl_epoch(
                            scst, rl_batcher, meter, profiler, sentinel, pre,
                            run,
                        )
                    except RollbackRequested as e:
                        self._apply_rollback("rl", e, sentinel)
                    except PeerLost as e:
                        if self.cfg.train.elastic != "degraded":
                            raise
                        self._continue_degraded("rl", e)
                        # the decode/update closures and the batcher's host
                        # share are mesh-shaped: rebuild on the shrunk mesh
                        scst, rl_batcher = build_scst()
                        self._rl_batcher = rl_batcher
                        run["first_step"] = True
                    except health_mod.HostRejoin as e:
                        if self._continue_regrown("rl", e):
                            # rebuild mesh-shaped closures on the FULL mesh
                            scst, rl_batcher = build_scst()
                            self._rl_batcher = rl_batcher
                            run["first_step"] = True
        finally:
            self._rl_batcher = None
        return last_val

    def _rl_epoch(self, scst, rl_batcher, meter, profiler, sentinel, pre,
                  run) -> float | None:
        """One RL epoch (possibly a resumed remainder)."""
        cfg = self.cfg
        log_every = cfg.train.log_every_steps
        # keyed off the global epoch so a resumed RL phase replays the same
        # per-epoch batch order as an uninterrupted run (pinned per epoch so
        # a rollback replay re-keys identically)
        rl_batcher.epoch_index = self.epoch
        skip = self._resume_rl_batch
        self._resume_rl_batch = 0
        # drain-aware seam replay: the tokens the drained pipeline decoded
        # for exactly this (epoch, batch) position — replayed so the seam
        # batch is not re-decoded against params one update fresher than
        # the uninterrupted schedule. Anything else (position mismatch,
        # strict pipeline off) falls back to the old re-decode.
        seam = None
        seam_capable = (
            cfg.rl.pipelined or cfg.train.rl_topology == "decoupled"
        )
        if skip and self._pending_seam is not None:
            cand, self._pending_seam = self._pending_seam, None
            if seam_capable and cand["epoch"] == self.epoch \
                    and cand["batch_index"] == skip:
                seam = cand
            else:
                self.log.log(
                    "seam_discarded", epoch=self.epoch, batch_index=skip,
                    seam_epoch=cand["epoch"],
                    seam_batch_index=cand["batch_index"],
                )
        # per-epoch sampling rng is FOLDED from the global epoch, not drawn
        # from a running split chain, so a resumed phase continues the stream
        # (epoch k uses fold_in(base, k) whether or not the process
        # restarted); a rollback salt re-randomizes it together with the
        # batch order
        # device_key: eager jax.random.key would stage the seed through
        # an implicit transfer once per epoch, inside the sanitized loop
        base_rng = device_key(cfg.train.seed + 1)
        if self.batcher.salt:
            base_rng = device_fold_in(base_rng, self.batcher.salt)
        ep_rng = device_fold_in(base_rng, self.epoch)
        # mid-epoch resume: advance the per-batch split chain past the
        # ``skip`` batches the checkpoint already trained on
        for _ in range(skip):
            ep_rng = jax.random.split(ep_rng)[0]
        step_counter = {"step": int(self.state.step)}
        batch_counter = {"n": skip}
        if obs.enabled():
            obs.set_context(phase="rl", epoch=self.epoch + 1)
        meter.begin_epoch()
        rewards = []
        valid_rows = []

        def on_step(m):
            rewards.append(m["reward_mean"])
            valid_rows.append(m["valid_rows"])
            step_counter["step"] += 1
            batch_counter["n"] += 1
            # record before push (see _xe_epoch): the dict mixes device
            # scalars (rl_loss, grad_norm, upd_ratio/*) with host floats
            # (reward_*, advantage_*, sample_entropy) — the recorder's
            # batched device_get handles both
            flight.record(step_counter["step"], "rl", m)
            sentinel.push(
                step_counter["step"], m["rl_loss"], m.get("nonfinite")
            )
            if obs.enabled():
                obs.set_context(step=step_counter["step"])
            if log_every and step_counter["step"] % log_every == 0:
                self.log.log(
                    "rl_step",
                    phase="rl",
                    step=step_counter["step"],
                    epoch=self.epoch + 1,
                    reward=float(m["reward_mean"]),
                    rl_loss=float(m["rl_loss"]),
                    grad_norm=float(m["grad_norm"]),
                )
                flight.flush()
            obs.maybe_snapshot(step_counter["step"])
            profiler.tick()
            meter.tick(cfg.data.batch_size, first=run["first_step"])
            run["first_step"] = False
            chaos.visit("rl.step")
            if self.health is not None:
                self.health.note_step(step_counter["step"])
            self._poll_rejoin()

        # pipelined epoch (rl.pipelined, default): host reward for batch i
        # overlaps device update i-1 + decode i+1; batches are prefetched
        # to device by a host thread. pipelined=False: strict on-policy.
        # should_stop: a SIGTERM stops consuming at the next batch boundary
        # and the pipeline drains, so state == batch_counter steps exactly
        stop = threading.Event()
        # the rl.epoch span's self time is everything the decode/reward/
        # update spans inside scst.train_epoch don't claim: input-pipeline
        # waits, rng bookkeeping, drain stalls
        # drain-aware stop: the pipelined loop decodes the seam batch at
        # its exact schedule position and captures the tokens here; the
        # preemption/peer-loss save persists them next to the state
        seam_sink: dict = {}
        with obs.span("rl.epoch"):
            try:
                self.state, _ = scst.train_epoch(
                    self.state,
                    self._rl_device_batches(rl_batcher, skip=skip,
                                            stop_event=stop),
                    ep_rng,
                    on_step=on_step,
                    pipelined=cfg.rl.pipelined,
                    should_stop=lambda: pre.requested or (
                        self.health is not None and self.health.peer_lost
                    ) or self._regrow_host is not None,
                    seam=seam,
                    seam_sink=seam_sink if seam_capable else None,
                )
            finally:
                stop.set()
            profiler.stop()
            if pre.requested:
                self._preempt_save(
                    "rl", step_counter["step"], batch_counter["n"], sentinel,
                    seam=seam_sink or None,
                )
            if self.health is not None and self.health.peer_lost:
                self._peer_loss_save(
                    "rl", step_counter["step"], batch_counter["n"], sentinel,
                    seam=seam_sink or None,
                )
            if self._regrow_host is not None:
                self._regrow_save(
                    "rl", step_counter["step"], batch_counter["n"], sentinel,
                    seam=seam_sink or None,
                )
            flight.flush()
            sentinel.flush()
        self.epoch += 1
        self.rl_epochs += 1
        n_valid = float(np.sum(valid_rows)) if valid_rows else 0.0
        self.log.log(
            "rl_epoch",
            epoch=self.epoch,
            # per-step rewards are scored on this host's rows only; weight
            # by valid rows (wrap-padded final batches have fewer) and
            # reduce exactly across processes
            reward=multihost.global_weighted_mean(
                # host floats from the reward computer — no device sync
                float(np.dot(rewards, valid_rows)) if valid_rows else 0.0,
                n_valid,
            ),
            **meter.epoch_summary(),
        )
        obs.snapshot_metrics(epoch=self.epoch)
        return self._validate_and_checkpoint(step_counter["step"])

    # ---- validation --------------------------------------------------------

    def _validate_and_checkpoint(self, step_no: int | None = None) -> float | None:
        value = None
        if self.validator is not None and (
            self.epoch % self.cfg.train.eval_every_epochs == 0
        ):
            # multi-host: validation runs on EVERY process (the sharded
            # decode is a collective program), but only process 0 writes the
            # checkpoint on the shared filesystem below
            result = self.validator.evaluate(self.state.params)
            value = result["metrics"].get("CIDEr-D")
            self.log.log("validate", epoch=self.epoch, cider_d=value)
        if jax.process_index() != 0:
            return value
        with obs.span("ckpt", kind="epoch"):
            is_best = self.ckpt.save(
                jax.device_get(self.state),
                value,
                # full config snapshot: the reference's `infos` pickle carried
                # the whole opt namespace (SURVEY.md §5 checkpoint row);
                # global_step/phase/batch_index/data_salt feed mid-epoch
                # resume ordering
                infos=self._ckpt_infos(step_no=step_no),
            )
        if is_best:
            self.log.log("new_best", epoch=self.epoch, cider_d=value)
        return value
