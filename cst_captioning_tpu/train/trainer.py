"""Experiment driver: XE phase, CST/RL phase, validation, checkpointing.

The orchestration layer of the reference's ``train.py`` (SURVEY.md §3.1-3.2,
§3.5): epoch loop -> jitted steps -> per-epoch greedy validation scored by
CIDEr-D -> best/latest checkpoints -> optional resume -> XE->RL handoff.

Device placement: with a multi-device mesh the step is the shard_map-parallel
variant and batches are placed sharded; single device uses the plain jitted
step. Host batch prep overlaps device compute via the prefetch thread.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

from cst_captioning_tpu.ckpt import CheckpointManager, load_params
from cst_captioning_tpu.config.config import EvalConfig, ExperimentConfig
from cst_captioning_tpu.data.batcher import Batcher
from cst_captioning_tpu.data.dataset import CaptionDataset
from cst_captioning_tpu.data.prefetch import prefetch_to_device
from cst_captioning_tpu.eval.evaluator import Evaluator
from cst_captioning_tpu.metrics.cider import CorpusDF
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.parallel import (
    make_sp_xe_step,
    sp_batch_shardings,
    sp_model,
)
from cst_captioning_tpu.rl import RewardComputer, SCSTTrainer
from cst_captioning_tpu.train import multihost
from cst_captioning_tpu.train.mesh import batch_sharding, make_mesh, replicate
from cst_captioning_tpu.train.schedule import make_optimizer
from cst_captioning_tpu.train.state import TrainState, create_train_state
from cst_captioning_tpu.train.steps import batch_arrays, make_parallel_xe_step, make_xe_step
from cst_captioning_tpu.utils.logging import EventLogger, StepTimer
from cst_captioning_tpu.utils.profiling import StepProfiler


# run-plumbing fields expected to differ between the original run and a
# resumed one; excluded from drift detection so the alert stays meaningful
_VOLATILE_CONFIG_FIELDS = frozenset({
    "train.resume", "train.ckpt_dir", "train.profile_dir",
    "train.profile_steps", "train.debug_nans", "train.log_every_steps",
    "train.log_every",  # pre-rename snapshots carry the old field name
    "eval.results_json",
})


def _config_drift(saved: dict, current: dict, prefix: str = "") -> list[str]:
    """Dotted paths whose values differ between two JSON-born snapshots."""
    out: list[str] = []
    for key in sorted(set(saved) | set(current)):
        path = f"{prefix}{key}"
        if path in _VOLATILE_CONFIG_FIELDS:
            continue
        a, b = saved.get(key), current.get(key)
        if isinstance(a, dict) and isinstance(b, dict):
            out.extend(_config_drift(a, b, prefix=path + "."))
        elif a != b:
            out.append(path)
    return out


class Trainer:
    def __init__(
        self,
        cfg: ExperimentConfig,
        train_ds: CaptionDataset,
        val_ds: CaptionDataset | None = None,
        log_path: str = "",
        use_mesh: bool | None = None,
    ):
        self.cfg = cfg
        self.train_ds = train_ds
        self.val_ds = val_ds
        self.model = CaptionModel(cfg.model)
        self.log = EventLogger(log_path)
        if cfg.train.debug_nans:
            # sanitizer mode (SURVEY.md §5 row 2): every jitted step re-runs
            # eagerly on NaN production and raises at the originating op
            jax.config.update("jax_debug_nans", True)

        n_dev = cfg.mesh.num_devices or len(jax.devices())
        sp = cfg.mesh.seq_devices > 1
        self.use_mesh = (n_dev > 1 or sp) if use_mesh is None else use_mesh
        self.mesh = (
            make_mesh(cfg.mesh.num_devices, seq_devices=cfg.mesh.seq_devices)
            if self.use_mesh else None
        )
        # 2-D ('data','seq') mesh: batch shards over 'data', the FRAME axis
        # over 'seq' (collective attention softmax — the long-context layout)
        self.sp = self.mesh is not None and "seq" in self.mesh.axis_names
        if self.mesh is not None:
            n_data = self.mesh.shape["data"]
            if cfg.data.batch_size % n_data:
                # unlike eval (which wrap-pads exactly, evaluator.py), padding
                # a TRAINING batch would change how rows group into optimizer
                # steps — fail early with guidance, not a device_put error
                raise ValueError(
                    f"training batch_size {cfg.data.batch_size} must be "
                    f"divisible by the mesh's {n_data}-device 'data' axis; "
                    "pick a multiple or set mesh.num_devices/seq_devices"
                )
            if self.sp and cfg.model.max_frames % self.mesh.shape["seq"]:
                raise ValueError(
                    f"model.max_frames {cfg.model.max_frames} must be "
                    f"divisible by mesh.seq_devices {self.mesh.shape['seq']}"
                )
            if self.sp and multihost.is_multiprocess():
                multihost.assert_seq_axis_within_host(self.mesh.devices)

        # multi-host: each process collates only its slice of every global
        # batch (identical global order — the shuffle is epoch-keyed);
        # put_global below assembles the slices into globally-sharded arrays
        self.batcher = Batcher(
            train_ds,
            batch_size=cfg.data.batch_size,
            max_len=cfg.model.max_len,
            mode="caption",
            seq_per_vid=cfg.data.seq_per_vid,
            seed=cfg.data.shuffle_seed,
            host_shard=multihost.host_shard() if self.use_mesh else (0, 1),
        )
        self.steps_per_epoch = self.batcher.num_batches()
        tx = make_optimizer(cfg.train, self.steps_per_epoch)
        sample = next(iter(self.batcher.epoch(shuffle=False)))
        feats, masks, labels, *_ = batch_arrays(sample)
        self.state = create_train_state(
            self.model, tx, (feats, masks, labels), seed=cfg.train.seed
        )
        if self.mesh is not None:
            self.state = replicate(self.mesh, self.state)
            if self.sp:
                # SP params are layout-identical to the plain model's, so the
                # state init above (plain model) feeds the SP step directly
                # donate=True: the step consumes self.state (rebound on every
                # call), so params + Adam moments update in place instead of
                # double-buffering — HBM headroom on the production path
                self.xe_step = make_sp_xe_step(
                    sp_model(cfg.model), self.mesh, cfg.train.label_smoothing,
                    data_axis="data", donate=True,
                )
            else:
                self.xe_step = make_parallel_xe_step(
                    self.model, self.mesh, cfg.train.label_smoothing,
                    donate=True,
                )
        else:
            self.xe_step = make_xe_step(
                self.model, cfg.train.label_smoothing, donate=True
            )

        if multihost.is_multiprocess():
            # verifiable evidence the cluster actually formed (a degraded
            # init would silently train N independent copies)
            self.log.log(
                "distributed",
                processes=jax.process_count(),
                process_index=jax.process_index(),
                devices=len(jax.devices()),
            )
        self.ckpt = CheckpointManager(cfg.train.ckpt_dir, metric="CIDEr-D")
        self.epoch = 0        # global epoch counter (batch-order key, logging)
        self.xe_epochs = 0    # per-phase progress: epochs-field budgets are
        self.rl_epochs = 0    # TOTALS, so a resumed run finishes the remainder
        if cfg.train.resume:
            self._resume()

        self.validator = (
            Evaluator(
                self.model,
                val_ds,
                EvalConfig(beam_size=1, max_len=cfg.model.max_len,
                           metrics=("CIDEr-D",)),
                batch_size=cfg.data.batch_size,
                mesh=self.mesh,
            )
            if val_ds is not None
            else None
        )

    # ---- resume / handoff --------------------------------------------------

    def _resume(self):
        # resume="auto": newest valid ckpt in this run's ckpt_dir;
        # resume=<dir>: explicit checkpoint directory (latest/best inside it)
        resume = self.cfg.train.resume
        src_dir = self.cfg.train.ckpt_dir if resume == "auto" else resume
        mgr = self.ckpt if resume == "auto" else CheckpointManager(src_dir)
        restored = mgr.restore_latest(jax.device_get(self.state))
        if restored is None:
            self.log.log("resume_not_found", dir=src_dir)
            return
        state, infos = restored
        self.state = (
            replicate(self.mesh, state) if self.mesh is not None else state
        )
        self.epoch = int(infos.get("epoch", 0))
        # old checkpoints without phase counters: assume all epochs were XE
        self.xe_epochs = int(infos.get("xe_epochs", self.epoch))
        self.rl_epochs = int(infos.get("rl_epochs", 0))
        # exact data-order resume: epoch-keyed shuffling continues where the
        # uninterrupted run would have been. The caption batcher consumes one
        # epoch index per *shuffled* (XE) epoch only — RL epochs run their own
        # video-mode batcher — so the XE count, not the global one, is the key
        self.batcher.epoch_index = self.xe_epochs
        # surface config drift between the checkpoint and this run
        saved_cfg = infos.get("config")
        if saved_cfg:
            # one json round-trip canonicalizes tuples to lists, matching the
            # JSON-born saved snapshot leaf for leaf
            drift = _config_drift(saved_cfg, json.loads(self.cfg.to_json()))
            if drift:
                self.log.log("resume_config_drift", fields=drift)
        self.log.log("resume", dir=src_dir, step=int(state.step), epoch=self.epoch)

    def load_params_from(self, ckpt_dir: str, name: str = "best"):
        """XE -> RL handoff: params only, fresh optimizer (SURVEY.md §5)."""
        params = load_params(ckpt_dir, name, jax.device_get(self.state.params))
        self.state = self.state.replace(params=params)
        if self.mesh is not None:
            self.state = replicate(self.mesh, self.state)
        self.log.log("handoff", source=f"{ckpt_dir}/{name}")

    # ---- phases ------------------------------------------------------------

    def _batch_sharding(self):
        """device_put target for the XE batch tuple: a single axis-0 sharding
        (1-D mesh; a tree prefix for every element), or the per-leaf SP tuple
        (frames over 'seq', batch over 'data')."""
        if self.mesh is None:
            return None
        if self.sp:
            return sp_batch_shardings(self.mesh, self.cfg.model)
        return batch_sharding(self.mesh)

    def _device_batches(self, batcher: Batcher):
        shardings = self._batch_sharding()

        def transform(b):
            if shardings is None:
                # valid rides along so wrap-padding rows get zero weight
                return batch_arrays(b) + (
                    jax.numpy.asarray(np.asarray(b.valid, np.float32)),
                )
            # keep the Batch's numpy arrays as-is: put_global transfers them
            # host->device exactly once, straight into the target sharding
            arrays = (
                b.feats, b.feat_masks, b.labels, b.mask, b.weights,
                np.asarray(b.valid, np.float32),
            )
            return multihost.put_global(shardings, arrays)

        yield from prefetch_to_device(
            batcher.epoch(),
            size=self.cfg.data.prefetch,
            transform=transform,
            place=shardings is None,
        )

    def _rl_device_batches(self, batcher: Batcher):
        """Prefetched RL batches: arrays staged to device (sharded when a mesh
        is in play), video ids + valid mask staying host-side (this process's
        rows) for the reward."""
        sharding = self._batch_sharding()
        if sharding is not None and self.sp:
            sharding = (sharding[0], sharding[1])  # (feats, masks) only

        def transform(b):
            if sharding is not None:
                # numpy straight into the target sharding (single transfer)
                feats, masks = multihost.put_global(
                    sharding, (b.feats, b.feat_masks)
                )
            else:
                feats, masks = jax.device_put((b.feats, b.feat_masks))
            return (feats, masks, b.video_ids, b.valid)

        yield from prefetch_to_device(
            batcher.epoch(shuffle=True),
            size=self.cfg.data.prefetch,
            transform=transform,
            place=False,
        )

    def train_xe(self, epochs: int | None = None) -> float | None:
        """Cross-entropy (XE/WXE) phase; returns last validation CIDEr-D.

        ``epochs=None`` treats ``cfg.train.epochs`` as the phase TOTAL: a
        resumed run trains only the remainder. An explicit ``epochs`` runs
        exactly that many more.
        """
        cfg = self.cfg
        if epochs is None:
            epochs = max(0, cfg.train.epochs - self.xe_epochs)
        timer = StepTimer()
        profiler = StepProfiler(
            os.path.join(cfg.train.profile_dir, "xe") if cfg.train.profile_dir
            else "",
            cfg.train.profile_steps,
        )
        last_val = None
        weighted = cfg.train.loss == "wxe"
        first_step = True
        log_every = cfg.train.log_every_steps
        # host-side step counter: reading int(self.state.step) in the loop
        # would block on the just-dispatched update every step (graftlint
        # GL001 — the RL phase's on_step counter already avoided this)
        step_no = int(self.state.step)
        for _ in range(epochs):
            timer.reset()
            losses = []
            for arrays in self._device_batches(self.batcher):
                feats, masks, labels, mask, weights, valid = arrays
                # invalid rows get zero weight -> excluded from loss + norm
                weights = valid if not weighted else weights * valid
                self.state, m = self.xe_step(
                    self.state, feats, masks, labels, mask, weights
                )
                # keep the device scalar: float() here would sync per step
                # (graftlint GL001); the epoch summary reads them all back
                # in one device_get
                losses.append(m["loss"])
                step_no += 1
                if log_every and step_no % log_every == 0:
                    # per-step event: a mid-epoch divergence (NaN, grad blowup)
                    # is locatable from the log alone (SURVEY.md §5); the
                    # float() syncs are gated — amortized over log_every steps
                    self.log.log(
                        "xe_step",
                        phase="xe",
                        step=step_no,
                        epoch=self.epoch + 1,
                        loss=float(m["loss"]),
                        grad_norm=float(m["grad_norm"]),
                    )
                profiler.tick()
                if first_step:
                    # exclude jit-compile time from the throughput meter
                    first_step = False
                    timer.reset()
                else:
                    timer.tick(cfg.data.batch_size)
            profiler.stop()
            self.epoch += 1
            self.xe_epochs += 1
            self.log.log(
                "xe_epoch",
                epoch=self.epoch,
                # ONE readback for the whole epoch's loss scalars
                loss=float(np.mean(jax.device_get(losses))),  # graftlint: disable=GL001 (once per epoch)
                clips_per_sec=timer.clips_per_sec,
            )
            last_val = self._validate_and_checkpoint()
        return last_val

    def train_rl(self, epochs: int | None = None) -> float | None:
        """CST/RL phase (SCST or consensus-CST per cfg.rl).

        ``epochs=None``: ``cfg.rl.epochs`` is the phase TOTAL (see train_xe).
        """
        cfg = self.cfg
        if epochs is None:
            epochs = max(0, cfg.rl.epochs - self.rl_epochs)
        if epochs == 0:
            return None
        tx = make_optimizer(cfg.train, self.steps_per_epoch, lr_override=cfg.rl.lr)
        if self.rl_epochs == 0:
            # XE -> RL transition: fresh optimizer at RL LR (handoff semantics)
            self.state = self.state.replace(
                step=jax.numpy.zeros((), jax.numpy.int32), opt_state=tx.init(
                    jax.device_get(self.state.params)
                ), tx=tx,
            )
            if self.mesh is not None:
                self.state = replicate(self.mesh, self.state)
        else:
            # resumed mid-RL: the restored opt_state/step already belong to the
            # RL optimizer (saved during RL) — keep the Adam moments and
            # schedule position, just re-attach the non-serialized tx. The
            # structures must match (make_optimizer differs only in LR value);
            # verify rather than assume, so a future phase-specific optimizer
            # change cannot silently misinterpret the restored moments
            fresh = jax.eval_shape(tx.init, self.state.params)
            if jax.tree.structure(fresh) != jax.tree.structure(self.state.opt_state):
                raise RuntimeError(
                    "mid-RL resume: the checkpoint's opt_state tree does not "
                    "match the RL optimizer built from this config — the "
                    "restored Adam moments would be misinterpreted. Did the "
                    "optimizer definition change between runs?"
                )
            self.state = self.state.replace(tx=tx)

        # df=None lets RewardComputer build the train-pool df itself
        df = CorpusDF.load(cfg.data.cider_df) if cfg.data.cider_df else None
        reward = RewardComputer(
            self.train_ds.vocab,
            self.train_ds.gts_pool(),
            df=df,
            cider_weight=cfg.rl.reward_cider_weight,
            bleu_weight=cfg.rl.reward_bleu4_weight,
            bleu_scale=cfg.rl.reward_bleu4_scale,
            num_threads=cfg.rl.reward_threads,
        )
        scst = SCSTTrainer(
            self.model, reward, cfg.rl, mesh=self.mesh,
            max_len=cfg.model.max_len, donate=True,
        )
        rl_batcher = Batcher(
            self.train_ds,
            batch_size=cfg.data.batch_size,
            max_len=cfg.model.max_len,
            mode="video",
            seed=cfg.data.shuffle_seed,
            host_shard=multihost.host_shard() if self.use_mesh else (0, 1),
        )
        # keyed off the global epoch so a resumed RL phase replays the same
        # per-epoch batch order as an uninterrupted run
        rl_batcher.epoch_index = self.epoch
        # per-epoch sampling rng is FOLDED from the global epoch, not drawn
        # from a running split chain, so a resumed phase continues the stream
        # (epoch k uses fold_in(base, k) whether or not the process restarted)
        base_rng = jax.random.key(cfg.train.seed + 1)
        timer = StepTimer()
        profiler = StepProfiler(
            os.path.join(cfg.train.profile_dir, "rl") if cfg.train.profile_dir
            else "",
            cfg.train.profile_steps,
        )
        last_val = None
        log_every = cfg.train.log_every_steps
        step_counter = {"step": int(self.state.step)}
        for _ in range(epochs):
            timer.reset()
            rewards = []
            valid_rows = []

            def on_step(m):
                rewards.append(m["reward_mean"])
                valid_rows.append(m["valid_rows"])
                step_counter["step"] += 1
                if log_every and step_counter["step"] % log_every == 0:
                    self.log.log(
                        "rl_step",
                        phase="rl",
                        step=step_counter["step"],
                        epoch=self.epoch + 1,
                        reward=float(m["reward_mean"]),
                        rl_loss=float(m["rl_loss"]),
                        grad_norm=float(m["grad_norm"]),
                    )
                profiler.tick()
                if len(rewards) == 1:
                    timer.reset()  # exclude jit-compile time of the first step
                else:
                    timer.tick(cfg.data.batch_size)

            # pipelined epoch (rl.pipelined, default): host reward for batch i
            # overlaps device update i-1 + decode i+1; batches are prefetched
            # to device by a host thread. pipelined=False: strict on-policy
            ep_rng = jax.random.fold_in(base_rng, self.epoch)
            self.state, _ = scst.train_epoch(
                self.state,
                self._rl_device_batches(rl_batcher),
                ep_rng,
                on_step=on_step,
                pipelined=cfg.rl.pipelined,
            )
            profiler.stop()
            self.epoch += 1
            self.rl_epochs += 1
            self.log.log(
                "rl_epoch",
                epoch=self.epoch,
                # per-step rewards are scored on this host's rows only; weight
                # by valid rows (wrap-padded final batches have fewer) and
                # reduce exactly across processes
                reward=multihost.global_weighted_mean(
                    # host floats from the reward computer — no device sync
                    float(np.dot(rewards, valid_rows)), float(np.sum(valid_rows))  # graftlint: disable=GL001 (once per epoch, host values)
                ),
                clips_per_sec=timer.clips_per_sec,
            )
            last_val = self._validate_and_checkpoint()
        return last_val

    # ---- validation --------------------------------------------------------

    def _validate_and_checkpoint(self) -> float | None:
        value = None
        if self.validator is not None and (
            self.epoch % self.cfg.train.eval_every_epochs == 0
        ):
            # multi-host: validation runs on EVERY process (the sharded
            # decode is a collective program), but only process 0 writes the
            # checkpoint on the shared filesystem below
            result = self.validator.evaluate(self.state.params)
            value = result["metrics"].get("CIDEr-D")
            self.log.log("validate", epoch=self.epoch, cider_d=value)
        if jax.process_index() != 0:
            return value
        is_best = self.ckpt.save(
            jax.device_get(self.state),
            value,
            # full config snapshot: the reference's `infos` pickle carried the
            # whole opt namespace (SURVEY.md §5 checkpoint row)
            infos={
                "epoch": self.epoch,
                "xe_epochs": self.xe_epochs,
                "rl_epochs": self.rl_epochs,
                "config": self.cfg.to_dict(),
            },
        )
        if is_best:
            self.log.log("new_best", epoch=self.epoch, cider_d=value)
        return value
