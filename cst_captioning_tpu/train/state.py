"""TrainState: params + optimizer state + step + RNG, one pytree.

The reference scatters this across the torch module, the optimizer object and
an ``infos`` pickle (SURVEY.md §3.5); here it is a single flax.struct pytree
so the whole training state shards/replicates/checkpoints as one unit.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax


def device_key(seed: int) -> jax.Array:
    """``jax.random.key`` with the seed compiled in as a static constant.

    Eager ``jax.random.key(int)`` stages the seed through an implicit
    host->device transfer (vetoed by the sanitizer gate's
    ``jax.transfer_guard("disallow")``); jitted with a static seed, the key
    materializes on device with no runtime transfer at all — and the jit
    cache makes per-epoch re-derivation free."""
    return jax.jit(jax.random.key, static_argnums=0)(seed)


def device_fold_in(key: jax.Array, n) -> jax.Array:
    """``jax.random.fold_in`` with the folded integer compiled in static.

    Eager ``fold_in(key, python_int)`` stages the int through an implicit
    host->device transfer on every call — once per epoch inside the
    sanitized RL loop; static-jitted, the constant lives in the (cached)
    executable. Bit-identical to the eager spelling."""
    return jax.jit(jax.random.fold_in, static_argnums=1)(key, int(n))


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray                 # scalar int32
    params: Any
    opt_state: Any
    rng: jax.Array                    # base RNG key (folded per step/device)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    apply_fn: Callable = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt_state,
        )


def create_train_state(
    model,
    tx: optax.GradientTransformation,
    sample_batch: tuple,
    seed: int = 0,
) -> TrainState:
    """Initialize params from a sample (feats, masks, labels) batch."""
    feats, masks, labels = sample_batch
    rng = device_key(seed)
    init_rng, state_rng = jax.random.split(rng)
    params = model.init(init_rng, feats, masks, labels)
    return TrainState(
        # device_put, not jnp.zeros: eager creation of the step counter is
        # a host->device transfer, and the sanitizer gate
        # (jax.transfer_guard("disallow")) holds setup to EXPLICIT ones
        step=jax.device_put(np.zeros((), np.int32)),
        params=params,
        opt_state=tx.init(params),
        rng=state_rng,
        tx=tx,
        apply_fn=model.apply,
    )
