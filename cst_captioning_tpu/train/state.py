"""TrainState: params + optimizer state + step + RNG, one pytree.

The reference scatters this across the torch module, the optimizer object and
an ``infos`` pickle (SURVEY.md §3.5); here it is a single flax.struct pytree
so the whole training state shards/replicates/checkpoints as one unit.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray                 # scalar int32
    params: Any
    opt_state: Any
    rng: jax.Array                    # base RNG key (folded per step/device)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    apply_fn: Callable = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt_state,
        )


def create_train_state(
    model,
    tx: optax.GradientTransformation,
    sample_batch: tuple,
    seed: int = 0,
) -> TrainState:
    """Initialize params from a sample (feats, masks, labels) batch."""
    feats, masks, labels = sample_batch
    rng = jax.random.key(seed)
    init_rng, state_rng = jax.random.split(rng)
    params = model.init(init_rng, feats, masks, labels)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        rng=state_rng,
        tx=tx,
        apply_fn=model.apply,
    )
