"""Multi-host distributed support: DP/SP past one host, over ICI + DCN.

The reference scaled with single-node ``torch.nn.DataParallel`` (NCCL
underneath — SURVEY.md §2 parallelism inventory, §5 dist-comm row). This
module is the multi-HOST extension the reference never had: each process
(host) runs the same program, ``jax.distributed.initialize`` forms the
global device set, and the jitted shard_map steps are IDENTICAL to the
single-host ones — XLA routes the gradient psums over ICI within a host and
DCN across hosts, exactly the mesh-axis layering SURVEY.md §5 reserved.

The host-side contract (the part XLA cannot do for us):

- **Input**: every process feeds only its own rows.
  :class:`~cst_captioning_tpu.data.batcher.Batcher` with
  ``host_shard=(process_index, process_count)`` deterministically slices the
  same global batch order (the shuffle is keyed by (seed, epoch), so all
  hosts agree without communicating); :func:`put_global` assembles the
  per-process rows into one globally-sharded array.
- **Output**: device results that the host must read (decoded tokens for
  the RL reward or eval) come back via :func:`to_host_local` (this host's
  rows only — the per-host reward path) or :func:`allgather_to_host`
  (replicated everywhere — eval needs every caption).

Single-process behavior is the identity: every helper degrades to the plain
device_put / np.asarray path, so the Trainer wiring is exercised by the
regular test suite and the 2-process parity test
(tests/test_multihost.py) pins multi == single numerically.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cst_captioning_tpu.compat import distributed_is_initialized
# DCN-stall probe (resilience/health.py): every cross-host barrier/broadcast
# below runs inside collective_span — a dcn.collective span + histogram, a
# structured dcn_stall event past the threshold, and a piggybacked liveness
# refresh on the active HealthMonitor (a completed collective proves every
# peer was alive). Single-process paths return before the span.
from cst_captioning_tpu.resilience.health import collective_span

# NOTE: jax.experimental.multihost_utils must NOT be imported at module
# level: importing it initializes the XLA backend, after which a later
# jax.distributed.initialize silently degrades to a single-process cluster
# (observed empirically: procs=1, XLA_FLAGS ignored). It is imported lazily
# inside the helpers, all of which run long after initialization.


def _looks_multiworker() -> bool:
    """True only for env markers that UNAMBIGUOUSLY mean this process is one
    worker of a multi-worker accelerator job (multi-host TPU pods).

    ``TPU_WORKER_HOSTNAMES`` counts: single-worker setups set it to one host
    (observed: 'localhost'), where auto-initialize would demand a
    coordinator and fail. Scheduler vars like SLURM_NTASKS /
    OMPI_COMM_WORLD_SIZE are deliberately NOT hints: they are also set for
    single-process runs inside an allocation (tasks reserved for dataloaders
    etc.) — SLURM/MPI users pass the explicit JAX_* env vars instead.
    """
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hosts.split(",") if h.strip()]) > 1:
        return True
    return bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """``jax.distributed.initialize`` wrapper.

    With no arguments, initializes only when the standard env vars are set
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``,
    or a TPU-pod environment where JAX auto-detects everything); a plain
    single-host run is untouched. Safe to call twice (second call no-ops).
    """
    # NOTE: must not touch jax.process_count()/jax.devices() here — any
    # backend-initializing call before jax.distributed.initialize is an error
    if distributed_is_initialized():
        return
    if os.environ.get("JAX_PLATFORMS"):
        # pin the platform list via config BEFORE distributed init: with a
        # registered out-of-tree PJRT plugin, the env var alone is not
        # honored by the distributed handshake and init silently degrades to
        # a single-process cluster (observed: procs=1 and XLA_FLAGS ignored
        # unless this config is set first)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_n = os.environ.get("JAX_NUM_PROCESSES")
    env_i = os.environ.get("JAX_PROCESS_ID")
    if num_processes is None and env_n is not None:
        num_processes = int(env_n)
    if process_id is None and env_i is not None:
        process_id = int(env_i)
    if coordinator_address is None and num_processes is None:
        # no explicit cluster spec: hand off to jax's auto-detection ONLY in
        # unambiguously multi-worker environments (a single-host run must
        # not risk a coordinator connect attempt). A failure here must
        # PROPAGATE: degrading one worker of a real pod to an independent
        # single-host run would corrupt the shared log/checkpoint paths
        if _looks_multiworker():
            jax.distributed.initialize()
            return
        # scheduler says multiple tasks but no JAX_* cluster spec: each rank
        # would train independently and race the shared checkpoint dir —
        # make the misconfiguration loud (we deliberately don't auto-init
        # from these vars; see _looks_multiworker)
        for var in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE"):
            val = os.environ.get(var, "")
            if val.isdigit() and int(val) > 1:
                import logging

                logging.getLogger(__name__).warning(
                    "%s=%s but no JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/"
                    "JAX_PROCESS_ID set: every rank will run SINGLE-HOST on "
                    "the full dataset and race shared output paths. Pass the "
                    "JAX_* env vars to form one cluster.", var, val,
                )
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def assert_seq_axis_within_host(device_grid) -> None:
    """Reject a 2-D ``('data','seq')`` device grid whose seq rows span
    processes.

    Host-sharded batch feeding partitions the 'data' axis by process; a seq
    row spanning hosts would psum frame shards of DIFFERENT videos — silent
    divergence (reproduced on a real 2-process cluster). Checks the ACTUAL
    device placement, not a local-count proxy: device-id order need not be
    process-contiguous on every topology.
    """
    for row in device_grid:
        procs = {d.process_index for d in row}
        if len(procs) > 1:
            raise ValueError(
                f"the mesh's 'seq' axis spans processes ({sorted(procs)}); "
                "pick mesh.seq_devices so every seq row stays on one host "
                "(host-sharded feeding partitions 'data' by process)"
            )


def host_shard() -> tuple[int, int]:
    """(process_index, process_count) — the Batcher ``host_shard`` argument."""
    return jax.process_index(), jax.process_count()


def put_global(shardings, local_tree):
    """Per-process rows -> globally sharded arrays.

    ``shardings`` is a NamedSharding pytree (a tree prefix of
    ``local_tree``); each process passes ONLY its own rows and the result is
    the global array every jitted step sees. Single-process this is exactly
    ``jax.device_put``.
    """
    if not is_multiprocess():
        return jax.device_put(local_tree, shardings)
    return _map_prefix(
        lambda s, x: jax.make_array_from_process_local_data(s, np.asarray(x)),
        shardings, local_tree,
    )


def put_full_global(shardings, full_tree):
    """Every-process-identical host arrays -> globally sharded arrays.

    The eval path: each process iterates the SAME (unsharded) batches, so
    the local data already has the global shape; passing ``global_shape``
    tells jax the input is fully replicated and only this process's shards
    should be extracted. Single-process this is exactly ``jax.device_put``.
    """
    if not is_multiprocess():
        return jax.device_put(full_tree, shardings)

    def put(s, x):
        # typed PRNG keys (TrainState.rng) can't pass through the raw-array
        # assembly; round-trip via their uint32 key data
        if hasattr(x, "dtype") and jax.dtypes.issubdtype(
            x.dtype, jax.dtypes.prng_key
        ):
            # explicit readback (not np.asarray): this is a deliberate,
            # once-per-restore host staging hop, and GL013 holds the hot
            # paths to zero implicit device→host conversions
            data = jax.device_get(jax.random.key_data(x))
            g = jax.make_array_from_process_local_data(
                s, data, global_shape=data.shape
            )
            return jax.random.wrap_key_data(g)
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            s, x, global_shape=x.shape
        )

    return _map_prefix(put, shardings, full_tree)


def _map_prefix(fn, shardings, tree):
    """Apply ``fn(sharding, leaf)`` with device_put's tree-prefix broadcast:
    a single sharding applies to every leaf below it."""

    def rec(s, x):
        if isinstance(s, jax.sharding.Sharding):
            return jax.tree.map(lambda leaf: fn(s, leaf), x)
        if isinstance(x, dict):
            return {k: rec(s[k], x[k]) for k in x}
        return type(x)(rec(si, xi) for si, xi in zip(s, x))

    return rec(shardings, tree)


def to_host_local(arr, mesh: Mesh, spec: P) -> np.ndarray:
    """Sharded global array -> THIS process's rows as numpy (per-host reward
    path). Single-process: plain ``np.asarray``."""
    if not is_multiprocess():
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    local = multihost_utils.global_array_to_host_local_array(arr, mesh, spec)
    return np.asarray(local)


def from_host_local(arr, mesh: Mesh, spec: P):
    """THIS process's rows -> sharded global array (advantage upload).

    Single-process: an explicit sharded ``device_put`` — handing the jitted
    update a single-device array instead would make XLA re-scatter it
    device-to-device at EVERY dispatch (an implicit per-batch transfer the
    sanitizer gate vetoes)."""
    if not is_multiprocess():
        return jax.device_put(arr, jax.sharding.NamedSharding(mesh, spec))
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        np.asarray(arr), mesh, spec
    )


def allgather_to_host(arr) -> np.ndarray:
    """Sharded global array -> full array on EVERY process (eval gather).
    Single-process: plain ``np.asarray``."""
    if not is_multiprocess():
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    with collective_span("allgather_to_host"):
        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def global_scalar_mean(x: float) -> float:
    """Mean of a host-side scalar across processes (one tiny collective) —
    for epoch-level stats whose per-step values are per-host (the RL reward).
    Single-process: the identity."""
    if not is_multiprocess():
        return float(x)
    from jax.experimental import multihost_utils

    with collective_span("global_scalar_mean"):
        return float(
            np.mean(
                multihost_utils.process_allgather(np.asarray(x, np.float64))
            )
        )


def allgather_pyobj(obj) -> list:
    """One JSON-serializable host object per process -> every process gets
    ``[obj_0, ..., obj_{P-1}]`` in process order. Two tiny collectives (byte
    lengths, then max-padded utf-8 bytes) regardless of payload structure —
    the host-sharded eval's once-per-split caption merge. Single-process:
    ``[obj]``."""
    if not is_multiprocess():
        return [obj]
    from jax.experimental import multihost_utils

    with collective_span("allgather_pyobj"):
        data = np.frombuffer(
            json.dumps(obj, default=float).encode("utf-8"), dtype=np.uint8
        )
        lengths = np.asarray(
            multihost_utils.process_allgather(np.asarray(data.size, np.int64))
        ).reshape(-1)
        padded = np.zeros((int(lengths.max()),), np.uint8)
        padded[: data.size] = data
        rows = np.asarray(multihost_utils.process_allgather(padded))
        return [
            json.loads(rows[i, : int(lengths[i])].tobytes().decode("utf-8"))
            for i in range(rows.shape[0])
        ]


def broadcast_pyobj(obj):
    """Process 0's JSON-serializable object -> every process (the sharded
    eval's metric fan-out: one process scores, the rest receive). Non-zero
    processes' ``obj`` is ignored. Single-process: the object itself."""
    if not is_multiprocess():
        return obj
    return allgather_pyobj(obj if jax.process_index() == 0 else None)[0]


def global_weighted_mean(value_sum: float, weight: float) -> float:
    """``sum(value_sum)/sum(weight)`` across processes (one tiny collective):
    the exact cross-host mean when hosts contribute unequal row counts (e.g.
    wrap-padded final RL batches). Single-process: the local ratio.
    A zero total weight returns 0.0 (fractional weights stay undistorted)."""
    if not is_multiprocess():
        total_v, total_w = float(value_sum), float(weight)
    else:
        from jax.experimental import multihost_utils

        with collective_span("global_weighted_mean"):
            pair = multihost_utils.process_allgather(
                np.asarray([value_sum, weight], np.float64)
            )
        total = np.sum(np.asarray(pair).reshape(-1, 2), axis=0)
        total_v, total_w = float(total[0]), float(total[1])
    return total_v / total_w if total_w > 0.0 else 0.0
