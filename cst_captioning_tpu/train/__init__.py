"""Training layer: TrainState, jitted steps, device-mesh data parallelism.

Rebuilds the reference's ``train.py`` loop machinery (SURVEY.md §2 row 7) the
TPU way: the whole optimization step — forward, loss, backward, grad clip,
allreduce, param update — is ONE jitted XLA program per phase, sharded over a
``jax.sharding.Mesh`` with explicit ``psum`` collectives riding ICI
(replacing ``torch.nn.DataParallel``/NCCL, SURVEY.md §2 parallelism table).
"""

from cst_captioning_tpu.train.state import TrainState, create_train_state
from cst_captioning_tpu.train.schedule import make_lr_schedule, make_optimizer
from cst_captioning_tpu.train.mesh import (
    make_mesh,
    shard_batch,
    replicate,
    batch_sharding,
)
from cst_captioning_tpu.train.steps import make_xe_step, make_parallel_xe_step

__all__ = [
    "TrainState",
    "create_train_state",
    "make_lr_schedule",
    "make_optimizer",
    "make_mesh",
    "shard_batch",
    "replicate",
    "batch_sharding",
    "make_xe_step",
    "make_parallel_xe_step",
]
