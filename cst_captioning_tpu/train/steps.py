"""Jitted XE train steps: single-device and mesh-parallel (shard_map).

The whole reference inner loop — forward, masked (weighted) XE, backward,
global-norm clip, allreduce, Adam update (SURVEY.md §3.1) — compiles to one
XLA program. Data parallelism is explicit shard_map over ``Mesh('data')``:

- the batch arrives sharded on axis 0 (``shard_batch``), params replicated,
- each device computes grads of its *local loss numerator* (sum of per-token
  losses) plus its local token count,
- one ``psum`` over 'data' reduces both; grads divide by the GLOBAL token
  count, so the parallel step is bit-comparable to the single-device step on
  the concatenated batch (asserted by the 8-fake-device test, SURVEY.md §4
  item 4) — not just approximately data-parallel,
- the update then runs identically on every device, keeping state replicated
  without a broadcast.

RNG: dropout key = fold_in(fold_in(state.rng, step), device_index) — distinct
per step and per shard, reproducible under resharding.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from cst_captioning_tpu.losses import masked_cross_entropy
from cst_captioning_tpu.resilience.guard import guarded_apply_gradients
from cst_captioning_tpu.train.state import TrainState


def _update_ratios(old_params, new_params) -> dict:
    """Per-family relative update magnitude, computed on device.

    For each top-level parameter family ``fam`` (the module groups under
    ``params``): ``upd_ratio/<fam> = ||new - old|| / max(||old||, eps)``,
    plus the all-params ``upd_ratio/global``. The classic LR-health signal:
    a healthy Adam step sits around 1e-3; a family pinned at ~0 is frozen,
    one at ~1 is being rewritten every step. Flight-recorder food — only
    traced when a step factory is built with ``stats=True``."""
    op = old_params.get("params", old_params)
    np_ = new_params.get("params", new_params)

    def ratio(o, n):
        delta = optax.global_norm(jax.tree.map(lambda a, b: b - a, o, n))
        return delta / jnp.maximum(optax.global_norm(o), 1e-12)

    out = {f"upd_ratio/{fam}": ratio(op[fam], np_[fam]) for fam in op}
    out["upd_ratio/global"] = ratio(op, np_)
    return out


def _apply(state, grads, loss, gnorm, guard: bool, key: str = "loss",
           stats: bool = False):
    """Optionally-guarded update; metrics grow a ``nonfinite`` flag when
    guarded (see resilience/guard.py — bit-identical on finite steps).
    ``key`` names the loss metric ("loss" for XE steps, "rl_loss" for the
    REINFORCE updates). ``stats=True`` (flight recorder on) additionally
    returns the per-family update ratios (:func:`_update_ratios`) — extra
    metric outputs only; the parameter math is untouched, and the default
    ``stats=False`` program is literally the pre-stats one."""
    old_params = state.params if stats else None
    if not guard:
        new_state = state.apply_gradients(grads)
        metrics = {key: loss, "grad_norm": gnorm}
    else:
        new_state, nonfinite = guarded_apply_gradients(
            state, grads, loss, gnorm
        )
        metrics = {key: loss, "grad_norm": gnorm, "nonfinite": nonfinite}
    if stats:
        metrics.update(_update_ratios(old_params, new_state.params))
    return new_state, metrics


def _local_loss_sums(model, params, feats, masks, labels, mask, weights,
                     dropout_rng, label_smoothing):
    """(numerator, denominator) of the masked XE on this shard."""
    logits = model.apply(
        params, feats, masks, labels, train=True, rngs={"dropout": dropout_rng}
    )
    w_mask = mask * weights[:, None]
    den = jnp.sum(w_mask)
    # masked_cross_entropy normalizes internally; recover the sum form so the
    # global normalization can happen after the cross-device reduce
    num = masked_cross_entropy(
        logits, labels, mask, weights=weights, label_smoothing=label_smoothing
    ) * den
    return num, den


def make_xe_step(model, label_smoothing: float = 0.0, donate: bool = False,
                 guard: bool = False, comm=None, stats: bool = False):
    """Single-device jitted step: (state, batch arrays) -> (state, metrics).

    ``donate=True`` donates the input ``state`` buffers to the output state
    (params + Adam moments update in place instead of double-buffering —
    free HBM headroom on the production path). The caller must then treat
    the passed-in state as consumed: rebind, never reuse. Off by default so
    exactness tests can replay one state through several step variants.

    ``guard=True`` suppresses non-finite updates on device and adds a
    ``nonfinite`` metric (resilience/guard.py); finite steps are bit-equal
    to the unguarded program.

    ``comm`` (parallel/comms.CommConfig) is accepted for factory-signature
    symmetry and ignored: the single-device step has no collectives.

    ``stats=True`` adds the flight recorder's per-family update-ratio
    metrics (:func:`_update_ratios`) — pure extra outputs, bit-identical
    params; note the old params stay live past the update, so the param
    buffers can't be donation-reused on stats builds.
    """
    del comm  # no cross-device reduction on this path
    # lazy for the same cycle reason as reduce_tree below
    from cst_captioning_tpu.parallel.compile import CompilePlan, compile_fn

    def step(state: TrainState, feats, masks, labels, mask, weights):
        drng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(p):
            num, den = _local_loss_sums(
                model, p, feats, masks, labels, mask, weights, drng, label_smoothing
            )
            return num / jnp.maximum(den, 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        gnorm = optax.global_norm(grads)
        return _apply(state, grads, loss, gnorm, guard, stats=stats)

    return compile_fn(
        step, CompilePlan(donate_argnums=(0,) if donate else ())
    )


def make_parallel_xe_step(model, mesh: Mesh, label_smoothing: float = 0.0,
                          axis: str = "data", donate: bool = False,
                          guard: bool = False, comm=None,
                          stats: bool = False):
    """shard_map data-parallel step, exact-equivalent to the fused batch.
    ``donate`` / ``guard`` / ``stats``: see :func:`make_xe_step`. The stats
    ratios are computed from psum'd (device-invariant) grads, so they stay
    replicated like the state.

    ``comm`` (parallel/comms.CommConfig) selects the grad-allreduce spelling:
    None keeps the original per-leaf psum; otherwise the reduction buckets
    (and optionally bf16-compresses) per the config. f32 configs are
    bit-identical to ``comm=None`` — psum is elementwise (tests/test_comms).
    """
    # imported lazily: parallel/__init__ -> seq_parallel imports this module,
    # so a module-level import here would close the cycle mid-initialization
    from cst_captioning_tpu.parallel.comms import reduce_tree
    from cst_captioning_tpu.parallel.compile import CompilePlan, compile_fn

    def device_step(state: TrainState, feats, masks, labels, mask, weights):
        drng = jax.random.fold_in(
            jax.random.fold_in(state.rng, state.step), jax.lax.axis_index(axis)
        )

        def local_num(p):
            num, den = _local_loss_sums(
                model, p, feats, masks, labels, mask, weights, drng, label_smoothing
            )
            return num, den

        (num, den), grads_num = jax.value_and_grad(local_num, has_aux=True)(
            state.params
        )
        den_total = jax.lax.psum(den, axis)
        num_total = jax.lax.psum(num, axis)
        grads = jax.tree.map(
            lambda g: g / jnp.maximum(den_total, 1.0),
            reduce_tree(grads_num, axis, comm),
        )
        loss = num_total / jnp.maximum(den_total, 1.0)
        gnorm = optax.global_norm(grads)
        # grads/loss are psum'd (device-invariant), so the guard's where()
        # selects identically on every shard — state stays replicated
        return _apply(state, grads, loss, gnorm, guard, stats=stats)

    return compile_fn(device_step, CompilePlan(
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(axis), P(axis), P(axis)),
        out_specs=(P(), P()),
        donate_argnums=(0,) if donate else (),
    ))


def batch_arrays(batch) -> tuple[Any, ...]:
    """Batch -> (feats, masks, labels, mask, weights) jnp pytrees."""
    return (
        {k: jnp.asarray(v) for k, v in batch.feats.items()},
        {k: jnp.asarray(v) for k, v in batch.feat_masks.items()},
        jnp.asarray(batch.labels),
        jnp.asarray(batch.mask),
        jnp.asarray(batch.weights),
    )
