"""Device mesh + sharding helpers (the NCCL/DataParallel replacement).

One ``Mesh`` axis ``'data'`` for v1 (the reference is pure data-parallel,
SURVEY.md §2 parallelism table). Axis naming leaves room for a future
``('dcn', 'data')`` multi-host hierarchy without changing call sites.

Batches shard along axis 0 across ``'data'``; params/state replicate.
``shard_batch``/``replicate`` place host arrays accordingly so jitted steps
see committed, correctly-laid-out inputs (no implicit transfers inside the
step).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_devices: int = 0, axis: str = "data",
              seq_devices: int = 1, seq_axis: str = "seq") -> Mesh:
    """1-D ``(data,)`` mesh, or 2-D ``(data, seq)`` when ``seq_devices > 1``
    (the long-context layout: batch over 'data', frames over 'seq')."""
    devices = jax.devices()
    if num_devices:
        devices = devices[:num_devices]
    if seq_devices > 1:
        n = len(devices)
        if n % seq_devices:
            raise ValueError(
                f"seq_devices {seq_devices} must divide the {n} mesh devices"
            )
        grid = np.asarray(devices).reshape(n // seq_devices, seq_devices)
        return Mesh(grid, (axis, seq_axis))
    return Mesh(np.asarray(devices), (axis,))


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Axis-0 sharding for batch pytrees."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, axis: str = "data"):
    """Place a host batch pytree with axis 0 split across the mesh."""
    return jax.device_put(batch, batch_sharding(mesh, axis))


def replicate(mesh: Mesh, tree):
    """Replicate a pytree (params / train state) on every mesh device.

    Multi-host: every process already holds an identical host copy (same
    init seed / same restored checkpoint), so the global replicated arrays
    assemble from the local ones without communication (multihost.py)."""
    from cst_captioning_tpu.train import multihost

    return multihost.put_full_global(replicated_sharding(mesh), tree)
