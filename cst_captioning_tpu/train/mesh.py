"""Device mesh + sharding helpers (the NCCL/DataParallel replacement).

One ``Mesh`` axis ``'data'`` for v1 (the reference is pure data-parallel,
SURVEY.md §2 parallelism table). Axis naming leaves room for a future
``('dcn', 'data')`` multi-host hierarchy without changing call sites.

Batches shard along axis 0 across ``'data'``; params/state replicate.
``shard_batch``/``replicate`` place host arrays accordingly so jitted steps
see committed, correctly-laid-out inputs (no implicit transfers inside the
step).
"""

from __future__ import annotations

import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_devices: int = 0, axis: str = "data",
              seq_devices: int = 1, seq_axis: str = "seq") -> Mesh:
    """1-D ``(data,)`` mesh, or 2-D ``(data, seq)`` when ``seq_devices > 1``
    (the long-context layout: batch over 'data', frames over 'seq')."""
    devices = jax.devices()
    if num_devices:
        devices = devices[:num_devices]
    if seq_devices > 1:
        n = len(devices)
        if n % seq_devices:
            raise ValueError(
                f"seq_devices {seq_devices} must divide the {n} mesh devices"
            )
        grid = np.asarray(devices).reshape(n // seq_devices, seq_devices)
        return Mesh(grid, (axis, seq_axis))
    return Mesh(np.asarray(devices), (axis,))


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Axis-0 sharding for batch pytrees."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, axis: str = "data"):
    """Place a host batch pytree with axis 0 split across the mesh."""
    return jax.device_put(batch, batch_sharding(mesh, axis))


# ---- parameter partition contract ------------------------------------------
#
# (family, path regex, PartitionSpec) for every parameter family of the
# caption model. v1 trains pure data-parallel — the reference is DP-only —
# so every family maps to P() (replicated); the row's value is the CONTRACT,
# not the spec: ``scripts/check_shardings.py`` dumps the real param tree
# into SHARDING_CONTRACT, and graftlint rule GL007 cross-checks that every
# regex still matches at least one parameter and every parameter is covered
# by some rule. A model refactor that renames a family then fails the
# linter instead of silently falling out of the (future model-parallel)
# sharded layout. Order matters: first match wins in param_partition_specs.
PARAM_PARTITION_RULES: tuple[tuple[str, str, P], ...] = (
    ("encoder_embed", r"params/encoder/embed_[^/]+/.*", P()),
    ("carry_init", r"params/init_[hc]\d+/.*", P()),
    ("decoder_attention", r"params/cell/attention/.*", P()),
    ("decoder_lstm", r"params/cell/lstm\d+/.*", P()),
    ("word_embed", r"params/cell/word_embed/.*", P()),
    ("output_head", r"params/cell/out_proj/.*", P()),
)

# repo-root-relative dump of the model param tree the rules above were
# written against (regenerate: `python scripts/check_shardings.py --write`)
SHARDING_CONTRACT = "scripts/shardings_contract.json"


def param_path_names(params) -> list[str]:
    """Flat '/'-joined key paths of a param pytree (the contract's naming)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for keypath, _ in flat:
        parts = []
        for k in keypath:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:  # pragma: no cover - defensive
                parts.append(str(k))
        out.append("/".join(parts))
    return out


def rule_coverage(param_names) -> tuple[list[str], list[str]]:
    """-> (families matching no param, params matched by no family)."""
    unmatched = []
    unruled = set(param_names)
    for family, pattern, _ in PARAM_PARTITION_RULES:
        rx = re.compile(pattern)
        hits = [p for p in param_names if rx.fullmatch(p)]
        if not hits:
            unmatched.append(family)
        unruled.difference_update(hits)
    return unmatched, sorted(unruled)


def param_partition_specs(params):
    """PartitionSpec pytree for ``params`` by first-matching family rule.

    Raises ``ValueError`` on an unruled parameter — an unruled param must be
    an explicit decision (add a family rule), never a silent default.
    """
    names = param_path_names(params)
    specs = []
    for name in names:
        for _, pattern, spec in PARAM_PARTITION_RULES:
            if re.fullmatch(pattern, name):
                specs.append(spec)
                break
        else:
            raise ValueError(
                f"parameter {name!r} matches no PARAM_PARTITION_RULES entry; "
                "add a family rule for it (scripts/check_shardings.py "
                "verifies coverage)"
            )
    flat, treedef = jax.tree_util.tree_flatten(params)
    assert len(flat) == len(specs)
    return jax.tree_util.tree_unflatten(treedef, specs)


def replicate(mesh: Mesh, tree):
    """Replicate a pytree (params / train state) on every mesh device.

    Multi-host: every process already holds an identical host copy (same
    init seed / same restored checkpoint), so the global replicated arrays
    assemble from the local ones without communication (multihost.py)."""
    from cst_captioning_tpu.train import multihost

    return multihost.put_full_global(replicated_sharding(mesh), tree)
