"""Device mesh + sharding helpers (the NCCL/DataParallel replacement).

One ``Mesh`` axis ``'data'`` for the data-parallel core (the reference is
pure data-parallel, SURVEY.md §2 parallelism table), with two optional
second axes that never coexist:

- ``('data', 'seq')`` — frame-axis sequence parallelism (long-context);
- ``('data', 'mp')``  — model parallelism for the flagship-XL configs:
  vocab/out-projection and LSTM gate matrices shard over ``'mp'`` per
  :data:`MP_PARAM_PARTITION_RULES`.

Batches shard along axis 0 across ``'data'``; params replicate (DP) or
follow :func:`match_partition_rules` over the ordered regex rule tables
(first match wins — the t5x/EasyLM ``match_partition_rules`` idiom).
``shard_batch``/``replicate`` place host arrays accordingly so jitted steps
see committed, correctly-laid-out inputs (no implicit transfers inside the
step).
"""

from __future__ import annotations

import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_devices: int = 0, axis: str = "data",
              seq_devices: int = 1, seq_axis: str = "seq",
              mp_devices: int = 1, mp_axis: str = "mp") -> Mesh:
    """1-D ``(data,)`` mesh, 2-D ``(data, seq)`` when ``seq_devices > 1``
    (the long-context layout: batch over 'data', frames over 'seq'), or
    2-D ``(data, mp)`` when ``mp_devices > 1`` (the flagship-XL layout:
    batch over 'data', vocab/gate dims over 'mp'). seq and mp do not
    compose yet — ExperimentConfig rejects the combination up front."""
    devices = jax.devices()
    if num_devices:
        devices = devices[:num_devices]
    if seq_devices > 1 and mp_devices > 1:
        raise ValueError(
            "seq_devices > 1 and mp_devices > 1 cannot compose yet: the "
            "collective attention softmax and the sharded-vocab decode "
            "assume different second axes (pick one)"
        )
    if seq_devices > 1:
        n = len(devices)
        if n % seq_devices:
            raise ValueError(
                f"seq_devices {seq_devices} must divide the {n} mesh devices"
            )
        grid = np.asarray(devices).reshape(n // seq_devices, seq_devices)
        return Mesh(grid, (axis, seq_axis))
    if mp_devices > 1:
        n = len(devices)
        if n % mp_devices:
            raise ValueError(
                f"mp_devices {mp_devices} must divide the {n} mesh devices"
            )
        grid = np.asarray(devices).reshape(n // mp_devices, mp_devices)
        return Mesh(grid, (axis, mp_axis))
    return Mesh(np.asarray(devices), (axis,))


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Axis-0 sharding for batch pytrees."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, axis: str = "data"):
    """Place a host batch pytree with axis 0 split across the mesh."""
    return jax.device_put(batch, batch_sharding(mesh, axis))


# ---- parameter partition contract ------------------------------------------
#
# Ordered (family, path regex, PartitionSpec) tables matched over the
# flattened param tree — first match wins, like t5x/EasyLM
# ``match_partition_rules``. Two tables, one contract:
#
# - PARAM_PARTITION_RULES: the canonical DP table. Every family maps to P()
#   (replicated) — the mp=1 degenerate case every default path compiles
#   against, pinned bit-identical in tests.
# - MP_PARAM_PARTITION_RULES: the flagship-XL model-parallel table. The
#   vocab families (word_embed rows, out_proj columns) and the LSTM gate
#   matrices shard over 'mp'; everything upstream of the gates replicates.
#
# The row's value is the CONTRACT, not just the spec:
# ``scripts/check_shardings.py`` dumps the real param tree into
# SHARDING_CONTRACT, graftlint rule GL007 cross-checks the canonical table
# (every regex matches >= 1 parameter, every parameter covered), and GL018
# extends the same coverage + first-match shadowing check to EVERY
# *PARTITION_RULES table, this one included. A model refactor that renames
# a family then fails the linter instead of silently falling out of the
# sharded layout.
PARAM_PARTITION_RULES: tuple[tuple[str, str, P], ...] = (
    ("encoder_embed", r"params/encoder/embed_[^/]+/.*", P()),
    ("carry_init", r"params/init_[hc]\d+/.*", P()),
    ("decoder_attention", r"params/cell/attention/.*", P()),
    ("decoder_lstm", r"params/cell/lstm\d+/.*", P()),
    ("word_embed", r"params/cell/word_embed/.*", P()),
    ("output_head", r"params/cell/out_proj/.*", P()),
)

# flagship-XL: Megatron-style column-parallel vocab projection + row-parallel
# embedding table, per-gate sharded LSTM kernels. Each gate is its own Dense
# (kernel [in, H], h-side bias [H]), so sharding the gate output dim needs
# mp | d_hidden; the vocab families need mp | vocab_size (config-validated).
MP_PARAM_PARTITION_RULES: tuple[tuple[str, str, P], ...] = (
    ("encoder_embed", r"params/encoder/embed_[^/]+/.*", P()),
    ("carry_init", r"params/init_[hc]\d+/.*", P()),
    ("decoder_attention", r"params/cell/attention/.*", P()),
    ("decoder_lstm_gate_kernel", r"params/cell/lstm\d+/[ih][ifgo]/kernel",
     P(None, "mp")),
    ("decoder_lstm_gate_bias", r"params/cell/lstm\d+/h[ifgo]/bias", P("mp")),
    ("word_embed", r"params/cell/word_embed/embedding", P("mp")),
    ("output_head_kernel", r"params/cell/out_proj/kernel", P(None, "mp")),
    ("output_head_bias", r"params/cell/out_proj/bias", P("mp")),
)

# repo-root-relative dump of the model param tree the rules above were
# written against (regenerate: `python scripts/check_shardings.py --write`)
SHARDING_CONTRACT = "scripts/shardings_contract.json"


def param_path_names(params) -> list[str]:
    """Flat '/'-joined key paths of a param pytree (the contract's naming)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for keypath, _ in flat:
        parts = []
        for k in keypath:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:  # pragma: no cover - defensive
                parts.append(str(k))
        out.append("/".join(parts))
    return out


def match_rule(rules, name: str) -> tuple[str, P]:
    """First (family, spec) whose regex fullmatches ``name``.

    Raises ``ValueError`` on an unruled parameter — an unruled param must be
    an explicit decision (add a family rule), never a silent default.
    """
    for family, pattern, spec in rules:
        if re.fullmatch(pattern, name):
            return family, spec
    raise ValueError(
        f"parameter {name!r} matches no partition rule; add a family rule "
        "for it (scripts/check_shardings.py verifies coverage)"
    )


def match_partition_rules(rules, params):
    """PartitionSpec pytree for ``params`` by first-matching ordered regex
    rule (the t5x/EasyLM ``match_partition_rules`` shape: ``rules`` is an
    ordered (family, pattern, spec) table, patterns fullmatch the
    '/'-joined param paths, first match wins, no-match raises)."""
    names = param_path_names(params)
    specs = [match_rule(rules, name)[1] for name in names]
    flat, treedef = jax.tree_util.tree_flatten(params)
    assert len(flat) == len(specs)
    return jax.tree_util.tree_unflatten(treedef, specs)


def rule_provenance(rules, param_names) -> dict[str, str]:
    """param path -> matching family name (the contract dump's provenance
    column — drift reports name the RULE that claimed each param)."""
    return {name: match_rule(rules, name)[0] for name in param_names}


def rule_coverage(param_names, rules=None) -> tuple[list[str], list[str]]:
    """-> (families matching no param, params matched by no family)."""
    if rules is None:
        rules = PARAM_PARTITION_RULES
    unmatched = []
    unruled = set(param_names)
    for family, pattern, _ in rules:
        rx = re.compile(pattern)
        hits = [p for p in param_names if rx.fullmatch(p)]
        if not hits:
            unmatched.append(family)
        unruled.difference_update(hits)
    return unmatched, sorted(unruled)


def param_partition_specs(params, rules=None):
    """PartitionSpec pytree for ``params`` by first-matching family rule
    (default: the canonical DP table — the mp=1 degenerate case)."""
    if rules is None:
        rules = PARAM_PARTITION_RULES
    return match_partition_rules(rules, params)


def replicate(mesh: Mesh, tree):
    """Replicate a pytree (params / train state) on every mesh device.

    Multi-host: every process already holds an identical host copy (same
    init seed / same restored checkpoint), so the global replicated arrays
    assemble from the local ones without communication (multihost.py)."""
    from cst_captioning_tpu.train import multihost

    return multihost.put_full_global(replicated_sharding(mesh), tree)
