"""Utilities: structured logging, timing (reference ``utils.py``, row 13)."""

from cst_captioning_tpu.utils.logging import EventLogger, StepTimer

__all__ = ["EventLogger", "StepTimer"]
